# Developer entry points (reference: Makefile test/test-integration/bench).
#
#   make test        run the full pytest suite
#   make lint        kueuelint static analysis (jit purity, lock discipline,
#                    retrace hygiene, API hygiene) + ruff when installed
#   make bench       full-scale benchmark; bench-smoke for CI shapes
#   make native      build the C++ runtime pieces
#   make dryrun      compile-check the flagship jit path

PYTHON ?= python

.PHONY: help test test-fast bench bench-smoke trace-smoke multichip-smoke \
	replica-smoke multihost-smoke fleet-smoke hetero-smoke ingest-smoke \
	fuzz-smoke fuzz-nightly fuzz-soak twin-smoke native lint verify-static \
	verify-det verify-threads verify-knobs knob-table install serve dryrun

help:
	@echo "kueue-tpu developer targets:"
	@echo "  make test           full pytest suite"
	@echo "  make test-fast      pytest, stop at first failure"
	@echo "  make lint           kueuelint ast engine (jit purity, locks,"
	@echo "                      retrace, API hygiene) + ruff if installed"
	@echo "  make verify-static  ALL analysis engines: ast + flow (lock"
	@echo "                      graph, ledger flow) + det (determinism"
	@echo "                      contract) + trace (kueueverify jaxpr"
	@echo "                      rules TRC01-04; needs jax)"
	@echo "  make verify-det     the determinism contract, statically:"
	@echo "                      DET01 unordered iteration into decision"
	@echo "                      state, DET02 wall-clock/randomness"
	@echo "                      taint, TNT01 knob decision contract +"
	@echo "                      the det/taint test module (fixture pins,"
	@echo "                      the static unsorted-members drill)"
	@echo "  make verify-threads fast slice: just the cross-thread engine"
	@echo "                      (THR01 shared-state races, THR02"
	@echo "                      unbounded blocking on service threads)"
	@echo "  make verify-knobs   the knob contract: KNOB01 + registry/"
	@echo "                      README-table sync tests"
	@echo "  make knob-table     print the README knob table generated"
	@echo "                      from the kueue_tpu.knobs registry"
	@echo "  make bench          full-scale benchmark (north-star shapes)"
	@echo "  make bench-smoke    tiny-shape bench for CI/laptops"
	@echo "  make trace-smoke    end-to-end trace: run the CLI with"
	@echo "                      --trace-out and schema-validate the"
	@echo "                      Chrome trace-event export (Perfetto)"
	@echo "  make multichip-smoke  8-shard cohort-mesh dryrun + sharded"
	@echo "                      differential goldens on CPU host devices"
	@echo "  make hetero-smoke   hetero solve-mode gates: churn goldens,"
	@echo "                      referee identity, smoke-scale bench gain"
	@echo "  make ingest-smoke   ingest-plane gates: batch-lane goldens,"
	@echo "                      then the ingest bench config — sustained"
	@echo "                      HTTP submit QPS (batch vs per-object),"
	@echo "                      submit->admitted p99, and the mid-window"
	@echo "                      snapshot-bootstrap rejoin drill"
	@echo "  make replica-smoke  3-replica multi-process run on CPU:"
	@echo "                      spawn-mode identity gate + fail-over"
	@echo "                      drill + the replica bench config with"
	@echo "                      commit-protocol evidence gates"
	@echo "  make multihost-smoke  2-emulated-host socket-transport run:"
	@echo "                      frame codec + channel tests, coordinator"
	@echo "                      kill + replica SIGKILL + revocation +"
	@echo "                      SIGSTOP-watchdog drills, packet-delay"
	@echo "                      injection, elastic scaling, and the"
	@echo "                      multihost bench config's evidence gates"
	@echo "  make fleet-smoke    fleet control-plane drill: TWO real OS"
	@echo "                      worker processes --join a coordinator"
	@echo "                      over TLS + auth token (no loopback"
	@echo "                      emulation), coordinator killed mid-"
	@echo "                      window -> degraded flat-cohort"
	@echo "                      admission continues, new incarnation"
	@echo "                      rejoin-reconciles == uninterrupted"
	@echo "                      single-process admitted set"
	@echo "  make fuzz-smoke     kueuefuzz CI budget: unit/corpus tests"
	@echo "                      (incl. the oracle-mutation self-test +"
	@echo "                      shrinker), then >= 25 seeded scenarios"
	@echo "                      replayed across the engine x shards x"
	@echo "                      replicas x kill-switch lattice with"
	@echo "                      zero oracle violations"
	@echo "  make fuzz-soak      hours-scale churn soak watching RSS /"
	@echo "                      arena occupancy / cache-hit / dispatch"
	@echo "                      drift (KUEUE_FUZZ_SOAK_SECONDS)"
	@echo "  make twin-smoke     digital twin CI budget: twin unit tests,"
	@echo "                      byte cross-check vs lattice.drive(), a"
	@echo "                      trace replay, and the 3-config what-if"
	@echo "                      sweep on a CPU-sized trace"
	@echo "  make native         build the C++ runtime pieces"
	@echo "  make serve          run the API server"
	@echo "  make dryrun         compile-check the flagship jit path"

test:
	$(PYTHON) -m pytest tests/ -q

test-fast:
	$(PYTHON) -m pytest tests/ -q -x

# Full-scale benchmark (50k x 1k x 8 north-star shape); runs on whatever
# jax backend is available. One JSON line per metric on stdout.
bench:
	$(PYTHON) bench.py

# Small-shape smoke variant for CI / laptops: tiny shapes, ~10 ticks per
# config — fast enough for every CI run, so perf wiring (solver dispatch,
# pipelining, the topology stage, churn) can't silently break. The arena
# gate re-reads the emitted BENCH lines: the incremental workload arena
# must REUSE rows inside the measured window (ratio > 0.9) with zero
# full rebuilds, or the from-scratch encode silently came back.
bench-smoke:
	KUEUE_BENCH_SMOKE=1 KUEUE_BENCH_TICKS=10 JAX_PLATFORMS=cpu \
	  $(PYTHON) bench.py > /tmp/kueue-bench-smoke.jsonl
	@cat /tmp/kueue-bench-smoke.jsonl
	$(PYTHON) -c "import json; \
	  from bench import METRIC_NAMES; \
	  lines = [json.loads(l) for l in open('/tmp/kueue-bench-smoke.jsonl') \
	           if l.strip().startswith('{')]; \
	  by = {l['metric']: l for l in lines}; \
	  missing = set(METRIC_NAMES.values()) - set(by); \
	  assert not missing, f'configs missing from BENCH output: {missing}'; \
	  noenv = [m for m, l in by.items() \
	           if not (l.get('environment') or {}).get('cpu_count')]; \
	  assert not noenv, f'BENCH records missing environment block: {noenv}'; \
	  steady = METRIC_NAMES['steady']; \
	  replica = METRIC_NAMES['replica']; \
	  multihost = METRIC_NAMES['multihost']; \
	  microtick = METRIC_NAMES['microtick']; \
	  ingest = METRIC_NAMES['ingest']; \
	  ratios = {m: l.get('arena_reuse_ratio') for m, l in by.items()}; \
	  bad = {m: r for m, r in ratios.items() \
	         if (r is None or r <= 0.9) and m not in (steady, replica, \
	                                                  multihost, microtick, \
	                                                  ingest)}; \
	  assert not bad, f'arena_reuse_ratio <= 0.9: {bad}'; \
	  rebuilds = {m: l.get('arena_full_rebuilds') for m, l in by.items()}; \
	  assert not any(rebuilds.values()), f'full rebuilds in window: {rebuilds}'; \
	  hit = by[steady].get('nominate_cache_hit_ratio'); \
	  assert hit is None or hit > 0.8, \
	    f'steady-state nominate_cache_hit_ratio <= 0.8: {hit}'; \
	  assert by[steady].get('solver_dispatches') == 0, \
	    f'quiescent window dispatched solves: {by[steady]}'; \
	  q = by[steady].get('quiescent_tick_ms'); \
	  assert q is not None, \
	    'quiescent_tick_ms missing from the steady config'; \
	  import os; \
	  budget = float(os.environ.get('KUEUE_QUIESCENT_BUDGET_MS', '50')); \
	  assert q <= budget, \
	    f'quiescent tick {q}ms over the {budget}ms budget (the ' \
	    f'nothing-changed fast path regressed)'; \
	  assert by[steady].get('quiescent_ticks_replayed', 0) > 0, \
	    'steady window never took the quiescent-tick replay path'; \
	  shard = by[METRIC_NAMES['shard']]; \
	  assert shard.get('shard_dispatches', 0) > 0 \
	    and shard.get('shard_imbalance_ratio') is not None \
	    and shard.get('reconcile_revocations') is not None, \
	    f'shard config missing per-shard evidence: {shard}'; \
	  fair = by[METRIC_NAMES['fair']]; \
	  r = fair.get('fair_vs_northstar_p99_ratio'); \
	  assert r is not None \
	    and fair.get('fair_share_compute_ms') is not None, \
	    f'fair config missing device-fair evidence: {fair}'; \
	  assert fair['ticks'] < 50 or r <= 1.10, \
	    f'fair p99 is x{r} the northstar twin (budget 1.10): the fair ' \
	    f'path is paying host DRF work again: {fair}'; \
	  fsteady = by[steady].get('fair_steady'); \
	  assert fsteady is not None \
	    and fsteady.get('solver_dispatches') == 0, \
	    f'fair steady state dispatched solves (the share state is ' \
	    f'defeating the nominate cache): {fsteady}'; \
	  print('bench-smoke arena gate OK:', ratios); \
	  print('bench-smoke steady gate OK: hit_ratio', hit, \
	        'quiescent_tick_ms', q, \
	        'replayed', by[steady].get('quiescent_ticks_replayed')); \
	  print('bench-smoke shard gate OK: imbalance', \
	        shard.get('shard_imbalance_ratio'), 'scaling', \
	        shard.get('p99_scaling_ratio')); \
	  rep = by[replica]; \
	  assert rep.get('identity_gate_admitted', 0) > 0, \
	    f'replica config missing the identity-gate evidence: {rep}'; \
	  drill = rep.get('forced_revocation_drill') or {}; \
	  assert drill.get('revocations', 0) >= 1, \
	    f'replica config produced no forced cross-replica revocation: {rep}'; \
	  rtt = rep.get('reconcile_rtt_ms') or {}; \
	  assert rtt.get('p99') is not None and rtt.get('p50') is not None, \
	    f'replica config missing reconcile_rtt_ms evidence: {rep}'; \
	  assert rep.get('peak_rss_mb', 0) > 0 and rep.get('n_replicas', 0) >= 2, \
	    f'replica config missing peak-RSS / replica-count evidence: {rep}'; \
	  mh = by[multihost]; \
	  assert mh.get('transport') == 'socket', mh; \
	  assert mh.get('coordinator_failover'), mh; \
	  assert (mh.get('elastic_drill') or {}).get('steady_dispatches') == 0, mh; \
	  mt = by[microtick]; \
	  assert mt.get('microticks', 0) > 0 \
	    and mt.get('micro_admitted', 0) > 0, \
	    f'microtick config never took the event-driven path: {mt}'; \
	  mvt = mt.get('micro_vs_tickpath_p50'); \
	  assert mvt is not None and mvt < 1.0, \
	    f'micro-tick p50 not below the kill-switch tick-path p50: {mt}'; \
	  minv = mt.get('invariants') or {}; \
	  assert minv.get('oversubscription') == 0 \
	    and minv.get('unjournaled_revocations') == 0 \
	    and minv.get('fifo_violations') == 0, \
	    f'microtick invariant gate missing/red: {mt}'; \
	  print('bench-smoke microtick gate OK: p99_admit_ms', \
	        mt.get('p99_microtick_admit_ms'), 'vs tickpath p50', \
	        mt.get('p50_tickpath_admit_ms'), 'microticks', \
	        mt.get('microticks')); \
	  print('bench-smoke fair gate OK: ratio', r, \
	        'share_compute_ms', fair.get('fair_share_compute_ms'), \
	        'fair_steady_dispatches', fsteady.get('solver_dispatches')); \
	  print('bench-smoke replica gate OK: replicas', rep.get('n_replicas'), \
	        'rtt_p99_ms', rtt.get('p99'), 'revocations', \
	        drill.get('revocations'), 'peak_rss_mb', rep.get('peak_rss_mb')); \
	  print('bench-smoke multihost gate OK: epoch', \
	        mh.get('reconcile_epoch'), 'rtt_p99_ms', \
	        (mh.get('reconcile_rtt_ms') or {}).get('p99'))"

# End-to-end tracing smoke: drive the real CLI with span tracing on,
# then prove the exported file is valid Chrome trace-event JSON (the
# Perfetto/chrome://tracing format) containing the tick pipeline's
# phase spans. Runs in CI next to bench-smoke, so the trace surface
# cannot silently rot.
trace-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) -m kueue_tpu \
	  --objects examples/single-clusterqueue-setup.yaml \
	  --objects examples/sample-job.yaml --ticks 6 \
	  --trace-out /tmp/kueue-trace-smoke.json
	$(PYTHON) -c "import json; \
	  from kueue_tpu.tracing import validate_chrome_trace; \
	  doc = json.load(open('/tmp/kueue-trace-smoke.json')); \
	  problems = validate_chrome_trace(doc); \
	  assert not problems, problems; \
	  names = {e['name'] for e in doc['traceEvents']}; \
	  assert 'tick' in names and 'admit' in names, sorted(names); \
	  print('trace-smoke OK:', len(doc['traceEvents']), 'events')"

# Heterogeneity-aware solve-mode smoke: the default-mode churn goldens
# (hetero on-but-unprofiled == off, per engine) + kill-switch A/B, the
# device-vs-referee oracle drives (borrowing + weighted KEP-79), the
# steady-state zero-dispatch test, then the smoke-scale hetero bench
# config whose in-process gates assert a measured aggregate-effective-
# throughput gain over the first-fit twin and a dispatch-free hetero
# steady window. Runs in CI next to bench-smoke/replica-smoke so the
# hetero seam cannot rot.
hetero-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_hetero.py \
	  tests/test_engine_coverage.py -q
	KUEUE_BENCH_SMOKE=1 KUEUE_BENCH_TICKS=10 KUEUE_BENCH_CONFIG=hetero \
	  JAX_PLATFORMS=cpu $(PYTHON) bench.py > /tmp/kueue-hetero-smoke.jsonl
	@cat /tmp/kueue-hetero-smoke.jsonl
	$(PYTHON) -c "import json; \
	  lines = [json.loads(l) for l in open('/tmp/kueue-hetero-smoke.jsonl') \
	           if l.strip().startswith('{')]; \
	  rep = lines[-1]; \
	  assert rep['metric'] == 'p99_hetero_tick_ms', rep; \
	  gain = rep.get('throughput_gain_vs_first_fit'); \
	  assert gain is not None and gain > 1.0, rep; \
	  steady = rep.get('hetero_steady') or {}; \
	  assert steady.get('solver_dispatches') == 0, rep; \
	  assert rep.get('hetero_overrides', 0) > 0, rep; \
	  util = rep.get('flavor_utilization') or {}; \
	  assert len(util) == 8, rep; \
	  print('hetero-smoke OK: gain', gain, \
	        'overrides', rep['hetero_overrides'], \
	        'steady dispatches', steady.get('solver_dispatches'))"

# Million-user ingest-plane smoke: the batch-lane differential goldens
# (batch vs per-object byte-identical decision trails, kill-switch A/B,
# snapshot bootstrap == line replay), then the ingest bench config whose
# in-process gates check sustained HTTP submit QPS (batch lane vs the
# per-object baseline), submit->admitted p99, bounded RSS growth, and
# the mid-window rejoin drill bootstrapping from a shipped snapshot in
# under 10% of the journal history. Runs in CI next to bench-smoke so
# the ingest seam cannot rot.
ingest-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_ingest.py -q
	KUEUE_BENCH_SMOKE=1 KUEUE_BENCH_CONFIG=ingest JAX_PLATFORMS=cpu \
	  $(PYTHON) bench.py > /tmp/kueue-ingest-smoke.jsonl
	@cat /tmp/kueue-ingest-smoke.jsonl
	$(PYTHON) -c "import json; \
	  lines = [json.loads(l) for l in open('/tmp/kueue-ingest-smoke.jsonl') \
	           if l.strip().startswith('{')]; \
	  rep = lines[-1]; \
	  assert rep['metric'] == 'submit_to_admitted_p99_ms', rep; \
	  ratio = rep.get('ingest_batch_vs_per_object'); \
	  assert ratio is not None and ratio > 1.2, \
	    f'batch lane not beating the per-object baseline: {rep}'; \
	  assert rep.get('ingest_qps_sustained', 0) > 0, rep; \
	  assert rep.get('submit_to_admitted_p99_ms') is not None, rep; \
	  assert rep.get('bootstrap_snapshot') is True, \
	    f'rejoin did not bootstrap from a shipped snapshot: {rep}'; \
	  hist = rep.get('bootstrap_history_lines', 0); \
	  replay = rep.get('bootstrap_replay_lines'); \
	  assert hist > 0 and replay is not None and replay < 0.10 * hist, \
	    f'bootstrap replayed {replay} of {hist} journal lines: {rep}'; \
	  print('ingest-smoke OK: qps', rep['ingest_qps_sustained'], \
	        f'({ratio}x per-object), admit p99', \
	        rep['submit_to_admitted_p99_ms'], 'ms, bootstrap', \
	        f'{replay}/{hist} lines in', \
	        rep.get('bootstrap_seconds'), 's')"

# Cohort-mesh smoke on CPU host devices: the 8-shard dryrun (sharded
# solve bitwise-equal to single-device, hierarchy + lending-clamp probes
# included) plus the sharded differential goldens and reconcile tests.
# Runs in CI next to bench-smoke so the scale-out seam cannot rot on
# hosts without an attached mesh.
multichip-smoke:
	JAX_PLATFORMS=cpu \
	  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	  $(PYTHON) __graft_entry__.py
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_shard.py \
	  tests/test_sharded_solve.py -q

# Multi-process replica smoke on CPU: the spawn-mode (real
# multiprocessing) identity gate — a churn drive over real pipes must
# match the single-process trail — plus the SIGKILL fail-over drill
# (lease reassignment + partition-journal replay), the deterministic
# cross-replica lending-clamp revocation, and a 3-replica replica bench
# config whose gates assert the in-run identity check, >= 1 forced
# revocation, and the reconcile-RTT/peak-RSS evidence. Runs in CI next
# to multichip-smoke so the process-scale-out seam cannot rot.
replica-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest \
	  "tests/test_replica.py::test_spawn_identity_smoke" \
	  "tests/test_replica.py::test_spawn_failover_drill" \
	  "tests/test_replica.py::test_lending_clamp_commit_protocol_revokes" \
	  "tests/test_replica.py::test_merged_trace_is_valid_chrome_with_flow_events" \
	  "tests/test_durable.py::test_replica_failover_replays_partition_journal" \
	  -q
	KUEUE_BENCH_SMOKE=1 KUEUE_BENCH_TICKS=10 KUEUE_TPU_REPLICAS=3 \
	  KUEUE_BENCH_CONFIG=replica JAX_PLATFORMS=cpu \
	  $(PYTHON) bench.py > /tmp/kueue-replica-smoke.jsonl
	@cat /tmp/kueue-replica-smoke.jsonl
	$(PYTHON) -c "import json; \
	  lines = [json.loads(l) for l in open('/tmp/kueue-replica-smoke.jsonl') \
	           if l.strip().startswith('{')]; \
	  rep = lines[-1]; \
	  assert rep['metric'] == 'p99_replica_tick_ms', rep; \
	  assert rep.get('n_replicas') == 3, rep; \
	  assert rep.get('transport') == 'spawn', rep; \
	  assert rep.get('identity_gate_admitted', 0) > 0, rep; \
	  assert (rep.get('forced_revocation_drill') or {}) \
	    .get('revocations', 0) >= 1, rep; \
	  rtt = rep.get('reconcile_rtt_ms') or {}; \
	  assert rtt.get('p50') is not None and rtt.get('p99') is not None, rep; \
	  assert rep.get('peak_rss_mb', 0) > 0, rep; \
	  print('replica-smoke OK: rtt_p99_ms', rtt.get('p99'), \
	        'revocations', rep['forced_revocation_drill']['revocations'], \
	        'peak_rss_mb', rep['peak_rss_mb'], \
	        'scaling', rep.get('p99_scaling_ratio'))"

# Multi-host smoke on CPU: the frame-codec / fault-injection / reliable-
# channel unit tests, the two-emulated-host (separate state dirs,
# loopback sockets) identity goldens vs the pipe transport — with and
# without injected packet delay — the coordinator-kill mid-window
# fail-over (epoch bump + journaled-verdict replay), the SIGSTOP
# barrier-stall watchdog regression, journal replication, the elastic
# scaling + capacity-loan drills, and then the multihost bench config
# whose in-run gates re-prove the kill drills (coordinator kill +
# replica SIGKILL == uninterrupted == single-process, zero
# oversubscription) and the Aryl elastic loop at smoke scale. Runs in
# CI next to replica-smoke so the network seam cannot rot.
multihost-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_transport.py \
	  tests/test_multihost.py -q
	KUEUE_BENCH_SMOKE=1 KUEUE_BENCH_TICKS=10 KUEUE_TPU_REPLICAS=2 \
	  KUEUE_BENCH_CONFIG=multihost JAX_PLATFORMS=cpu \
	  $(PYTHON) bench.py > /tmp/kueue-multihost-smoke.jsonl
	@cat /tmp/kueue-multihost-smoke.jsonl
	$(PYTHON) -c "import json; \
	  lines = [json.loads(l) for l in open('/tmp/kueue-multihost-smoke.jsonl') \
	           if l.strip().startswith('{')]; \
	  rep = lines[-1]; \
	  assert rep['metric'] == 'p99_multihost_tick_ms', rep; \
	  assert rep.get('transport') == 'socket', rep; \
	  assert rep.get('per_host_state') is True, rep; \
	  assert rep.get('fault_delay_ms'), rep; \
	  fo = rep.get('coordinator_failover') or {}; \
	  assert fo.get('epoch_after', 0) > fo.get('epoch_before', 0), rep; \
	  kd = rep.get('kill_drill') or {}; \
	  assert kd.get('admitted', 0) > 0, rep; \
	  el = rep.get('elastic_drill') or {}; \
	  assert el.get('scaled_up') and (el.get('scaled_down') or \
	    el.get('returned')), rep; \
	  assert el.get('steady_dispatches') == 0, rep; \
	  assert el.get('loan_throughput_gain') is not None, rep; \
	  assert rep.get('identity_gate_admitted', 0) > 0, rep; \
	  assert (rep.get('forced_revocation_drill') or {}) \
	    .get('revocations', 0) >= 1, rep; \
	  rtt = rep.get('reconcile_rtt_ms') or {}; \
	  assert rtt.get('p99') is not None, rep; \
	  dd = rep.get('degraded_drill') or {}; \
	  assert dd.get('degraded_window_ticks', 0) >= 3, rep; \
	  assert dd.get('degraded_admissions', 0) > 0, rep; \
	  assert dd.get('rejoin_revocations', 0) >= 1, rep; \
	  assert dd.get('time_to_recover_s') is not None, rep; \
	  print('multihost-smoke OK: rtt_p99_ms', rtt.get('p99'), \
	        'epoch', rep.get('reconcile_epoch'), 'elastic', \
	        el.get('actions'), 'gain', el.get('loan_throughput_gain'), \
	        'degraded', dd)"

# Fleet control-plane smoke: two REAL OS worker processes join an
# in-driver coordinator via `python -m kueue_tpu --join 127.0.0.1:PORT`
# with TLS on and a shared auth token (zero loopback emulation), the
# channel-protocol lease service + degraded-mode tests first, then the
# kill drill: coordinator torn down mid-window with a wave pending ->
# both workers' watchdogs + failed re-election probes drop them to
# journaled degraded admission (flat cohorts keep admitting), a new
# coordinator incarnation on the same port rejoin-reconciles, and the
# final admitted set must equal the uninterrupted single-process run
# with zero quota oversubscription. Runs in CI next to multihost-smoke.
fleet-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_lease_channel.py \
	  tests/test_fleet.py tests/test_disk_faults.py -q -m "not slow"
	JAX_PLATFORMS=cpu $(PYTHON) -m kueue_tpu.transport.fleet_smoke \
	  > /tmp/kueue-fleet-smoke.jsonl
	@cat /tmp/kueue-fleet-smoke.jsonl
	$(PYTHON) -c "import json; \
	  rep = json.loads(open('/tmp/kueue-fleet-smoke.jsonl').read() \
	                   .strip().splitlines()[-1]); \
	  assert rep['ok'] is True, rep; \
	  assert rep['tls'] and rep['auth'], rep; \
	  assert rep['degraded_admissions'] > 0, rep; \
	  assert rep['degraded_window_ticks'] >= 3, rep; \
	  assert rep['admitted'] == 12, rep; \
	  print('fleet-smoke OK: recover', rep['time_to_recover_s'], 's,', \
	        rep['degraded_admissions'], 'degraded admissions over', \
	        rep['degraded_window_ticks'], 'ticks')"

# Nightly fuzz budget: the campaign WITH the multi-HOST socket lattice
# points (real framed TCP replica drives, clean + seeded packet faults)
# — excluded from fuzz-smoke's 25-seed CI budget by cost, run here and
# in the soak instead.
fuzz-nightly:
	JAX_PLATFORMS=cpu $(PYTHON) -m kueue_tpu.fuzz --seeds 12 \
	  --lattice socket --out /tmp/kueue-fuzz-nightly.json
	$(PYTHON) -c "import json; \
	  rep = json.load(open('/tmp/kueue-fuzz-nightly.json')); \
	  assert rep['violations'] == [], rep['violations'][:3]; \
	  ax = rep['lattice_axes']; \
	  assert 'socket' in ax.get('transports', []), ax; \
	  print('fuzz-nightly OK:', rep['scenarios'], 'scenarios, axes', ax)"

# kueuefuzz CI budget (the acceptance gate): the unit + corpus + soak
# tests first — including the oracle-mutation self-test, which proves the
# fuzzer CATCHES an env-gated revert of the name-sorted Cohort member
# walk within a bounded seed budget and shrinks the divergence to a
# reproducer <= 3 CQs / <= 10 workloads (the checked-in corpus under
# tests/fixtures/fuzz/ replays green, and each entry goes RED under its
# bug's mutation drill) — then the seeded campaign: >= 25 scenarios,
# each replayed across the (engine x shards {1,2} x replicas {1,2} x
# kill-switch set) lattice plus the fail-over (journal replay) and
# capacity-loan drill points, with ZERO oracle violations.
fuzz-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_fuzz.py \
	  tests/test_fuzz_corpus.py tests/test_fuzz_soak.py -q -m "not slow"
	JAX_PLATFORMS=cpu $(PYTHON) -m kueue_tpu.fuzz --seeds 25 \
	  --out /tmp/kueue-fuzz-smoke.json
	$(PYTHON) -c "import json; \
	  rep = json.load(open('/tmp/kueue-fuzz-smoke.json')); \
	  assert rep['scenarios'] >= 25, rep['scenarios']; \
	  assert rep['violations'] == [], rep['violations'][:3]; \
	  ax = rep['lattice_axes']; \
	  assert {1, 2} <= set(ax['shards']), ax; \
	  assert {1, 2} <= set(ax['replicas']), ax; \
	  assert True in ax['kill_switches'], ax; \
	  assert 'referee' in ax['engines'] and 'jax' in ax['engines'], ax; \
	  assert {'failover', 'loan', 'degraded'} <= set(ax['drills']), ax; \
	  assert True in ax.get('micro', []), ax; \
	  assert rep['environment'].get('cpu_count'), rep['environment']; \
	  print('fuzz-smoke OK:', rep['scenarios'], 'scenarios, axes', ax)"

# Digital-twin CI budget (< 2 min on CPU): the twin unit tests (trace
# model, generators, duration model, what-if algebra, determinism, and
# the pinned twin-vs-drive() byte-identity seeds), then the CLI three
# ways — byte cross-check against lattice.drive() on fresh generator
# seeds, a small replay that must finish with zero quota violations,
# and the what-if sweep over >= 3 capacity configs whose report gates
# on per-config oracle cleanliness.
twin-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_twin.py -q \
	  -m "not slow"
	JAX_PLATFORMS=cpu $(PYTHON) -m kueue_tpu.twin --crosscheck 3
	JAX_PLATFORMS=cpu $(PYTHON) -m kueue_tpu.twin \
	  --shape diurnal_heavy --workloads 20000 --days 1 --cqs 16 \
	  --cohorts 4 --engine referee --whatif baseline \
	  --whatif quota-75:quota=0.75 --whatif quota-150:quota=1.5 \
	  --out /tmp/kueue-twin-smoke.json
	$(PYTHON) -c "import json; \
	  rep = json.load(open('/tmp/kueue-twin-smoke.json')); \
	  assert rep['format'] == 'kueuetwin-report/v1', rep['format']; \
	  assert rep['ok'], [r['name'] for r in rep['configs'] \
	                     if r['quota_violations']]; \
	  names = [r['name'] for r in rep['configs']]; \
	  assert len(names) >= 3, names; \
	  base = rep['configs'][0]['metrics']; \
	  assert base['completed'] > 0, base; \
	  assert base['goodput_wl_per_vday'] > 0, base; \
	  print('twin-smoke OK:', names, 'goodput', \
	        {r['name']: r['metrics']['goodput_wl_per_vday'] \
	         for r in rep['configs']})"

# Hours-scale churn soak (default 2h; KUEUE_FUZZ_SOAK_SECONDS overrides):
# RSS / arena-occupancy / nominate-cache-hit / dispatch-rate curves must
# show no monotonic drift between the early and late halves of the run.
# The 120s pytest twin is registered behind the `slow` marker
# (tests/test_fuzz_soak.py); seconds-scale drift-detector units ride
# tier-1.
fuzz-soak:
	JAX_PLATFORMS=cpu $(PYTHON) -m kueue_tpu.fuzz \
	  --soak $${KUEUE_FUZZ_SOAK_SECONDS:-7200} \
	  --out /tmp/kueue-fuzz-soak.json

# Build the C++ runtime pieces (keyed heap, admission decoder) explicitly;
# they are also built lazily on first import.
native:
	$(PYTHON) -c "from kueue_tpu.utils import native_heap, native_decode; \
	  print('heap:', native_heap.native_available(), \
	        'decode:', native_decode.decode_available())"

# Codebase-specific static analysis (kueue_tpu/analysis): fails on any
# error-severity finding, same gate as tests/test_kueuelint.py and CI.
# Runs ruff too when it is installed (dev extra), but does not require it.
lint:
	$(PYTHON) -m kueue_tpu.analysis kueue_tpu/
	@if $(PYTHON) -c "import ruff" 2>/dev/null; then \
	  $(PYTHON) -m ruff check kueue_tpu/; \
	else \
	  echo "ruff not installed; skipped (pip install -e .[dev])"; \
	fi

# Every analysis engine at the CI gate severity: ast + flow + det + trace
# (kueueverify lowers the registered solver kernels to jaxprs — needs jax,
# unlike `make lint` which stays import-free).
verify-static:
	$(PYTHON) -m kueue_tpu.analysis --engine all --fail-on error kueue_tpu/

# The determinism contract, statically — the det engine alone (DET01
# unordered iteration reaching decision state, DET02 wall-clock/
# randomness taint into decision records and sort keys, TNT01 the knob
# registry's decision contract), then the test module that pins the
# fixture pairs and proves the unsorted-members oracle mutation is
# caught on SOURCE without running a fuzz campaign. Import-free and
# sub-second, same as `make lint`.
verify-det:
	$(PYTHON) -m kueue_tpu.analysis --engine det --fail-on error kueue_tpu/
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_det_taint.py -q

# Fast thread-safety slice: only the cross-thread shared-state engine
# (THR01 inconsistent locking across thread roots, THR02 unbounded
# blocking calls on service threads) over the threaded surfaces —
# import-free, sub-second, the right loop while editing transport code.
verify-threads:
	$(PYTHON) -m kueue_tpu.analysis --select THR01 --select THR02 \
	  --fail-on error kueue_tpu/

# The knob contract end to end: KNOB01 (no raw KUEUE_TPU_* env reads,
# no unregistered accessor names, no dead registry entries) plus the
# registry sanity + README-table drift tests.
verify-knobs:
	$(PYTHON) -m kueue_tpu.analysis --select KNOB01 \
	  --fail-on error kueue_tpu/
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_knobs.py -q

# The README "Environment knobs" table, generated from the registry —
# paste between the knob-table markers in README.md when knobs change
# (tests/test_knobs.py and CI fail on drift).
knob-table:
	@$(PYTHON) -c "from kueue_tpu import knobs; print(knobs.markdown_table())"

install:
	$(PYTHON) -m pip install -e .

serve:
	$(PYTHON) -m kueue_tpu --serve --port 8082

# Compile-check the flagship jit path single-chip and on a virtual
# 8-device mesh.
dryrun:
	$(PYTHON) -c "import __graft_entry__ as g; fn, a = g.entry(); fn(*a); print('entry OK')"
	$(PYTHON) __graft_entry__.py
