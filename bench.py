"""Admission-solve benchmark.

Shape: the north-star target from BASELINE.md -- 1k ClusterQueues x 100
cohorts x 8 ResourceFlavors with a 50k-deep pending backlog. The reference
admits one head per ClusterQueue per scheduling cycle (manager.go:489-508),
so each tick nominates <=1k workloads; the backlog drains across ticks.

The timed region is one tick's nomination solve -- what the reference does
sequentially in nominate()/flavorassigner.Assign (scheduler.go:317-351) --
here as: usage tensor refresh + batched device solve + decision decode.
The ClusterQueue-side encoding is static across ticks (keyed on allocatable
generations) and the backlog is pre-encoded once, modeling the incremental
encoder of the production scheduler.

Prints ONE JSON line:
  {"metric": "p99_tick_solve_ms", "value": ..., "unit": "ms",
   "vs_baseline": <north-star 100ms / value>}

Env knobs: KUEUE_BENCH_SMOKE=1 (tiny shapes), KUEUE_BENCH_TICKS=N.
"""

from __future__ import annotations

import gc
import json
import os
import sys
import time

import numpy as np


def main() -> None:
    smoke = os.environ.get("KUEUE_BENCH_SMOKE") == "1"
    if smoke:
        num_cqs, num_cohorts, num_flavors = 32, 8, 4
        backlog, ticks = 256, 12
    else:
        num_cqs, num_cohorts, num_flavors = 1000, 100, 8
        backlog, ticks = 50_000, int(os.environ.get("KUEUE_BENCH_TICKS", "50"))
    heads_per_tick = num_cqs

    from kueue_tpu.models.flavor_fit import (
        decode_assignments,
        device_static,
        fetch_outputs,
        fit_usage_delta,
        solve_flavor_fit_async,
    )
    from kueue_tpu.solver import schema as sch
    from kueue_tpu.utils.synthetic import synthetic_problem

    import jax

    t0 = time.perf_counter()
    cache, pending = synthetic_problem(
        num_cqs=num_cqs, num_cohorts=num_cohorts, num_flavors=num_flavors,
        num_pending=backlog, usage_fill=0.7, seed=42)
    snapshot = cache.snapshot()
    t_gen = time.perf_counter() - t0

    t0 = time.perf_counter()
    enc = sch.encode_cluster_queues(snapshot)
    static = device_static(enc)
    # Pre-encode the whole backlog once (incremental-encoder model).
    wt_all = sch.encode_workloads(pending, snapshot, enc,
                                  pad_to=len(pending))
    t_enc = time.perf_counter() - t0

    usage_enc = sch.UsageEncoder(enc)

    def slice_wt(lo: int, hi: int) -> sch.WorkloadTensors:
        return sch.WorkloadTensors(
            wl_cq=wt_all.wl_cq[lo:hi], req=wt_all.req[lo:hi],
            has_req=wt_all.has_req[lo:hi],
            podset_valid=wt_all.podset_valid[lo:hi],
            podset_unsat=wt_all.podset_unsat[lo:hi],
            elig=wt_all.elig[lo:hi], resume_slot=wt_all.resume_slot[lo:hi],
            wl_valid=wt_all.wl_valid[lo:hi], num_real=hi - lo)

    def dispatch(i: int):
        """Stage 1: per-tick usage refresh + encode + async device solve."""
        lo = (i * heads_per_tick) % backlog
        hi = min(lo + heads_per_tick, backlog)
        # Incremental refresh: re-reads only rows whose usage_version moved
        # (all hits in steady state -- admissions arrive via apply_batch).
        usage = usage_enc.refresh(snapshot)
        wt = slice_wt(lo, hi)
        return lo, wt, solve_flavor_fit_async(enc, usage, wt, static=static)

    folded = set()

    def collect(pending_tick):
        """Stage 2+3: fetch the in-flight solve, decode decisions, and fold
        the admitted usage back into the incremental encoder (the batched
        mirror of the scheduler's assume fast path). A wrapped-around slice
        (ticks > backlog/heads) is re-solved but not re-folded: its
        workloads were already admitted once."""
        lo, wt, handle = pending_tick
        out = fetch_outputs(handle)
        batch = pending[lo:lo + wt.num_real]
        assignments = decode_assignments(batch, snapshot, enc, out)
        if lo not in folded:
            folded.add(lo)
            delta, touched = fit_usage_delta(out, wt, enc)
            usage_enc.apply_batch(delta, touched)
            for ci in touched.tolist():
                # The cache's version bump from assume_workload; encoder and
                # cache advance in lockstep (BatchSolver.note_admission).
                snapshot.cluster_queues[enc.cq_names[ci]].usage_version += 1
        return out, assignments

    # The tick pipeline. A synchronized device round trip on a
    # remote-attached TPU costs ~100x the solve itself, so the scheduler
    # keeps `depth` nomination solves in flight: while tick i's outputs
    # cross back over the wire, ticks i+1..i+depth dispatch and tick i-1
    # decodes. Depth 1 (KUEUE_BENCH_DEPTH=1) is the fully synchronous
    # reference mode. Timing covers the steady state only: pipeline fill
    # and drain are excluded from the samples (and from the decision
    # counts, so decisions/s matches the timed window).
    depth = max(1, int(os.environ.get("KUEUE_BENCH_DEPTH", "8")))
    depth = min(depth, max(1, ticks - 1))

    # Warmup (compile), then reset the encoder state so the warmup tick's
    # admitted usage isn't double-counted when tick 0 runs again below
    # (the snapshot's bumped versions force a full clean re-read).
    collect(dispatch(0))
    usage_enc = sch.UsageEncoder(enc)
    folded.clear()

    # Long-running-scheduler GC discipline: the setup objects (50k encoded
    # workloads, the snapshot) are permanent; keep collector passes from
    # stalling the tick loop. Per-tick garbage is acyclic and dies by
    # refcount.
    gc.collect()
    gc.freeze()
    gc.set_threshold(200_000, 100, 100)

    times = []
    decisions = 0
    fit_count = 0
    if ticks <= depth:
        # Degenerate run (e.g. KUEUE_BENCH_TICKS=1): synchronous timing.
        for i in range(ticks):
            t0 = time.perf_counter()
            out, assignments = collect(dispatch(i))
            times.append(time.perf_counter() - t0)
            decisions += len(assignments)
            fit_count += int((out["wl_mode"][:len(assignments)] == 2).sum())
    else:
        # Fill: the first `depth` solves go in flight untimed.
        inflight = [dispatch(i) for i in range(depth)]
        # Warmup: drain the fill backlog off the device queue untimed --
        # the first few collects wait out solves that queued back-to-back
        # during fill, which is startup transient, not steady-state tick
        # latency.
        warm = min(depth + 2, max(0, ticks - depth - 8))
        for i in range(depth, depth + warm):
            inflight.append(dispatch(i))
            collect(inflight.pop(0))
        # Steady state: each iteration dispatches one tick and collects the
        # oldest in-flight one; collect-to-collect interval is the sample.
        t_prev = time.perf_counter()
        for i in range(depth + warm, ticks):
            inflight.append(dispatch(i))
            out, assignments = collect(inflight.pop(0))
            decisions += len(assignments)
            fit_count += int((out["wl_mode"][:len(assignments)] == 2).sum())
            now = time.perf_counter()
            times.append(now - t_prev)
            t_prev = now
        # Drain: completes the run but contributes no samples or counts.
        for pending_tick in inflight:
            collect(pending_tick)

    times_ms = np.array(times) * 1000.0
    p50 = float(np.percentile(times_ms, 50))
    p99 = float(np.percentile(times_ms, 99))
    decisions_per_sec = decisions / (sum(times) or 1e-9)

    print(
        f"# shape: {num_cqs} CQs x {num_cohorts} cohorts x {num_flavors} "
        f"flavors, backlog {backlog}, {heads_per_tick} heads/tick, "
        f"{ticks} ticks on {jax.default_backend()}, pipeline depth {depth}\n"
        f"# setup: generate {t_gen:.2f}s, encode {t_enc:.2f}s\n"
        f"# tick solve: p50 {p50:.2f}ms  p99 {p99:.2f}ms  "
        f"({decisions_per_sec:,.0f} decisions/s; {fit_count}/{decisions} Fit)",
        file=sys.stderr)
    print(json.dumps({
        "metric": "p99_tick_solve_ms",
        "value": round(p99, 3),
        "unit": "ms",
        "vs_baseline": round(100.0 / p99, 3) if p99 > 0 else None,
    }))


if __name__ == "__main__":
    main()
