"""End-to-end scheduling-tick benchmark.

Unlike the round-1/2 proxy (which timed the solver kernel on a hand-rolled
harness), this drives the REAL product: `Framework.tick()` — heap pops,
incremental snapshot mirror, batched device solve (pipelined, depth 8),
preemption-target search, entry ordering, the one-borrow-per-cohort
admission cycle with staleness re-validation, assume/apply, requeues and
the reconcile pass — at the north-star shape from BASELINE.md:
50k pending Workloads x 1k ClusterQueues x 100 cohorts x 8 flavors.

Two configs run:
  1. BASELINE config #3 (preemption-heavy): reclaimWithinCohort=Any +
     borrowWithinCohort=LowerPriority + priority classes; most nominations
     preempt victims (preemption.go:81-231 path).
  2. North-star admission mix (config #5 shape): the headline metric.

Steady-state churn: workloads admitted N ticks ago finish (releasing quota
and flushing cohort parking lots) and a fresh workload is submitted per
finish — the reference perf harness's arrival/completion flux
(test/performance/config.yaml) at north-star scale, so the backlog stays
deep and every tick does real admission work.

Prints one JSON line per config; the LAST line is the headline metric:
  {"metric": "p99_e2e_tick_ms", "value": ..., "unit": "ms",
   "vs_baseline": <north-star 100ms / value>}

Env knobs: KUEUE_BENCH_SMOKE=1 (tiny shapes), KUEUE_BENCH_TICKS=N,
KUEUE_BENCH_DEPTH=N (pipeline depth, default 8).
"""

from __future__ import annotations

import gc
import json
import os
import random
import sys
import time
from collections import deque

import numpy as np

# How many ticks an admitted workload runs before the churn loop finishes
# it (quota release + cohort flush + replacement submission). Varied per
# workload (4..6) like real job runtimes — a constant linger synchronizes
# completion waves into artificial once-every-N-ticks churn bursts.
LINGER_TICKS = (4, 5, 6)


def _rss_mb() -> float:
    """Current resident set of this process in MB (the replica
    runtime's reader, converted)."""
    from kueue_tpu.controllers.replica_runtime import _rss_bytes

    return _rss_bytes() / (1024.0 ** 2)


def _pctl(samples, q):
    if not samples:
        return None
    return float(np.percentile(np.asarray(samples, dtype=np.float64), q))


def run_config(*, label, num_cqs, num_cohorts, num_flavors, backlog, ticks,
               usage_fill, depth, preemption_heavy, fair_hierarchy=False,
               lending=False, topology=False, strict_fifo=False,
               no_preemption=False, churn_enabled=True, seed=42,
               shards=None, hetero_cluster=False, hetero_mode=False):
    from kueue_tpu.models.flavor_fit import BatchSolver
    from kueue_tpu.api.types import PodSet, Workload
    from kueue_tpu.utils.synthetic import synthetic_framework

    from kueue_tpu import features

    # Explicit on AND off: the fair config measures fair-on and fair-off
    # windows in one process (the northstar twin + the A/B/A
    # re-baseline), so the gate must track the window instead of
    # latching on.
    features.set_enabled(features.FAIR_SHARING, fair_hierarchy)
    if lending:
        features.set_enabled(features.LENDING_LIMIT, True)
    t0 = time.perf_counter()
    fw = synthetic_framework(
        num_cqs=num_cqs, num_cohorts=num_cohorts, num_flavors=num_flavors,
        num_pending=backlog, usage_fill=usage_fill, seed=seed,
        preemption_heavy=preemption_heavy, fair_hierarchy=fair_hierarchy,
        lending=lending, topology=topology, strict_fifo=strict_fifo,
        no_preemption=no_preemption, hetero=hetero_cluster,
        batch_solver=BatchSolver(shards=shards, hetero=hetero_mode),
        pipeline_depth=depth)
    t_setup = time.perf_counter() - t0

    inject_ms = float(os.environ.get("KUEUE_BENCH_INJECT_MS", "0") or 0)
    if inject_ms:
        # Transfer-latency injection: replay a measured device round-trip
        # (the round-1/2 microbench saw ~9-12 ms per dispatch over the
        # attachment link) into the pipeline — collect() blocks until the
        # dispatch is at least `inject_ms` old, exactly like waiting on a
        # remote device. Shows whether depth-k pipelining hides real
        # transfer latency without the device being reachable.
        solver = fw.scheduler.batch_solver
        orig_collect = solver.collect

        def delayed_collect(inflight):
            dispatched = inflight.get("dispatched")
            if dispatched is not None:
                remaining = inject_ms / 1000.0 \
                    - (time.perf_counter() - dispatched)
                if remaining > 0:
                    time.sleep(remaining)
            return orig_collect(inflight)

        solver.collect = delayed_collect

    # Track admissions as they apply so churn can finish them later
    # without scanning the 50k-workload map per tick. One expiry-ordered
    # deque per linger class.
    admitted_logs = [deque() for _ in LINGER_TICKS]
    admit_seq = [0]
    tick_no = [0]
    orig_apply = fw.scheduler.apply_admission

    def apply_admission(wl):
        ok = orig_apply(wl)
        if ok:
            i = admit_seq[0] % len(LINGER_TICKS)
            admit_seq[0] += 1
            admitted_logs[i].append((tick_no[0] + LINGER_TICKS[i], wl))
        return ok

    fw.scheduler.apply_admission = apply_admission

    rnd = random.Random(seed + 1)
    submit_seq = [0]

    def submit_replacement():
        """A fresh arrival with the generator's distribution (one shared
        draw — utils/synthetic.churn_arrival_draw — with the replica
        churn loop and the fuzz generator); in the preemption config
        arrivals alternate low/high priority so the preemption flux
        sustains (victims to preempt keep existing)."""
        from kueue_tpu.utils.synthetic import churn_arrival_draw

        submit_seq[0] += 1
        i = submit_seq[0]
        spec = churn_arrival_draw(
            rnd, num_cqs, num_flavors, preemption_heavy=preemption_heavy,
            topology=topology, hetero=hetero_cluster, seq=i)
        fw.submit(Workload(
            name=f"churn-{label}-{i}", namespace="default",
            queue_name=f"lq-{spec['queue_index']}",
            priority=spec["priority"],
            creation_time=float(100_000 + i),
            pod_sets=[PodSet.make(
                "ps0", count=spec["count"], cpu=spec["cpu"],
                memory=f"{spec['memory_gi']}Gi",
                flavor_throughputs=spec["tputs"], **spec["topo_kw"])]))

    def churn():
        """Completion flux: finish workloads whose linger expired, then
        delete them (the owning job's GC in the reference deletes the
        Workload object; without it the object population would grow
        unboundedly, which no real cluster does). The steady-state
        config runs with the flux off (churn_enabled=False): after the
        warmup saturates the quotas, nothing changes between ticks and
        every tick is quiescent."""
        if churn_enabled:
            for log in admitted_logs:
                while log and log[0][0] <= tick_no[0]:
                    _, wl = log.popleft()
                    if wl.is_admitted and not wl.is_finished:
                        fw.finish(wl)
                        fw.delete_workload(wl)
                        submit_replacement()
        # Idle-window bucket prewarm (untimed, like the production serve
        # loop's inter-tick gap): imminent head-count bucket rotations
        # compile here instead of inside a measured tick.
        fw.prewarm_idle()

    # Warmup: compile the solve for the steady-state head-count bucket,
    # fill the pipeline, and let the admission/completion flux reach steady
    # state (the first ~15 ticks drain the initial backlog mix with heavier
    # requeue churn than the steady state the metric describes).
    warmup = max(depth + 6, 20)
    preempted_before = fw.scheduler.metrics.preempted
    for _ in range(warmup):
        tick_no[0] += 1
        fw.tick()
        churn()
    if not churn_enabled:
        # Quiescent-window warmup: keep ticking until the backlog has
        # saturated every quota and a whole tick dispatches no solve
        # (every head replays its fingerprint-cached verdict). The
        # measured window then certifies the "nothing-changed ticks cost
        # nothing" contract.
        solver0 = fw.scheduler.batch_solver
        quiet = 0
        for _ in range(300):
            before_d = solver0.dispatches
            tick_no[0] += 1
            fw.tick()
            churn()
            # Require a full window of consecutive quiescent ticks: the
            # resume-from-last-flavor protocol cycles each NoFit head
            # through a short fingerprint loop, and every arm of the
            # loop must be cached before the window is dispatch-free.
            quiet = quiet + 1 if solver0.dispatches == before_d else 0
            if quiet >= max(8, depth + 2):
                break
        else:
            raise RuntimeError(
                f"[{label}] the churn-free warmup never reached a "
                "quiescent window (a solve kept dispatching): the "
                "nominate cache is not replaying unchanged heads")

    # Long-running-scheduler GC discipline: the permanent objects (50k
    # workloads, the mirror) are frozen into the permanent generation and
    # the cyclic collector is DISABLED during scheduling — per-tick
    # garbage is overwhelmingly acyclic and dies by refcount (measured:
    # ~60 cyclic objects/tick at north-star scale), while automatic
    # gen0/gen1 passes cost 10-120ms each and set tick p99. Cycles are
    # reaped by an explicit collect in the idle window between ticks
    # (the completion-flux slot, which the tick timer excludes — the
    # production serve loop has the same idle gap while Heads blocks).
    gc.collect()
    gc.freeze()
    gc.disable()

    from kueue_tpu.metrics import REGISTRY
    from kueue_tpu.tracing import TRACER, validate_chrome_trace

    phases = REGISTRY.tick_phase_seconds
    phase_base = dict(phases.sums)
    verbose = os.environ.get("KUEUE_BENCH_VERBOSE") == "1"
    # Compile-proof ticks, verified on EVERY bench run (not just in
    # tests/test_prewarm.py): any XLA compile landing inside the measured
    # window means a bucket rotation escaped the idle-window prewarm and
    # the p99 below is a compile cliff, not a scheduling number.
    solver = getattr(fw.scheduler, "batch_solver", None)
    cold_before = getattr(solver, "cold_dispatches", 0) if solver else 0
    # Incremental-arena evidence for the measured window: row reuse ratio,
    # rows re-encoded (the dirty deltas), and full arena rebuilds — the
    # last is asserted ZERO below, mirroring the cold_dispatches gate
    # (an encoding rotation inside the window means the p99 paid a whole
    # backlog re-encode, not a scheduling cost).
    arena_reused_before = getattr(solver, "arena_rows_reused", 0) \
        if solver else 0
    arena_missed_before = getattr(solver, "arena_rows_missed", 0) \
        if solver else 0
    arena_encoded_before = getattr(solver, "arena_rows_encoded", 0) \
        if solver else 0
    arena_rebuilds_before = getattr(solver, "arena_full_rebuilds", 0) \
        if solver else 0
    nom_hits_before = getattr(solver, "nominate_cache_hits", 0) \
        if solver else 0
    nom_misses_before = getattr(solver, "nominate_cache_misses", 0) \
        if solver else 0
    dispatches_before = getattr(solver, "dispatches", 0) if solver else 0
    # Cohort-shard evidence: per-shard head sums / imbalance-ratio sums
    # over the window, plus the reconcile pass's revocation count.
    shard_before = solver.shard_stats() if solver and shards else None
    hetero_overrides_before = getattr(solver, "hetero_overrides_total", 0) \
        if solver else 0
    revoked_before = fw.scheduler.metrics.reconcile_revocations
    quiescent_before = fw.scheduler.metrics.quiescent_ticks
    tick_phases = []
    base_admitted = fw.scheduler.metrics.admitted
    # Per-window peak RSS, sampled once per tick (/proc read, ~µs): at
    # 1M-backlog scale memory is first-class evidence next to latency,
    # so EVERY config's BENCH record carries it (single process here —
    # the replica config adds the children).
    rss_peak = [0.0]

    def measure(n):
        window = []
        for _ in range(n):
            tick_no[0] += 1
            if verbose:
                before = dict(phases.sums)
            t = time.perf_counter()
            fw.tick()
            window.append(time.perf_counter() - t)
            rss_peak[0] = max(rss_peak[0], _rss_mb())
            if verbose:
                tick_phases.append(
                    {k[0]: phases.sums[k] - before.get(k, 0.0)
                     for k in phases.sums})
            churn()
            if tick_no[0] % 20 == 0:
                gc.collect()   # idle-window cycle reaping (untimed)
        return window

    # The headline window runs with tracing ENABLED at default sampling —
    # the production posture the overhead assertion below certifies, and
    # the source of the slowest-tick trace artifact.
    TRACER.reset()
    TRACER.configure(enabled=True)
    times = measure(ticks)
    admitted = fw.scheduler.metrics.admitted - base_admitted
    preempted = fw.scheduler.metrics.preempted - preempted_before
    phase_means = {
        k[0]: 1000.0 * (phases.sums[k] - phase_base.get(k, 0.0)) / ticks
        for k in sorted(phases.sums)}
    times_ms = np.array(times) * 1000.0
    p50 = float(np.percentile(times_ms, 50))
    p99 = float(np.percentile(times_ms, 99))

    # Slowest-tick trace: head+tail sampling retained the worst tick of
    # the window; export it as Chrome trace JSON (Perfetto-loadable) and
    # point to it from the BENCH record, so the p99 outlier is a file an
    # operator can open, not just a number.
    slowest = TRACER.slowest_tick()
    trace_doc = TRACER.export_chrome(slowest_only=True)
    problems = validate_chrome_trace(trace_doc)
    if problems:
        raise RuntimeError(f"[{label}] invalid trace export: {problems[:3]}")
    import tempfile
    trace_path = os.environ.get("KUEUE_BENCH_TRACE_OUT") or os.path.join(
        tempfile.gettempdir(), f"kueue_bench_{label}_slowest_tick.json")
    with open(trace_path, "w", encoding="utf-8") as f:
        json.dump(trace_doc, f)

    # Compile-proof check for the measured (traced) window, BEFORE the
    # overhead window runs, so a compile there cannot be blamed here.
    cold_during = (getattr(solver, "cold_dispatches", 0) - cold_before
                   if solver else 0)
    if cold_during:
        raise RuntimeError(
            f"[{label}] {cold_during} cold dispatch(es) inside the measured "
            f"window: a head-count bucket rotation compiled in-tick, so the "
            "reported p99 is an XLA compile cliff. Fix the prewarm path "
            "(BatchSolver._maybe_prewarm / prewarm_idle) or raise "
            "KUEUE_PREWARM_MAX_BUCKET before trusting this run.")

    # Arena-incrementalism gate for the measured window (the
    # cold_dispatches discipline applied to the host encode): zero full
    # rebuilds, and the reuse/encode split recorded in the BENCH json.
    arena_reused = (getattr(solver, "arena_rows_reused", 0)
                    - arena_reused_before if solver else 0)
    arena_missed = (getattr(solver, "arena_rows_missed", 0)
                    - arena_missed_before if solver else 0)
    arena_encoded = (getattr(solver, "arena_rows_encoded", 0)
                     - arena_encoded_before if solver else 0)
    arena_rebuilds = (getattr(solver, "arena_full_rebuilds", 0)
                      - arena_rebuilds_before if solver else 0)
    if arena_rebuilds:
        raise RuntimeError(
            f"[{label}] {arena_rebuilds} full workload-arena rebuild(s) "
            "inside the measured window: the CQ encoding rotated mid-"
            "window, so the reported p99 includes a whole-backlog "
            "re-encode. Structural mutations belong outside the measured "
            "window; fix the churn loop (or the rotation trigger) before "
            "trusting this run.")
    # Reuse ratio over the GATHER path: rows served from the arena vs
    # rows a tick had to re-encode in-line (misses). Event-time encodes
    # (churn arrivals, noted in the untimed completion-flux slot) are the
    # design — they appear in encoded_rows_delta, not as misses. A fully
    # quiescent window gathers nothing at all (every head replayed its
    # cached verdict), leaving the ratio None.
    arena_reuse_ratio = (arena_reused / (arena_reused + arena_missed)
                         if arena_reused + arena_missed else None)
    # Fingerprinted-nominate evidence: heads replayed vs re-solved, and
    # how many ticks actually dispatched a device solve.
    nom_hits = (getattr(solver, "nominate_cache_hits", 0)
                - nom_hits_before if solver else 0)
    nom_misses = (getattr(solver, "nominate_cache_misses", 0)
                  - nom_misses_before if solver else 0)
    nominate_cache_hit_ratio = (nom_hits / (nom_hits + nom_misses)
                                if nom_hits + nom_misses else None)
    dispatches_during = (getattr(solver, "dispatches", 0)
                         - dispatches_before if solver else 0)
    quiescent_tick_ms = None
    if not churn_enabled:
        # Steady-state window: p50 IS the quiescent tick (the warmup
        # asserted quiescence before measuring), and a dispatched solve
        # inside the window means a fingerprint invalidated spuriously.
        quiescent_tick_ms = p50
        if dispatches_during:
            raise RuntimeError(
                f"[{label}] {dispatches_during} solve dispatch(es) inside "
                "the quiescent measured window: nothing changed between "
                "ticks, so every head must replay its fingerprint-cached "
                "verdict without touching the device. A dispatch here "
                "means a generation counter moved spuriously (or the "
                "nominate cache dropped entries).")

    # Tracer-overhead gate (north-star config): p99 with tracing at
    # default sampling must sit within 2% of tracing-off — the no-op
    # claim, measured on the real tick loop. A 0.5ms floor absorbs timer
    # jitter. The HARD failure only arms with >= 50 samples per window:
    # below that (bench-smoke's 10 ticks) "p99" is literally the single
    # slowest tick and one OS preemption would flake CI — the numbers
    # are still recorded in the BENCH json either way.
    TRACER.configure(enabled=False)
    overhead = None
    if label == "northstar":
        cold_before_off = getattr(solver, "cold_dispatches", 0) \
            if solver else 0
        p99_off = float(np.percentile(
            np.array(measure(ticks)) * 1000.0, 99))
        cold_off = (getattr(solver, "cold_dispatches", 0) - cold_before_off
                    if solver else 0)
        tol = max(0.02 * p99_off, 0.5)
        gated = ticks >= 50 and cold_off == 0
        overhead = {"p99_on_ms": round(p99, 3),
                    "p99_off_ms": round(p99_off, 3),
                    "tolerance_ms": round(tol, 3),
                    "gated": gated}
        if cold_off:
            # A compile inside the untraced window pollutes p99_off (it
            # would only LOOSEN the gate) — report, don't compare.
            print(f"# [{label}] {cold_off} cold dispatch(es) in the "
                  "untraced overhead window; overhead gate skipped",
                  file=sys.stderr)
        elif gated and p99 > p99_off + tol:
            raise RuntimeError(
                f"[{label}] tracer overhead above budget: p99 {p99:.2f}ms "
                f"traced vs {p99_off:.2f}ms untraced (tolerance "
                f"{tol:.2f}ms). The default-sampling tracer must be a "
                "no-op on the tick hot path — profile the span ring "
                "before trusting this run.")
    gc.enable()
    gc.unfreeze()
    gc.collect()
    import jax
    backend = jax.default_backend()
    inject_ms = float(os.environ.get("KUEUE_BENCH_INJECT_MS", "0") or 0)
    if inject_ms:
        backend = f"{backend}+inject{inject_ms:g}ms"
    from kueue_tpu.utils.envinfo import environment_block

    stats = {
        "backend": backend,
        # Machine-checkable home of the "bench boxes drift run to run —
        # compare within-run only" caveat: cpu count, load average at
        # measurement end, python/jax versions, container hint. Readers
        # comparing two BENCH artifacts can now verify the box shape
        # instead of trusting the prose note.
        "environment": environment_block(),
        "ticks": ticks,
        "p50_ms": round(p50, 3),
        "p99_ms": round(p99, 3),
        "mean_ms": round(float(times_ms.mean()), 3),
        "admitted": admitted,
        "preempted": preempted,
        # Compile-proof-tick evidence: cold XLA dispatches during the
        # measured window (asserted zero above) and over the whole run.
        "cold_dispatches": cold_during,
        "cold_dispatches_total": getattr(solver, "cold_dispatches", 0)
        if solver else 0,
        # Incremental-arena evidence for the measured window: row reuse
        # ratio (make bench-smoke gates on > 0.9), rows re-encoded by
        # dirty deltas, and full rebuilds (asserted zero above).
        "arena_reuse_ratio": (round(arena_reuse_ratio, 4)
                              if arena_reuse_ratio is not None else None),
        "encoded_rows_delta": arena_encoded,
        "arena_full_rebuilds": arena_rebuilds,
        "arena_full_rebuilds_total": getattr(
            solver, "arena_full_rebuilds", 0) if solver else 0,
        # Fingerprinted-nominate evidence (tentpole: unchanged heads skip
        # tensorize/solve/decode; make bench-smoke gates the steady
        # config's ratio > 0.8 and its window at zero dispatches).
        "nominate_cache_hit_ratio": (round(nominate_cache_hit_ratio, 4)
                                     if nominate_cache_hit_ratio is not None
                                     else None),
        "nominate_cache_hits": nom_hits,
        "solver_dispatches": dispatches_during,
        "quiescent_tick_ms": (round(quiescent_tick_ms, 3)
                              if quiescent_tick_ms is not None else None),
        "admissions_per_s": round(admitted / (sum(times) or 1e-9), 1),
        # Quiescent-tick fast-path evidence: how many measured ticks
        # replayed the previous provably-identical outcome instead of
        # recomputing sort/admit/requeue bookkeeping.
        "quiescent_ticks_replayed": (
            fw.scheduler.metrics.quiescent_ticks - quiescent_before),
        # Derived from tracer phase spans (the kueue_tick_phase_seconds
        # histogram is fed exclusively by TRACER.phase — one measurement
        # serves metrics, bench and the trace export).
        "phase_means_ms": {k: round(v, 2) for k, v in phase_means.items()
                           if v >= 0.05},
        "slowest_tick_trace": trace_path,
        "slowest_tick_ms": round(slowest.duration * 1000.0, 3)
        if slowest is not None else None,
        # Memory + commit-latency evidence, recorded for EVERY config:
        # peak RSS over the measured window (self only here — the
        # replica config sums the worker processes in) and the
        # cross-replica reconcile round trip (None in single-process
        # mode: phase B is an in-process pass, there is no commit
        # protocol to time).
        "peak_rss_mb": round(rss_peak[0], 1),
        "reconcile_rtt_ms": None,
        # Heterogeneity evidence, recorded for EVERY config: per-flavor
        # utilization histogram (primary resource) and the Gavel
        # objective over the live admitted set — the hetero config gates
        # its gain over the first-fit twin on these.
        "flavor_utilization": (solver.flavor_utilization()
                               if solver is not None else {}),
        "aggregate_effective_throughput": round(
            _aggregate_throughput(fw), 2),
    }
    if hetero_mode and solver is not None:
        stats["hetero_overrides"] = (solver.hetero_overrides_total
                                     - hetero_overrides_before)
        stats["hetero_score_version"] = solver.hetero_version
    if overhead is not None:
        stats["tracer_overhead"] = overhead
    if fair_hierarchy:
        # Device-fair evidence for the measured window: what the
        # incremental share-state refresh (weighted-DRF recompute for
        # dirty cohorts + rank upkeep) cost per tick — the
        # `nominate.fair` phase span, so metrics/bench/traces report
        # the same measurement.
        stats["fair_share_compute_ms"] = round(
            phase_means.get("nominate.fair", 0.0), 3)
    if shard_before is not None:
        sa = solver.shard_stats()
        d = sa["shard_dispatches"] - shard_before["shard_dispatches"]
        h1 = sa["shard_heads_sum"]
        h0 = shard_before["shard_heads_sum"]
        h0 = h0 + [0] * (len(h1) - len(h0))
        heads_delta = [a - b for a, b in zip(h1, h0)]
        stats.update({
            # Per-shard dispatch evidence for the measured window: mean
            # heads per shard per dispatch, the mean per-dispatch
            # imbalance ratio (max/mean shard load), the last per-shard
            # padded bucket, the dispatch/solve phase means the sharded
            # program rode, and the reconcile pass's revocations.
            "shard_dispatches": d,
            "shard_heads_mean": ([round(h / d, 2) for h in heads_delta]
                                 if d else heads_delta),
            "shard_imbalance_ratio": (round(
                (sa["shard_imbalance_sum"]
                 - shard_before["shard_imbalance_sum"]) / d, 3)
                if d else None),
            "shard_bucket": sa["shard_bucket_last"],
            "shard_phase_means_ms": {
                k: round(phase_means.get(k, 0.0), 3)
                for k in ("tensorize.dispatch", "device_solve")},
            "reconcile_revocations": (
                fw.scheduler.metrics.reconcile_revocations
                - revoked_before),
        })
    print(
        f"# [{label}] {num_cqs} CQs x {num_cohorts} cohorts x {num_flavors} "
        f"flavors, backlog {backlog}, {ticks} ticks on "
        f"{backend}, depth {depth}, setup {t_setup:.1f}s\n"
        f"# [{label}] e2e tick: p50 {p50:.2f}ms  p99 {p99:.2f}ms  "
        f"({admitted} admitted, {preempted} preempted, "
        f"{admitted / (sum(times) or 1e-9):,.0f} admissions/s)\n"
        f"# [{label}] phase means/tick: "
        + "  ".join(f"{k}={v:.1f}ms" for k, v in phase_means.items()),
        file=sys.stderr)
    if verbose:
        for i, (ms, row) in enumerate(zip(times_ms, tick_phases)):
            print(f"# [{label}] tick {i:3d} {ms:7.1f}ms  "
                  + "  ".join(f"{k}={v * 1000:.1f}"
                              for k, v in sorted(row.items())),
                  file=sys.stderr)
    return stats


def _aggregate_throughput(fw) -> float:
    from kueue_tpu.hetero.profile import aggregate_effective_throughput

    return aggregate_effective_throughput(fw.cache)


def _microtick_caps(fw):
    """Total nominal capacity per cohort root (canonical milli-units,
    straight from the cache specs) — the zero-oversubscription gate's
    denominator."""
    caps = {}
    for name, cq in fw.cache.cluster_queues.items():
        root = cq.cohort.root_name if cq.cohort is not None else "~" + name
        d = caps.setdefault(root, {})
        for rg in cq.resource_groups:
            for fq in rg.flavors:
                for rname, quota in fq.resources:
                    key = (fq.name, rname)
                    d[key] = d.get(key, 0) + quota.nominal
    return caps


def _microtick_oversub(fw, caps):
    """Oversubscribed (root, flavor, resource, used, cap) tuples at
    MILLI-unit resolution (cache usage is already canonical units)."""
    used = {}
    for name, cq in fw.cache.cluster_queues.items():
        root = cq.cohort.root_name if cq.cohort is not None else "~" + name
        d = used.setdefault(root, {})
        for fname, res in cq.usage.items():
            for rname, val in res.items():
                key = (fname, rname)
                d[key] = d.get(key, 0) + val
    bad = []
    for root, d in used.items():
        for key, val in d.items():
            cap = caps.get(root, {}).get(key, 0)
            if val > cap:
                bad.append((root, key[0], key[1], val, cap))
    return bad


def run_microtick_config(*, label, num_cqs, num_cohorts, num_flavors,
                         backlog, ticks, bursts_per_tick=2, seed=42,
                         strict_gate=True):
    """The event-driven admission bench: a bursty arrival trace lands
    BETWEEN full ticks and is admitted by dirty-cohort micro-ticks;
    `p99_microtick_admit_ms` is the submit->admitted wall time of those
    arrivals. Two windows run on the same framework: the micro window,
    then a KUEUE_TPU_NO_MICROTICK=1 twin where identical bursts wait
    for the next full tick — the tick-path latency the fast path
    replaces. Gated IN-RUN: micro p50 strictly below the tick-path p50
    at every scale, and (`strict_gate`, the northstar shape) micro p99
    strictly below the full-tick p50 — at small smoke shapes a steady
    incremental tick replays fingerprints in ~2ms while any fresh
    arrival costs one real solve dispatch, so the cross-population p99
    <p50 form only means something where ticks earn their latency.

    The three linearizability invariants the async path is pinned by
    (instead of byte identity with the sequential tick) are also gated
    in-run: zero quota oversubscription at milli-unit resolution after
    every slot, zero revocations/evictions (no admitted workload is
    ever taken back without a journaled verdict — single-process
    micro-ticks never arbitrate remotely, so the count must be 0), and
    per-ClusterQueue FIFO over the uniform burst arrivals."""
    from kueue_tpu.models.flavor_fit import BatchSolver
    from kueue_tpu.api.types import PodSet, Workload
    from kueue_tpu.utils.synthetic import heavy_tailed_int, \
        synthetic_framework
    from kueue_tpu.metrics import REGISTRY

    from kueue_tpu.api.types import (ClusterQueue, FlavorQuotas,
                                     LocalQueue, ResourceGroup)

    t0 = time.perf_counter()
    fw = synthetic_framework(
        num_cqs=num_cqs, num_cohorts=num_cohorts, num_flavors=num_flavors,
        num_pending=backlog, usage_fill=0.3, seed=seed,
        no_preemption=True, batch_solver=BatchSolver(), pipeline_depth=1)
    # The co-located-serving trace (ROADMAP item 2's regime): bursty
    # latency-critical arrivals land on dedicated SERVING cohorts with
    # shallow queues — they reach their CQ heads immediately, which is
    # what a sub-tick admission path is for — while the batch cohorts'
    # deep backlog keeps the full tick earning its latency.
    n_serving = 4
    serving_members = 4
    for s in range(n_serving):
        for m in range(serving_members):
            fw.create_cluster_queue(ClusterQueue(
                name=f"srv-cq-{s}-{m}", cohort=f"srv-pool-{s}",
                resource_groups=(ResourceGroup(
                    ("cpu",),
                    (FlavorQuotas.make("flavor-0", cpu=64),)),)))
            fw.create_local_queue(LocalQueue(
                name=f"srv-lq-{s}-{m}", namespace="default",
                cluster_queue=f"srv-cq-{s}-{m}"))
    t_setup = time.perf_counter() - t0
    caps = _microtick_caps(fw)

    in_micro = [False]
    tick_no = [0]
    submit_t = {}                 # key -> submit wall time
    admit_t = {}                  # key -> (admit wall time, via micro)
    fifo_order = {}               # cq index -> [creation_time] in admit order
    burst_keys = set()
    admitted_log = deque()        # (expiry tick, wl) completion flux
    orig_apply = fw.scheduler.apply_admission

    def apply_admission(wl):
        ok = orig_apply(wl)
        if ok:
            admit_t[wl.key] = (time.perf_counter(), in_micro[0])
            admitted_log.append((tick_no[0] + 4, wl))
            if wl.key in burst_keys:
                fifo_order.setdefault(wl.queue_name, []).append(
                    wl.creation_time)
        return ok

    fw.scheduler.apply_admission = apply_admission
    rnd = random.Random(seed + 7)
    seq = [0]

    def burst(measured: bool):
        """One bursty arrival slot: a heavy-tailed batch landing on one
        SERVING cohort's queues (uniform 1-cpu pods, priority 0 — so the
        FIFO invariant over them is strict: equal size + priority means
        no legal overtaking), admitted by ONE micro-tick."""
        pool = rnd.randrange(n_serving)
        n = heavy_tailed_int(rnd, lo=2, hi=serving_members * 2)
        t_sub = time.perf_counter()
        for _ in range(n):
            seq[0] += 1
            member = rnd.randrange(serving_members)
            wl = Workload(
                name=f"burst-{seq[0]}", namespace="default",
                queue_name=f"srv-lq-{pool}-{member}", priority=0,
                creation_time=float(500_000 + seq[0]),
                pod_sets=[PodSet.make("ps0", count=1, cpu=1)])
            if measured:
                submit_t[wl.key] = t_sub
                burst_keys.add(wl.key)
            fw.submit(wl)
        in_micro[0] = True
        try:
            fw.microtick()
        finally:
            in_micro[0] = False

    def churn():
        while admitted_log and admitted_log[0][0] <= tick_no[0]:
            _, wl = admitted_log.popleft()
            if wl.is_admitted and not wl.is_finished:
                fw.finish(wl)
                fw.delete_workload(wl)
        fw.prewarm_idle()

    # Warmup: drain the initial backlog mix, compile both the full-tick
    # bucket and the small micro-tick buckets (warmup bursts hit them).
    warmup = 12
    for _ in range(warmup):
        tick_no[0] += 1
        for _ in range(bursts_per_tick):
            burst(measured=False)
        fw.tick()
        churn()

    solver = fw.scheduler.batch_solver
    cold_before = solver.cold_dispatches
    revoked_before = fw.scheduler.metrics.reconcile_revocations
    evicted_before = sum(REGISTRY.evicted_workloads_total.values.values())
    micro_before = fw.scheduler.metrics.microticks
    micro_admitted_before = fw.scheduler.metrics.micro_admitted
    gc.collect()
    gc.freeze()
    gc.disable()

    def window(n_ticks):
        full = []
        for _ in range(n_ticks):
            tick_no[0] += 1
            for _ in range(bursts_per_tick):
                burst(measured=True)
            t = time.perf_counter()
            fw.tick()
            full.append(time.perf_counter() - t)
            churn()
            bad = _microtick_oversub(fw, caps)
            if bad:
                raise RuntimeError(
                    f"[{label}] micro-tick OVERSUBSCRIBED (milli-unit "
                    f"gate): {bad[:3]}")
            if tick_no[0] % 20 == 0:
                gc.collect()
        return full

    # Window 1: micro-ticks ON — bursts admit on the event-driven path.
    full_times = window(ticks)
    # Window 2: the kill-switch twin — the SAME burst distribution
    # waits for the next full tick (the latency regime the fast path
    # replaces), measured on the same framework.
    os.environ["KUEUE_TPU_NO_MICROTICK"] = "1"
    try:
        window(max(4, ticks // 2))
    finally:
        os.environ.pop("KUEUE_TPU_NO_MICROTICK", None)
    gc.enable()
    gc.unfreeze()
    gc.collect()

    # Invariant: no admitted workload revoked without a journaled
    # verdict. Single-process micro-ticks never ship reconcile rounds,
    # so the revocation AND eviction counts over the window must be 0.
    revoked = fw.scheduler.metrics.reconcile_revocations - revoked_before
    evicted = sum(REGISTRY.evicted_workloads_total.values.values()) \
        - evicted_before
    if revoked or evicted:
        raise RuntimeError(
            f"[{label}] unjournaled take-back: {revoked} revocations / "
            f"{evicted} evictions in a config that must have none")
    # Invariant: FIFO within each ClusterQueue over the uniform bursts.
    fifo_violations = sum(
        1 for times_ in fifo_order.values() if times_ != sorted(times_))
    if fifo_violations:
        bad_q = next(q for q, times_ in fifo_order.items()
                     if times_ != sorted(times_))
        raise RuntimeError(
            f"[{label}] per-CQ FIFO violated on {fifo_violations} "
            f"queue(s), e.g. {bad_q}: {fifo_order[bad_q][:6]}...")
    cold = solver.cold_dispatches - cold_before
    if cold:
        raise RuntimeError(
            f"[{label}] {cold} cold dispatch(es) in the measured window "
            "(micro-tick bucket rotation compiled in-tick)")

    micro_lat = [
        (admit_t[k][0] - t_sub) * 1000.0
        for k, t_sub in submit_t.items()
        if k in admit_t and admit_t[k][1]]
    tickpath_lat = [
        (admit_t[k][0] - t_sub) * 1000.0
        for k, t_sub in submit_t.items()
        if k in admit_t and not admit_t[k][1]]
    microticks = fw.scheduler.metrics.microticks - micro_before
    micro_admitted = fw.scheduler.metrics.micro_admitted \
        - micro_admitted_before
    if len(micro_lat) < 20 or len(tickpath_lat) < 10:
        raise RuntimeError(
            f"[{label}] too few samples (micro {len(micro_lat)}, "
            f"tick-path {len(tickpath_lat)}); the fast path (or the "
            "kill-switch twin) is not engaging")
    full_ms = np.array(full_times) * 1000.0
    p50_full = float(np.percentile(full_ms, 50))
    p99_full = float(np.percentile(full_ms, 99))
    p50_micro = _pctl(micro_lat, 50)
    p99_micro = _pctl(micro_lat, 99)
    p50_tickpath = _pctl(tickpath_lat, 50)
    p99_tickpath = _pctl(tickpath_lat, 99)
    if p50_micro >= p50_tickpath:
        raise RuntimeError(
            f"[{label}] micro-tick p50 submit->admitted {p50_micro:.2f}ms "
            f"is NOT below the kill-switch tick-path p50 "
            f"{p50_tickpath:.2f}ms on the same arrivals — the event-"
            "driven fast path is not beating the tick cadence")
    if strict_gate and p99_micro >= p50_full:
        raise RuntimeError(
            f"[{label}] micro-tick p99 submit->admitted {p99_micro:.2f}ms "
            f"is NOT below the full-tick p50 {p50_full:.2f}ms — the "
            "event-driven fast path is not beating the tick cadence")
    import jax
    from kueue_tpu.utils.envinfo import environment_block

    stats = {
        "backend": jax.default_backend(),
        "environment": environment_block(),
        "ticks": ticks,
        "p99_microtick_admit_ms": round(p99_micro, 3),
        "p50_microtick_admit_ms": round(p50_micro, 3),
        "p99_tickpath_admit_ms": round(p99_tickpath, 3),
        "p50_tickpath_admit_ms": round(p50_tickpath, 3),
        "p50_full_tick_ms": round(p50_full, 3),
        "p99_full_tick_ms": round(p99_full, 3),
        "micro_vs_tickpath_p50": round(p50_micro / p50_tickpath, 4)
        if p50_tickpath else None,
        "strict_gate": bool(strict_gate),
        "microticks": microticks,
        "micro_admitted": micro_admitted,
        "micro_samples": len(micro_lat),
        # The MEASURED invariant counts (each already raised above if
        # nonzero — recording the computed values, not constants, keeps
        # the Makefile gate honest).
        "invariants": {
            "oversubscription": 0,  # raise-on-first: reaching here == 0
            "unjournaled_revocations": revoked + evicted,
            "fifo_violations": fifo_violations,
        },
        "peak_rss_mb": round(_rss_mb(), 1),
    }
    print(
        f"# [{label}] {num_cqs} CQs x {num_cohorts} cohorts, backlog "
        f"{backlog}, {ticks} ticks, setup {t_setup:.1f}s\n"
        f"# [{label}] micro submit->admit: p50 {p50_micro:.2f}ms  "
        f"p99 {p99_micro:.2f}ms  vs full tick p50 {p50_full:.2f}ms "
        f"p99 {p99_full:.2f}ms  ({microticks} microticks, "
        f"{micro_admitted} micro admissions)",
        file=sys.stderr)
    return stats


def run_ingest_config(*, label, num_cqs, total_submits, batch_size,
                      seed=42, strict_gate=True):
    """The million-user ingest plane bench: submit->admitted as a
    measured streaming pipeline.

    Three phases on the REAL serve-path lanes (Store + StoreAdapter,
    not a direct Framework driver):

      1. Sustained-QPS window — the same submission doc stream pushed
         through (a) the per-object lane (decode -> create per doc,
         exactly what KUEUE_TPU_NO_BATCH_INGEST=1 reverts to) and
         (b) the batch lane (decode_workload_batch -> create_batch:
         one validation sweep, one dirty-event flush). Records
         `ingest_qps_sustained` and the ratio; full runs gate the
         batch lane at >= 5x the per-object baseline AND >= 10k
         submits/s, with RSS growth over the window bounded.
      2. Admission latency — bursts land through the batch lane and
         are admitted by dirty-cohort micro-ticks; records
         `submit_to_admitted_p99_ms` (bounded in full runs).
      3. Mid-window rejoin drill — a per-host replica deployment
         churns workloads to grow journal history, a worker is killed
         mid-window, and the rejoin must bootstrap from a shipped
         compacted snapshot: `bootstrap_replay_lines` is gated below
         10% of the journal history, `bootstrap_seconds` is the
         takeover tick's wall time.
    """
    import tempfile

    from kueue_tpu import knobs as knobs_mod
    from kueue_tpu.api import serialization
    from kueue_tpu.api.types import (ClusterQueue, FlavorQuotas,
                                     LocalQueue, PodSet, ResourceFlavor,
                                     ResourceGroup, Workload)
    from kueue_tpu.config import Configuration, TPUSolverConfig
    from kueue_tpu.controllers.replica_runtime import ReplicaRuntime
    from kueue_tpu.controllers.runtime import Framework
    from kueue_tpu.controllers.store import (
        KIND_CLUSTER_QUEUE, KIND_LOCAL_QUEUE, KIND_RESOURCE_FLAVOR,
        KIND_WORKLOAD, Store, StoreAdapter)
    from kueue_tpu.models.flavor_fit import BatchSolver

    t0 = time.perf_counter()
    fw = Framework(batch_solver=BatchSolver(), config=Configuration(
        tpu_solver=TPUSolverConfig(enable=False)))
    fw.create_namespace("default", labels={})
    store = Store()
    StoreAdapter(store, fw)
    store.create(KIND_RESOURCE_FLAVOR, ResourceFlavor.make("flavor-0"))
    for i in range(num_cqs):
        store.create(KIND_CLUSTER_QUEUE, ClusterQueue(
            name=f"ing-cq-{i}", cohort=f"ing-pool-{i % 8}",
            resource_groups=(ResourceGroup(
                ("cpu",), (FlavorQuotas.make("flavor-0", cpu=64),)),)))
        store.create(KIND_LOCAL_QUEUE, LocalQueue(
            name=f"ing-lq-{i}", namespace="default",
            cluster_queue=f"ing-cq-{i}"))
    t_setup = time.perf_counter() - t0

    # One encoded doc template; each submission doc differs only in
    # metadata.name — the shape a burst of same-manifest users
    # produces, and what the batch decoder's template-clone path is
    # for. Built through encode() so the docs match the POST wire shape.
    base = serialization.encode(KIND_WORKLOAD, Workload(
        name="ing-proto", namespace="default", queue_name="ing-lq-0",
        pod_sets=[PodSet.make("ps0", count=1, cpu=1)]))
    base.pop("status", None)

    def make_docs(n, start, prefix):
        # Uniform within a submission batch (the queue rotates per
        # chunk, not per doc): a burst of same-manifest users, the shape
        # the template-clone decode and one-sweep validation are for.
        docs = []
        for i in range(start, start + n):
            doc = json.loads(json.dumps(base))
            doc["metadata"]["name"] = f"{prefix}-{i}"
            doc["spec"]["queueName"] = \
                f"ing-lq-{(i // batch_size) % num_cqs}"
            docs.append(doc)
        return docs

    def drain():
        """Delete every submitted workload between windows (untimed) so
        each window starts from the same store/queue shape."""
        for wl in store.list(KIND_WORKLOAD):
            store.delete(KIND_WORKLOAD, f"{wl.namespace}/{wl.name}")
        gc.collect()

    # -- phase 1: sustained-QPS window ------------------------------------
    # Both lanes measured at the REAL serve surface: HTTP POSTs against
    # the API server on loopback over one keep-alive connection. The
    # per-object baseline is what a client submitting N manifests
    # individually pays (JSON parse, route, webhook, create, response —
    # per object); the batch lane is ONE WorkloadList POST per
    # `batch_size` docs landing through decode_workload_batch +
    # create_batch.
    import http.client
    import socket

    from kueue_tpu.server.api_server import APIServer

    srv = APIServer(store, fw).start()
    wl_path = ("/apis/kueue.x-k8s.io/v1beta1/namespaces/default/"
               "workloads")
    conn = http.client.HTTPConnection("127.0.0.1", srv.port)
    conn.connect()
    conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def post(payload):
        conn.request("POST", wl_path, json.dumps(payload).encode(),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        body = resp.read()
        if resp.status != 201:
            raise RuntimeError(
                f"[{label}] ingest POST failed ({resp.status}): "
                f"{body[:300]!r}")

    try:
        # Per-object baseline: a fraction of the batch total is enough
        # for a stable rate — the per-POST loop is the slow side.
        n_base = max(total_submits // 8, 512)
        docs = make_docs(n_base, 0, "po")
        t = time.perf_counter()
        for doc in docs:
            post(doc)
        qps_base = n_base / (time.perf_counter() - t)
        drain()

        rss_before = _rss_mb()
        docs = make_docs(total_submits, 0, "bl")
        t = time.perf_counter()
        for i in range(0, total_submits, batch_size):
            post({"apiVersion": "kueue.x-k8s.io/v1beta1",
                  "kind": "WorkloadList",
                  "items": docs[i:i + batch_size]})
        qps_batch = total_submits / (time.perf_counter() - t)
        rss_growth = _rss_mb() - rss_before
        drain()
    finally:
        conn.close()
        srv.stop()
    ratio = qps_batch / qps_base if qps_base else None
    if ratio is not None and ratio < (5.0 if strict_gate else 1.2):
        raise RuntimeError(
            f"[{label}] batch ingest lane at {qps_batch:,.0f} submits/s "
            f"is only {ratio:.2f}x the per-object baseline "
            f"({qps_base:,.0f}/s) — the one-pass decode/validate/flush "
            "lane is not paying for itself")
    if strict_gate and qps_batch < 10_000:
        raise RuntimeError(
            f"[{label}] sustained batch ingest {qps_batch:,.0f} "
            "submits/s is below the 10k/s target")
    if strict_gate and rss_growth > 2048:
        raise RuntimeError(
            f"[{label}] RSS grew {rss_growth:.0f}MB over the sustained "
            "window — the ingest path is not bounded")

    # -- phase 2: submit->admitted over the batch lane --------------------
    submit_t = {}
    admit_t = {}
    orig_apply = fw.scheduler.apply_admission

    def apply_admission(wl):
        ok = orig_apply(wl)
        if ok and wl.key in submit_t:
            admit_t[wl.key] = time.perf_counter()
        return ok

    fw.scheduler.apply_admission = apply_admission
    rnd = random.Random(seed)
    seq = [0]

    def burst(n, measured):
        docs = make_docs(n, seq[0], "adm")
        seq[0] += n
        t_sub = time.perf_counter()
        wls = serialization.decode_workload_batch(docs)
        created = store.create_batch(KIND_WORKLOAD, wls)
        if measured:
            for wl in created:
                submit_t[wl.key] = t_sub
        fw.microtick()

    for _ in range(6):          # warmup: compile the micro buckets
        burst(rnd.randrange(2, 9), measured=False)
    n_bursts = 40
    for _ in range(n_bursts):
        burst(rnd.randrange(2, 9), measured=True)
        # Completion flux keeps quota free and the store bounded.
        for wl in list(fw.workloads.values()):
            if wl.is_admitted and not wl.is_finished:
                fw.finish(wl)
                fw.delete_workload(wl)
    lat_ms = [(admit_t[k] - t_sub) * 1000.0
              for k, t_sub in submit_t.items() if k in admit_t]
    if len(lat_ms) < n_bursts:
        raise RuntimeError(
            f"[{label}] only {len(lat_ms)} submit->admitted samples — "
            "the batch lane's arrivals are not reaching admission")
    p50_adm = _pctl(lat_ms, 50)
    p99_adm = _pctl(lat_ms, 99)
    if strict_gate and p99_adm >= 100.0:
        raise RuntimeError(
            f"[{label}] submit->admitted p99 {p99_adm:.2f}ms breaches "
            "the 100ms ingest-plane bound")

    # -- phase 3: mid-window rejoin drill ---------------------------------
    old_floor = os.environ.get("KUEUE_TPU_SNAPSHOT_BOOT_FLOOR")
    os.environ["KUEUE_TPU_SNAPSHOT_BOOT_FLOOR"] = "16"
    try:
        with tempfile.TemporaryDirectory() as td:
            rt = ReplicaRuntime(2, spawn=False, engine="host",
                                transport="pipe", per_host=True,
                                state_dir=td)
            try:
                rt.create_resource_flavor(ResourceFlavor.make("flavor-0"))
                for i in range(6):
                    rt.create_cluster_queue(ClusterQueue(
                        name=f"rj-cq-{i}", resource_groups=(ResourceGroup(
                            ("cpu",),
                            (FlavorQuotas.make("flavor-0", cpu=8),)),)))
                    rt.create_local_queue(LocalQueue(
                        name=f"rj-lq-{i}", namespace="default",
                        cluster_queue=f"rj-cq-{i}"))
                # Churn history: submitted + finished + deleted workloads
                # leave journal lines but no live state, so the shipped
                # snapshot must be a small fraction of the history.
                n_churn = 120
                for r in range(4):
                    pairs = []
                    for i in range(r * (n_churn // 4),
                                   (r + 1) * (n_churn // 4)):
                        rt.submit(Workload(
                            name=f"rj-{i}", namespace="default",
                            queue_name=f"rj-lq-{i % 6}",
                            creation_time=float(i),
                            pod_sets=[PodSet.make("ps0", count=1, cpu=1)]))
                        pairs.append((f"default/rj-{i}", f"rj-cq-{i % 6}"))
                    rt.tick()
                    rt.finish_many(pairs)
                    rt.tick()
                victim = rt.group_owner[min(rt.group_owner)]
                rt.kill_replica(victim)
                t = time.perf_counter()
                rt.tick()       # detects the death, adopts via snapshot
                bootstrap_seconds = time.perf_counter() - t
                evidence = rt.bootstrap_evidence
                rt.tick()       # the adopter keeps scheduling
            finally:
                rt.close()
    finally:
        if old_floor is None:
            os.environ.pop("KUEUE_TPU_SNAPSHOT_BOOT_FLOOR", None)
        else:
            os.environ["KUEUE_TPU_SNAPSHOT_BOOT_FLOOR"] = old_floor
    if not evidence or not evidence.get("snapshot"):
        raise RuntimeError(
            f"[{label}] rejoin drill did not bootstrap from a shipped "
            f"snapshot (evidence: {evidence}) — the O(live-state) "
            "takeover path is not engaging")
    history = evidence["history_lines"]
    replay_lines = evidence["lines"]
    if history <= 0 or replay_lines >= 0.10 * history:
        raise RuntimeError(
            f"[{label}] rejoin replayed {replay_lines} of "
            f"{history} journal lines (>= 10%) — snapshot shipping is "
            "not compacting the bootstrap")

    import jax
    from kueue_tpu.utils.envinfo import environment_block

    stats = {
        "backend": jax.default_backend(),
        "environment": environment_block(),
        "submit_to_admitted_p99_ms": round(p99_adm, 3),
        "submit_to_admitted_p50_ms": round(p50_adm, 3),
        "admitted_samples": len(lat_ms),
        "ingest_qps_sustained": round(qps_batch, 1),
        "ingest_qps_per_object": round(qps_base, 1),
        "ingest_batch_vs_per_object": round(ratio, 2)
        if ratio is not None else None,
        "ingest_batch_size": batch_size,
        "ingest_total_submits": total_submits,
        "ingest_rss_growth_mb": round(rss_growth, 1),
        "bootstrap_replay_lines": replay_lines,
        "bootstrap_history_lines": history,
        "bootstrap_snapshot": bool(evidence.get("snapshot")),
        "bootstrap_seconds": round(bootstrap_seconds, 3),
        "strict_gate": bool(strict_gate),
        "peak_rss_mb": round(_rss_mb(), 1),
    }
    print(
        f"# [{label}] {num_cqs} CQs, {total_submits} submits (batch "
        f"{batch_size}), setup {t_setup:.1f}s\n"
        f"# [{label}] ingest: batch {qps_batch:,.0f}/s vs per-object "
        f"{qps_base:,.0f}/s ({ratio:.1f}x)  submit->admitted p50 "
        f"{p50_adm:.2f}ms p99 {p99_adm:.2f}ms\n"
        f"# [{label}] rejoin: {replay_lines}/{history} lines replayed "
        f"({100.0 * replay_lines / history:.1f}% of history) in "
        f"{bootstrap_seconds * 1000:.0f}ms",
        file=sys.stderr)
    return stats


METRIC_NAMES = {
    "single": "p99_single_cq_tick_ms",
    "cohortlend": "p99_cohort_lending_tick_ms",
    "preempt": "p99_preemption_tick_ms",
    "fair": "p99_fair_hier_tick_ms",
    "topo": "p99_topology_tick_ms",
    "steady": "p99_steady_state_tick_ms",
    "shard": "p99_sharded_tick_ms",
    "replica": "p99_replica_tick_ms",
    "multihost": "p99_multihost_tick_ms",
    "hetero": "p99_hetero_tick_ms",
    "microtick": "p99_microtick_admit_ms",
    "ingest": "submit_to_admitted_p99_ms",
    "northstar": "p99_e2e_tick_ms",
}


def _shard_identity_gate(n_shards: int, ticks: int = 25) -> int:
    """Drive the golden seed through shards=N and shards=1 and FAIL the
    bench if they admit different workload sets — the decision-identity
    contract the differential goldens pin at test scale, re-checked on
    every bench run at bench scale. Returns the admitted count."""
    from kueue_tpu.models.flavor_fit import BatchSolver
    from kueue_tpu.utils.synthetic import synthetic_framework

    def admitted_set(shards):
        fw = synthetic_framework(
            num_cqs=24, num_cohorts=6, num_flavors=4, num_pending=256,
            usage_fill=0.7, seed=7, preemption_heavy=False,
            batch_solver=BatchSolver(shards=shards), pipeline_depth=2)
        keys = set()
        orig = fw.scheduler.apply_admission

        def hook(wl):
            ok = orig(wl)
            if ok:
                keys.add(wl.key)
            return ok

        fw.scheduler.apply_admission = hook
        for _ in range(ticks):
            fw.tick()
            fw.prewarm_idle()
        return keys

    sharded = admitted_set(n_shards)
    single = admitted_set(1)
    if sharded != single:
        raise RuntimeError(
            f"[shard] shards={n_shards} and shards=1 admitted DIFFERENT "
            f"workload sets on the golden seed "
            f"(only-sharded={sorted(sharded - single)[:5]}, "
            f"only-single={sorted(single - sharded)[:5]}) — the "
            "cohort-sharded solve or the two-phase reconcile broke "
            "decision identity; do not trust this run.")
    return len(sharded)


def _replica_identity_gate(replicas: int, ticks: int = 25,
                           transport: str = "pipe",
                           state_dir=None) -> int:
    """`_shard_identity_gate` for the PROCESS split: drive the golden
    seed through a replicas=N deployment (loopback transport — the
    protocol and worker code are identical to spawn mode, pinned by
    tests/test_replica.py's spawn smoke) and through the single-process
    scheduler, and FAIL the bench if they admit different workload sets.
    Returns the admitted count."""
    from kueue_tpu.config import Configuration, TPUSolverConfig
    from kueue_tpu.controllers.replica_runtime import ReplicaRuntime
    from kueue_tpu.models.flavor_fit import BatchSolver
    from kueue_tpu.utils.synthetic import synthetic_framework

    kw = dict(num_cqs=24, num_cohorts=6, num_flavors=4, num_pending=256,
              usage_fill=0.7, seed=7)

    # Single-process reference, constructed exactly like a replica
    # worker's vertical slice (explicit BatchSolver, no probing, barrier
    # depth 1) so the only difference IS the partitioning.
    fw = synthetic_framework(
        batch_solver=BatchSolver(), pipeline_depth=1,
        config=Configuration(tpu_solver=TPUSolverConfig(enable=False)),
        **kw)
    single: set = set()
    orig = fw.scheduler.apply_admission

    def hook(wl):
        ok = orig(wl)
        if ok:
            single.add(wl.key)
        return ok

    fw.scheduler.apply_admission = hook
    for _ in range(ticks):
        fw.tick()
        fw.prewarm_idle()

    rt = ReplicaRuntime(replicas, spawn=False, transport=transport,
                        state_dir=state_dir)
    try:
        rt.load_synthetic(**kw)
        sharded: set = set()
        for _ in range(ticks):
            for key, _cq in rt.tick()["admitted"]:
                sharded.add(key)
    finally:
        rt.close()
    if sharded != single:
        raise RuntimeError(
            f"[replica] replicas={replicas} and the single-process "
            f"scheduler admitted DIFFERENT workload sets on the golden "
            f"seed (only-replica={sorted(sharded - single)[:5]}, "
            f"only-single={sorted(single - sharded)[:5]}) — the "
            "shard-group partition or the commit protocol broke decision "
            "identity; do not trust this run.")
    return len(sharded)


def _replica_revocation_drill(transport: str = "pipe",
                              state_dir=None) -> dict:
    """Force >= 1 cross-replica revocation and return the coordinator's
    evidence: two same-tick heads on different replicas of a split
    KEP-79 tree both borrow from one lending-limited pool that can serve
    only one — each replica's optimistic local pass admits its own, the
    coordinator commits exactly one in global cycle order and REVOKES
    the other. The bench fails if the protocol never revokes (the
    optimistic-local-pass / global-revoke loop went dead)."""
    import zlib

    from kueue_tpu import features
    from kueue_tpu.api.types import CohortSpec, PodSet, Workload
    from kueue_tpu.controllers.replica_runtime import ReplicaRuntime

    features.set_enabled(features.LENDING_LIMIT, True)
    names = ["east", "west", "north", "south", "alpha", "beta"]
    pair = next(
        (a, b) for i, a in enumerate(names) for b in names[i + 1:]
        if zlib.crc32(a.encode()) % 2 != zlib.crc32(b.encode()) % 2)

    from kueue_tpu.api.types import (
        ClusterQueue, FlavorQuotas, LocalQueue, ResourceFlavor,
        ResourceGroup)

    def _rg(*quotas):
        return ResourceGroup(covered_resources=("cpu",),
                             flavors=tuple(quotas))

    rt = ReplicaRuntime(2, spawn=False, engine="host",
                        transport=transport, state_dir=state_dir)
    try:
        rt.create_resource_flavor(ResourceFlavor.make("on-demand"))
        rt.create_cohort(CohortSpec(name="hroot"))
        rt.create_cohort(CohortSpec(name=pair[0], parent="hroot"))
        rt.create_cohort(CohortSpec(name=pair[1], parent="hroot"))
        rt.create_cohort(CohortSpec(
            name="hpool", parent="hroot",
            resource_groups=(
                _rg(FlavorQuotas.make("on-demand", cpu=(8, None, 4))),)))
        for side, cq in ((pair[0], "drill-a"), (pair[1], "drill-b")):
            rt.create_cluster_queue(ClusterQueue(
                name=cq, cohort=side,
                resource_groups=(
                    _rg(FlavorQuotas.make("on-demand", cpu=4)),)))
            rt.create_local_queue(LocalQueue(
                name=f"lq-{cq}", namespace="default", cluster_queue=cq))
        assert "hroot" in rt.gmap.split_roots
        for i, cq in enumerate(("drill-a", "drill-b")):
            rt.submit(Workload(
                name=f"borrow-{cq}", namespace="default",
                queue_name=f"lq-{cq}", creation_time=float(i + 1),
                pod_sets=[PodSet.make("ps0", count=1, cpu=8)]))
        revocations = 0
        for _ in range(6):
            revocations += rt.tick()["revocations"]
        evidence = {
            "revocations": revocations,
            "coordinator_commits": rt.coordinator.commits,
            "coordinator_rounds": rt.coordinator.rounds,
        }
    finally:
        rt.close()
    if revocations < 1:
        raise RuntimeError(
            "[replica] the forced lending-clamp drill produced ZERO "
            "cross-replica revocations: both borrowers were committed "
            "against a pool that can serve only one — the coordinator's "
            "merged lending-clamp replay is not gating split-root "
            "admissions; do not trust this run.")
    return evidence


def _multihost_kill_drill_gate(state_root: str, ticks: int = 14) -> dict:
    """The multi-host fail-over identity gate: drive one seed through
    THREE deployments — (A) socket transport, per-host state dirs,
    seeded packet delay, a coordinator kill AND a replica SIGKILL
    mid-window; (B) the same deployment uninterrupted; (C) the
    single-process scheduler — and FAIL the bench unless all three end
    on the SAME admitted set with zero quota oversubscription. This is
    the drill the transport subsystem exists to survive."""
    import os as _os

    from kueue_tpu.config import Configuration, TPUSolverConfig
    from kueue_tpu.controllers.replica_runtime import ReplicaRuntime
    from kueue_tpu.controllers.runtime import Framework
    from kueue_tpu.transport import FaultPlan

    def build(t):
        from kueue_tpu.api.types import (
            ClusterQueue, FlavorQuotas, LocalQueue, PodSet,
            ResourceFlavor, ResourceGroup, Workload)

        t.create_resource_flavor(ResourceFlavor.make("default"))
        for i in range(6):
            t.create_cluster_queue(ClusterQueue(
                name=f"mh-cq-{i}", resource_groups=(ResourceGroup(
                    covered_resources=("cpu",),
                    flavors=(FlavorQuotas.make("default", cpu=6),)),)))
            t.create_local_queue(LocalQueue(
                name=f"mh-lq-{i}", namespace="default",
                cluster_queue=f"mh-cq-{i}"))
        for i in range(6):
            for j in range(4):
                t.submit(Workload(
                    name=f"mh-{i}-{j}", namespace="default",
                    queue_name=f"mh-lq-{i}", priority=j % 2,
                    creation_time=float(i * 10 + j),
                    pod_sets=[PodSet.make("ps0", count=1, cpu=3)]))

    # (C) single-process reference.
    fw = Framework(batch_solver=None, config=Configuration(
        tpu_solver=TPUSolverConfig(enable=False)))
    fw.create_namespace("default", labels={})
    build(fw)
    fw.run_until_settled(max_ticks=ticks)
    expect = {name: sorted(cq.workloads)
              for name, cq in fw.cache.cluster_queues.items()}
    # cpu=6 in milli-units, the cache's usage resolution.
    quota = {name: 6000 for name in expect}

    def run(tag, kill):
        rt = ReplicaRuntime(
            2, spawn=True, engine="host", transport="socket",
            state_dir=_os.path.join(state_root, tag),
            faults=FaultPlan(seed=9, delay_ms=2.0, delay_prob=0.4))
        try:
            build(rt)
            for i in range(ticks):
                if kill and i == 4:
                    rt.kill_coordinator()
                if kill and i == 7:
                    rt.kill_replica(rt.group_owner[
                        rt.gmap.cq_group["mh-cq-0"]])
                rt.tick()
            dump = rt.dump()
            for name, usage in dump["usage"].items():
                used = sum(usage.get("default", {}).values())
                if used > quota.get(name, 0):
                    raise RuntimeError(
                        f"[multihost] quota OVERSUBSCRIBED on {name}: "
                        f"{used} > {quota[name]} after the {tag} drill")
            return ({name: sorted(keys)
                     for name, keys in dump["admitted"].items()},
                    rt.failover_evidence, rt.coordinator.epoch)
        finally:
            rt.close()

    interrupted, failover, epoch = run("drill", kill=True)
    clean, _, _ = run("clean", kill=False)
    for tag, got in (("interrupted", interrupted), ("clean", clean)):
        if got != expect:
            raise RuntimeError(
                f"[multihost] the {tag} multi-host run admitted a "
                f"DIFFERENT set than single-process: {got} != {expect} "
                "— fail-over or the socket transport broke decision "
                "identity; do not trust this run.")
    if failover is None or failover["epoch_after"] <= \
            failover["epoch_before"]:
        raise RuntimeError(
            "[multihost] the coordinator kill drill never failed over "
            f"(evidence: {failover}); do not trust this run.")
    return {"admitted": sum(len(v) for v in expect.values()),
            "coordinator_failover": failover,
            "final_epoch": epoch}


def _multihost_elastic_drill(ticks: int = 24, n_cqs: int = 48,
                             backlog_per_cq: int = 6,
                             spawn: bool = False) -> dict:
    """The Aryl elastic drill: replicas scale N -> N+1 (load) -> N
    (drain) LIVE during churn, with capacity LOANED from an idle
    replica to the loaded one in between — and after resettling, a
    steady window must dispatch ZERO solves (the quiescent-tick
    discipline survives every migration). Returns throughput evidence:
    admitted/s for the LOADED groups before vs during the loan — the
    number Aryl's loaning loop exists to raise. (Per-tick host cost
    scales with the number of ClusterQueues carrying heads, so the
    loaded groups hold MANY small CQs; the loan splits them across
    processes and the wall-clock per tick — hence admissions/s at
    constant per-tick quota — improves.)"""
    from kueue_tpu.controllers.replica_runtime import ReplicaRuntime
    from kueue_tpu.transport import ElasticController

    from kueue_tpu.api.types import (
        ClusterQueue, FlavorQuotas, LocalQueue, PodSet, ResourceFlavor,
        ResourceGroup, Workload)

    rt = ReplicaRuntime(2, spawn=spawn, engine=None, transport="socket",
                        n_groups=8)
    ctl = ElasticController(rt, scale_up_backlog=8, idle_backlog=0,
                            loan_min_backlog=4, min_replicas=2,
                            max_replicas=3, cooldown_ticks=1)
    try:
        rt.create_resource_flavor(ResourceFlavor.make("default"))
        for i in range(n_cqs):
            rt.create_cluster_queue(ClusterQueue(
                name=f"el-cq-{i}", resource_groups=(ResourceGroup(
                    covered_resources=("cpu",),
                    flavors=(FlavorQuotas.make("default", cpu=4),)),)))
            rt.create_local_queue(LocalQueue(
                name=f"el-lq-{i}", namespace="default",
                cluster_queue=f"el-cq-{i}"))
        # Load ONLY worker 0's groups (the "loaded group" of the gate);
        # worker 1 idles — the Aryl shape.
        loaded_cqs = [
            i for i in range(n_cqs)
            if rt.group_owner[rt.gmap.cq_group[f"el-cq-{i}"]] == 0]
        seq = [0]
        outstanding: set = set()

        def submit_loaded(n_each):
            for i in loaded_cqs:
                for _ in range(n_each):
                    seq[0] += 1
                    key = f"default/el-{seq[0]}"
                    outstanding.add(key)
                    rt.submit(Workload(
                        name=f"el-{seq[0]}", namespace="default",
                        queue_name=f"el-lq-{i}",
                        creation_time=float(seq[0]),
                        pod_sets=[PodSet.make("ps0", count=1, cpu=2)]))

        rr = [0]

        def resupply(n):
            """One fresh arrival per finished workload (round-robin over
            the loaded CQs): the loaded groups stay loaded, so both
            measured windows see the same sustained demand."""
            for _ in range(n):
                i = loaded_cqs[rr[0] % len(loaded_cqs)]
                rr[0] += 1
                seq[0] += 1
                key = f"default/el-{seq[0]}"
                outstanding.add(key)
                rt.submit(Workload(
                    name=f"el-{seq[0]}", namespace="default",
                    queue_name=f"el-lq-{i}",
                    creation_time=float(seq[0]),
                    pod_sets=[PodSet.make("ps0", count=1, cpu=2)]))

        submit_loaded(backlog_per_cq)
        rt.tick()  # settle routing + first admissions off the clock

        def window(n, step_ctl, churn=True):
            """n churn ticks: finish everything admitted and resupply
            (so quota refills and throughput is compute-bound, not
            quota- or supply-bound); returns
            (admitted_for_loaded_groups, elapsed_s)."""
            admitted = 0
            t0 = time.perf_counter()
            for _ in range(n):
                stats = rt.tick()
                done = [(k, cq) for k, cq in stats["admitted"]]
                admitted += sum(
                    1 for _k, cq in done if cq.startswith("el-cq-"))
                if done:
                    for k, _cq in done:
                        outstanding.discard(k)
                    rt.finish_many(done)
                    if churn:
                        resupply(len(done))
                if step_ctl:
                    ctl.step(rt.backlog_last)
            return admitted, time.perf_counter() - t0

        # Window 1: loaded worker alone (controller off) — the
        # steady-state BEFORE any capacity arrives.
        a1, t1 = window(max(ticks // 3, 4), step_ctl=False)
        submit_loaded(backlog_per_cq // 2 or 1)
        # Transition (unmeasured): the controller loans/scales while
        # churn continues; migrations + the new workers' cold compiles
        # land here, not in either measured window. Settled = three
        # consecutive idle policy steps.
        idle_steps = 0
        for _ in range(ticks):
            stats = rt.tick()
            done = [(k, cq) for k, cq in stats["admitted"]]
            if done:
                for k, _cq in done:
                    outstanding.discard(k)
                rt.finish_many(done)
                resupply(len(done))
            act = ctl.step(rt.backlog_last)
            idle_steps = 0 if act else idle_steps + 1
            if idle_steps >= 3 and any(
                    a.startswith(("loan", "scale-up"))
                    for a in ctl.actions):
                break
        # Window 2: the loaded groups now run on the borrowed capacity
        # (controller off again) — the steady-state DURING the loan.
        a2, t2 = window(max(ticks // 3, 4), step_ctl=False)
        # Window 3: churn stops refilling; the backlog drains and the
        # controller takes the DOWN half (return + scale-down).
        a3, t3 = window(max(ticks // 3, 4), step_ctl=True, churn=False)
        # Drain: finish the last admissions, CANCEL the rest of the
        # synthetic backlog (the drill measured what it needed), and
        # let the controller finish the DOWN half — loans return home,
        # the surplus replica empties and stops.
        stats = rt.tick()
        done = [(k, cq) for k, cq in stats["admitted"]]
        if done:
            for k, _cq in done:
                outstanding.discard(k)
            rt.finish_many(done)
        for key in sorted(outstanding):
            rt.delete_workload(key)
        outstanding.clear()
        for _ in range(10):
            stats = rt.tick()
            done = [(k, cq) for k, cq in stats["admitted"]]
            if done:
                rt.finish_many(done)
            ctl.step(rt.backlog_last)
        # Post-resettle steady window: zero dispatches, or the elastic
        # churn broke the quiescent-tick discipline.
        steady_dispatches = 0
        for _ in range(3):
            steady_dispatches += rt.tick()["dispatches"] or 0
        tput_before = a1 / t1 if t1 else 0.0
        tput_during = a2 / t2 if t2 else 0.0
        evidence = {
            "actions": list(ctl.actions),
            "scaled_up": any(a.startswith("scale-up")
                             for a in ctl.actions),
            "loaned": any(a.startswith("scale-up") or a.startswith("loan")
                          for a in ctl.actions),
            "scaled_down": any(a.startswith("scale-down")
                               for a in ctl.actions),
            "returned": any(a.startswith("return") for a in ctl.actions),
            "n_workers_final": len([w for w in rt.workers if w.alive]),
            "loaded_tput_before_per_s": round(tput_before, 1),
            "loaded_tput_during_loan_per_s": round(tput_during, 1),
            "loan_throughput_gain": (round(tput_during / tput_before, 3)
                                     if tput_before else None),
            "steady_dispatches": steady_dispatches,
            "drained": sum(rt.dump()["pending"].values()) == 0,
        }
    finally:
        rt.close()
    if not evidence["scaled_up"]:
        raise RuntimeError(
            "[multihost] the elastic drill never scaled up under load "
            f"(actions: {evidence['actions']}); do not trust this run.")
    if not (evidence["scaled_down"] or evidence["returned"]):
        raise RuntimeError(
            "[multihost] the elastic drill never scaled back down / "
            f"returned the loan (actions: {evidence['actions']}).")
    if evidence["steady_dispatches"]:
        raise RuntimeError(
            "[multihost] the post-resettle steady window dispatched "
            f"{evidence['steady_dispatches']} solves — elastic churn "
            "broke the quiescent-tick discipline.")
    return evidence


def _multihost_degraded_drill(window_s: float = 1.5, n_cqs: int = 6,
                              cpu: int = 6) -> dict:
    """The degraded-window drill: the coordinator goes SILENT for the
    whole window (>= K self-ticks on every replica) while flat-cohort
    admission keeps flowing shard-locally under the journaled safe
    mode; it then comes back knowing a SMALLER quota on a third of the
    ClusterQueues, so the rejoin reconcile must REVOKE (newest-first,
    counted) — with the zero-oversubscription gate held at milli-unit
    resolution throughout the recovery. Records the four acceptance
    numbers: degraded_window_ticks, degraded_admissions,
    rejoin_revocations, time_to_recover_s."""
    from kueue_tpu.api.types import (
        ClusterQueue, FlavorQuotas, LocalQueue, PodSet, ResourceFlavor,
        ResourceGroup, Workload)
    from kueue_tpu.controllers.replica_runtime import ReplicaRuntime
    from kueue_tpu.controllers.store import KIND_CLUSTER_QUEUE, MODIFIED

    def cq_spec(i, c):
        return ClusterQueue(
            name=f"dg-cq-{i}", resource_groups=(ResourceGroup(
                covered_resources=("cpu",),
                flavors=(FlavorQuotas.make("default", cpu=c),)),))

    rt = ReplicaRuntime(2, spawn=False, engine="host", solver=False,
                        transport="socket", degraded_after=0.3)
    try:
        rt.create_resource_flavor(ResourceFlavor.make("default"))
        for i in range(n_cqs):
            rt.create_cluster_queue(cq_spec(i, cpu))
            rt.create_local_queue(LocalQueue(
                name=f"dg-lq-{i}", namespace="default",
                cluster_queue=f"dg-cq-{i}"))
        half = cpu // 2
        for i in range(n_cqs):
            rt.submit(Workload(
                name=f"dg-old-{i}", namespace="default",
                queue_name=f"dg-lq-{i}", creation_time=float(i),
                pod_sets=[PodSet.make("ps0", count=1, cpu=half)]))
        for _ in range(2):
            rt.tick()
        for i in range(n_cqs):
            rt.submit(Workload(
                name=f"dg-new-{i}", namespace="default",
                queue_name=f"dg-lq-{i}", creation_time=float(100 + i),
                pod_sets=[PodSet.make("ps0", count=1, cpu=half)]))
        rt.degraded_window(window_s)
        # The restarted coordinator's config halves a third of the CQs:
        # their degraded-window admission no longer fits.
        shrunk = list(range(0, n_cqs, 3))
        for i in shrunk:
            spec = cq_spec(i, half)
            rt._cq_specs[spec.name] = spec
            rt.coordinator.note_cluster_queue(spec)
        t0 = time.perf_counter()
        ev = rt.rejoin()
        for i in shrunk:
            rt.apply_event(KIND_CLUSTER_QUEUE, MODIFIED,
                           obj=rt._cq_specs[f"dg-cq-{i}"])
        rt.tick()  # first post-recovery barrier tick
        recover_s = time.perf_counter() - t0
        # Zero-oversubscription gate at MILLI-unit resolution, post-
        # recovery AND after two more settle ticks.
        caps = {f"dg-cq-{i}": (half if i in shrunk else cpu) * 1000
                for i in range(n_cqs)}
        for _ in range(3):
            for name, usage in rt.dump()["usage"].items():
                used = sum(usage.get("default", {}).values())
                if used > caps[name]:
                    raise RuntimeError(
                        f"[multihost] degraded drill OVERSUBSCRIBED "
                        f"{name}: {used} > {caps[name]} milli-units")
            rt.tick()
        evidence = {
            "degraded_window_ticks": ev["degraded_window_ticks"],
            "degraded_admissions": ev["degraded_admissions"],
            "degraded_workers": ev["degraded_workers"],
            "parked": ev["parked"],
            "rejoin_revocations": ev["rejoin_revocations"],
            "time_to_recover_s": round(recover_s, 3),
            "window_s": window_s,
        }
    finally:
        rt.close()
    if evidence["degraded_window_ticks"] < 3:
        raise RuntimeError(
            "[multihost] the degraded window ran fewer than 3 self-"
            f"ticks ({evidence}); the safe mode never engaged.")
    if evidence["degraded_admissions"] <= 0:
        raise RuntimeError(
            "[multihost] flat-cohort admission throughput did NOT stay "
            f"> 0 during the degraded window ({evidence}).")
    if evidence["rejoin_revocations"] < 1:
        raise RuntimeError(
            "[multihost] the quota shrink produced no rejoin "
            f"revocation ({evidence}); the catch-up reconcile is not "
            "replaying the degraded window.")
    return evidence


def run_replica_config(*, label, replicas, num_cqs, num_cohorts,
                       num_flavors, backlog, ticks, usage_fill, seed=42,
                       spawn=True, warmup=12, transport="pipe",
                       state_dir=None, fault_delay_ms=0.0,
                       mid_window=None):
    """One multi-process replica window: N spawn-mode worker processes
    (each owning its shard groups' full vertical slice), the parent
    driving the tick barrier + coordinator. The synthetic load is
    generated WORKER-SIDE (each process keeps only its cohort-hash
    slice from the shared seed), so the 1M-backlog window loads without
    a million workloads ever crossing the parent pipe; churn rides the
    compact submit_many/finish_many bulk messages.

    `transport="socket"` runs the framed multi-host protocol with
    per-host state dirs under `state_dir` (+ coordinator journal
    replication) and optional seeded packet-delay injection;
    `mid_window(i, rt)` fires before measured tick i — the coordinator-
    kill / replica-SIGKILL drill hook."""
    from kueue_tpu.controllers.replica_runtime import ReplicaRuntime
    from kueue_tpu.transport import FaultPlan

    t0 = time.perf_counter()
    faults = FaultPlan(seed=seed, delay_ms=fault_delay_ms,
                       delay_prob=0.5) if fault_delay_ms else None
    # First ticks at 1M backlog pay the whole-backlog encode + XLA
    # compile inside one barrier round; the default 60s deadline would
    # misread that as a dead worker — on BOTH sides of the watchdog:
    # the env var reaches the spawned workers' verdict wait, which the
    # parent-side round_timeout alone would not.
    if float(os.environ.get("KUEUE_TPU_BARRIER_DEADLINE", "0") or 0) \
            < 900.0:
        os.environ["KUEUE_TPU_BARRIER_DEADLINE"] = "900"
    rt = ReplicaRuntime(replicas, spawn=spawn, transport=transport,
                        state_dir=state_dir, faults=faults)
    rt.round_timeout = max(rt.round_timeout, 900.0)
    try:
        rt.load_synthetic(
            num_cqs=num_cqs, num_cohorts=num_cohorts,
            num_flavors=num_flavors, num_pending=backlog,
            usage_fill=usage_fill, seed=seed)
        t_setup = time.perf_counter() - t0

        rnd = random.Random(seed + 1)
        admitted_logs = [deque() for _ in LINGER_TICKS]
        admit_seq = [0]
        submit_seq = [0]
        tick_no = [0]

        def churn(stats):
            """The run_config completion flux over the bulk wire: track
            this tick's admissions, finish the expired ones in one
            message per owning replica, replace each with a fresh
            arrival routed by its LocalQueue hash."""
            for key, cq in stats["admitted"]:
                i = admit_seq[0] % len(LINGER_TICKS)
                admit_seq[0] += 1
                admitted_logs[i].append(
                    (tick_no[0] + LINGER_TICKS[i], key, cq))
            done = []
            for log in admitted_logs:
                while log and log[0][0] <= tick_no[0]:
                    _, key, cq = log.popleft()
                    done.append((key, cq))
            if not done:
                return
            rt.finish_many(done)
            from kueue_tpu.utils.synthetic import churn_arrival_draw

            specs = []
            for _ in done:
                submit_seq[0] += 1
                i = submit_seq[0]
                d = churn_arrival_draw(rnd, num_cqs, num_flavors, seq=i)
                specs.append({
                    "name": f"churn-{label}-{i}",
                    "queue": f"lq-{d['queue_index']}",
                    "priority": d["priority"],
                    "creation_time": float(100_000 + i),
                    "count": d["count"],
                    "cpu": d["cpu"],
                    "memory_gi": d["memory_gi"],
                })
            rt.submit_many(specs)

        for _ in range(warmup):
            tick_no[0] += 1
            churn(rt.tick())
        # Freeze the warmup survivors out of the cyclic GC's scan set
        # (workers already froze the bulk load): a gen-2 pass over a
        # million-workload heap is a multi-second stop, and at the
        # barrier ANY worker's pause stalls the whole measured tick.
        rt.gc_settle()

        times = []
        rtts = []
        worker_ticks = []
        rss_peak = 0.0
        admitted = 0
        preempted = 0
        revocations = 0
        for i in range(ticks):
            if mid_window is not None:
                mid_window(i, rt)
            tick_no[0] += 1
            t = time.perf_counter()
            stats = rt.tick()
            times.append(time.perf_counter() - t)
            admitted += stats["n"]
            preempted += len(stats["preempted"])
            revocations += stats["revocations"]
            rtts.extend(stats["rtt"])
            worker_ticks.extend(stats["tick_s"])
            # Peak RSS of the WHOLE deployment: the parent plus every
            # worker process, sampled at each one's tick end.
            rss_peak = max(rss_peak, stats["rss"] / (1024.0 ** 2))
            churn(stats)
        times_ms = np.array(times) * 1000.0
        p50 = float(np.percentile(times_ms, 50))
        p99 = float(np.percentile(times_ms, 99))
        from kueue_tpu.utils.envinfo import environment_block

        out = {
            "ticks": ticks,
            # Same machine-evidence block as run_config: EVERY BENCH
            # record carries it (the within-run-only caveat, checkable).
            "environment": environment_block(),
            "n_replicas": replicas,
            "transport": ("socket" if transport == "socket"
                          else "spawn" if spawn else "loopback"),
            "process_mode": "spawn" if spawn else "loopback",
            "fault_delay_ms": fault_delay_ms or None,
            "per_host_state": rt.per_host,
            "coordinator_failover": rt.failover_evidence,
            "barrier_stalls": rt.stall_count,
            "journal_replicated_lines": (
                rt.replicator.applied_lines
                if rt.replicator is not None else None),
            "reconcile_epoch": rt.coordinator.epoch,
            "p50_ms": round(p50, 3),
            "p99_ms": round(p99, 3),
            "mean_ms": round(float(times_ms.mean()), 3),
            "admitted": admitted,
            "preempted": preempted,
            "admissions_per_s": round(admitted / (sum(times) or 1e-9), 1),
            # Commit-protocol evidence: the in-cycle round trip each
            # replica pays at the coordinator barrier (ship candidates,
            # wait for every peer's phase A, receive verdicts) and the
            # revocations the merged replay issued inside the window.
            "reconcile_rtt_ms": {
                "p50": round(_pctl(rtts, 50) * 1000.0, 3) if rtts else None,
                "p99": round(_pctl(rtts, 99) * 1000.0, 3) if rtts else None,
                "rounds": len(rtts),
            },
            "reconcile_revocations": revocations,
            # Memory evidence: peak RSS of parent + all replica workers
            # over the measured window.
            "peak_rss_mb": round(rss_peak, 1),
            "worker_tick_ms_mean": (
                round(1000.0 * sum(worker_ticks) / len(worker_ticks), 3)
                if worker_ticks else None),
        }
        print(
            f"# [{label}] {num_cqs} CQs x {num_cohorts} cohorts, backlog "
            f"{backlog}, replicas={replicas} "
            f"({'spawn' if spawn else 'loopback'}), {ticks} ticks, "
            f"setup {t_setup:.1f}s\n"
            f"# [{label}] barrier tick: p50 {p50:.2f}ms  p99 {p99:.2f}ms  "
            f"({admitted} admitted, peak RSS {rss_peak:.0f}MB, "
            f"rtt p99 {out['reconcile_rtt_ms']['p99']}ms)",
            file=sys.stderr)
        return out
    finally:
        rt.close()


def run_one(config: str) -> None:
    if config == "shard":
        # The cohort mesh needs its devices BEFORE the backend
        # initializes; on the CPU backend that is the
        # host-platform-device-count trick (same as conftest.py and the
        # multichip dryrun).
        n_sh = int(os.environ.get("KUEUE_TPU_SHARDS", "8") or 8)
        if os.environ.get("KUEUE_BENCH_FORCE_CPU") == "1" \
                or os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
            xf = os.environ.get("XLA_FLAGS", "")
            if "host_platform_device_count" not in xf:
                os.environ["XLA_FLAGS"] = (
                    xf + " --xla_force_host_platform_device_count"
                    f"={n_sh}").strip()
    if os.environ.get("KUEUE_BENCH_FORCE_CPU") == "1":
        # The parent's device probe found the accelerator unreachable
        # (e.g. a remote-attachment outage). Pin the CPU backend through
        # jax.config — the platform plugin ignores JAX_PLATFORMS alone —
        # so the run still produces a measurement instead of hanging.
        import jax

        jax.config.update("jax_platforms", "cpu")
    smoke = os.environ.get("KUEUE_BENCH_SMOKE") == "1"
    depth = max(1, int(os.environ.get("KUEUE_BENCH_DEPTH", "4")))
    if smoke:
        shape = dict(num_cqs=32, num_cohorts=8, num_flavors=4, backlog=512)
        ticks = int(os.environ.get("KUEUE_BENCH_TICKS", "12"))
    else:
        shape = dict(num_cqs=1000, num_cohorts=100, num_flavors=8,
                     backlog=50_000)
        # Enough samples that p99 reflects the steady-state heavy-tick
        # population rather than a single outlier (with 60 ticks p99 ~= max).
        ticks = int(os.environ.get("KUEUE_BENCH_TICKS", "150"))

    def emit(metric, stats, target_ms=100.0):
        p99 = stats["p99_ms"]
        line = {
            "metric": metric, "value": p99, "unit": "ms",
            "vs_baseline": round(target_ms / p99, 3) if p99 > 0 else None,
        }
        line.update(stats)
        print(json.dumps(line), flush=True)

    if config == "preempt":
        # BASELINE config #3: preemption-heavy.
        emit(METRIC_NAMES[config], run_config(
            label="preempt", ticks=max(ticks // 2, 8), usage_fill=0.9,
            depth=depth, preemption_heavy=True, **shape))
    elif config == "fair":
        # BASELINE config #4: weighted-DRF fair sharing over a KEP-79
        # hierarchical cohort tree (leaf cohorts -> mids -> root) — the
        # greenfield feature pair, at the same scale as the headline.
        # Since the fair path went tensor-resident (incremental share
        # state + packed fair sort key + vectorized fair-preemption
        # victim search) the config also measures the SAME shape with
        # fair sharing OFF — the northstar twin (run_config pins the
        # FAIR_SHARING gate per window, so each window measures its
        # true path) — and records the p99 ratio: the "fair sharing is
        # not a tax" contract (ROADMAP item 4), gated at <= 1.10
        # in-process when the window has enough samples for a stable
        # percentile.
        w_ticks = max(ticks // 2, 8)
        twin = run_config(
            label="fair_twin", ticks=w_ticks, usage_fill=0.7,
            depth=depth, preemption_heavy=False, **shape)
        stats = run_config(
            label="fair", ticks=w_ticks, usage_fill=0.7,
            depth=depth, preemption_heavy=False, fair_hierarchy=True,
            **shape)
        ratio = (stats["p99_ms"] / twin["p99_ms"]
                 if twin["p99_ms"] else None)
        stats["northstar_twin"] = {"p50_ms": twin["p50_ms"],
                                   "p99_ms": twin["p99_ms"]}
        if ratio is not None and ratio > 1.10:
            # A/B/A re-baseline: this class of container drifts (the
            # r06 BENCH note) — a load spike landing after the first
            # twin window inflates every fair phase uniformly and fakes
            # a regression. Re-measure the twin AFTER the fair window:
            # if it is slow too, the box moved, not the fair path (use
            # the slower baseline); a real fair regression keeps both
            # twins fast and the ratio high.
            twin2 = run_config(
                label="fair_twin_aba", ticks=w_ticks, usage_fill=0.7,
                depth=depth, preemption_heavy=False, **shape)
            stats["northstar_twin_aba"] = {"p50_ms": twin2["p50_ms"],
                                           "p99_ms": twin2["p99_ms"]}
            base = max(twin["p99_ms"], twin2["p99_ms"])
            ratio = stats["p99_ms"] / base if base else None
        stats["fair_vs_northstar_p99_ratio"] = (
            round(ratio, 3) if ratio is not None else None)
        # The HARD gate arms at >= 50 measured ticks per window — the
        # tracer-overhead gate's sample-count discipline: below that,
        # "p99" is literally the single slowest tick and one OS
        # contention burst (this box sustains multi-second 5x bursts,
        # see the r06 note) flakes CI. The ratio is recorded either
        # way; CI can arm the gate with KUEUE_BENCH_TICKS>=100.
        if w_ticks >= 50 and ratio is not None and ratio > 1.10:
            raise RuntimeError(
                f"[fair] fair-hier p99 {stats['p99_ms']:.1f}ms is "
                f"x{ratio:.2f} the northstar twin's (budget 1.10): the "
                "device-side fair path is paying host DRF work again — "
                "check fair.bulk_miss and the share-state memoization "
                "before trusting this run.")
        emit(METRIC_NAMES[config], stats)
    elif config == "topo":
        # Topology-aware scheduling: every flavor declares a
        # block→rack→host tree and every arrival requests slice packing
        # (1/4 required, 3/4 preferred) — the batched fit stage, cycle
        # charging and the leaf ledger all run inside the measured tick.
        emit(METRIC_NAMES[config], run_config(
            label="topo", ticks=max(ticks // 2, 8), usage_fill=0.7,
            depth=depth, preemption_heavy=False, topology=True, **shape))
    elif config == "single":
        # BASELINE config #1: one BestEffortFIFO ClusterQueue, cpu+memory
        # flavors, no cohort (examples/admin/single-clusterqueue-setup.yaml
        # shape scaled to a steady arrival flux).
        emit(METRIC_NAMES[config], run_config(
            label="single", num_cqs=1, num_cohorts=0,
            num_flavors=2,
            backlog=min(2000, shape["backlog"]),
            ticks=max(ticks // 2, 8), usage_fill=0.5, depth=depth,
            preemption_heavy=False))
    elif config == "cohortlend":
        # BASELINE config #2: 10 ClusterQueues in one cohort, borrowing
        # with lendingLimit clamps (clusterqueue.go:583-629 semantics).
        emit(METRIC_NAMES[config], run_config(
            label="cohortlend", num_cqs=10, num_cohorts=1, num_flavors=4,
            backlog=min(5000, shape["backlog"]),
            ticks=max(ticks // 2, 8), usage_fill=0.7, depth=depth,
            preemption_heavy=False, lending=True))
    elif config == "steady":
        # Steady-state northstar shape with the completion flux OFF and
        # StrictFIFO queues: after warmup saturates the quotas the same
        # heads re-pop every tick with nothing changed — the
        # "nothing-changed ticks cost nothing" window. Gates: the
        # measured window must dispatch zero solves (asserted inside
        # run_config) and bench-smoke additionally requires
        # nominate_cache_hit_ratio > 0.8.
        w_ticks = max(ticks // 2, 8)
        stats = run_config(
            label="steady", ticks=w_ticks, usage_fill=1.0,
            depth=depth, preemption_heavy=False, strict_fifo=True,
            no_preemption=True, churn_enabled=False, **shape)
        # Quiescent FAIR steady state: the same churn-free window over
        # the weighted KEP-79 tree with FairSharing ON. run_config's
        # in-window assertion proves a fair steady state ALSO
        # dispatches zero solves — the share state replays on untouched
        # usage-value generations instead of defeating the nominate
        # cache (the PR-6/PR-7 machinery fair sharing used to bypass).
        fair_stats = run_config(
            label="fair_steady", ticks=w_ticks, usage_fill=1.0,
            depth=depth, preemption_heavy=False, strict_fifo=True,
            no_preemption=True, churn_enabled=False,
            fair_hierarchy=True, **shape)
        stats["fair_steady"] = {
            "p50_ms": fair_stats["p50_ms"],
            "p99_ms": fair_stats["p99_ms"],
            "solver_dispatches": fair_stats["solver_dispatches"],
            "quiescent_tick_ms": fair_stats["quiescent_tick_ms"],
            "quiescent_ticks_replayed":
                fair_stats["quiescent_ticks_replayed"],
            "fair_share_compute_ms":
                fair_stats.get("fair_share_compute_ms"),
        }
        emit(METRIC_NAMES[config], stats, target_ms=15.0)
    elif config == "shard":
        # Cohort-sharded scale axis (ROADMAP item 1): the same admission
        # mix at the northstar-ish backlog and again at 4x backlog /
        # more CQs, both on the cohort mesh — near-flat p99 across the
        # two windows is the tentpole's scaling contract. The identity
        # gate re-proves shards=N == shards=1 decisions on every run.
        n_sh = int(os.environ.get("KUEUE_TPU_SHARDS", "8") or 8)
        identity_admitted = _shard_identity_gate(n_sh)
        if smoke:
            small = dict(num_cqs=32, num_cohorts=8, num_flavors=4,
                         backlog=512)
            large = dict(num_cqs=64, num_cohorts=16, num_flavors=4,
                         backlog=2048)
        else:
            small = dict(num_cqs=1000, num_cohorts=100, num_flavors=8,
                         backlog=50_000)
            large = dict(num_cqs=2000, num_cohorts=200, num_flavors=8,
                         backlog=200_000)
        w_ticks = max(ticks // 2, 8)
        s_small = run_config(label="shard", ticks=w_ticks, usage_fill=0.7,
                             depth=depth, preemption_heavy=False,
                             shards=n_sh, **small)
        s_large = run_config(label="shard4x", ticks=w_ticks,
                             usage_fill=0.7, depth=depth,
                             preemption_heavy=False, shards=n_sh, **large)
        backlog_ratio = large["backlog"] / small["backlog"]
        p99_ratio = (s_large["p99_ms"] / s_small["p99_ms"]
                     if s_small["p99_ms"] else None)
        s_large.update({
            "n_shards": n_sh,
            "identity_gate_admitted": identity_admitted,
            "small_window": {"backlog": small["backlog"],
                             "num_cqs": small["num_cqs"],
                             "p50_ms": s_small["p50_ms"],
                             "p99_ms": s_small["p99_ms"],
                             "shard_imbalance_ratio":
                                 s_small.get("shard_imbalance_ratio"),
                             "reconcile_revocations":
                                 s_small.get("reconcile_revocations")},
            "backlog_ratio": backlog_ratio,
            "p99_scaling_ratio": (round(p99_ratio, 3)
                                  if p99_ratio is not None else None),
        })
        # Sublinear-scaling gate (full scale only: smoke shapes are too
        # small for stable percentiles): 4x backlog must cost < 4x p99.
        if not smoke and p99_ratio is not None \
                and p99_ratio >= backlog_ratio:
            raise RuntimeError(
                f"[shard] p99 scaled superlinearly with backlog: "
                f"{s_small['p99_ms']:.1f}ms -> {s_large['p99_ms']:.1f}ms "
                f"(x{p99_ratio:.2f} for x{backlog_ratio:.0f} backlog) — "
                "the cohort-sharded solve is not absorbing the scale "
                "axis it exists for.")
        emit(METRIC_NAMES[config], s_large)
    elif config == "hetero":
        # Heterogeneity-aware solve mode (ROADMAP item 2, Gavel-style):
        # a synthetic 8-flavor heterogeneous cluster (speed-class ladder
        # 1.0..4.5, per-workload speedup profiles, ClusterQueues listing
        # flavors SLOWEST FIRST — the regime where ordered first-fit
        # burns 2-3x aggregate throughput per Gavel). Three windows in
        # one process: the first-fit TWIN (same cluster, mode off), the
        # hetero window (mode on — gated to beat the twin's aggregate
        # effective throughput), and a churn-free hetero STEADY window
        # (run_config's in-window assertion proves a hetero steady
        # state dispatches zero solves).
        h_shape = dict(shape)
        h_shape["num_flavors"] = 8
        w_ticks = max(ticks // 2, 8)
        ff = run_config(
            label="hetero_firstfit", ticks=w_ticks, usage_fill=0.3,
            depth=depth, preemption_heavy=False, hetero_cluster=True,
            hetero_mode=False, **h_shape)
        stats = run_config(
            label="hetero", ticks=w_ticks, usage_fill=0.3,
            depth=depth, preemption_heavy=False, hetero_cluster=True,
            hetero_mode=True, **h_shape)
        steady = run_config(
            label="hetero_steady", ticks=w_ticks, usage_fill=1.0,
            depth=depth, preemption_heavy=False, strict_fifo=True,
            no_preemption=True, churn_enabled=False,
            hetero_cluster=True, hetero_mode=True, **h_shape)
        agg_h = stats["aggregate_effective_throughput"]
        agg_ff = ff["aggregate_effective_throughput"]
        gain = (agg_h / agg_ff) if agg_ff else None
        stats.update({
            "throughput_gain_vs_first_fit": (round(gain, 3)
                                             if gain is not None else None),
            "first_fit_twin": {
                "p50_ms": ff["p50_ms"], "p99_ms": ff["p99_ms"],
                "aggregate_effective_throughput": agg_ff,
                "flavor_utilization": ff["flavor_utilization"]},
            "hetero_steady": {
                "p50_ms": steady["p50_ms"], "p99_ms": steady["p99_ms"],
                "solver_dispatches": steady["solver_dispatches"],
                "quiescent_tick_ms": steady["quiescent_tick_ms"],
                "quiescent_ticks_replayed":
                    steady["quiescent_ticks_replayed"]},
        })
        # The headline gate: measured aggregate-effective-throughput
        # gain over the first-fit twin on the 8-flavor cluster.
        if gain is None or gain <= 1.0:
            raise RuntimeError(
                f"[hetero] no throughput gain over the first-fit twin: "
                f"aggregate {agg_h} vs {agg_ff} (gain "
                f"{gain if gain is not None else 'n/a'}) — the hetero "
                "solve mode is not steering workloads to their faster "
                "flavors.")
        if steady["solver_dispatches"]:
            raise RuntimeError(
                "[hetero] the hetero steady window dispatched solves — "
                "the score-matrix version is invalidating fingerprints "
                "spuriously.")
        emit(METRIC_NAMES[config], stats)
    elif config == "replica":
        # Multi-process replica scheduler (ROADMAP item 1, the process
        # era): N spawn-mode worker processes each owning its shard
        # groups' full vertical slice, the parent driving the tick
        # barrier + the cross-replica commit protocol. Two windows — the
        # shard config's 200k large window, then the 1M-backlog / 10k-CQ
        # window the single process cannot hold — with the decision-
        # identity gate (replicas=N == single-process admitted set) and
        # a forced cross-replica revocation drill re-proven on EVERY
        # run before anything is measured.
        if os.environ.get("KUEUE_BENCH_FORCE_CPU") == "1":
            # Spawned workers see only the environment, not this
            # process's jax.config — pin their backend the same way.
            os.environ["JAX_PLATFORMS"] = "cpu"
        n_rep = int(os.environ.get("KUEUE_TPU_REPLICAS", "4") or 4)
        identity_admitted = _replica_identity_gate(n_rep)
        drill = _replica_revocation_drill()
        if smoke:
            small = dict(num_cqs=48, num_cohorts=12, num_flavors=4,
                         backlog=768)
            large = dict(num_cqs=96, num_cohorts=24, num_flavors=4,
                         backlog=3840)
        else:
            small = dict(num_cqs=2000, num_cohorts=200, num_flavors=8,
                         backlog=200_000)
            large = dict(num_cqs=10_000, num_cohorts=1000, num_flavors=8,
                         backlog=1_000_000)
        w_ticks = max(ticks // 4, 8)
        s_small = run_replica_config(
            label="replica", replicas=n_rep, ticks=w_ticks,
            usage_fill=0.7, **small)
        s_large = run_replica_config(
            label="replica5x", replicas=n_rep, ticks=w_ticks,
            usage_fill=0.7, **large)
        backlog_ratio = large["backlog"] / small["backlog"]
        p99_ratio = (s_large["p99_ms"] / s_small["p99_ms"]
                     if s_small["p99_ms"] else None)
        s_large.update({
            "identity_gate_admitted": identity_admitted,
            "forced_revocation_drill": drill,
            "small_window": {
                "backlog": small["backlog"],
                "num_cqs": small["num_cqs"],
                "p50_ms": s_small["p50_ms"],
                "p99_ms": s_small["p99_ms"],
                "peak_rss_mb": s_small["peak_rss_mb"],
                "reconcile_rtt_ms": s_small["reconcile_rtt_ms"]},
            "backlog_ratio": backlog_ratio,
            "p99_scaling_ratio": (round(p99_ratio, 3)
                                  if p99_ratio is not None else None),
        })
        # Sublinear-scaling gate, the shard config's discipline on the
        # process axis: 5x backlog (+5x CQs) must cost < 5x p99 — the
        # whole point of one scheduler process per shard group is that
        # per-replica host tick cost scales with process count.
        if not smoke and p99_ratio is not None \
                and p99_ratio >= backlog_ratio:
            raise RuntimeError(
                f"[replica] p99 scaled superlinearly with backlog: "
                f"{s_small['p99_ms']:.1f}ms -> {s_large['p99_ms']:.1f}ms "
                f"(x{p99_ratio:.2f} for x{backlog_ratio:.0f} backlog) — "
                "the replica split is not absorbing the scale axis it "
                "exists for.")
        emit(METRIC_NAMES[config], s_large)
    elif config == "multihost":
        # Multi-host transport (ROADMAP item 1, the network era): the
        # replica deployment over the framed SOCKET protocol — separate
        # per-host state dirs, coordinator-owned journal replication,
        # seeded packet-delay injection — with every drill the subsystem
        # exists to survive re-proven in-run BEFORE the measured window:
        # the socket identity gate, the cross-replica revocation drill
        # over sockets, the kill-drill gate (coordinator kill + replica
        # SIGKILL mid-window == uninterrupted == single-process, zero
        # oversubscription), and the Aryl elastic drill (scale
        # N->N+1->N live, capacity loaned idle->loaded, post-resettle
        # steady window dispatching zero solves). The measured window
        # then runs the socket transport at scale WITH injected delay
        # and a coordinator kill mid-window. (The replica SIGKILL drill
        # lives in the store-fed kill-drill gate: the measured window's
        # worker-side synthetic load deliberately bypasses the Store,
        # so it has no journal to fail over from.)
        import tempfile

        if os.environ.get("KUEUE_BENCH_FORCE_CPU") == "1":
            os.environ["JAX_PLATFORMS"] = "cpu"
        n_rep = int(os.environ.get("KUEUE_TPU_REPLICAS", "2") or 2)
        with tempfile.TemporaryDirectory() as td:
            identity_admitted = _replica_identity_gate(
                n_rep, transport="socket",
                state_dir=os.path.join(td, "ident"))
            drill = _replica_revocation_drill(
                transport="socket", state_dir=os.path.join(td, "revoke"))
            kill_drill = _multihost_kill_drill_gate(
                os.path.join(td, "kill"))
            elastic = _multihost_elastic_drill(
                spawn=not smoke,
                n_cqs=48 if smoke else 240,
                backlog_per_cq=6 if smoke else 8)
            degraded = _multihost_degraded_drill(
                window_s=1.5 if smoke else 4.0)
            if smoke:
                shape = dict(num_cqs=48, num_cohorts=12, num_flavors=4,
                             backlog=768)
            else:
                # The acceptance shape: the 1M-backlog / 10k-CQ window
                # over real sockets with packet delay.
                shape = dict(num_cqs=10_000, num_cohorts=1000,
                             num_flavors=8, backlog=1_000_000)
            w_ticks = max(ticks // 2, 8)
            kill_at = max(w_ticks // 3, 2)

            def mid_window(i, rt):
                if i == kill_at:
                    rt.kill_coordinator()

            s = run_replica_config(
                label="multihost", replicas=n_rep, ticks=w_ticks,
                usage_fill=0.7, transport="socket",
                state_dir=os.path.join(td, "bench"),
                fault_delay_ms=2.0, mid_window=mid_window, **shape)
        s.update({
            "n_hosts": n_rep,
            "identity_gate_admitted": identity_admitted,
            "forced_revocation_drill": drill,
            "kill_drill": kill_drill,
            "elastic_drill": elastic,
            "degraded_drill": degraded,
        })
        if s.get("coordinator_failover") is None:
            raise RuntimeError(
                "[multihost] the measured window's coordinator kill "
                "never fired; do not trust this run.")
        gain = elastic.get("loan_throughput_gain")
        if not smoke and (gain is None or gain <= 1.0):
            raise RuntimeError(
                f"[multihost] capacity loaning did not raise the loaded "
                f"group's admitted throughput (gain {gain}); the Aryl "
                "loop is not delivering; do not trust this run.")
        emit(METRIC_NAMES[config], s)
    elif config == "microtick":
        # Event-driven admission: bursty arrivals between full ticks are
        # admitted by dirty-cohort micro-ticks; the headline is the
        # submit->admitted p99, gated in-run strictly below the same
        # run's full-tick p50 (plus the three linearizability-invariant
        # gates). Smoke keeps the shape tiny; the full run uses the
        # northstar shape so the comparison is against the real tick.
        if smoke:
            # Big enough that a full tick does real work (256 heads to
            # solve/sort/cycle/requeue every tick): the gate compares
            # micro p99 against a tick that earns its latency, not a
            # quiescent replay.
            mshape = dict(num_cqs=256, num_cohorts=32, num_flavors=4,
                          backlog=2048)
            mticks = int(os.environ.get("KUEUE_BENCH_TICKS", "12"))
        else:
            mshape = dict(num_cqs=1000, num_cohorts=100, num_flavors=8,
                          backlog=50_000)
            mticks = int(os.environ.get("KUEUE_BENCH_TICKS", "60"))
        stats = run_microtick_config(label="microtick", ticks=mticks,
                                     strict_gate=not smoke, **mshape)
        p99m = stats["p99_microtick_admit_ms"]
        line = {
            "metric": METRIC_NAMES[config], "value": p99m, "unit": "ms",
            # The in-run gate's headroom, as the recorded ratio: how far
            # below the full-tick p50 the micro p99 landed.
            "vs_baseline": (round(stats["p50_full_tick_ms"] / p99m, 3)
                            if p99m else None),
        }
        line.update(stats)
        print(json.dumps(line), flush=True)
    elif config == "ingest":
        # The million-user ingest plane: sustained-QPS submission window
        # over the batch lane vs the per-object lane, submit->admitted
        # micro-latency through dirty-cohort micro-ticks, and a
        # mid-window rejoin drill bootstrapping from a shipped snapshot.
        if smoke:
            ishape = dict(num_cqs=32, total_submits=6_000, batch_size=256)
        else:
            ishape = dict(num_cqs=256, total_submits=60_000,
                          batch_size=512)
        stats = run_ingest_config(label="ingest", strict_gate=not smoke,
                                  **ishape)
        p99i = stats["submit_to_admitted_p99_ms"]
        line = {
            "metric": METRIC_NAMES[config], "value": p99i, "unit": "ms",
            # Recorded ratio: how much faster the batch ingest lane
            # sustains submissions than the per-object lane it replaces.
            "vs_baseline": stats["ingest_batch_vs_per_object"],
        }
        line.update(stats)
        print(json.dumps(line), flush=True)
    else:
        # North-star headline (config #5 shape): LAST line = parsed metric.
        emit(METRIC_NAMES["northstar"], run_config(
            label="northstar", ticks=ticks, usage_fill=0.7, depth=depth,
            preemption_heavy=False, **shape))


def _probe_device(timeout_s: float = 120.0) -> bool:
    """True when the accelerator backend initializes within the budget.

    Runs in a subprocess so a hung remote attachment (device tunnel
    outage) can be killed instead of hanging the whole benchmark; the
    caller falls back to the CPU backend in that case.
    """
    import subprocess
    try:
        res = subprocess.run(
            [sys.executable, "-c",
             "import jax; assert jax.devices()"],
            timeout=timeout_s, capture_output=True)
        return res.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def main() -> None:
    config = os.environ.get("KUEUE_BENCH_CONFIG")
    if config:
        run_one(config)
        return
    # Each config runs in its own process: a long-lived scheduler serves
    # ONE cluster, and the first config's 50k-object heap would otherwise
    # fragment the allocator under the second's measurement.
    import subprocess
    env_extra = {}
    if not _probe_device():
        print("# accelerator backend unreachable; falling back to the CPU "
              "backend for this run", file=sys.stderr)
        env_extra["KUEUE_BENCH_FORCE_CPU"] = "1"
    for config in ("single", "cohortlend", "preempt", "fair", "topo",
                   "steady", "shard", "hetero", "microtick", "ingest",
                   "replica", "multihost", "northstar"):
        env = dict(os.environ, KUEUE_BENCH_CONFIG=config, **env_extra)
        # Generous ceiling: a healthy config finishes in minutes; a
        # device attachment dying MID-RUN (after the probe passed)
        # hangs forever otherwise. The replica config gets longer — its
        # 1M-backlog window generates and loads 4 worker processes'
        # slices before the first measured tick.
        budget = 3600 if config in ("replica", "multihost") else 1800
        try:
            res = subprocess.run([sys.executable, os.path.abspath(__file__)],
                                 env=env, stdout=subprocess.PIPE,
                                 timeout=budget)
        except subprocess.TimeoutExpired:
            print(f"# {config}: run hung (device lost mid-run?); "
                  "retrying on the CPU backend", file=sys.stderr)
            env["KUEUE_BENCH_FORCE_CPU"] = "1"
            env_extra["KUEUE_BENCH_FORCE_CPU"] = "1"
            try:
                res = subprocess.run(
                    [sys.executable, os.path.abspath(__file__)],
                    env=env, stdout=subprocess.PIPE, timeout=budget)
            except subprocess.TimeoutExpired:
                # Even the CPU retry hung: report the failed config and
                # keep measuring the rest instead of crashing the driver.
                print(json.dumps({
                    "metric": METRIC_NAMES[config], "value": None,
                    "unit": "ms", "vs_baseline": None,
                    "error": "run timed out on both backends"}), flush=True)
                continue
        sys.stdout.buffer.write(res.stdout)
        sys.stdout.flush()
        if res.returncode != 0:
            raise SystemExit(res.returncode)


if __name__ == "__main__":
    main()
