"""kueue_tpu: a TPU-native quota-admission framework.

A ground-up rebuild of the capabilities of Kueue (the Kubernetes-native job
queueing controller, reference snapshot ~v0.6.1): quota-based admission of
batch workloads across ResourceFlavors, ClusterQueues and cohorts, with
borrowing/lending limits, StrictFIFO/BestEffortFIFO queueing, priority
preemption, flavor fungibility and partial admission.

The design is TPU-first, not a port: a host-side control plane keeps the
reference's admission semantics (queue manager, cache, lifecycle
controllers), while the per-tick hot path -- flavor assignment and
preemption-victim search over the pending-Workload x ClusterQueue x
ResourceFlavor state -- is encoded as dense integer tensors
(`kueue_tpu.solver.schema`) and solved as one batched JAX/XLA program
(`kueue_tpu.models.flavor_fit`) that runs on every workload at once instead
of the reference's sequential per-head loop
(reference: pkg/scheduler/scheduler.go:174-288).

Package layout:
  api/         object model (ResourceFlavor, ClusterQueue, Workload, ...)
  core/        workload resource math, admitted-state cache, snapshots
  queue/       pending-state queue manager (FIFO heaps, inadmissible parking)
  solver/      dense tensor schema + sequential referee solver
  models/      batched JAX solver models (flavor-fit, preemption, fair share)
  ops/         reusable masked/segment kernels used by the models
  parallel/    device-mesh sharding of the solve
  scheduler/   the scheduling tick orchestration
  controllers/ in-memory API store + lifecycle reconcilers + jobframework
  utils/       generic helpers (keyed heap, backoff)
"""

__version__ = "0.1.0"
