"""Single-binary entry point (counterpart of reference cmd/kueue/main.go).

    python -m kueue_tpu --config controller.yaml --objects setup.yaml \
        --feature-gates FlavorFungibility=true,FairSharing=true -v 2

Wires the whole runtime the way main.go does (main.go:101-189): load the
--config Configuration file, apply --feature-gates, build the watchable
API store + Framework + StoreAdapter (core controllers), register the
SIGUSR2 state dumper, optionally join leader election, apply the --objects
manifests (reference example YAML works unchanged), then drive scheduling
ticks and print the admission summary. --serve keeps the process running
like the real controller manager, ticking at --tick-interval.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import uuid
from typing import List, Optional, Sequence

from kueue_tpu import config as config_mod
from kueue_tpu import knobs
from kueue_tpu import features
from kueue_tpu.api import serialization
from kueue_tpu.controllers.debugger import Dumper
from kueue_tpu.controllers.leaderelection import LeaderElector, LeaseStore
from kueue_tpu.controllers.runtime import Framework
from kueue_tpu.controllers.store import (
    KIND_ADMISSION_CHECK,
    KIND_CLUSTER_QUEUE,
    KIND_LOCAL_QUEUE,
    KIND_RESOURCE_FLAVOR,
    KIND_WORKLOAD,
    KIND_WORKLOAD_PRIORITY_CLASS,
    Store,
    StoreAdapter,
)
from kueue_tpu.metrics import REGISTRY

# Admin kinds apply before workloads regardless of file order, like the
# reference's informer start ordering guarantees.
_APPLY_ORDER = [
    KIND_RESOURCE_FLAVOR, KIND_WORKLOAD_PRIORITY_CLASS, KIND_ADMISSION_CHECK,
    "Cohort", KIND_CLUSTER_QUEUE, KIND_LOCAL_QUEUE, KIND_WORKLOAD, "Job",
]


def _parse_feature_gates(spec: Optional[str]) -> None:
    """--feature-gates Gate=true,Other=false (component-base format,
    main.go:106-108)."""
    if not spec:
        return
    truthy = {"true", "t", "1", "yes", "y"}
    falsy = {"false", "f", "0", "no", "n"}
    for part in spec.split(","):
        if not part.strip():
            continue
        if "=" not in part:
            raise SystemExit(f"--feature-gates: invalid entry {part!r} "
                             "(want Name=true|false)")
        name, _, value = part.partition("=")
        value = value.strip().lower()
        if value not in truthy | falsy:
            raise SystemExit(f"--feature-gates: invalid bool {value!r} "
                             f"for gate {name.strip()!r}")
        try:
            features.set_enabled(name.strip(), value in truthy)
        except KeyError:
            raise SystemExit(f"--feature-gates: unknown gate {name.strip()!r} "
                             f"(known: {', '.join(sorted(features.all_gates()))})")


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m kueue_tpu",
        description="TPU-native quota-admission controller runtime")
    parser.add_argument("--config", help="Configuration YAML file "
                        "(reference --config format)")
    parser.add_argument("--feature-gates", default="",
                        help="comma-separated Gate=bool pairs")
    parser.add_argument("--objects", action="append", default=[],
                        help="manifest YAML file(s) to apply on startup "
                        "(repeatable; reference example format)")
    parser.add_argument("-v", "--verbosity", type=int, default=0,
                        help="log verbosity (0-6, zap analog)")
    parser.add_argument("--ticks", type=int, default=None,
                        help="run exactly N scheduling ticks")
    parser.add_argument("--serve", action="store_true",
                        help="keep running, ticking at --tick-interval")
    parser.add_argument("--port", type=int, default=None,
                        help="serve the HTTP API (object store, watch, "
                        "visibility, /metrics) on this port (0 = ephemeral; "
                        "prints the bound port to stderr)")
    parser.add_argument("--host", default="127.0.0.1",
                        help="bind address for --port")
    parser.add_argument("--tick-interval", type=float, default=0.1,
                        help="seconds between ticks with --serve")
    parser.add_argument("--batch-solver", action="store_true",
                        help="solve each tick's nominations as one batched "
                        "device program (TPU path)")
    parser.add_argument("--pipeline-depth", type=int, default=None,
                        help="keep N ticks' device solves in flight "
                        "(overrides tpuSolver.pipelineDepth; default 1)")
    parser.add_argument("--replicas", type=int, default=None,
                        help="run N scheduler replica processes (one per "
                        "shard group) behind the coordinator commit "
                        "protocol; defaults to $KUEUE_TPU_REPLICAS, and "
                        "KUEUE_TPU_NO_REPLICA=1 forces single-process")
    parser.add_argument("--transport", choices=("pipe", "socket"),
                        default=None,
                        help="replica transport: pipe (single-machine "
                        "multiprocessing pipes) or socket (framed "
                        "reconcile protocol over TCP with per-host state "
                        "dirs + journal replication); defaults to the "
                        "config file's transport.mode, and "
                        "KUEUE_TPU_NO_SOCKET=1 forces pipe")
    parser.add_argument("--listen", default=None, metavar="HOST:PORT",
                        help="coordinator bind address for the socket "
                        "transport (port 0 = ephemeral; defaults to "
                        "transport.listen, 127.0.0.1:0)")
    parser.add_argument("--join", default=None, metavar="HOST:PORT",
                        help="run as a WORKER-ONLY process: dial the "
                        "remote coordinator at HOST:PORT, identify via "
                        "hello, receive the shard-group assignment + "
                        "admin-object seed over the channel, and serve "
                        "the tick barrier (journals land under this "
                        "host's --state-dir)")
    parser.add_argument("--remote-workers", action="store_true",
                        help="with --replicas N: do NOT spawn local "
                        "replicas — wait for N remote workers to "
                        "--join this coordinator's --listen address")
    parser.add_argument("--join-timeout", type=float, default=60.0,
                        help="seconds to wait for remote workers to "
                        "join (--remote-workers) or for the "
                        "assignment (--join)")
    parser.add_argument("--degraded-after", type=float, default=None,
                        metavar="SECONDS",
                        help="worker-side watchdog: after this much "
                        "coordinator silence (and a failed re-election "
                        "probe) drop to journaled degraded admission — "
                        "flat cohorts keep admitting shard-locally, "
                        "split roots park (default 5s for --join "
                        "workers, off otherwise)")
    parser.add_argument("--tls-cert", default=None, metavar="FILE",
                        help="TLS certificate: served by the "
                        "coordinator's listener (with --tls-key), "
                        "trusted as the CA pin by --join workers")
    parser.add_argument("--tls-key", default=None, metavar="FILE",
                        help="TLS private key for the coordinator "
                        "listener")
    parser.add_argument("--auth-token", default=None,
                        help="shared token carried in channel hellos; "
                        "the listener rejects (counts + logs) hellos "
                        "that do not present it")
    parser.add_argument("--node-name", default=None,
                        help="this worker's fleet identity for --join "
                        "(default: hostname-pid)")
    parser.add_argument("--leader-elect", action="store_true",
                        help="join lease-based leader election")
    parser.add_argument("--lease-file", default=None,
                        help="shared lease file for cross-process leader "
                        "election (defaults to <state-dir>/leases.json; "
                        "put it on the mount all replicas share)")
    parser.add_argument("--lease-server", default=None,
                        metavar="HOST:PORT",
                        help="lease arbitration over the channel "
                        "protocol instead of a shared file: dial the "
                        "LeaseService riding this coordinator "
                        "listener (no shared filesystem needed; "
                        "honors --tls-cert/--auth-token)")
    parser.add_argument("--state-dir", default=None,
                        help="directory for the durable state journal; the "
                        "process recovers admitted/pending workloads from "
                        "it on restart (the apiserver-externalization "
                        "analog)")
    parser.add_argument("--dump-state", action="store_true",
                        help="print the debugger state dump on exit")
    parser.add_argument("--metrics", action="store_true",
                        help="print the metrics registry on exit")
    parser.add_argument("--trace-out", default=None, metavar="FILE",
                        help="enable span tracing and write the retained "
                        "ticks as Chrome trace-event JSON to FILE on exit "
                        "(load in Perfetto / chrome://tracing; also served "
                        "live at GET /debug/traces with --port)")
    return parser


def _replica_main(args, cfg, n_replicas: int) -> int:
    """Multi-process deployment: N spawn-mode scheduler replicas (one
    vertical slice per shard group) + the coordinator commit protocol,
    fed by the partitioned watch stream off the parent's Store. The
    parent serves the HTTP object API and the MERGED Chrome trace at
    GET /debug/traces; per-workload runtime endpoints (jobs, finish)
    live in the replicas and answer 501 here."""
    from kueue_tpu.controllers.replica_runtime import (
        ReplicaRuntime,
        ReplicaStoreBridge,
    )
    from kueue_tpu.controllers.replica_runtime import transport_from_env
    from kueue_tpu.transport import parse_fault_env

    # Precedence: --transport flag > KUEUE_TPU_TRANSPORT env > config
    # (KUEUE_TPU_NO_SOCKET=1 beats all of them, inside the runtime).
    transport = args.transport or transport_from_env(cfg.transport.mode)
    listen = None
    if args.listen:
        try:
            host, _, port = args.listen.rpartition(":")
            listen = (host or "127.0.0.1", int(port))
        except (ValueError, TypeError):
            raise SystemExit(
                f"--listen: invalid address {args.listen!r} "
                "(want host:port, port 0 for ephemeral)")
    elif transport == "socket":
        listen = cfg.transport.listen_addr()
    if args.remote_workers and transport != "socket":
        transport = "socket"  # remote workers only exist on the wire
        if listen is None:
            listen = cfg.transport.listen_addr()
    rt = ReplicaRuntime(n_replicas,
                        spawn=not args.remote_workers,
                        state_dir=args.state_dir,
                        solver=args.batch_solver,
                        trace=bool(args.trace_out),
                        transport=transport, listen=listen,
                        remote=args.remote_workers,
                        join_timeout=args.join_timeout,
                        degraded_after=args.degraded_after,
                        tls_cert=args.tls_cert, tls_key=args.tls_key,
                        auth_token=args.auth_token,
                        faults=parse_fault_env(cfg.transport.faults))
    store = Store()
    ReplicaStoreBridge(store, rt)
    # SIGUSR2 in replica mode dumps the COORDINATOR's view: barrier
    # round + epoch, per-shard-group backlog depth, group ownership.
    dumper = Dumper(reconcile=rt.reconcile_info)
    dumper.listen_for_signal()

    server = None
    if args.port is not None:
        from kueue_tpu.server import APIServer

        server = APIServer(
            store, None, host=args.host, port=args.port,
            trace_export=lambda slowest: rt.export_chrome(
                slowest_only=slowest))
        server.start()
        print(f"serving HTTP API on {server.url} "
              f"({n_replicas} scheduler replicas)",
              file=sys.stderr, flush=True)

    applied = 0
    errors: List[str] = []
    manifests = []
    for path in args.objects:
        manifests.extend(serialization.load_manifests(path))
    for kind_wanted in _APPLY_ORDER:
        for kind, obj in manifests:
            if kind != kind_wanted:
                continue
            try:
                if kind == "Job":
                    raise ValueError(
                        "Job manifests are not supported in replica "
                        "mode; submit Workload objects")
                store.create(kind, obj)
                applied += 1
            except Exception as exc:  # surface, don't abort the rest
                errors.append(f"{kind} {getattr(obj, 'name', '?')}: {exc}")
    if args.verbosity >= 1:
        print(f"applied {applied} objects"
              + (f", {len(errors)} errors" if errors else ""),
              file=sys.stderr)
    for err in errors:
        print(f"apply error: {err}", file=sys.stderr)

    if args.remote_workers:
        # Fleet restart path: the joined workers may have served a
        # DEGRADED window while no coordinator existed. Now that the
        # manifests are applied (the capacity map is current), run the
        # catch-up reconcile BEFORE the first tick — it collects each
        # worker's degraded report and revokes whatever the merged
        # capacity no longer fits. A fresh fleet answers with empty
        # reports; the call is harmless.
        ev = rt.rejoin()
        if ev.get("degraded_workers"):
            print(f"rejoin reconcile: {ev['degraded_admissions']} "
                  f"degraded admissions over "
                  f"{ev['degraded_window_ticks']} ticks, "
                  f"{ev['rejoin_revocations']} revoked",
                  file=sys.stderr, flush=True)

    total_admitted = 0
    try:
        if args.serve:
            try:
                while True:
                    total_admitted += rt.tick()["n"]
                    time.sleep(args.tick_interval)
            except KeyboardInterrupt:
                pass
        elif args.ticks is not None:
            for _ in range(args.ticks):
                total_admitted += rt.tick()["n"]
        else:
            idle = 0
            for _ in range(1000):
                n = rt.tick()["n"]
                total_admitted += n
                idle = idle + 1 if n == 0 else 0
                if idle >= 2:
                    break

        dump = rt.dump()
        summary = {
            "admitted": total_admitted,
            "replicas": n_replicas,
            "clusterQueues": {
                name: {
                    "admitted": len(keys),
                    "pending": dump["pending"].get(name, 0),
                }
                for name, keys in sorted(dump["admitted"].items())
            },
        }
        print(json.dumps(summary, indent=2 if args.verbosity else None))
        if args.trace_out:
            with open(args.trace_out, "w", encoding="utf-8") as f:
                json.dump(rt.export_chrome(), f)
            print(f"wrote merged {n_replicas}-replica trace to "
                  f"{args.trace_out} (load in Perfetto / chrome://tracing)",
                  file=sys.stderr)
    finally:
        if server is not None:
            server.stop()
        rt.close()
    return 1 if errors else 0


def _parse_hostport(spec: str, flag: str) -> tuple:
    try:
        host, _, port = spec.rpartition(":")
        return (host or "127.0.0.1", int(port))
    except (ValueError, TypeError):
        raise SystemExit(f"{flag}: invalid address {spec!r} "
                         "(want host:port)")


def _join_main(args) -> int:
    """Worker-only fleet process (`--join HOST:PORT`)."""
    from kueue_tpu.controllers.replica_runtime import worker_join_main

    state_dir = args.state_dir
    if state_dir:
        os.makedirs(state_dir, exist_ok=True)
    return worker_join_main(
        _parse_hostport(args.join, "--join"),
        state_dir=state_dir,
        tls_cafile=args.tls_cert,
        auth_token=args.auth_token,
        node=args.node_name,
        join_timeout=args.join_timeout,
        degraded_after=(args.degraded_after
                        if args.degraded_after is not None else 5.0))


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    cfg = config_mod.load(args.config) if args.config else config_mod.Configuration()
    _parse_feature_gates(args.feature_gates)

    if args.join:
        return _join_main(args)

    if args.trace_out:
        from kueue_tpu.tracing import TRACER

        TRACER.configure(enabled=True)

    from kueue_tpu.controllers.replica_runtime import replicas_from_env

    n_replicas = (args.replicas if args.replicas is not None
                  else replicas_from_env())
    if knobs.flag("KUEUE_TPU_NO_REPLICA"):
        n_replicas = 0  # the kill switch beats the flag too
    if n_replicas:
        return _replica_main(args, cfg, n_replicas)

    batch_solver = None
    if args.batch_solver:
        from kueue_tpu.models.flavor_fit import BatchSolver
        batch_solver = BatchSolver()

    fw = Framework(batch_solver=batch_solver, config=cfg,
                   pipeline_depth=args.pipeline_depth)
    store = Store()
    restored = 0
    # With leader election, the journal attach (an exclusive flock) is
    # DEFERRED until this replica actually leads: replicas share ONE
    # state dir (the etcd analog) and the standby replays the leader's
    # journal at takeover, exactly like the reference rebuilding its
    # caches from the apiserver on becoming leader (cache.go:295-328).
    pending_journal = [None]
    if args.state_dir:
        from kueue_tpu.controllers.durable import Journal

        os.makedirs(args.state_dir, exist_ok=True)
        journal = Journal(os.path.join(args.state_dir, "journal.jsonl"))
        if args.leader_elect or cfg.leader_election.enable:
            pending_journal[0] = journal
        else:
            # No election: replay BEFORE the controllers attach so their
            # initial watch replay rebuilds the runtime (admitted
            # workloads keep quota, pending ones re-queue).
            restored = journal.attach(store)
    adapter = StoreAdapter(store, fw)
    if restored and args.verbosity >= 0:
        print(f"restored {restored} objects from the state journal",
              file=sys.stderr, flush=True)

    server = None
    runtime_lock = None
    if args.port is not None:
        import threading

        from kueue_tpu.controllers.visibility import VisibilityServer
        from kueue_tpu.server import APIServer

        runtime_lock = threading.RLock()
        server = APIServer(store, fw,
                           visibility=VisibilityServer(
                               fw.queues, explain=fw.scheduler.explain),
                           host=args.host, port=args.port,
                           runtime_lock=runtime_lock,
                           sync_status=adapter.sync_status)
        server.start()
        print(f"serving HTTP API on {server.url}", file=sys.stderr, flush=True)

    dumper = Dumper(fw.cache, fw.queues, events=fw.events,
                    explain=fw.scheduler.explain)
    dumper.listen_for_signal()  # SIGUSR2, like debugger.go:41-48

    elector = None
    if args.leader_elect or cfg.leader_election.enable:
        lease_path = args.lease_file or (
            os.path.join(args.state_dir, "leases.json")
            if args.state_dir else None)
        if args.lease_server:
            # Channel-protocol election: the CAS lives behind a
            # LeaseService (another coordinator's listener) — no
            # shared filesystem between the candidates.
            from kueue_tpu.transport.lease_channel import ChannelLeaseStore

            tls_ctx = None
            if args.tls_cert:
                from kueue_tpu.transport.security import client_tls_context

                tls_ctx = client_tls_context(args.tls_cert)
            lease_store = ChannelLeaseStore(
                _parse_hostport(args.lease_server, "--lease-server"),
                tls_context=tls_ctx, auth_token=args.auth_token)
        elif lease_path:
            # Cross-process election: the lease lives on a shared mount
            # (the etcd analog), so a standby replica actually defers.
            from kueue_tpu.controllers.leaderelection import FileLeaseStore
            lease_store = FileLeaseStore(lease_path)
        else:
            lease_store = LeaseStore()
        elector = LeaderElector(lease_store, identity=str(uuid.uuid4()),
                                config=cfg.leader_election)
        elector.step()

    applied = 0
    errors: List[str] = []
    manifests = []
    for path in args.objects:
        manifests.extend(serialization.load_manifests(path))
    for kind_wanted in _APPLY_ORDER:
        for kind, obj in manifests:
            if kind != kind_wanted:
                continue
            try:
                if kind == "Job":
                    fw.submit_job(obj)
                else:
                    store.create(kind, obj)
                applied += 1
            except Exception as exc:  # surface, don't abort the rest
                errors.append(f"{kind} {getattr(obj, 'name', '?')}: {exc}")
    if args.verbosity >= 1:
        print(f"applied {applied} objects"
              + (f", {len(errors)} errors" if errors else ""),
              file=sys.stderr)
    for err in errors:
        print(f"apply error: {err}", file=sys.stderr)

    total_admitted = 0

    def tick_once() -> int:
        if elector is not None:
            elector.step()
            if not elector.is_leader():
                return 0  # hot standby: reconcile nothing (leader_aware)
            if pending_journal[0] is not None:
                # Deferred journal attach: replicas share ONE state dir,
                # and the standby replays the (dead) leader's journal the
                # moment it takes the lease — the reference rebuilding its
                # caches from the apiserver on becoming leader
                # (cache.go:295-328). The journal's exclusive flock may
                # outlive a SIGKILLed leader for a moment; retry next tick
                # rather than leading without state.
                journal = pending_journal[0]
                try:
                    if runtime_lock is not None:
                        with runtime_lock:
                            replayed = journal.attach(store)
                    else:
                        replayed = journal.attach(store)
                except RuntimeError as exc:
                    print(f"journal attach deferred: {exc}",
                          file=sys.stderr, flush=True)
                    return 0
                pending_journal[0] = None
                print(f"took leadership; replayed {replayed} objects from "
                      "the shared journal", file=sys.stderr, flush=True)
        if runtime_lock is not None:
            with runtime_lock:
                return adapter.tick()
        return adapter.tick()

    if args.serve:
        # Gauge refresh rides the serve loop (the reference's CQ
        # reconciler re-reports on events), throttled so the O(workloads)
        # walk never lands on every tick — scrapes just export.
        last_gauges = 0.0
        try:
            while True:
                total_admitted += tick_once()
                # Idle-window bucket prewarm: imminent head-count bucket
                # rotations compile here, never inside the tick.
                fw.prewarm_idle()
                now = time.monotonic()
                if now - last_gauges >= 5.0:
                    last_gauges = now
                    if runtime_lock is not None:
                        with runtime_lock:
                            fw.update_metrics_gauges()
                    else:
                        fw.update_metrics_gauges()
                # Event-driven admission between ticks: instead of one
                # opaque sleep, the idle window polls the dirty-cohort
                # marks and micro-ticks arrivals the moment they land —
                # submit->admitted stops riding the tick interval.
                # Only when this process may actually schedule: the
                # kill switch is off, it HOLDS the lease (a standby
                # must not admit), and no deferred journal attach is
                # pending (a fresh leader that has not replayed the
                # dead leader's journal yet would admit against a cache
                # missing its workloads). Otherwise the window is one
                # plain sleep, exactly the pre-micro serve loop.
                micro_ok = fw.scheduler.microtick_enabled() \
                    and (elector is None or elector.is_leader()) \
                    and pending_journal[0] is None
                if not micro_ok:
                    time.sleep(args.tick_interval)
                    continue
                deadline = time.monotonic() + args.tick_interval
                while True:
                    if fw.queues.has_dirty_cohorts():
                        # Status publication rides every micro admission
                        # (the StoreAdapter.tick contract): a workload
                        # admitted between ticks must be VISIBLE between
                        # ticks, or the fast path only moved internal
                        # state.
                        if runtime_lock is not None:
                            with runtime_lock:
                                n = fw.microtick()
                                if n:
                                    adapter.sync_status()
                        else:
                            n = fw.microtick()
                            if n:
                                adapter.sync_status()
                        total_admitted += n
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    time.sleep(min(0.02, remaining))
        except KeyboardInterrupt:
            pass
    elif args.ticks is not None:
        for _ in range(args.ticks):
            total_admitted += tick_once()
    else:
        # Default: run to quiescence (the single-binary demo of SURVEY §7).
        idle = 0
        for _ in range(1000):
            n = tick_once()
            total_admitted += n
            idle = idle + 1 if n == 0 else 0
            if idle >= 2:
                break

    summary = {
        "admitted": total_admitted,
        "clusterQueues": {
            name: {
                "admitted": len(cq.workloads),
                "pending": fw.queues.pending(name),
            }
            for name, cq in sorted(fw.cache.cluster_queues.items())
        },
    }
    print(json.dumps(summary, indent=2 if args.verbosity else None))

    if server is not None:
        server.stop()
    if args.trace_out:
        from kueue_tpu.tracing import TRACER

        with open(args.trace_out, "w", encoding="utf-8") as f:
            f.write(TRACER.export_json())
        print(f"wrote trace to {args.trace_out} "
              "(load in Perfetto / chrome://tracing)", file=sys.stderr)
    if args.dump_state:
        print(dumper.dump_json(), file=sys.stderr)
    if args.metrics:
        for line in REGISTRY.export_text().splitlines():
            print(line, file=sys.stderr)
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
