"""kueuelint — codebase-specific static analysis for kueue-tpu.

Rule families (see `python -m kueue_tpu.analysis --list-rules`):

  JIT01-03  jit purity: host syncs, traced control flow, closure mutation
  RET01-02  retrace hygiene: static-arg hazards, closure captures
  LOCK01-02 lock discipline: blocking under a lock, inconsistent guarding
  API01-03  API hygiene: mutable defaults, freezable dataclasses,
            serialization roundtrip coverage

Suppress a finding on its line with `# kueuelint: disable=RULE` (several:
`disable=RULE1,RULE2`; everything: bare `disable`); suppress a whole file
with `# kueuelint: skip-file`.
"""

from kueue_tpu.analysis.core import (  # noqa: F401
    Finding, Rule, Severity, all_rules, run_analysis)
# Rule modules register themselves into the registry on import.
from kueue_tpu.analysis import api_rules, jit_rules, lock_rules  # noqa: F401
from kueue_tpu.analysis.reporters import (  # noqa: F401
    render_json, render_text)

__all__ = ["Finding", "Rule", "Severity", "all_rules", "run_analysis",
           "render_json", "render_text"]
