"""kueuelint — codebase-specific static analysis for kueue-tpu.

Rule families (see `python -m kueue_tpu.analysis --list-rules`):

  ast engine (default; pure-AST, import-free)
    JIT01-03  jit purity: host syncs, traced control flow, closure mutation
    RET01-02  retrace hygiene: static-arg hazards, closure captures
    LOCK01-02 lock discipline: blocking under a lock, inconsistent guarding
    API01-03  API hygiene: mutable defaults, freezable dataclasses,
              serialization roundtrip coverage
    OBS01     raw time.monotonic/perf_counter timing bypassing the tracer
    PERF01    quadratic full-scan idioms on hot tick paths
    THR01-02  cross-thread shared state without a consistent lock;
              unbounded blocking calls on service thread roots
    KNOB01    KUEUE_TPU_* env knobs bypassing the knob-contract registry
    W001      stale `# kueuelint: disable=RULE` suppressions

  flow engine (`--engine flow`; whole-program AST flow analysis)
    LOCK03    lock-acquisition order cycles (potential deadlocks)
    LED01     ledger charge without release on a forget/delete/error path

  det engine (`--engine det`; determinism & decision-taint dataflow over
  the decision core — the static twin of the fuzzer)
    DET01     unordered-collection iteration order reaching
              decision-bearing state (the PR 8 victim-flip bug class)
    DET02     wall-clock/randomness taint flowing into decision state
              instead of the injected TickClock (the PR 9 bug class)
    TNT01     knob decision contract: neutral-knob values reaching
              decision state; gate knobs read off their registered sites

  trace engine (`--engine trace`; kueueverify — lowers every registered
  solver kernel to a jaxpr and interprets the equations; needs jax)
    TRC01     dtype-promotion hazards (mixed-dtype writes, silent casts)
    TRC02     sentinel/interval overflow through the kernel arithmetic
    TRC03     recompile hazards: jaxpr structure must match across
              adjacent head-count buckets (one XLA compile per bucket)
    TRC04     forbidden effects (callbacks/debug prints) in jitted kernels

`--engine all` runs every engine. Suppress a finding on its line with
`# kueuelint: disable=RULE` (several: `disable=RULE1,RULE2`; everything:
bare `disable`); suppress a whole file with `# kueuelint: skip-file`.
"""

from kueue_tpu.analysis.core import (  # noqa: F401
    Finding, Rule, Severity, all_rules, run_analysis)
# Rule modules register themselves into the registry on import. The trace
# module defers its jax import to rule execution, so importing the package
# stays jax-free (the ast/flow engines never need it).
from kueue_tpu.analysis import api_rules, jit_rules, lock_rules  # noqa: F401
from kueue_tpu.analysis import flow_rules, trace_rules  # noqa: F401
from kueue_tpu.analysis import obs_rules, perf_rules  # noqa: F401
from kueue_tpu.analysis import knob_rules, thread_rules  # noqa: F401
from kueue_tpu.analysis import det_rules, taint_rules  # noqa: F401
from kueue_tpu.analysis.reporters import (  # noqa: F401
    render_json, render_text)

__all__ = ["Finding", "Rule", "Severity", "all_rules", "run_analysis",
           "render_json", "render_text"]
