"""kueuelint CLI: `python -m kueue_tpu.analysis [paths...]`.

Exit codes: 0 clean (no findings at/above --fail-on), 1 findings, 2 usage
error. Pure-AST — never imports the code under analysis, needs no jax.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path
from typing import Optional, Sequence

from kueue_tpu.analysis.core import Severity, run_analysis
from kueue_tpu.analysis.reporters import (render_json, render_rule_list,
                                          render_text)


def _default_paths() -> list:
    # Analyze the installed package when invoked bare.
    return [str(Path(__file__).resolve().parent.parent)]


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="kueuelint",
        description="Codebase-specific static analysis for kueue-tpu: "
                    "jit purity, retrace hygiene, lock discipline, API "
                    "hygiene (ast engine); lock-order/ledger-flow analysis "
                    "(flow engine); determinism & decision-taint dataflow "
                    "over the decision core (det engine); trace-level "
                    "jaxpr verification of the solver kernels — "
                    "kueueverify (trace engine).")
    parser.add_argument("paths", nargs="*",
                        help="files or directories to analyze "
                             "(default: the kueue_tpu package)")
    parser.add_argument("--engine",
                        choices=("ast", "flow", "det", "trace", "all"),
                        default="ast",
                        help="analysis engine: ast (default, import-free), "
                             "flow (lock graph + ledger flow), det "
                             "(determinism/decision-taint dataflow), trace "
                             "(jaxpr verification; imports jax), or all")
    parser.add_argument("--det-wide", action="store_true",
                        help="drop the det engine's decision-core roster "
                             "filter and analyze every given file (the "
                             "nightly wide sweep over tests/ and "
                             "examples/)")
    parser.add_argument("--format", "-f", choices=("text", "json"),
                        default="text")
    parser.add_argument("--fail-on", choices=("error", "warning"),
                        default="error",
                        help="lowest severity that makes the exit code "
                             "non-zero (default: error)")
    parser.add_argument("--select", action="append", default=None,
                        metavar="RULE", help="run only these rule ids")
    parser.add_argument("--disable", action="append", default=None,
                        metavar="RULE", help="skip these rule ids")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule registry and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        print(render_rule_list())
        return 0

    # A typo'd --select would otherwise filter the registry to nothing and
    # report a clean run — fail fast on unknown ids instead. Likewise a
    # --select naming a rule of an engine that is not active (e.g.
    # `--select TRC02` without `--engine trace`) would run nothing and
    # exit 0, reading as "clean" when the rule never executed.
    from kueue_tpu.analysis.core import all_rules
    known = {r.id for r in all_rules()}
    for opt, ids in (("--select", args.select), ("--disable", args.disable)):
        unknown = sorted(set(ids or ()) - known)
        if unknown:
            print(f"kueuelint: unknown rule id(s) for {opt}: "
                  f"{', '.join(unknown)} (see --list-rules)",
                  file=sys.stderr)
            return 2
    if args.select and args.engine != "all":
        engine_of = {r.id: r.engine for r in all_rules()}
        inactive = sorted(rid for rid in set(args.select)
                          if engine_of[rid] != args.engine)
        if inactive:
            needed = sorted({engine_of[rid] for rid in inactive})
            print(f"kueuelint: --select {', '.join(inactive)} needs "
                  f"--engine {'/'.join(needed)} (or --engine all); the "
                  f"{args.engine} engine would never run it",
                  file=sys.stderr)
            return 2
    if args.select and set(args.select) == {"W001"}:
        # W001 judges the suppressions of the rules that RAN; alone it
        # has nothing to judge and would report a misleading clean run.
        print("kueuelint: --select W001 alone runs no other rules, so no "
              "suppression can be judged stale; run without --select (or "
              "select W001 together with the rules to audit)",
              file=sys.stderr)
        return 2

    paths = args.paths or _default_paths()
    for p in paths:
        if not Path(p).exists():
            print(f"kueuelint: path does not exist: {p}", file=sys.stderr)
            return 2

    findings = run_analysis(paths, select=args.select, disable=args.disable,
                            engine=args.engine,
                            options={"det_wide": args.det_wide})
    if args.format == "json":
        print(render_json(findings, engine=args.engine))
    else:
        print(render_text(findings))

    threshold = Severity.ERROR if args.fail_on == "error" else Severity.WARNING
    gating = [f for f in findings if f.severity >= threshold]
    return 1 if gating else 0


if __name__ == "__main__":
    sys.exit(main())
