"""kueuelint CLI: `python -m kueue_tpu.analysis [paths...]`.

Exit codes: 0 clean (no findings at/above --fail-on), 1 findings, 2 usage
error. Pure-AST — never imports the code under analysis, needs no jax.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path
from typing import Optional, Sequence

from kueue_tpu.analysis.core import Severity, run_analysis
from kueue_tpu.analysis.reporters import (render_json, render_rule_list,
                                          render_text)


def _default_paths() -> list:
    # Analyze the installed package when invoked bare.
    return [str(Path(__file__).resolve().parent.parent)]


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="kueuelint",
        description="Codebase-specific static analysis for kueue-tpu: "
                    "jit purity, retrace hygiene, lock discipline, API "
                    "hygiene.")
    parser.add_argument("paths", nargs="*",
                        help="files or directories to analyze "
                             "(default: the kueue_tpu package)")
    parser.add_argument("--format", "-f", choices=("text", "json"),
                        default="text")
    parser.add_argument("--fail-on", choices=("error", "warning"),
                        default="error",
                        help="lowest severity that makes the exit code "
                             "non-zero (default: error)")
    parser.add_argument("--select", action="append", default=None,
                        metavar="RULE", help="run only these rule ids")
    parser.add_argument("--disable", action="append", default=None,
                        metavar="RULE", help="skip these rule ids")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule registry and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        print(render_rule_list())
        return 0

    # A typo'd --select would otherwise filter the registry to nothing and
    # report a clean run — fail fast on unknown ids instead.
    from kueue_tpu.analysis.core import all_rules
    known = {r.id for r in all_rules()}
    for opt, ids in (("--select", args.select), ("--disable", args.disable)):
        unknown = sorted(set(ids or ()) - known)
        if unknown:
            print(f"kueuelint: unknown rule id(s) for {opt}: "
                  f"{', '.join(unknown)} (see --list-rules)",
                  file=sys.stderr)
            return 2

    paths = args.paths or _default_paths()
    for p in paths:
        if not Path(p).exists():
            print(f"kueuelint: path does not exist: {p}", file=sys.stderr)
            return 2

    findings = run_analysis(paths, select=args.select, disable=args.disable)
    if args.format == "json":
        print(render_json(findings))
    else:
        print(render_text(findings))

    threshold = Severity.ERROR if args.fail_on == "error" else Severity.WARNING
    gating = [f for f in findings if f.severity >= threshold]
    return 1 if gating else 0


if __name__ == "__main__":
    sys.exit(main())
