"""API-hygiene rules (API01-03).

API01 — mutable default arguments anywhere in the package: the shared
list/dict/set outlives the call and aliases across callers; in a scheduler
that reuses Workload/PodSet objects across ticks this shows up as quota
leaking between unrelated workloads.

API02 — non-frozen dataclasses in `api/types.py` whose fields are all
immutable-typed: spec objects are hashed into snapshot/solver memo keys and
shared across threads, so anything that *can* be frozen should be. Status
objects that are mutated in place (Workload, Condition, ...) either carry
mutable-typed fields (excluded automatically) or an explicit
`# kueuelint: disable=API02` stating why.

API03 — serialization roundtrip coverage: for every dataclass from a
`types.py` that the sibling `serialization.py` constructs, each field must
appear somewhere in the serialization module (constructor kwarg, attribute
read on the encode side, or a snake/camelCase key string). A field that
never appears is silently dropped by encode/decode and corrupts MultiKueue
mirrors and the durable store on the next roundtrip.
"""

from __future__ import annotations

import ast
from pathlib import PurePosixPath
from typing import Dict, List, Optional, Set

from kueue_tpu.analysis.core import (
    AnalysisContext, Rule, Severity, SourceFile, dotted_name, finding,
    register)

_IMMUTABLE_NAMES = {"str", "int", "float", "bool", "bytes", "complex",
                    "None", "Tuple", "tuple", "FrozenSet", "frozenset",
                    "Optional", "Union", "Literal", "IntEnum", "Enum"}


def _mutable_default(node: ast.AST) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set)):
        return True
    if isinstance(node, ast.Call):
        return dotted_name(node.func) in ("list", "dict", "set", "bytearray")
    return False


def _check_api01(f: SourceFile, ctx: AnalysisContext):
    for node in ast.walk(f.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
            continue
        args = node.args
        for default in list(args.defaults) + list(args.kw_defaults):
            if default is not None and _mutable_default(default):
                yield finding(
                    API01, f, default,
                    "mutable default argument is shared across every call; "
                    "use None (or a dataclass field default_factory) and "
                    "construct inside the function")


# ---------------------------------------------------------------------------
# API02 — freezable dataclasses left mutable
# ---------------------------------------------------------------------------


def _dataclass_decorator(cls: ast.ClassDef) -> Optional[ast.AST]:
    for dec in cls.decorator_list:
        name = dotted_name(dec)
        if name in ("dataclass", "dataclasses.dataclass"):
            return dec
        if isinstance(dec, ast.Call) and dotted_name(dec.func) in (
                "dataclass", "dataclasses.dataclass"):
            return dec
    return None


def _is_frozen(dec: ast.AST) -> bool:
    if isinstance(dec, ast.Call):
        for kw in dec.keywords:
            if kw.arg == "frozen" and isinstance(kw.value, ast.Constant):
                return bool(kw.value.value)
    return False


def _anno_immutable(anno: ast.AST, frozen_classes: Set[str]) -> bool:
    if isinstance(anno, ast.Constant):
        # string annotation — only trust obvious scalar names
        return str(anno.value) in _IMMUTABLE_NAMES | frozen_classes
    name = dotted_name(anno)
    if name is not None:
        leaf = name.rsplit(".", 1)[-1]
        return leaf in _IMMUTABLE_NAMES or leaf in frozen_classes
    if isinstance(anno, ast.Subscript):
        head = dotted_name(anno.value)
        leaf = head.rsplit(".", 1)[-1] if head else ""
        if leaf not in ("Tuple", "tuple", "Optional", "Union", "Literal",
                        "FrozenSet", "frozenset"):
            return False
        inner = anno.slice
        elts = inner.elts if isinstance(inner, ast.Tuple) else [inner]
        return all(
            (isinstance(e, ast.Constant) and e.value in (None, Ellipsis))
            or _anno_immutable(e, frozen_classes)
            for e in elts)
    return False


def _check_api02(f: SourceFile, ctx: AnalysisContext):
    frozen_classes: Set[str] = set()
    for node in ast.walk(f.tree):
        if isinstance(node, ast.ClassDef):
            dec = _dataclass_decorator(node)
            if dec is not None and _is_frozen(dec):
                frozen_classes.add(node.name)
    for node in ast.walk(f.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        dec = _dataclass_decorator(node)
        if dec is None or _is_frozen(dec):
            continue
        annos = [s.annotation for s in node.body
                 if isinstance(s, ast.AnnAssign) and s.annotation is not None]
        if not annos:
            continue
        if all(_anno_immutable(a, frozen_classes) for a in annos):
            yield finding(
                API02, f, node,
                f"dataclass `{node.name}` has only immutable-typed fields "
                "but is not frozen=True; spec objects are shared across "
                "threads and used in memo keys — freeze it (or suppress "
                "with a comment stating why in-place mutation is needed)")


# ---------------------------------------------------------------------------
# API03 — serialization roundtrip coverage
# ---------------------------------------------------------------------------


def _snake_to_camel(name: str) -> str:
    head, *rest = name.split("_")
    return head + "".join(w.capitalize() for w in rest)


def _dataclass_fields(cls: ast.ClassDef) -> List[str]:
    out = []
    for stmt in cls.body:
        if isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target,
                                                          ast.Name):
            name = stmt.target.id
            if name.startswith("_"):
                continue
            anno = dotted_name(stmt.annotation)
            if anno and anno.rsplit(".", 1)[-1] == "ClassVar":
                continue
            if isinstance(stmt.annotation, ast.Subscript):
                head = dotted_name(stmt.annotation.value)
                if head and head.rsplit(".", 1)[-1] == "ClassVar":
                    continue
            out.append(name)
    return out


def _check_api03(ctx: AnalysisContext):
    # Pair every serialization.py with a types.py in the same directory.
    for ser in ctx.files:
        p = PurePosixPath(ser.display_path)
        if "serialization" not in p.name or ser.tree is None:
            continue
        types_path = str(p.parent / "types.py")
        types_file = ctx.by_path.get(types_path)
        if types_file is None or types_file.tree is None:
            continue

        classes: Dict[str, ast.ClassDef] = {}
        for node in ast.walk(types_file.tree):
            if isinstance(node, ast.ClassDef) \
                    and _dataclass_decorator(node) is not None:
                classes[node.name] = node

        # Evidence of a field being carried through serialization:
        kwargs_by_class: Dict[str, Set[str]] = {}
        pos_arity: Dict[str, int] = {}
        strings: Set[str] = set()
        attr_reads: Set[str] = set()
        constructed: List[ast.Call] = []
        for node in ast.walk(ser.tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                strings.add(node.value)
            elif isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, ast.Load):
                attr_reads.add(node.attr)
            elif isinstance(node, ast.Call):
                name = dotted_name(node.func)
                leaf = name.rsplit(".", 1)[-1] if name else None
                if leaf in classes:
                    constructed.append(node)
                    kws = kwargs_by_class.setdefault(leaf, set())
                    for kw in node.keywords:
                        if kw.arg is not None:
                            kws.add(kw.arg)
                        else:
                            # **kwargs splat: assume full coverage
                            kws.add("*")
                    pos_arity[leaf] = max(pos_arity.get(leaf, 0),
                                          len(node.args))

        for cls_name, kws in sorted(kwargs_by_class.items()):
            if "*" in kws:
                continue
            cls = classes[cls_name]
            fields = _dataclass_fields(cls)
            for i, field_name in enumerate(fields):
                if field_name in kws:
                    continue
                if i < pos_arity.get(cls_name, 0):
                    continue
                if field_name in strings \
                        or _snake_to_camel(field_name) in strings:
                    continue
                if field_name in attr_reads:
                    continue
                yield finding(
                    API03, ser, _first_ctor(constructed, cls_name),
                    f"field `{cls_name}.{field_name}` never appears in "
                    f"{p.name} (no kwarg, key string, or attribute read) — "
                    "an encode/decode roundtrip silently drops it")


def _first_ctor(calls: List[ast.Call], cls_name: str) -> ast.AST:
    for c in calls:
        name = dotted_name(c.func)
        if name and name.rsplit(".", 1)[-1] == cls_name:
            return c
    return calls[0]


API01 = register(Rule(
    id="API01", severity=Severity.ERROR,
    summary="mutable default argument",
    check=_check_api01))

API02 = register(Rule(
    id="API02", severity=Severity.ERROR,
    summary="freezable dataclass in api/types.py left non-frozen",
    check=_check_api02,
    path_fragments=("api/types.py", "fixtures/lint/")))

API03 = register(Rule(
    id="API03", severity=Severity.ERROR,
    summary="dataclass field missing from the serialization roundtrip",
    check=_check_api03, project=True))
