"""kueuelint core: findings, rule registry, suppressions, analysis driver.

The analyzer is pure-AST (never imports the code under analysis), so it runs
in milliseconds, without jax, and is safe on broken trees: a file that does
not parse is itself reported as a finding (PARSE) instead of aborting.

Rule IDs are stable strings (JIT01, LOCK01, ...) so that per-line
suppressions (`# kueuelint: disable=RULE[,RULE...]`) and CI configs never
break when messages are reworded.
"""

from __future__ import annotations

import ast
import dataclasses
import enum
import io
import re
import tokenize
from pathlib import Path
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple


class Severity(enum.IntEnum):
    """Ordered so that max() picks the gating severity."""

    WARNING = 1
    ERROR = 2

    @property
    def label(self) -> str:
        return self.name.lower()


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    severity: Severity
    path: str
    line: int
    col: int
    message: str

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "severity": self.severity.label,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
        }

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule} [{self.severity.label}] {self.message}")


# `# kueuelint: disable=JIT01` or `# kueuelint: disable=JIT01,LOCK02` on the
# finding line suppresses those rules there; bare `disable` suppresses every
# rule on the line. `# kueuelint: skip-file` anywhere suppresses the file.
_DISABLE_RE = re.compile(
    r"#\s*kueuelint:\s*disable(?:=(?P<rules>[A-Za-z0-9_,\s]+))?")
_SKIP_FILE_RE = re.compile(r"#\s*kueuelint:\s*skip-file")

_ALL = "__all__"


class SourceFile:
    """One parsed module plus its suppression map."""

    def __init__(self, path: Path, text: str, display_path: str):
        self.path = path
        self.display_path = display_path
        self.text = text
        self.lines = text.splitlines()
        self.tree: Optional[ast.Module] = None
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(text, filename=str(path))
        except SyntaxError as exc:
            self.parse_error = exc
        # Directives are honored only in REAL `#` comments (tokenized), so
        # a docstring that merely MENTIONS the syntax neither suppresses
        # anything nor reads as a stale suppression to W001. Unparseable
        # files fall back to the line scan — a suppression must keep
        # working while its file is mid-edit.
        comments = self._comment_lines()
        self.skip_file = any(
            _SKIP_FILE_RE.search(c) for c in comments.values())
        # line number -> set of suppressed rule ids (or _ALL)
        self.suppressions: Dict[int, set] = {}
        for i, line in comments.items():
            m = _DISABLE_RE.search(line)
            if not m:
                continue
            rules = m.group("rules")
            if rules is None:
                self.suppressions.setdefault(i, set()).add(_ALL)
            else:
                for r in rules.replace(",", " ").split():
                    self.suppressions.setdefault(i, set()).add(r.strip())

    def _comment_lines(self) -> Dict[int, str]:
        """line number -> comment text, for real COMMENT tokens only."""
        try:
            return {
                tok.start[0]: tok.string
                for tok in tokenize.generate_tokens(
                    io.StringIO(self.text).readline)
                if tok.type == tokenize.COMMENT}
        except (tokenize.TokenError, IndentationError, SyntaxError):
            return {i: line for i, line in enumerate(self.lines, start=1)
                    if "#" in line}

    def suppressed(self, rule: str, line: int) -> bool:
        if self.skip_file:
            return True
        s = self.suppressions.get(line)
        return bool(s) and (_ALL in s or rule in s)


class AnalysisContext:
    """Everything a rule may look at: the full set of analyzed files,
    plus engine options (e.g. det_wide=True drops the determinism
    engine's decision-core roster filter for nightly wide runs)."""

    def __init__(self, files: Sequence[SourceFile],
                 options: Optional[Dict[str, object]] = None):
        self.files = list(files)
        self.by_path: Dict[str, SourceFile] = {
            f.display_path: f for f in files}
        self.options: Dict[str, object] = dict(options or {})


@dataclasses.dataclass(frozen=True)
class Rule:
    """A registered check.

    `path_fragments` limits where the rule applies: a file participates when
    any fragment occurs in its posix path. None means every file. Project
    rules (`project=True`) receive the whole context once instead of being
    called per file.

    `engine` selects which analysis engine runs the rule:

      ast    pure-AST, no imports, milliseconds (the default engine);
      flow   whole-program flow analysis over the ASTs (lock-order graph,
             ledger charge/release pairing) — still import-free;
      trace  jaxpr-level verification (kueueverify): lowers the registered
             solver kernels with jax.make_jaxpr and interprets the
             equations — needs jax, runs in seconds.
    """

    id: str
    severity: Severity
    summary: str
    check: Callable[..., Iterable[Finding]]
    path_fragments: Optional[Tuple[str, ...]] = None
    project: bool = False
    engine: str = "ast"

    def applies_to(self, f: SourceFile) -> bool:
        if self.path_fragments is None:
            return True
        posix = f.path.as_posix()
        return any(frag in posix for frag in self.path_fragments)


_REGISTRY: Dict[str, Rule] = {}


def register(rule: Rule) -> Rule:
    if rule.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {rule.id}")
    _REGISTRY[rule.id] = rule
    return rule


def all_rules() -> List[Rule]:
    return sorted(_REGISTRY.values(), key=lambda r: r.id)


def collect_files(paths: Sequence[str]) -> List[SourceFile]:
    out: List[SourceFile] = []
    seen = set()
    for raw in paths:
        p = Path(raw)
        candidates = sorted(p.rglob("*.py")) if p.is_dir() else [p]
        for c in candidates:
            key = c.resolve()
            if key in seen:
                continue
            seen.add(key)
            try:
                text = c.read_text(encoding="utf-8")
            except (OSError, UnicodeDecodeError):
                continue
            out.append(SourceFile(c, text, c.as_posix()))
    return out


ENGINES = ("ast", "flow", "det", "trace")


def run_analysis(paths: Sequence[str],
                 select: Optional[Sequence[str]] = None,
                 disable: Optional[Sequence[str]] = None,
                 engine: str = "ast",
                 options: Optional[Dict[str, object]] = None
                 ) -> List[Finding]:
    """Analyze `paths` (files or directories) and return active findings,
    with per-line suppressions already applied.

    `engine` selects the analysis engine(s): "ast" (default), "flow",
    "det", "trace", or "all". The trace engine imports jax; the others
    never import anything. `options` are engine options exposed to the
    rules on the context (e.g. {"det_wide": True})."""
    if engine != "all" and engine not in ENGINES:
        raise ValueError(f"unknown engine {engine!r} "
                         f"(choose from {ENGINES + ('all',)})")
    engines = set(ENGINES) if engine == "all" else {engine}
    # Rule modules register on import; pulled in here to avoid import cycles.
    from kueue_tpu.analysis import api_rules, jit_rules, lock_rules  # noqa: F401
    from kueue_tpu.analysis import flow_rules, obs_rules, trace_rules  # noqa: F401
    from kueue_tpu.analysis import knob_rules, perf_rules, thread_rules  # noqa: F401
    from kueue_tpu.analysis import det_rules, taint_rules  # noqa: F401

    files = collect_files(paths)
    ctx = AnalysisContext(files, options)
    rules = [r for r in all_rules() if r.engine in engines]
    if select:
        wanted = set(select)
        rules = [r for r in rules if r.id in wanted]
    if disable:
        dropped = set(disable)
        rules = [r for r in rules if r.id not in dropped]

    findings: List[Finding] = []
    for f in files:
        if f.parse_error is not None:
            findings.append(Finding(
                rule="PARSE", severity=Severity.ERROR,
                path=f.display_path,
                line=f.parse_error.lineno or 1,
                col=f.parse_error.offset or 0,
                message=f"syntax error: {f.parse_error.msg}"))
    for rule in rules:
        if rule.id == W001_ID:
            continue  # runs last, over the raw findings (below)
        if rule.project:
            findings.extend(rule.check(ctx))
            continue
        for f in files:
            if f.tree is None or not rule.applies_to(f):
                continue
            findings.extend(rule.check(f, ctx))

    if any(r.id == W001_ID for r in rules):
        findings.extend(_stale_suppressions(ctx, rules, findings))

    active = []
    # Findings are frozen (hashable): identical findings reported through
    # several rules (e.g. a kernel-lowering failure surfaced by every
    # trace rule so --select cannot drop it) collapse to one.
    seen = set()
    for fin in findings:
        if fin in seen:
            continue
        seen.add(fin)
        src = ctx.by_path.get(fin.path)
        if src is not None and src.suppressed(fin.rule, fin.line):
            continue
        active.append(fin)
    active.sort(key=lambda x: (x.path, x.line, x.col, x.rule))
    return active


# ---------------------------------------------------------------------------
# W001 — stale suppressions
# ---------------------------------------------------------------------------

W001_ID = "W001"


def _stale_suppressions(ctx: AnalysisContext, rules: Sequence[Rule],
                        raw: Sequence[Finding]) -> List[Finding]:
    """A `# kueuelint: disable=RULE` comment whose rule did not fire on
    that line is dead weight — it either outlived the code it excused or
    names the wrong line, and both silently mask a future regression.

    Only rules that actually RAN are considered (a TRC suppression is not
    stale in an ast-only run), bare `disable` / `skip-file` are exempt
    (they make no per-rule claim), and W001 never judges itself."""
    ran = {r.id for r in rules}
    fired = {(f.path, f.line, f.rule) for f in raw}
    out: List[Finding] = []
    for f in ctx.files:
        # A file that failed to parse ran no rules at all, so none of its
        # suppressions had a chance to fire — they are not stale (the
        # suppression must keep working while the file is mid-edit).
        if f.skip_file or f.parse_error is not None:
            continue
        for line, ruleset in sorted(f.suppressions.items()):
            for rid in sorted(r for r in ruleset if r is not _ALL):
                if rid == W001_ID or rid not in ran:
                    continue
                if (f.display_path, line, rid) not in fired:
                    out.append(Finding(
                        rule=W001_ID, severity=Severity.WARNING,
                        path=f.display_path, line=line, col=0,
                        message=f"stale suppression: {rid} no longer fires "
                                "on this line — remove the disable comment "
                                "(or move it to the line that needs it)"))
    return out


register(Rule(
    id=W001_ID, severity=Severity.WARNING,
    summary="stale suppression: the named rule no longer fires on the line",
    check=lambda ctx: (), project=True))


# ---------------------------------------------------------------------------
# Shared AST helpers used by several rule modules
# ---------------------------------------------------------------------------


def dotted_name(node: ast.AST) -> Optional[str]:
    """'jax.jit' for Attribute/Name chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def finding(rule: Rule, f: SourceFile, node: ast.AST, message: str,
            severity: Optional[Severity] = None) -> Finding:
    return Finding(
        rule=rule.id,
        severity=rule.severity if severity is None else severity,
        path=f.display_path,
        line=getattr(node, "lineno", 1),
        col=getattr(node, "col_offset", 0),
        message=message)
