"""Determinism engine (DET01, DET02): the static twin of the fuzzer.

Every load-bearing contract in this repo — the fuzz lattice's identity
oracles, HA journal replay, the shards=N==1 and replicas=N==1 gates, the
twin byte cross-check — reduces to ONE property: the decision trail is a
pure deterministic function of (store events, TickClock, declared
knobs). Both worst bugs so far violated it silently, and each cost hours
of fuzz campaign + shrinking to find:

  * PR 8: `Cohort.members` is a set of identity-hashed objects; the
    preemption walk iterated it raw, so victim selection flipped
    run-to-run (fixed by the name-sorted `sorted_members()` memo);
  * PR 9: wall-clock Condition stamps made A/B tiebreaks
    nondeterministic (fixed by stamping from the injected TickClock).

These rules make the contract itself statically checkable, so the next
bug of either class dies in CI in seconds instead of in a nightly
1000-seed campaign:

DET01 (error) — iteration over an unordered collection whose order can
reach decision-bearing state. Unordered sources: sets of non-str /
identity-hashed elements (annotation- and `add()`-site-inferred; sets
proven str-keyed are exempt — hash order of strs is still arbitrary
across processes, but every str-keyed walk in the repo feeds a
`sorted()` or a reduction, and flagging them would bury the
identity-hash class this rule exists for), `.keys()/.values()/.items()`
over object-keyed dicts, and unsorted `os.listdir`/`iterdir`/`glob`.
The order is "observed" when the source is materialized
(`list`/`tuple`, or a directory listing used raw), position-paired
(`enumerate`/`zip`), first-element-picked (`next(iter(..))`),
list-comprehended, or driven through a loop whose body is
order-sensitive — appends/extends/yields, breaks or returns
(first-match selection), directly or through a call into an analyzed
function that does (bounded two-level resolution via the flow engine's
program model). Sanitizers are recognized: `sorted(...)`, reductions
(`sum`/`min`/`max`/`len`/`any`/`all`), set/frozenset rebuilds,
membership tests, and loops whose bodies are commutative (set adds,
keyed stores, numeric accumulation).

DET02 (error) — wall-clock / randomness taint flowing into decision
state instead of the injected TickClock. Sources: `time.time` /
`monotonic` / `perf_counter` (+`_ns`), `datetime.now/utcnow/today`,
unseeded module-level `random.*`, `os.urandom`, `uuid.uuid1/uuid4`.
Taint propagates through assignments, arithmetic, conditionals,
containers, attribute stores on `self`, and function returns (bounded,
two-level call context); the finding carries the full source→sink
path. Sinks are DECISION STATE: arguments into constructors of classes
defined in the analyzed program (Condition stamps, decision records)
and sort keys (`sorted`/`sort`/`min`/`max` key callables). Deadline
anchors and elapsed-time comparisons (`now - t0 > timeout`) never sink
— that is liveness machinery, deliberately wall-clock-driven, which is
exactly the flow-sensitivity OBS01's per-module blanket ban lacked
(controllers/ carried six OBS01 suppressions for clean anchors; DET02
checks the same modules with zero). Seeded `random.Random(seed)`
instances and injectable clock DEFAULTS (`clock: ... = time.time` — the
TickClock seam itself, an attribute reference, never a call) are not
sources.

Scope: the decision core (scheduler/, queue/, core/, models/, solver/,
ops/, parallel/, hetero/, topology/). DET02 additionally covers
controllers/ (liveness machinery whose wall-clock must stay OUT of
decision records) and twin/ (virtual-time by contract: the byte
cross-check vs lattice.drive() dies if wall time leaks into the
simulated trail). `tests/test_det_taint.py` keeps the roster in sync
with the package layout. The nightly wide run (`--det-wide`) drops the
roster filter and analyzes everything it is pointed at, warnings
allowed.

Both rules are pure-AST and import-free, like the flow engine.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from kueue_tpu.analysis.core import (
    AnalysisContext, Finding, Rule, Severity, SourceFile, dotted_name,
    finding, register)
from kueue_tpu.analysis.flow_rules import _Program

# ---------------------------------------------------------------------------
# The decision-core roster. tests/test_det_taint.py asserts every
# top-level entry of the package appears in exactly one roster, so a new
# subsystem cannot silently ship outside the determinism contract.
# ---------------------------------------------------------------------------

# Decision core: modules whose state IS the decision trail.
DECISION_CORE = ("scheduler", "queue", "core", "models", "solver",
                 "ops", "parallel", "hetero", "topology")

# DET02-only extension: wall-clock is legitimate here (liveness
# deadlines; bench wall timing) but must never flow into decision
# records or sort keys.
CLOCK_SENSITIVE = ("controllers", "twin")

# Everything else at the package top level, explicitly: glue, I/O,
# tooling, and surfaces whose determinism is checked dynamically
# (transport framing, server). The roster meta-test fails when a new
# top-level module appears in none of the three tuples.
NON_DECISION = ("analysis", "api", "fuzz", "jobs", "native", "server",
                "tracing", "transport", "utils", "webhooks",
                "__init__", "__main__", "config", "events", "features",
                "importer", "knobs", "metrics")

_DET01_PATHS = tuple(f"{d}/" for d in DECISION_CORE) + ("fixtures/lint/",)
_DET02_PATHS = _DET01_PATHS + tuple(f"{d}/" for d in CLOCK_SENSITIVE)


def _in_scope(f: SourceFile, fragments: Tuple[str, ...],
              ctx: AnalysisContext) -> bool:
    if f.tree is None:
        return False
    if getattr(ctx, "options", {}).get("det_wide"):
        return True
    posix = f.path.as_posix()
    return any(p in posix for p in fragments)


# ---------------------------------------------------------------------------
# Shared: per-function parent map and small AST predicates
# ---------------------------------------------------------------------------


def _parents(root: ast.AST) -> Dict[int, ast.AST]:
    out: Dict[int, ast.AST] = {}
    for node in ast.walk(root):
        for child in ast.iter_child_nodes(node):
            out[id(child)] = node
    return out


def _functions(tree: ast.Module):
    """(class name or None, function node) for every top-level def and
    method in the module."""
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    yield node.name, item
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield None, node


def _self_name(fn: ast.AST, cls: Optional[str]) -> Optional[str]:
    if cls and getattr(fn, "args", None) and fn.args.args:
        return fn.args.args[0].arg
    return None


class _CallerLike:
    """Just enough _Func surface for _Program.resolve_call."""

    def __init__(self, cls: Optional[str], self_name: Optional[str],
                 src: Optional[SourceFile]):
        self.cls = cls
        self.self_name = self_name
        self.src = src


# ---------------------------------------------------------------------------
# DET01 — unordered iteration reaching decision-bearing state
# ---------------------------------------------------------------------------

# Element kinds for set-typed state: 'str' is exempt (name-keyed walks),
# 'obj' fires, 'unknown' stays quiet (precision over recall — the
# annotation or an add()-site names the element type wherever it
# matters; Cohort.members is `Set["CachedClusterQueue"]`).
_STR_TYPES = {"str", "bytes", "int", "float", "bool", "Tuple", "tuple"}

_SANITIZERS = {"sorted", "sum", "min", "max", "len", "any", "all",
               "set", "frozenset", "Counter", "sorted_members",
               "isdisjoint", "issubset", "issuperset", "update",
               "intersection", "union", "difference"}

_ORDER_SENSITIVE_METHODS = {"append", "extend", "insert", "appendleft"}


def _elem_kind_of_annotation(node: ast.AST) -> str:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        try:
            node = ast.parse(node.value, mode="eval").body
        except SyntaxError:
            return "unknown"
    name = dotted_name(node)
    if name is None:
        return "unknown"
    leaf = name.rsplit(".", 1)[-1]
    return "str" if leaf in _STR_TYPES else "obj"


def _unwrap_annotation(node: ast.AST) -> Optional[ast.AST]:
    """Strip string quoting and Optional/Final/ClassVar/Annotated."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        try:
            node = ast.parse(node.value, mode="eval").body
        except SyntaxError:
            return None
    if isinstance(node, ast.Subscript):
        outer = (dotted_name(node.value) or "").rsplit(".", 1)[-1]
        if outer in ("Optional", "Final", "ClassVar", "Annotated"):
            inner = node.slice
            if isinstance(inner, ast.Tuple) and inner.elts:
                inner = inner.elts[0]
            return _unwrap_annotation(inner)
    return node


def _ann_set_elem(node: ast.AST) -> Optional[str]:
    """'str' / 'obj' / 'unknown' when the annotation is a set type."""
    node = _unwrap_annotation(node)
    if node is None:
        return None
    if isinstance(node, ast.Subscript):
        leaf = (dotted_name(node.value) or "").rsplit(".", 1)[-1]
        if leaf in ("Set", "FrozenSet", "MutableSet", "AbstractSet",
                    "set", "frozenset"):
            elem = node.slice
            if isinstance(elem, ast.Tuple) and elem.elts:
                elem = elem.elts[0]
            return _elem_kind_of_annotation(elem)
        return None
    leaf = (dotted_name(node) or "").rsplit(".", 1)[-1]
    if leaf in ("set", "frozenset", "Set", "FrozenSet"):
        return "unknown"  # bare `x: set` — element type unstated
    return None


def _ann_dict_key(node: ast.AST) -> Optional[str]:
    """'str' / 'obj' key kind when the annotation is a Dict type."""
    node = _unwrap_annotation(node)
    if isinstance(node, ast.Subscript):
        leaf = (dotted_name(node.value) or "").rsplit(".", 1)[-1]
        if leaf in ("Dict", "MutableMapping", "Mapping", "dict",
                    "DefaultDict", "OrderedDict"):
            key = node.slice
            if isinstance(key, ast.Tuple) and key.elts:
                key = key.elts[0]
            return _elem_kind_of_annotation(key)
    return None


def _str_ish(node: ast.AST) -> bool:
    """The added element is string-shaped: a literal, an f-string, or a
    `.name`/`.key`-style attribute read."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.JoinedStr):
        return True
    if isinstance(node, ast.Attribute) and node.attr in (
            "name", "key", "uid", "id"):
        return True
    if isinstance(node, ast.Call):
        name = dotted_name(node.func) or ""
        if name.rsplit(".", 1)[-1] in ("str", "repr", "format"):
            return True
    return False


class _SetIndex:
    """Which class attributes are unordered sets (and of what), per file."""

    def __init__(self, f: SourceFile):
        # (class name, attr) -> elem kind; dict keys indexed under
        # (class name, attr + ".__dictkey__")
        self.attr_elems: Dict[Tuple[str, str], str] = {}
        for cls in ast.walk(f.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            for node in ast.walk(cls):
                if isinstance(node, ast.AnnAssign):
                    target, attr = node.target, None
                    if isinstance(target, ast.Name):
                        attr = target.id
                    elif isinstance(target, ast.Attribute) \
                            and isinstance(target.value, ast.Name) \
                            and target.value.id in ("self", "cls"):
                        attr = target.attr
                    if attr is None:
                        continue
                    kind = _ann_set_elem(node.annotation)
                    if kind is not None:
                        self.attr_elems[(cls.name, attr)] = kind
                        continue
                    dk = _ann_dict_key(node.annotation)
                    if dk is not None:
                        self.attr_elems[
                            (cls.name, f"{attr}.__dictkey__")] = dk
                elif isinstance(node, ast.Assign):
                    if not (isinstance(node.value, ast.Call)
                            and (dotted_name(node.value.func) or "")
                            .rsplit(".", 1)[-1] in ("set", "frozenset")
                            and not node.value.args):
                        continue
                    for t in node.targets:
                        if isinstance(t, ast.Attribute) \
                                and isinstance(t.value, ast.Name) \
                                and t.value.id in ("self", "cls"):
                            self.attr_elems.setdefault(
                                (cls.name, t.attr), "unknown")
            # refine unknown element kinds from add() sites
            for node in ast.walk(cls):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr in ("add", "discard") \
                        and node.args \
                        and isinstance(node.func.value, ast.Attribute) \
                        and isinstance(node.func.value.value, ast.Name) \
                        and node.func.value.value.id in ("self", "cls"):
                    key = (cls.name, node.func.value.attr)
                    if self.attr_elems.get(key) == "unknown":
                        self.attr_elems[key] = (
                            "str" if _str_ish(node.args[0]) else "obj")


def _local_sets(fn: ast.AST) -> Dict[str, str]:
    """local name -> elem kind for set-typed locals (and parameters)."""
    out: Dict[str, str] = {}
    args = getattr(fn, "args", None)
    if args is not None:
        for a in list(args.args) + list(args.kwonlyargs):
            if a.annotation is not None:
                kind = _ann_set_elem(a.annotation)
                if kind is not None:
                    out[a.arg] = kind
    for node in ast.walk(fn):
        if isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name):
            kind = _ann_set_elem(node.annotation)
            if kind is not None:
                out[node.target.id] = kind
        elif isinstance(node, ast.Assign) \
                and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            v = node.value
            if isinstance(v, ast.Call) and not v.args \
                    and (dotted_name(v.func) or "").rsplit(".", 1)[-1] \
                    in ("set", "frozenset"):
                out.setdefault(name, "unknown")
            elif isinstance(v, ast.Set):
                kinds = {("str" if _str_ish(e) else "obj")
                         for e in v.elts}
                out[name] = "str" if kinds == {"str"} else "obj"
            elif isinstance(v, ast.SetComp):
                out[name] = "str" if _str_ish(v.elt) else "obj"
    # refine unknowns from add() sites
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("add", "discard") and node.args \
                and isinstance(node.func.value, ast.Name):
            name = node.func.value.id
            if out.get(name) == "unknown":
                out[name] = "str" if _str_ish(node.args[0]) else "obj"
    return out


_LISTING_LEAVES = ("listdir", "iterdir", "glob", "rglob", "scandir")


def _unordered_desc(node: ast.AST, caller: _CallerLike, sets: "_SetIndex",
                    local: Dict[str, str]
                    ) -> Optional[Tuple[str, bool]]:
    """(description, is_materialized_listing) when `node` evaluates to
    an unordered collection of non-str elements, else None."""
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name):
        if caller.self_name and node.value.id == caller.self_name \
                and caller.cls:
            if sets.attr_elems.get((caller.cls, node.attr)) == "obj":
                return f"set `self.{node.attr}`", False
        return None
    if isinstance(node, ast.Name):
        if local.get(node.id) == "obj":
            return f"set `{node.id}`", False
        return None
    if isinstance(node, ast.Set):
        if node.elts and any(not _str_ish(e) for e in node.elts):
            return "set literal", False
        return None
    if isinstance(node, ast.Call):
        name = dotted_name(node.func) or ""
        leaf = name.rsplit(".", 1)[-1]
        if leaf in ("keys", "values", "items") \
                and isinstance(node.func, ast.Attribute):
            base = node.func.value
            if isinstance(base, ast.Attribute) \
                    and isinstance(base.value, ast.Name) \
                    and caller.self_name \
                    and base.value.id == caller.self_name \
                    and caller.cls:
                if sets.attr_elems.get(
                        (caller.cls,
                         f"{base.attr}.__dictkey__")) == "obj":
                    return (f"object-keyed dict "
                            f"`self.{base.attr}.{leaf}()`", False)
            return None
        if leaf in _LISTING_LEAVES:
            return f"`{name or leaf}(...)` directory listing", True
        return None
    if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)):
        for side in (node.left, node.right):
            d = _unordered_desc(side, caller, sets, local)
            if d is not None:
                return f"set expression over {d[0]}", False
    return None


def _loop_order_sensitivity(body: Sequence[ast.AST], prog: _Program,
                            caller, depth: int = 0,
                            loop_body: bool = True) -> Optional[str]:
    """Why this body observes iteration order, or None when every
    statement is commutative. `loop_body=True` means `body` is the body
    of a loop iterating the unordered value directly, where an early
    exit (`break`/`return`) IS first-match selection; a CALLEE's body
    (`loop_body=False`, reached through the bounded two-level descent)
    runs once per element, so its own returns are harmless — only
    ordered OUTPUT (append/extend/yield) leaks the order from there."""
    for stmt in body:
        for node in ast.walk(stmt):
            if loop_body and isinstance(node, ast.Break):
                return (f"`break` (first-match selection) at line "
                        f"{node.lineno}")
            if loop_body and isinstance(node, ast.Return):
                return f"`return` inside the loop at line {node.lineno}"
            if isinstance(node, (ast.Yield, ast.YieldFrom)):
                return f"`yield` (ordered stream) at line {node.lineno}"
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute):
                if node.func.attr in _ORDER_SENSITIVE_METHODS:
                    recv = dotted_name(node.func.value) or "<expr>"
                    return (f"`{recv}.{node.func.attr}(...)` (ordered "
                            f"output) at line {node.lineno}")
                if depth < 2:
                    # bounded descent: a call into an analyzed function
                    # that appends/yields observes the order too
                    for callee in prog.resolve_call(caller, node):
                        why = _loop_order_sensitivity(
                            callee.node.body, prog, callee, depth + 1,
                            loop_body=False)
                        if why is not None:
                            return (f"call into `{callee.qualname}` "
                                    f"at line {node.lineno} -> {why}")
    return None


def _consumption(node: ast.AST, parents: Dict[int, ast.AST],
                 prog: _Program, caller: _CallerLike,
                 materialized: bool) -> Optional[str]:
    """How the unordered value's ORDER escapes, or None when the
    consumer is order-insensitive. `materialized` means the value is
    already an arbitrarily-ordered SEQUENCE (a directory listing, or a
    `list()` of a set): any consumer that is not a recognized sanitizer
    observes the order, including a plain assignment or return."""
    parent = parents.get(id(node))
    while isinstance(parent, (ast.Starred, ast.keyword)):
        node = parent
        parent = parents.get(id(node))
    if isinstance(parent, ast.Call) and node in parent.args:
        name = dotted_name(parent.func) or ""
        leaf = name.rsplit(".", 1)[-1]
        if leaf in _SANITIZERS:
            return None
        if leaf in ("list", "tuple"):
            why = _consumption(parent, parents, prog, caller,
                               materialized=True)
            if why is None:
                return None
            return (f"materialized in arbitrary order by `{leaf}(...)` "
                    f"at line {parent.lineno} -> {why}")
        if leaf in ("enumerate", "zip"):
            return (f"position-paired by `{leaf}(...)` at line "
                    f"{parent.lineno}")
        if leaf == "iter":
            return (f"`iter(...)`/`next(...)` picks an arbitrary "
                    f"element at line {parent.lineno}")
        if leaf in ("join", "writelines"):
            return f"emitted unsorted at line {parent.lineno}"
        # a call into the analyzed program: does the callee observe
        # the order of this argument?
        for callee in prog.resolve_call(caller, parent):
            try:
                idx = parent.args.index(node)
            except ValueError:
                break
            params = [a.arg for a in callee.node.args.args]
            if callee.cls is not None:
                params = params[1:]
            if idx >= len(params):
                continue
            pname = params[idx]
            why = _param_order_sensitivity(callee, pname, prog)
            if why is not None:
                return (f"passed into `{callee.qualname}({pname})` at "
                        f"line {parent.lineno} -> {why}")
        return None
    if isinstance(parent, ast.Compare) and any(
            isinstance(op, (ast.In, ast.NotIn)) for op in parent.ops):
        return None
    if isinstance(parent, (ast.For, ast.AsyncFor)) \
            and parent.iter is node:
        why = _loop_order_sensitivity(parent.body, prog, caller)
        if why is None:
            return None
        return f"loop at line {parent.lineno} is order-sensitive: {why}"
    if isinstance(parent, ast.comprehension) and parent.iter is node:
        comp = parents.get(id(parent))
        if isinstance(comp, ast.ListComp):
            return (f"list comprehension at line {comp.lineno} "
                    "materializes the arbitrary order")
        if isinstance(comp, ast.GeneratorExp):
            return _consumption(comp, parents, prog, caller,
                                materialized)
        return None  # set/dict comprehensions stay unordered
    if materialized:
        # An arbitrarily-ordered sequence escaping through assignment,
        # return, or any unrecognized consumer IS the order leak — this
        # is exactly `sm = list(self.members)`, the PR 8 revert shape.
        line = getattr(parent, "lineno", getattr(node, "lineno", 0))
        kind = type(parent).__name__ if parent is not None else "module"
        return f"arbitrary order escapes via {kind} at line {line}"
    return None


def _param_order_sensitivity(callee, pname: str,
                             prog: _Program) -> Optional[str]:
    """Does `callee` observe the iteration order of parameter `pname`?"""
    for node in ast.walk(callee.node):
        if isinstance(node, (ast.For, ast.AsyncFor)) \
                and isinstance(node.iter, ast.Name) \
                and node.iter.id == pname:
            why = _loop_order_sensitivity(node.body, prog, callee,
                                          depth=1)
            if why is not None:
                return why
        if isinstance(node, ast.Call) and node.args:
            name = (dotted_name(node.func) or "").rsplit(".", 1)[-1]
            if name in ("list", "tuple", "enumerate", "zip") and any(
                    isinstance(a, ast.Name) and a.id == pname
                    for a in node.args):
                return (f"`{name}({pname})` materializes it at line "
                        f"{node.lineno}")
    return None


def _check_det01(ctx: AnalysisContext) -> Iterable[Finding]:
    files = [f for f in ctx.files if _in_scope(f, _DET01_PATHS, ctx)]
    if not files:
        return
    prog = _Program(files)
    for f in files:
        sets = _SetIndex(f)
        for cls, fn in _functions(f.tree):
            caller = _CallerLike(cls, _self_name(fn, cls), f)
            local = _local_sets(fn)
            parents = _parents(fn)
            for node in ast.walk(fn):
                got = _unordered_desc(node, caller, sets, local)
                if got is None:
                    continue
                desc, listing = got
                why = _consumption(node, parents, prog, caller,
                                   materialized=listing)
                if why is None:
                    continue
                yield finding(
                    DET01, f, node,
                    f"iteration order of {desc} can reach "
                    f"decision-bearing state: {why} — identity-hash "
                    "order flips decisions run-to-run (the PR 8 "
                    "victim-flip bug class); sort first "
                    "(`sorted(..., key=...)` / a name-keyed walk) or "
                    "reduce order-insensitively")


# ---------------------------------------------------------------------------
# DET02 — wall-clock / randomness taint into decision state
# ---------------------------------------------------------------------------

_CLOCK_FNS = {"time.time", "time.monotonic", "time.perf_counter",
              "time.monotonic_ns", "time.perf_counter_ns",
              "time.time_ns"}
_DATETIME_LEAVES = {"now", "utcnow", "today"}
_RANDOM_FNS = {"random", "randint", "randrange", "uniform", "choice",
               "choices", "sample", "shuffle", "gauss", "betavariate",
               "expovariate", "triangular", "vonmisesvariate"}
_MISC_SOURCES = {"os.urandom", "uuid.uuid1", "uuid.uuid4"}


class _Taint:
    """A wall-clock/randomness value plus the path it travelled."""

    __slots__ = ("source", "line", "hops")

    def __init__(self, source: str, line: int,
                 hops: Optional[List[str]] = None):
        self.source = source
        self.line = line
        self.hops = hops or []

    def via(self, hop: str) -> "_Taint":
        # keep the rendered path readable: bound its length, keep the
        # most recent hops (the source itself is always retained)
        hops = self.hops + [hop]
        return _Taint(self.source, self.line, hops[-6:])

    def render(self) -> str:
        return " -> ".join(
            [f"{self.source} (line {self.line})"] + self.hops)


def _time_aliases(tree: ast.Module) -> Dict[str, str]:
    """alias -> canonical dotted prefix for the source modules."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name in ("time", "datetime", "random", "os",
                              "uuid", "secrets"):
                    out[a.asname or a.name] = a.name
        elif isinstance(node, ast.ImportFrom):
            if node.module in ("time", "datetime", "random", "os",
                               "uuid", "secrets"):
                for a in node.names:
                    out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


def _source_desc(call: ast.Call, aliases: Dict[str, str]
                 ) -> Optional[str]:
    """`time.time()` etc. rendered canonically when `call` is a
    wall-clock/randomness source, else None."""
    name = dotted_name(call.func)
    if name is None:
        return None
    parts = name.split(".")
    canon = aliases.get(parts[0])
    if canon is not None:
        parts = canon.split(".") + parts[1:]
    full = ".".join(parts)
    if full in _CLOCK_FNS or full in _MISC_SOURCES:
        return f"`{full}()`"
    if parts[0] == "secrets":
        return f"`{full}()`"
    if parts[0] == "datetime" and parts[-1] in _DATETIME_LEAVES:
        return f"`{full}()`"
    if parts[0] == "random" and len(parts) == 2 \
            and parts[1] in _RANDOM_FNS:
        # only module-level reads of the shared global PRNG fire;
        # `random.Random(seed)` instances are the sanctioned path
        return f"`{full}()`"
    return None


class _TaintPass:
    """One function's wall-clock taint environment."""

    def __init__(self, f: SourceFile, cls: Optional[str], fn: ast.AST,
                 aliases: Dict[str, str],
                 fn_summaries: Dict[str, "_Taint"],
                 attr_taint: Dict[Tuple[str, str], "_Taint"],
                 prog: _Program):
        self.f = f
        self.cls = cls
        self.fn = fn
        self.self_name = _self_name(fn, cls)
        self.caller = _CallerLike(cls, self.self_name, f)
        self.aliases = aliases
        self.fn_summaries = fn_summaries
        self.attr_taint = attr_taint
        self.prog = prog
        self.env: Dict[str, _Taint] = {}

    def taint_of(self, node: ast.AST) -> Optional["_Taint"]:
        if isinstance(node, ast.Call):
            src = _source_desc(node, self.aliases)
            if src is not None:
                return _Taint(src, node.lineno)
            # bounded interprocedural: calls into analyzed functions
            # whose returns are wall-clock values
            for callee in self.prog.resolve_call(self.caller, node):
                t = self.fn_summaries.get(callee.qualname)
                if t is not None:
                    return t.via(f"returned by `{callee.qualname}` "
                                 f"(call at line {node.lineno})")
            return None
        if isinstance(node, ast.Name):
            return self.env.get(node.id)
        if isinstance(node, ast.Attribute):
            if isinstance(node.value, ast.Name) and self.self_name \
                    and node.value.id == self.self_name and self.cls:
                t = self.attr_taint.get((self.cls, node.attr))
                if t is not None:
                    return t.via(f"read back from `self.{node.attr}` "
                                 f"at line {node.lineno}")
            return None
        if isinstance(node, ast.BinOp):
            return self.taint_of(node.left) or self.taint_of(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.taint_of(node.operand)
        if isinstance(node, ast.IfExp):
            return self.taint_of(node.body) or self.taint_of(node.orelse)
        if isinstance(node, ast.BoolOp):
            for v in node.values:
                t = self.taint_of(v)
                if t is not None:
                    return t
            return None
        if isinstance(node, (ast.List, ast.Tuple, ast.Set)):
            for e in node.elts:
                t = self.taint_of(e)
                if t is not None:
                    return t.via("carried in a container literal")
            return None
        if isinstance(node, ast.Subscript):
            return self.taint_of(node.value)
        return None

    def run_env(self) -> None:
        """Two linear passes so loop-carried assignments settle."""
        for _ in range(2):
            for node in ast.walk(self.fn):
                if isinstance(node, ast.Assign):
                    t = self.taint_of(node.value)
                    if t is None:
                        continue
                    for target in node.targets:
                        if isinstance(target, ast.Name):
                            self.env[target.id] = t.via(
                                f"assigned to `{target.id}` at line "
                                f"{node.lineno}")
                elif isinstance(node, ast.AnnAssign) \
                        and node.value is not None \
                        and isinstance(node.target, ast.Name):
                    t = self.taint_of(node.value)
                    if t is not None:
                        self.env[node.target.id] = t.via(
                            f"assigned to `{node.target.id}` at line "
                            f"{node.lineno}")
                elif isinstance(node, ast.AugAssign) \
                        and isinstance(node.target, ast.Name):
                    t = self.taint_of(node.value)
                    if t is not None:
                        self.env[node.target.id] = t.via(
                            f"accumulated into `{node.target.id}` at "
                            f"line {node.lineno}")


def _decision_ctor(call: ast.Call, prog: _Program) -> Optional[str]:
    name = dotted_name(call.func)
    if name is None:
        return None
    leaf = name.rsplit(".", 1)[-1]
    if leaf[:1].isupper() and leaf in prog.classes:
        return leaf
    return None


_SORTERS = {"sorted", "sort", "min", "max", "nsmallest", "nlargest"}


def _key_callable_taint(call: ast.Call, tp: "_TaintPass"
                        ) -> Optional["_Taint"]:
    """Tainted value referenced inside a sort-key callable."""
    name = (dotted_name(call.func) or "").rsplit(".", 1)[-1]
    if name not in _SORTERS:
        return None
    for kw in call.keywords:
        if kw.arg != "key":
            continue
        for sub in ast.walk(kw.value):
            if isinstance(sub, (ast.Name, ast.Call, ast.Attribute)):
                t = tp.taint_of(sub)
                if t is not None:
                    return t
    return None


def _check_det02(ctx: AnalysisContext) -> Iterable[Finding]:
    files = [f for f in ctx.files if _in_scope(f, _DET02_PATHS, ctx)]
    if not files:
        return
    prog = _Program(files)
    alias_by_file = {id(f): _time_aliases(f.tree) for f in files}

    # Pass 1: function return summaries + self-attribute taint, to a
    # bounded fixed point (two rounds = two-level call context).
    fn_summaries: Dict[str, _Taint] = {}
    attr_taint: Dict[Tuple[str, str], _Taint] = {}
    for _ in range(2):
        for f in files:
            aliases = alias_by_file[id(f)]
            for cls, fn in _functions(f.tree):
                tp = _TaintPass(f, cls, fn, aliases, fn_summaries,
                                attr_taint, prog)
                tp.run_env()
                qual = f"{cls}.{fn.name}" if cls else \
                    f"{f.path.stem}:{fn.name}"
                for node in ast.walk(fn):
                    if isinstance(node, ast.Return) \
                            and node.value is not None:
                        t = tp.taint_of(node.value)
                        if t is not None and qual not in fn_summaries:
                            fn_summaries[qual] = t
                    elif isinstance(node, ast.Assign):
                        t = tp.taint_of(node.value)
                        if t is None:
                            continue
                        for target in node.targets:
                            if isinstance(target, ast.Attribute) \
                                    and isinstance(
                                        target.value, ast.Name) \
                                    and tp.self_name \
                                    and target.value.id \
                                    == tp.self_name and cls:
                                key = (cls, target.attr)
                                if key not in attr_taint:
                                    attr_taint[key] = t.via(
                                        f"stored to `self."
                                        f"{target.attr}` at line "
                                        f"{node.lineno}")

    # Pass 2: sinks — program-class constructor arguments and sort keys.
    for f in files:
        aliases = alias_by_file[id(f)]
        for cls, fn in _functions(f.tree):
            tp = _TaintPass(f, cls, fn, aliases, fn_summaries,
                            attr_taint, prog)
            tp.run_env()
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                ctor = _decision_ctor(node, prog)
                if ctor is not None:
                    for arg in (list(node.args)
                                + [k.value for k in node.keywords]):
                        t = tp.taint_of(arg)
                        if t is not None:
                            yield finding(
                                DET02, f, node,
                                "wall-clock/randomness flows into "
                                f"decision state: {t.render()} -> "
                                f"`{ctor}(...)` constructor argument "
                                f"at line {node.lineno} — decisions "
                                "must be a pure function of (store "
                                "events, TickClock, knobs); stamp from "
                                "the injected clock instead (the PR 9 "
                                "wall-clock-stamp bug class)")
                            break
                t = _key_callable_taint(node, tp)
                if t is not None:
                    yield finding(
                        DET02, f, node,
                        "wall-clock/randomness flows into a sort key: "
                        f"{t.render()} -> `key=` callable at line "
                        f"{node.lineno} — ordering decisions on wall "
                        "time makes A/B tiebreaks nondeterministic; "
                        "key on stable fields (names, TickClock "
                        "stamps)")


DET01 = register(Rule(
    id="DET01", severity=Severity.ERROR,
    summary="unordered-collection iteration order reaching "
            "decision-bearing state",
    check=_check_det01, project=True, engine="det"))

DET02 = register(Rule(
    id="DET02", severity=Severity.ERROR,
    summary="wall-clock/randomness taint flowing into decision state "
            "instead of the injected TickClock",
    check=_check_det02, project=True, engine="det"))
