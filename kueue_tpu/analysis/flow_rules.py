"""Flow engine: whole-program lock-order and ledger-flow analysis.

LOCK01/LOCK02 (ast engine) reason about one `with` block at a time; these
rules reason about the PROGRAM:

  LOCK03  builds the lock-acquisition order graph across the controller
          runtime (scheduler/, queue/, core/, controllers/, server/):
          nodes are lock identities (`Cache._lock`, `Manager._cond`, a
          module-level lock), an edge A→B means some code path acquires B
          while holding A — either a nested `with`, or a call (resolved
          through `self.X = Class()` attribute types, `self` methods and
          module functions, transitively) into code that acquires B.
          Any cycle in that graph is a potential deadlock the moment two
          threads take the locks from opposite ends; each cycle is
          reported once, naming the acquisition sites. Self-edges are
          ignored (the repo's locks on reentrant paths are RLocks).

  LED01   pairs ledger charges with releases. A "charge site" is a call
          like `X.charge(adm, 1)` / `X.charge(adm, -1)` (the
          TopologyLedger protocol) or the quota twin
          `add_workload_usage` / `remove_workload_usage`. Two checks:

            * balance: a ledger charged (+) somewhere in a class/file
              must be released (-) somewhere in it — an assume/add path
              without the forget/delete twin leaks occupancy forever
              (HA replay then rebuilds wrong leaf state);
            * error exits: inside one function, a positive charge
              followed by a lexically reachable `raise` leaks unless the
              charge sits in a `try` whose handler/finally releases it —
              the cache mutation and the charge must commit atomically.

Both rules are pure-AST (no imports), like the ast engine; they live in a
separate engine because the whole-program fixed point is quadratic-ish
and the ast engine promises per-file millisecond runs.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from kueue_tpu.analysis.core import (
    AnalysisContext, Finding, Rule, Severity, SourceFile, dotted_name,
    finding, register)
from kueue_tpu.analysis.lock_rules import _looks_like_lock

_FLOW_PATHS = ("scheduler/", "core/", "queue/", "controllers/", "server/",
               "topology/", "metrics.py", "__main__.py", "fixtures/lint/")


def _in_scope(f: SourceFile) -> bool:
    posix = f.path.as_posix()
    return f.tree is not None and any(p in posix for p in _FLOW_PATHS)


# ---------------------------------------------------------------------------
# Program model: classes, methods, attribute types, module functions
# ---------------------------------------------------------------------------


class _Func:
    """One function/method with its lock behavior."""

    def __init__(self, qualname: str, node: ast.AST, src: SourceFile,
                 cls: Optional[str], self_name: Optional[str]):
        self.qualname = qualname
        self.node = node
        self.src = src
        self.cls = cls
        self.self_name = self_name
        # lock ids acquired anywhere in this function (directly), and the
        # transitive closure after the fixed point.
        self.direct_locks: Set[str] = set()
        self.all_locks: Set[str] = set()
        # unresolved calls as (kind, name) for the fixed point
        self.calls: List[Tuple[str, str, ast.Call]] = []


def _annotation_class(node: ast.AST):
    """Leaf class name of a type annotation: `Foo`, `mod.Foo`, `"Foo"`,
    `Optional[Foo]` / any single-parameter generic wrapper. None when
    the annotation names no resolvable class."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        try:
            node = ast.parse(node.value, mode="eval").body
        except SyntaxError:
            return None
    if isinstance(node, ast.Subscript):
        outer = dotted_name(node.value)
        if outer and outer.rsplit(".", 1)[-1] in ("Optional", "Final",
                                                  "ClassVar", "Annotated"):
            inner = node.slice
            if isinstance(inner, ast.Tuple) and inner.elts:
                inner = inner.elts[0]
            return _annotation_class(inner)
        return None
    name = dotted_name(node)
    if name is None:
        return None
    leaf = name.rsplit(".", 1)[-1]
    return leaf if leaf[:1].isupper() else None


class _Program:
    def __init__(self, files: Sequence[SourceFile]):
        self.funcs: Dict[str, _Func] = {}           # qualname -> func
        self.methods: Dict[str, List[str]] = {}     # method name -> quals
        self.attr_types: Dict[Tuple[str, str], str] = {}  # (cls, attr) -> cls
        self.classes: Set[str] = set()
        # Protocol machinery: protocol class -> its declared method
        # names; class -> explicit base names. A call through a
        # Protocol-typed attribute fans out to every conforming class
        # (explicit subclassing OR structural: defines all the
        # protocol's methods) — the coordinator/replica channel objects
        # are exactly this shape.
        self.protocols: Dict[str, Set[str]] = {}
        self.bases: Dict[str, Set[str]] = {}
        self.class_methods: Dict[str, Set[str]] = {}
        for f in files:
            self._index(f)
        self._conformers: Dict[str, List[str]] = {}

    def _index(self, f: SourceFile) -> None:
        for node in f.tree.body:
            if isinstance(node, ast.ClassDef):
                self.classes.add(node.name)
                base_names = {
                    (dotted_name(b) or "").rsplit(".", 1)[-1]
                    for b in node.bases}
                self.bases[node.name] = base_names
                meths = {item.name for item in node.body
                         if isinstance(item, (ast.FunctionDef,
                                              ast.AsyncFunctionDef))}
                self.class_methods[node.name] = meths
                if "Protocol" in base_names:
                    self.protocols[node.name] = meths - {"__init__"}
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self._add(f, item, node.name)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add(f, node, None)
        # attribute types, two sources (constructor assignment wins over
        # a bare annotation — it names the concrete class):
        #   * annotations: class-level `x: Foo` / `self.x: Foo = ...`
        #   * assignments: `self.X = Class(...)` anywhere in the class
        for cls in ast.walk(f.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            for node in ast.walk(cls):
                if isinstance(node, ast.AnnAssign):
                    target = node.target
                    attr = None
                    if isinstance(target, ast.Name):
                        attr = target.id          # class-level `x: Foo`
                    elif isinstance(target, ast.Attribute) \
                            and isinstance(target.value, ast.Name) \
                            and target.value.id in ("self", "cls"):
                        attr = target.attr        # `self.x: Foo = ...`
                    ann = _annotation_class(node.annotation) \
                        if attr is not None else None
                    if ann is not None \
                            and (cls.name, attr) not in self.attr_types:
                        self.attr_types[(cls.name, attr)] = ann
                    continue
                if not isinstance(node, ast.Assign):
                    continue
                # First class-looking constructor call anywhere in the
                # assigned expression (covers `x = C()`, `x = C() if cond
                # else y`, `x = wrap(C())`).
                ctor = None
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Call):
                        name = dotted_name(sub.func)
                        if name is None:
                            continue
                        leaf = name.rsplit(".", 1)[-1]
                        if leaf[:1].isupper():
                            ctor = leaf
                            break
                if ctor is None:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id in ("self", "cls"):
                        self.attr_types[(cls.name, t.attr)] = ctor

    def conformers(self, protocol: str) -> List[str]:
        """Classes a Protocol-typed attribute may hold at runtime:
        explicit implementers plus structural conformers (every declared
        protocol method present)."""
        hit = self._conformers.get(protocol)
        if hit is not None:
            return hit
        wanted = self.protocols.get(protocol, set())
        out = []
        for cls in self.classes:
            if cls == protocol or cls in self.protocols:
                continue
            if protocol in self.bases.get(cls, ()):
                out.append(cls)
            elif wanted and wanted <= self.class_methods.get(cls, set()):
                out.append(cls)
        self._conformers[protocol] = out
        return out

    def _add(self, f: SourceFile, node, cls: Optional[str]) -> None:
        qual = f"{cls}.{node.name}" if cls else \
            f"{f.path.stem}:{node.name}"
        self_name = None
        if cls and node.args.args:
            self_name = node.args.args[0].arg
        fn = _Func(qual, node, f, cls, self_name)
        self.funcs[qual] = fn
        self.methods.setdefault(node.name, []).append(qual)

    # -- resolution ----------------------------------------------------------

    def resolve_call(self, caller: _Func, call: ast.Call) -> List[_Func]:
        """Callees of `call` within the analyzed program (best effort)."""
        name = dotted_name(call.func)
        if name is None:
            return []
        parts = name.split(".")
        out: List[_Func] = []
        if caller.self_name and parts[0] == caller.self_name:
            if len(parts) == 2:                      # self.m()
                q = f"{caller.cls}.{parts[1]}"
                if q in self.funcs:
                    out.append(self.funcs[q])
            elif len(parts) == 3:                    # self.attr.m()
                target_cls = self.attr_types.get((caller.cls, parts[1]))
                if target_cls:
                    targets = [target_cls]
                    if target_cls in self.protocols:
                        # Protocol-typed attribute: the call lands on
                        # whichever conformer is wired at runtime — take
                        # every one (lock edges are may-acquire).
                        targets += self.conformers(target_cls)
                    for tc in targets:
                        q = f"{tc}.{parts[2]}"
                        if q in self.funcs:
                            out.append(self.funcs[q])
        elif len(parts) == 1:                        # module function f()
            for q in self.methods.get(parts[0], []):
                fn = self.funcs[q]
                if fn.cls is None and fn.src is caller.src:
                    out.append(fn)
        elif len(parts) == 2 and parts[0] in self.classes:
            q = name                                 # Class.m() / ctor chain
            if q in self.funcs:
                out.append(self.funcs[q])
        return out


def _lock_id(fn: _Func, expr: ast.AST) -> Optional[str]:
    """Stable identity of a lock-ish context manager expression."""
    name = _looks_like_lock(expr)
    if name is None:
        return None
    parts = name.split(".")
    if fn.self_name and parts[0] == fn.self_name and len(parts) >= 2:
        return f"{fn.cls}.{parts[-1]}"
    return f"{fn.src.path.stem}:{name}"


# ---------------------------------------------------------------------------
# LOCK03 — lock-acquisition order cycles
# ---------------------------------------------------------------------------


def _check_lock03(ctx: AnalysisContext):
    files = [f for f in ctx.files if _in_scope(f)]
    if not files:
        return []
    prog = _Program(files)

    # Pass 1: direct acquisitions per function.
    for fn in prog.funcs.values():
        for node in ast.walk(fn.node):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lid = _lock_id(fn, item.context_expr)
                    if lid:
                        fn.direct_locks.add(lid)
        fn.all_locks = set(fn.direct_locks)

    # Pass 2: transitive closure of "locks this function may acquire".
    changed = True
    rounds = 0
    while changed and rounds < 50:
        changed = False
        rounds += 1
        for fn in prog.funcs.values():
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                for callee in prog.resolve_call(fn, node):
                    extra = callee.all_locks - fn.all_locks
                    if extra:
                        fn.all_locks |= extra
                        changed = True

    # Pass 3: edges — while holding L, what gets acquired?
    edges: Dict[Tuple[str, str], Tuple[SourceFile, ast.AST, str]] = {}
    for fn in prog.funcs.values():
        for node in ast.walk(fn.node):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            held = [
                lid for item in node.items
                for lid in [_lock_id(fn, item.context_expr)] if lid]
            if not held:
                continue
            for inner in ast.walk(node):
                if isinstance(inner, (ast.With, ast.AsyncWith)) \
                        and inner is not node:
                    for item in inner.items:
                        lid = _lock_id(fn, item.context_expr)
                        for h in held:
                            if lid and lid != h:
                                edges.setdefault(
                                    (h, lid),
                                    (fn.src, inner,
                                     f"nested `with` in {fn.qualname}"))
                elif isinstance(inner, ast.Call):
                    for callee in prog.resolve_call(fn, inner):
                        for lid in callee.all_locks:
                            for h in held:
                                if lid != h:
                                    edges.setdefault(
                                        (h, lid),
                                        (fn.src, inner,
                                         f"{fn.qualname} calls "
                                         f"{callee.qualname}"))

    # Pass 4: cycles. DFS over the edge graph; report each cycle once.
    graph: Dict[str, List[str]] = {}
    for a, b in edges:
        graph.setdefault(a, []).append(b)
    reported: Set[frozenset] = set()

    def dfs(start: str, node: str, path: List[str], seen: Set[str]):
        for nxt in graph.get(node, ()):
            if nxt == start and len(path) >= 1:
                cyc = path + [start]
                key = frozenset(cyc)
                if key in reported:
                    continue
                reported.add(key)
                yield cyc
            elif nxt not in seen:
                seen.add(nxt)
                yield from dfs(start, nxt, path + [nxt], seen)

    out: List[Finding] = []
    for start in sorted(graph):
        for cyc in dfs(start, start, [start], {start}):
            order = " -> ".join(cyc)
            src, node, how = edges[(cyc[0], cyc[1])]
            sites = "; ".join(
                f"{edges[(a, b)][2]} at "
                f"{edges[(a, b)][0].display_path}:"
                f"{edges[(a, b)][1].lineno}"
                for a, b in zip(cyc, cyc[1:]))
            out.append(finding(
                LOCK03, src, node,
                f"lock-order cycle {order}: two threads entering from "
                f"opposite ends deadlock ({sites}) — impose one global "
                "acquisition order or narrow one critical section"))
    return out


# ---------------------------------------------------------------------------
# LED01 — ledger charges without releases
# ---------------------------------------------------------------------------

_CHARGE_PAIRS = {
    # method name -> (ledger family, sign); receiver refines the family
    "add_workload_usage": ("workload_usage", +1),
    "remove_workload_usage": ("workload_usage", -1),
}


def _charge_sign(call: ast.Call) -> Optional[int]:
    """Sign of an explicit `X.charge(obj, sign)` call (the TopologyLedger
    protocol), resolved for literal +1/-1 (also `sign=...` keywords)."""
    args = list(call.args)
    for kw in call.keywords:
        if kw.arg == "sign":
            args.append(kw.value)
    if not args:
        return None
    node = args[-1]
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        node = node.operand
        neg = True
    else:
        neg = False
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return -node.value if neg else node.value
    return None


def _ledger_sites(f: SourceFile):
    """(ledger key, sign, call node, enclosing function node) for every
    charge/release site in the file. The key is class-qualified so two
    unrelated ledgers never pair."""
    funcs: List[Tuple[Optional[str], ast.AST]] = []
    for node in f.tree.body:
        if isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    funcs.append((node.name, item))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            funcs.append((None, node))
    for cls, fn in funcs:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute):
                continue
            method = node.func.attr
            recv = dotted_name(node.func.value) or "<expr>"
            scope = cls or f.path.stem
            if method == "charge":
                sign = _charge_sign(node)
                if sign in (1, -1):
                    # normalize `self.x.charge` and `x.charge` receivers
                    leaf = recv.split(".", 1)[-1] if recv.startswith(
                        ("self.", "cls.")) else recv
                    yield f"{scope}:{leaf}.charge", sign, node, fn
            elif method in _CHARGE_PAIRS:
                family, sign = _CHARGE_PAIRS[method]
                yield f"{scope}:{family}", sign, node, fn


def _raise_after(fn: ast.AST, charge: ast.Call) -> Optional[ast.Raise]:
    """A `raise` statement lexically after the charge inside the same
    function body — the error exit that leaves the ledger charged. A
    charge wrapped in a `try` with a handler or finally is exempt (the
    rollback lives there)."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Try) \
                and node.lineno <= charge.lineno \
                and (node.end_lineno or node.lineno) >= charge.lineno \
                and (node.handlers or node.finalbody):
            return None
    for node in ast.walk(fn):
        if isinstance(node, ast.Raise) and node.lineno > charge.lineno:
            return node
    return None


def _check_led01(ctx: AnalysisContext):
    out: List[Finding] = []
    for f in ctx.files:
        if not _in_scope(f):
            continue
        sites = list(_ledger_sites(f))
        by_key: Dict[str, Dict[int, List]] = {}
        for key, sign, node, fn in sites:
            by_key.setdefault(key, {}).setdefault(sign, []).append(
                (node, fn))
        for key, signs in sorted(by_key.items()):
            if +1 in signs and -1 not in signs:
                node, _fn = signs[+1][0]
                out.append(finding(
                    LED01, f, node,
                    f"ledger `{key.split(':', 1)[1]}` is charged here but "
                    "never released in this scope — every assume/add "
                    "charge needs the forget/delete twin, or occupancy "
                    "leaks forever (and HA replay rebuilds it wrong)"))
            if -1 in signs and +1 not in signs:
                node, _fn = signs[-1][0]
                out.append(finding(
                    LED01, f, node,
                    f"ledger `{key.split(':', 1)[1]}` is released here but "
                    "never charged in this scope — a double-release goes "
                    "negative silently"))
            for node, fn in signs.get(+1, ()):
                r = _raise_after(fn, node)
                if r is not None:
                    out.append(finding(
                        LED01, f, node,
                        f"ledger charge can leak on the error exit at "
                        f"line {r.lineno}: the later `raise` leaves the "
                        "charge applied — release in a try/finally or "
                        "charge after the last failure point"))
    return out


LOCK03 = register(Rule(
    id="LOCK03", severity=Severity.ERROR,
    summary="lock-acquisition order cycle (potential deadlock) across the "
            "controller runtime",
    check=_check_lock03, project=True, engine="flow"))

LED01 = register(Rule(
    id="LED01", severity=Severity.ERROR,
    summary="ledger charge without a matching release (scope imbalance or "
            "error-path leak)",
    check=_check_led01, project=True, engine="flow"))
