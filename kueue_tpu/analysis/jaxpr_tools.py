"""Jaxpr-level analysis primitives for the kueueverify trace engine.

Three capabilities, all operating on the ClosedJaxpr a kernel lowers to:

  * recursive equation iteration (descending into scan/cond/pjit/pallas
    sub-jaxprs) with source-line attribution, so findings anchor to the
    kernel's own file:line and per-line suppressions keep working;
  * a structural signature that is invariant under shape changes —
    primitive sequence, parameter structure (ints stripped), operand
    dtypes — used by TRC03 to prove that two adjacent head-count buckets
    lower to the SAME program (one XLA compile per bucket, no
    shape-specialized divergence);
  * an interval abstract interpreter over the integer equations: every
    value gets a [lo, hi] range seeded from the kernel's input contract
    (sentinels like NO_LIMIT/BIG are 2^62, real quotas are bounded by the
    canonical-unit ceiling), propagated through the arithmetic, and any
    add/sub/mul/sum whose exact result range exceeds the operand dtype is
    an overflow hazard (TRC02). Scan carries are widened linearly by trip
    count, which keeps monotone accumulators finite and sound.

Packed byte-buffer kernels are covered by a second abstract domain: a
`Packed` value is a window into a uint8 argument whose byte ranges carry
per-field intervals (the kernel's wire layout, declared by the roster's
`packed_seeds`). The domain survives the canonical unpack chain — 1-D
`slice` shifts the window, `reshape` is byte-order-preserving, and
`bitcast_convert_type` only changes the element width — so when a field
finally reaches arithmetic it degrades to exactly its seeded interval
(sentinel fields stay 2^62, bool fields stay [0, 1]) instead of the whole
dtype. `select_n` additionally refines each case's interval under the
selecting predicate when that predicate is a comparison over the case
operands (mask-aware `where`), and `pallas_call` bodies are interpreted
with ref semantics (`get`/`swap`/`addupdate` over a mutable cell, widened
by the grid size like a scan carry).

This module imports jax lazily inside functions: the analysis package
itself must stay importable (and the ast/flow engines runnable) on hosts
without jax.
"""

from __future__ import annotations

import re
from typing import (
    Callable, Dict, Iterable, List, Optional, Sequence, Tuple)

INT64_MAX = 2**63 - 1
INT64_MIN = -(2**63)


def _jaxpr_types():
    from jax.core import ClosedJaxpr, Jaxpr
    return Jaxpr, ClosedJaxpr


def sub_jaxprs(eqn) -> Iterable:
    """The raw Jaxprs nested in an equation's params (scan/cond/pjit/
    pallas_call bodies), in a stable order."""
    Jaxpr, ClosedJaxpr = _jaxpr_types()
    for key in sorted(eqn.params, key=str):
        val = eqn.params[key]
        vals = val if isinstance(val, (list, tuple)) else [val]
        for x in vals:
            if isinstance(x, ClosedJaxpr):
                yield x.jaxpr
            elif isinstance(x, Jaxpr):
                yield x


def iter_eqns(jaxpr) -> Iterable:
    """Every equation, depth-first through sub-jaxprs."""
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in sub_jaxprs(eqn):
            yield from iter_eqns(sub)


def eqn_location(eqn) -> Optional[Tuple[str, int]]:
    """(file, line) of the user frame that emitted the equation, or None
    when jax provides no usable traceback."""
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
        if frame is None:
            return None
        return frame.file_name, frame.start_line
    except Exception:
        return None


# ---------------------------------------------------------------------------
# Structural signature (TRC03)
# ---------------------------------------------------------------------------

_DIGITS = re.compile(r"\d+")


def _canon_param(x):
    if isinstance(x, (bool, str, type(None))):
        return x
    if isinstance(x, (int, float)):
        return "#"
    if isinstance(x, (tuple, list)):
        return tuple(_canon_param(i) for i in x)
    Jaxpr, ClosedJaxpr = _jaxpr_types()
    if isinstance(x, ClosedJaxpr):
        return structural_signature(x.jaxpr)
    if isinstance(x, Jaxpr):
        return structural_signature(x)
    # Opaque param objects (dimension numbers, gather specs, dtypes):
    # their repr carries the structure; concrete sizes are stripped.
    return _DIGITS.sub("#", repr(x))


def structural_signature(jaxpr) -> tuple:
    """Shape-free fingerprint of a jaxpr: the primitive sequence, each
    equation's parameter structure with every integer (shape, length,
    axis size) canonicalized away, and the operand/output dtypes. Two
    lowerings of the same kernel at different padded bucket shapes must
    produce EQUAL signatures — anything else means the Python trace took
    a shape-dependent path and the one-compile-per-bucket contract that
    prewarm_idle relies on is broken."""
    out = []
    for eqn in jaxpr.eqns:
        out.append((
            eqn.primitive.name,
            tuple(sorted((str(k), _canon_param(v))
                         for k, v in eqn.params.items())),
            tuple(str(getattr(v.aval, "dtype", "?")) for v in eqn.invars),
            tuple(str(getattr(v.aval, "dtype", "?")) for v in eqn.outvars),
        ))
    return tuple(out)


def first_divergence(sig_a: tuple, sig_b: tuple) -> Optional[Tuple[int, str]]:
    """(index, description) of the first differing equation, or None."""
    for i, (a, b) in enumerate(zip(sig_a, sig_b)):
        if a != b:
            return i, f"equation {i}: {a[0]} vs {b[0]}"
    if len(sig_a) != len(sig_b):
        i = min(len(sig_a), len(sig_b))
        longer = sig_a if len(sig_a) > len(sig_b) else sig_b
        return i, (f"equation count {len(sig_a)} vs {len(sig_b)} "
                   f"(first extra: {longer[i][0]})")
    return None


# ---------------------------------------------------------------------------
# Interval abstract interpretation (TRC02)
# ---------------------------------------------------------------------------


class Interval:
    """[lo, hi] over exact Python ints; None bounds = unknown value."""

    __slots__ = ("lo", "hi")

    def __init__(self, lo: Optional[int], hi: Optional[int]):
        self.lo = lo
        self.hi = hi

    @property
    def known(self) -> bool:
        return self.lo is not None and self.hi is not None

    def union(self, other: "Interval") -> "Interval":
        if not (self.known and other.known):
            return UNKNOWN
        return Interval(min(self.lo, other.lo), max(self.hi, other.hi))

    def __repr__(self):
        return f"[{self.lo}, {self.hi}]"


UNKNOWN = Interval(None, None)


class Packed:
    """A window into a packed byte buffer whose wire layout is known.

    `sections` is a tuple of `(start, stop, width, lo, hi)` in byte
    coordinates of the ORIGINAL buffer argument: bytes [start, stop)
    reinterpret (little-endian, as the kernels pack them) as integers of
    `width` bytes with values in [lo, hi]. The window is bytes
    [base, base + nbytes) viewed as elements of `elem_bytes` each.

    The domain is closed under the unpack chain — rank-1 unit-stride
    `slice` (shifts the window), `reshape`/`squeeze`/`expand_dims`
    (byte-order preserving), `bitcast_convert_type` (element width
    change) — and degrades to an Interval the moment anything else
    consumes it: the union of the overlapped sections when the window is
    fully covered at a matching width and aligned on element boundaries,
    UNKNOWN otherwise (an unknown never produces a false finding)."""

    __slots__ = ("base", "nbytes", "elem_bytes", "sections")

    def __init__(self, base: int, nbytes: int, elem_bytes: int,
                 sections: Tuple[Tuple[int, int, int, int, int], ...]):
        self.base = base
        self.nbytes = nbytes
        self.elem_bytes = elem_bytes
        self.sections = sections

    def to_interval(self) -> Interval:
        lo = self.base
        hi = self.base + self.nbytes
        out: Optional[Interval] = None
        covered = 0
        for start, stop, width, slo, shi in self.sections:
            os_, oe = max(start, lo), min(stop, hi)
            if os_ >= oe:
                continue
            if width != self.elem_bytes:
                return UNKNOWN
            # A window that enters a field mid-element fuses bytes of two
            # fields into one value — unknowable.
            if (os_ - lo) % self.elem_bytes or (oe - os_) % self.elem_bytes:
                return UNKNOWN
            covered += oe - os_
            iv = Interval(slo, shi)
            out = iv if out is None else out.union(iv)
        if out is None or covered < self.nbytes:
            return UNKNOWN
        return out

    # Interval-protocol shims so a Packed that leaks past the degrade
    # boundary (e.g. a kernel returning a raw window) stays harmless.
    @property
    def known(self) -> bool:
        return self.to_interval().known

    @property
    def lo(self):
        return self.to_interval().lo

    @property
    def hi(self):
        return self.to_interval().hi

    def union(self, other) -> Interval:
        return self.to_interval().union(as_interval(other))

    def __repr__(self):
        return (f"Packed[{self.base}:{self.base + self.nbytes}]"
                f"x{self.elem_bytes}")


def as_interval(x) -> Interval:
    return x.to_interval() if isinstance(x, Packed) else x


def packed_layout(
        fields: Sequence[Tuple[int, int, Tuple[int, int]]]) -> Packed:
    """Declare a packed byte-buffer argument's wire layout as a seed
    value: `fields` lists `(count, width, (lo, hi))` in pack order —
    `count` elements of `width` bytes each, valued in [lo, hi] — and the
    result is the whole-buffer `Packed` window the roster hands to the
    interval analysis in place of a flat Interval."""
    sections = []
    off = 0
    for count, width, (lo, hi) in fields:
        nbytes = int(count) * int(width)
        sections.append((off, off + nbytes, int(width), int(lo), int(hi)))
        off += nbytes
    return Packed(0, off, 1, tuple(sections))


def _dtype_range(dtype) -> Optional[Tuple[int, int]]:
    import numpy as np

    try:
        if np.issubdtype(dtype, np.bool_):
            return (0, 1)
        if np.issubdtype(dtype, np.integer):
            info = np.iinfo(dtype)
            return (int(info.min), int(info.max))
    except Exception:
        pass
    return None  # floats and exotics: not interval-tracked


def default_seed(aval) -> Interval:
    """Input contract when the kernel spec declares nothing: quantities
    are canonical-unit integers well inside the dtype (the schema's
    NO_LIMIT/BIG sentinels must be seeded explicitly by the spec)."""
    import numpy as np

    dtype = getattr(aval, "dtype", None)
    if dtype is None:
        return UNKNOWN
    if np.issubdtype(dtype, np.bool_):
        return Interval(0, 1)
    if np.issubdtype(dtype, np.integer):
        bits = np.iinfo(dtype).bits
        if bits >= 64:
            return Interval(-(2**50), 2**50)
        if bits >= 32:
            return Interval(-(2**28), 2**28)
        return Interval(int(np.iinfo(dtype).min), int(np.iinfo(dtype).max))
    return UNKNOWN


class Overflow:
    """One overflow hazard: the equation whose exact result interval
    escapes its output dtype."""

    __slots__ = ("eqn", "prim", "lo", "hi", "dtype", "location")

    def __init__(self, eqn, prim, lo, hi, dtype, location):
        self.eqn = eqn
        self.prim = prim
        self.lo = lo
        self.hi = hi
        self.dtype = dtype
        self.location = location


def _shape_size(shape) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return max(n, 1)


def _reduced_count(eqn) -> int:
    """How many elements fold into each output element of a reduction."""
    in_shape = getattr(eqn.invars[0].aval, "shape", ())
    out_shape = getattr(eqn.outvars[0].aval, "shape", ())
    return max(_shape_size(in_shape) // _shape_size(out_shape), 1)


def _const_interval(val) -> Interval:
    """Interval of a concrete constant (closed-jaxpr const)."""
    import numpy as np

    try:
        arr = np.asarray(val)
        if arr.dtype.kind in "iub" and arr.size:
            return Interval(int(arr.min()), int(arr.max()))
    except Exception:
        pass
    return UNKNOWN


class _Scope:
    """Var-resolution view for cross-call pattern chasing: producers and
    intervals resolve at this jaxpr level, falling through to the
    enclosing level for vars bound to outer values (call invars). The
    interval env lives only at the root level — inner scopes read
    through their varmap."""

    __slots__ = ("prods", "env", "parent", "varmap")

    def __init__(self, prods: Dict, env: Optional[Dict],
                 parent: Optional["_Scope"] = None,
                 varmap: Optional[Dict] = None):
        self.prods = prods
        self.env = env
        self.parent = parent
        self.varmap = varmap or {}

    @classmethod
    def inner(cls, closed, call_eqn, parent: "_Scope") -> "_Scope":
        jaxpr = getattr(closed, "jaxpr", closed)
        prods = {ov: e for e in jaxpr.eqns for ov in e.outvars}
        varmap = dict(zip(jaxpr.invars, call_eqn.invars))
        env: Dict = {}
        for cv, val in zip(jaxpr.constvars,
                           getattr(closed, "consts", ()) or ()):
            env[cv] = _const_interval(val)
        return cls(prods, env or None, parent, varmap)

    def producer(self, v):
        from jax.core import Literal

        if isinstance(v, Literal):
            return None, self
        e = self.prods.get(v)
        if e is not None:
            return e, self
        outer = self.varmap.get(v)
        if outer is not None and self.parent is not None:
            return self.parent.producer(outer)
        return None, self

    def read(self, v) -> Interval:
        from jax.core import Literal

        if isinstance(v, Literal):
            try:
                val = int(v.val)
                return Interval(val, val)
            except (TypeError, ValueError, OverflowError):
                return UNKNOWN
        if self.env is not None and v in self.env:
            return as_interval(self.env[v])
        outer = self.varmap.get(v)
        if outer is not None and self.parent is not None:
            return self.parent.read(outer)
        return UNKNOWN


class IntervalAnalysis:
    """One pass of abstract interpretation over a closed jaxpr."""

    def __init__(self, on_overflow: Callable[[Overflow], None]):
        self.on_overflow = on_overflow
        self._reported: set = set()
        # (scope, varmap) frames linking descended sub-jaxpr runs (cond
        # branches, calls, pallas bodies) to their callers, so pattern
        # matchers can chase producer chains across the boundary.
        self._outer_stack: List = []
        # Contract intervals for pallas out/scratch refs, indexed from
        # the first body invar past the kernel operands (they have no
        # outer operand to seed through) — set from the roster's
        # KernelSpec.scratch_seeds.
        self._scratch_seeds: Optional[Dict[int, Tuple[int, int]]] = None

    def _push_scope(self, prods: Dict, env: Dict,
                    inner_invars, outer_invars) -> None:
        if self._outer_stack:
            pscope, pmap = self._outer_stack[-1]
            scope = _Scope(prods, env, pscope, pmap)
        else:
            scope = _Scope(prods, env)
        self._outer_stack.append(
            (scope, dict(zip(inner_invars, outer_invars))))

    def _pop_scope(self) -> None:
        self._outer_stack.pop()

    # -- environment --------------------------------------------------------

    @staticmethod
    def _read(env: Dict, v) -> Interval:
        from jax.core import Literal

        if isinstance(v, Literal):
            try:
                val = int(v.val)
                return Interval(val, val)
            except (TypeError, ValueError, OverflowError):
                return UNKNOWN
        return env.get(v, UNKNOWN)

    def _check(self, eqn, lo: int, hi: int, aval=None) -> Interval:
        """Flag the equation when [lo, hi] escapes the output dtype; the
        returned interval is clamped so one hazard does not cascade into
        a finding on every downstream consumer."""
        if aval is None:
            aval = eqn.outvars[0].aval
        rng = _dtype_range(getattr(aval, "dtype", None))
        if rng is None:
            return Interval(lo, hi)
        dlo, dhi = rng
        if lo < dlo or hi > dhi:
            key = id(eqn)
            if key not in self._reported:
                self._reported.add(key)
                self.on_overflow(Overflow(
                    eqn, eqn.primitive.name, lo, hi,
                    str(aval.dtype), eqn_location(eqn)))
            return Interval(max(lo, dlo), min(hi, dhi))
        return Interval(lo, hi)

    # -- the interpreter -----------------------------------------------------

    def run(self, jaxpr, consts: List[Interval],
            args: List[Interval]) -> List[Interval]:
        outs, _env = self.run_env(jaxpr, consts, args)
        return outs

    def run_env(self, jaxpr, consts: List[Interval],
                args: List[Interval]) -> Tuple[List[Interval], Dict]:
        """Like `run`, but also returns the final environment — the
        pallas widening pass needs the end state of the mutated refs,
        which are invars, not outvars."""
        from jax.core import DropVar, Literal

        env: Dict = {}
        prods: Dict = {}
        for v, iv in zip(jaxpr.constvars, consts):
            env[v] = iv
        for v, iv in zip(jaxpr.invars, args):
            env[v] = iv
        for eqn in jaxpr.eqns:
            ins = [self._read(env, v) for v in eqn.invars]
            outs = self._eqn(eqn, ins, prods, env)
            prim = eqn.primitive.name
            if prim in ("swap", "addupdate") and eqn.invars \
                    and not isinstance(eqn.invars[0], Literal):
                # Ref mutation: the target is invars[0], not an outvar.
                ref_v = eqn.invars[0]
                old = as_interval(self._read(env, ref_v))
                val = as_interval(ins[1]) if len(ins) > 1 else UNKNOWN
                if prim == "addupdate" and old.known and val.known:
                    acc = self._check(eqn, old.lo + min(val.lo, 0),
                                      old.hi + max(val.hi, 0),
                                      aval=getattr(ref_v.aval, "inner_aval",
                                                   ref_v.aval))
                    env[ref_v] = old.union(acc)
                elif old.known and val.known:
                    env[ref_v] = old.union(val)
                else:
                    env[ref_v] = UNKNOWN
            for v, iv in zip(eqn.outvars, outs):
                if not isinstance(v, DropVar):
                    env[v] = iv
                    prods[v] = eqn
        return [self._read(env, v) for v in jaxpr.outvars], env

    # Prims the Packed domain passes through unchanged (byte order and
    # element width preserved).
    _PACKED_THRU = ("reshape", "squeeze", "expand_dims")
    # Producer chains _origin follows when matching a select predicate's
    # comparison operands to the select cases (value-preserving).
    _ORIGIN_THRU = ("broadcast_in_dim", "reshape", "squeeze",
                    "expand_dims", "copy", "transpose")

    def _eqn(self, eqn, ins: List[Interval], prods: Optional[Dict] = None,
             env: Optional[Dict] = None) -> List[Interval]:
        prim = eqn.primitive.name
        n_out = len(eqn.outvars)

        if any(isinstance(x, Packed) for x in ins):
            if prim in self._PACKED_THRU:
                return [ins[0]] * n_out
            if prim == "slice":
                return [self._packed_slice(eqn, ins[0])] * n_out
            if prim == "bitcast_convert_type":
                p = ins[0]
                width = _itemsize(getattr(eqn.outvars[0].aval, "dtype",
                                          None))
                if isinstance(p, Packed) and width:
                    return [Packed(p.base, p.nbytes, width, p.sections)]
                return [UNKNOWN] * n_out
            if prim not in ("pjit", "closed_call", "core_call"):
                # Anything else consumes the bytes as values.
                ins = [as_interval(x) for x in ins]

        def allk(*ivs):
            return all(iv.known for iv in ivs)

        if prim in ("add", "sub", "mul"):
            a, b = ins
            if not allk(a, b):
                return [UNKNOWN]
            if prim == "add":
                lo, hi = a.lo + b.lo, a.hi + b.hi
            elif prim == "sub":
                lo, hi = a.lo - b.hi, a.hi - b.lo
            else:
                prods = (a.lo * b.lo, a.lo * b.hi, a.hi * b.lo, a.hi * b.hi)
                lo, hi = min(prods), max(prods)
            return [self._check(eqn, lo, hi)]
        if prim == "neg":
            a = ins[0]
            return [Interval(-a.hi, -a.lo) if a.known else UNKNOWN]
        if prim in ("max", "min"):
            a, b = ins
            if not allk(a, b):
                return [UNKNOWN]
            f = max if prim == "max" else min
            return [Interval(f(a.lo, b.lo), f(a.hi, b.hi))]
        if prim in ("reduce_sum", "cumsum"):
            a = ins[0]
            if not a.known:
                return [UNKNOWN]
            if prim == "reduce_sum" and prods is not None \
                    and env is not None:
                onehot = self._onehot_factor(eqn, prods, env)
                if onehot is not None:
                    k, sel_iv = onehot
                    lo = min(sel_iv.lo * k, 0)
                    hi = max(sel_iv.hi * k, 0)
                    return [self._check(eqn, lo, hi)]
            k = _reduced_count(eqn) if prim == "reduce_sum" else \
                _shape_size(getattr(eqn.invars[0].aval, "shape", ()))
            return [self._check(eqn, min(a.lo * k, a.lo),
                                max(a.hi * k, a.hi))]
        if prim in ("reduce_max", "reduce_min"):
            return [ins[0]]
        if prim in ("reduce_and", "reduce_or", "and", "or", "not", "xor",
                    "eq", "ne", "lt", "le", "gt", "ge", "is_finite"):
            return [Interval(0, 1)] * n_out
        if prim == "select_n":
            cases = [as_interval(c) for c in ins[1:]]
            if prods is not None and env is not None and len(cases) == 2:
                cases = self._refine_select(eqn, cases, prods, env)
            out = cases[0]
            for c in cases[1:]:
                out = out.union(c)
            return [out]
        if prim == "div":
            a, b = ins
            if allk(a, b) and b.lo >= 1:
                cands = [_trunc_div(x, y)
                         for x in (a.lo, a.hi) for y in (b.lo, b.hi)]
                return [Interval(min(cands), max(cands))]
            return [UNKNOWN]
        if prim == "rem":
            a, b = ins
            if allk(a, b) and b.lo >= 1:
                # lax.rem takes the dividend's sign; |rem| < |divisor|.
                lo = -(b.hi - 1) if a.lo < 0 else 0
                hi = (b.hi - 1) if a.hi > 0 else 0
                return [Interval(lo, hi)]
            return [UNKNOWN]
        if prim == "sign":
            return [Interval(-1, 1)]
        if prim == "get":
            return [as_interval(ins[0])] * n_out
        if prim == "swap":
            return [as_interval(ins[0])] * n_out
        if prim == "addupdate":
            return []
        if prim == "program_id":
            grid = getattr(self, "_grid", None)
            if grid:
                return [Interval(0, max(grid - 1, 0))]
            return [UNKNOWN]
        if prim == "pallas_call":
            return self._pallas(eqn, ins)
        if prim in ("broadcast_in_dim", "reshape", "squeeze", "transpose",
                    "rev", "slice", "copy", "stop_gradient", "expand_dims",
                    "gather", "dynamic_slice", "reduce_precision"):
            # Value-preserving data movement: the data operand is first;
            # index operands do not affect the value range.
            return [ins[0]] * n_out
        if prim == "dynamic_update_slice":
            return [ins[0].union(ins[1])]
        if prim == "concatenate":
            out = ins[0]
            for iv in ins[1:]:
                out = out.union(iv)
            return [out]
        if prim == "pad":
            return [ins[0].union(ins[1])]
        if prim == "iota":
            size = _shape_size(getattr(eqn.outvars[0].aval, "shape", (1,)))
            return [Interval(0, size - 1)]
        if prim in ("argmax", "argmin"):
            size = _shape_size(getattr(eqn.invars[0].aval, "shape", (1,)))
            return [Interval(0, max(size - 1, 0))]
        if prim == "convert_element_type":
            a = ins[0]
            rng = _dtype_range(getattr(eqn.outvars[0].aval, "dtype", None))
            if rng is None:
                return [UNKNOWN]
            if not a.known:
                # An unknown value is still bounded by its INPUT dtype: a
                # widening i32->i64 conversion of an unknown stays inside
                # the i32 range (returning the full i64 range here would
                # cascade spurious overflows through every consumer).
                in_rng = _dtype_range(getattr(eqn.invars[0].aval, "dtype",
                                              None))
                if in_rng is None:
                    return [Interval(*rng)]
                return [Interval(max(in_rng[0], rng[0]),
                                 min(in_rng[1], rng[1]))]
            # Out-of-range conversions wrap; TRC01 owns flagging those.
            return [Interval(max(a.lo, rng[0]), min(a.hi, rng[1]))]
        if prim.startswith("scatter"):
            op, _idx, upd = ins[0], ins[1], ins[2]
            if prim == "scatter-add":
                if not allk(op, upd):
                    return [UNKNOWN]
                # One index row writes each operand element at most once,
                # so an element accumulates at most one update per row:
                # k is the number of index rows (the update dims that are
                # NOT window dims), not the total update size — under
                # vmap the batched window dims would otherwise inflate
                # the widening quadratically.
                dn = eqn.params.get("dimension_numbers")
                window = set(getattr(dn, "update_window_dims", ()) or ())
                upd_shape = getattr(eqn.invars[2].aval, "shape", ())
                k = 1
                for d, size in enumerate(upd_shape):
                    if d not in window:
                        k *= int(size)
                k = max(k, 1)
                return [self._check(
                    eqn, op.lo + min(0, upd.lo) * k,
                    op.hi + max(0, upd.hi) * k)]
            return [op.union(upd)]
        if prim == "pjit" or prim == "closed_call" or prim == "core_call":
            sub = eqn.params.get("jaxpr")
            if sub is None:
                return [UNKNOWN] * n_out
            consts = [UNKNOWN] * len(sub.jaxpr.constvars)
            self._push_scope(prods or {}, env or {},
                             sub.jaxpr.invars, eqn.invars)
            try:
                res, senv = self.run_env(sub.jaxpr, consts, ins)
            finally:
                self._pop_scope()
            self._propagate_refs(eqn, eqn.invars, sub.jaxpr.invars,
                                 ins, senv, env)
            return res
        if prim == "scan":
            return self._scan(eqn, ins)
        if prim == "cond":
            branches = eqn.params.get("branches", ())
            outs = None
            for br in branches:
                sub = br.jaxpr if hasattr(br, "jaxpr") else br
                self._push_scope(prods or {}, env or {},
                                 sub.invars, eqn.invars[1:])
                try:
                    res, benv = self.run_env(
                        sub, [UNKNOWN] * len(sub.constvars), ins[1:])
                finally:
                    self._pop_scope()
                self._propagate_refs(eqn, eqn.invars[1:], sub.invars,
                                     ins[1:], benv, env)
                outs = res if outs is None else [
                    a.union(b) for a, b in zip(outs, res)]
            return outs if outs is not None else [UNKNOWN] * n_out
        if prim == "while":
            return [UNKNOWN] * n_out
        return [UNKNOWN] * n_out

    def _propagate_refs(self, eqn, outer_vars, inner_vars,
                        ins: List[Interval], sub_env: Dict,
                        env: Optional[Dict]) -> None:
        """Carry ref mutations out of a descended call/branch: a ref
        whose interval changed inside the sub-jaxpr (swap/addupdate
        mutate invars, not outvars) must widen the caller's binding —
        otherwise `pl.when`-guarded writes are silently dropped and the
        pallas widening pass reasons about stale ref states. Plain
        values never change (SSA), so this is a no-op for them."""
        from jax.core import Literal

        if env is None:
            return
        for outer_v, inner_v, init in zip(outer_vars, inner_vars, ins):
            if isinstance(outer_v, Literal):
                continue
            init = as_interval(init)
            fin = as_interval(sub_env.get(inner_v, UNKNOWN))
            if fin.known and init.known:
                if fin.lo < init.lo or fin.hi > init.hi:
                    cur = as_interval(env.get(outer_v, UNKNOWN))
                    env[outer_v] = cur.union(fin) if cur.known else UNKNOWN
            elif init.known and not fin.known:
                env[outer_v] = UNKNOWN

    def _scan(self, eqn, ins: List[Interval]) -> List[Interval]:
        """Linear widening: run the body once from the initial carry, then
        extrapolate each carry bound by the trip count and run once more
        for the per-equation overflow checks and the stacked outputs.
        Sound for the kernels' monotone accumulators (usage +=/-= one
        candidate per step bounds total drift by N * per-step range)."""
        p = eqn.params
        length = int(p.get("length", 1))
        num_consts = int(p.get("num_consts", 0))
        num_carry = int(p.get("num_carry", 0))
        body = p["jaxpr"].jaxpr
        consts = ins[:num_consts]
        carry0 = ins[num_consts:num_consts + num_carry]
        xs = ins[num_consts + num_carry:]
        # xs arrive stacked [T, ...]; each step sees one slice with the
        # same value range.
        body_in = consts + carry0 + xs
        silent = IntervalAnalysis(lambda o: None)
        out1 = silent.run(body, [UNKNOWN] * len(body.constvars), body_in)
        carry1 = out1[:num_carry]
        widened: List[Interval] = []
        for c0, c1 in zip(carry0, carry1):
            if not (c0.known and c1.known):
                widened.append(UNKNOWN)
                continue
            grow_lo = min(c1.lo - c0.lo, 0) * length
            grow_hi = max(c1.hi - c0.hi, 0) * length
            widened.append(Interval(c0.lo + grow_lo, c0.hi + grow_hi))
        out2 = self.run(body, [UNKNOWN] * len(body.constvars),
                        consts + widened + xs)
        return out2[:num_carry] + out2[num_carry:]

    # -- packed / select / pallas helpers ------------------------------------

    def _packed_slice(self, eqn, p: Packed):
        """Shift the byte window for a rank-1 unit-stride slice; any other
        slice degrades to the window's interval (a subset of it — sound)."""
        if not isinstance(p, Packed):
            return as_interval(p)
        starts = eqn.params.get("start_indices", ())
        limits = eqn.params.get("limit_indices", ())
        strides = eqn.params.get("strides")
        if len(starts) == 1 and (strides is None or tuple(strides) == (1,)):
            start, limit = int(starts[0]), int(limits[0])
            return Packed(p.base + start * p.elem_bytes,
                          (limit - start) * p.elem_bytes,
                          p.elem_bytes, p.sections)
        return p.to_interval()

    def _origin(self, v, prods):
        """Chase `v` back through value-preserving reshapes/broadcasts to
        the var the data originates from."""
        from jax.core import Literal

        for _ in range(32):
            if isinstance(v, Literal):
                return v
            src = prods.get(v)
            if src is None or src.primitive.name not in self._ORIGIN_THRU:
                return v
            v = src.invars[0]
        return v

    def _refine_select(self, eqn, cases: List[Interval], prods: Dict,
                       env: Dict) -> List[Interval]:
        """Mask-aware `where`: when select_n's predicate is a comparison
        whose operands are (broadcasts of) the case operands, each case
        holds only where its branch condition does — narrow its interval
        accordingly. `where(x <= cap, x, cap)` caps the true case at
        cap.hi and floors the false case at cap.lo + 1."""
        import numpy as np

        from jax.core import Literal

        pred = self._origin(eqn.invars[0], prods)
        if isinstance(pred, Literal):
            return cases
        cmp = prods.get(pred)
        if cmp is None or cmp.primitive.name not in ("lt", "le", "gt",
                                                     "ge", "eq"):
            return cases
        op = cmp.primitive.name
        a_v, b_v = (self._origin(v, prods) for v in cmp.invars)
        bounds = [as_interval(self._read(env, v)) for v in cmp.invars]
        dtype = getattr(eqn.outvars[0].aval, "dtype", None)
        try:
            integral = dtype is not None and (
                np.issubdtype(dtype, np.integer)
                or np.issubdtype(dtype, np.bool_))
        except Exception:
            integral = False
        step = 1 if integral else 0
        out = list(cases)
        for idx, case_var in enumerate(eqn.invars[1:]):
            cv = self._origin(case_var, prods)
            if isinstance(cv, Literal):
                continue
            if cv is a_v:
                role = 0
            elif cv is b_v:
                role = 1
            else:
                continue
            # select_n picks case 0 when the predicate is False, case 1
            # when True; the false branch holds the negated comparison.
            op_b = op if idx == 1 else _CMP_NEG[op]
            if op_b is None:
                continue
            if role == 1:
                op_b = _CMP_MIRROR[op_b]
            iv, other = cases[idx], bounds[1 - role]
            if not (iv.known and other.known):
                continue
            if op_b == "eq":
                lo, hi = max(iv.lo, other.lo), min(iv.hi, other.hi)
            elif op_b == "lt":
                lo, hi = iv.lo, min(iv.hi, other.hi - step)
            elif op_b == "le":
                lo, hi = iv.lo, min(iv.hi, other.hi)
            elif op_b == "gt":
                lo, hi = max(iv.lo, other.lo + step), iv.hi
            else:  # ge
                lo, hi = max(iv.lo, other.lo), iv.hi
            if lo <= hi:
                out[idx] = Interval(lo, hi)
        return out

    def _chase(self, v, scope: "_Scope", depth: int = 32):
        """(var, scope, producer) after chasing shape-preserving hops
        and unwrapping call results (jnp.where wraps its select in a
        pjit) to the var's real producing equation. Only hops that keep
        the axis structure intact are followed — the one-hot matcher
        relies on the reduce axes mapping straight onto the select's."""
        from jax.core import Literal

        for _ in range(depth):
            if isinstance(v, Literal):
                return v, scope, None
            # Translate call-invar bindings to the enclosing scope so the
            # returned (var, scope) pair is internally consistent.
            while scope.parent is not None and v not in scope.prods \
                    and v in scope.varmap:
                v, scope = scope.varmap[v], scope.parent
                if isinstance(v, Literal):
                    return v, scope, None
            src, s = scope.producer(v)
            if src is None:
                return v, scope, None
            prim = src.primitive.name
            if prim in ("copy", "reshape"):
                in_shape = tuple(getattr(src.invars[0].aval, "shape", ())
                                 or ())
                out_shape = tuple(getattr(v.aval, "shape", ()) or ())
                if in_shape != out_shape:
                    return v, s, src
                v, scope = src.invars[0], s
                continue
            if prim in ("pjit", "closed_call", "core_call"):
                closed = src.params.get("jaxpr") \
                    or src.params.get("call_jaxpr")
                inner = getattr(closed, "jaxpr", closed)
                if inner is None:
                    return v, s, src
                try:
                    k = list(src.outvars).index(v)
                except ValueError:
                    return v, s, src
                scope = _Scope.inner(closed, src, s)
                v = inner.outvars[k]
                continue
            return v, s, src
        return v, scope, None

    def _value_of(self, v, scope: "_Scope", depth: int = 0) -> Interval:
        """Interval of `v`, chasing value-preserving broadcasts/reshapes
        and call boundaries (broadcasting never changes the value SET,
        only the shape — fine for interval reads, unlike axis mapping)."""
        for _ in range(32):
            v, scope, src = self._chase(v, scope)
            if src is not None and src.primitive.name in self._ORIGIN_THRU:
                v = src.invars[0]
                continue
            if src is not None \
                    and src.primitive.name == "convert_element_type" \
                    and depth < 8:
                # Value-preserving iff the source values fit the target
                # dtype (e.g. a weak int64 literal 0 cast down to int32).
                out_rng = _dtype_range(
                    getattr(src.outvars[0].aval, "dtype", None))
                inner = self._value_of(src.invars[0], scope, depth + 1)
                if inner.known and out_rng \
                        and out_rng[0] <= inner.lo \
                        and inner.hi <= out_rng[1]:
                    return inner
                return UNKNOWN
            return scope.read(v)
        return UNKNOWN

    def _onehot_factor(self, eqn, prods: Dict, env: Dict):
        """One-hot masked reduction: when reduce_sum's operand is
        `where(iota_d == y, x, 0)` with `y` invariant along `d` and `d`
        among the reduced axes, each output element sums at most ONE
        element of `x` per position along `d` (the row/column-select
        idiom in the Pallas kernels) — so the sum is bounded by x's own
        interval times the residual reduction size, not the full
        reduced count. Returns (residual_factor, x_interval) or None."""
        axes = tuple(eqn.params.get("axes", ()) or ())
        if not axes or not eqn.invars:
            return None
        if self._outer_stack:
            pscope, pmap = self._outer_stack[-1]
            root = _Scope(prods, env, pscope, pmap)
        else:
            root = _Scope(prods, env)
        _, s, src = self._chase(eqn.invars[0], root)
        if src is None or src.primitive.name != "select_n" \
                or len(src.invars) != 3:
            return None
        # where(pred, x, 0) lowers to select_n(pred, 0, x): the false
        # case (invars[1]) must be exactly zero for the bound to hold.
        zero = self._value_of(src.invars[1], s)
        if not (zero.known and zero.lo == 0 and zero.hi == 0):
            return None
        sel = self._value_of(src.invars[2], s)
        if not sel.known:
            return None
        _, cs, cmp = self._chase(src.invars[0], s)
        if cmp is None or cmp.primitive.name != "eq":
            return None
        d = None
        for lhs, rhs in ((cmp.invars[0], cmp.invars[1]),
                         (cmp.invars[1], cmp.invars[0])):
            di = self._iota_dim(lhs, cs)
            if di is not None and di in axes \
                    and self._invariant_along(rhs, di, cs):
                d = di
                break
        if d is None:
            return None
        shape = tuple(getattr(eqn.invars[0].aval, "shape", ()) or ())
        k = 1
        for ax in axes:
            if ax != d and 0 <= ax < len(shape):
                k *= int(shape[ax])
        return max(k, 1), sel

    def _iota_dim(self, v, scope: "_Scope", depth: int = 0):
        """The output axis along which `v` counts 0..n-1 (an iota,
        possibly broadcast with the axis remapped), or None. Broadcasts
        that stretch the iota axis itself disqualify it — the values
        would repeat and the one-hot property would not hold."""
        from jax.core import Literal

        if depth > 16 or isinstance(v, Literal):
            return None
        src, s = scope.producer(v)
        if src is None:
            return None
        prim = src.primitive.name
        if prim == "iota":
            dim = src.params.get("dimension")
            return int(dim) if dim is not None else None
        if prim == "broadcast_in_dim":
            bd = tuple(src.params.get("broadcast_dimensions", ()) or ())
            inner = self._iota_dim(src.invars[0], s, depth + 1)
            if inner is None or inner >= len(bd):
                return None
            in_shape = tuple(getattr(src.invars[0].aval, "shape", ())
                             or ())
            out_shape = tuple(getattr(src.outvars[0].aval, "shape", ())
                              or ())
            outer = int(bd[inner])
            if inner >= len(in_shape) or outer >= len(out_shape) \
                    or int(in_shape[inner]) != int(out_shape[outer]):
                return None
            return outer
        if prim in ("convert_element_type", "copy"):
            return self._iota_dim(src.invars[0], s, depth + 1)
        return None

    def _invariant_along(self, v, d: int, scope: "_Scope",
                         depth: int = 0) -> bool:
        """True when `v` provably takes a single value along axis `d`
        (so eq against an iota on `d` matches at most one position)."""
        from jax.core import Literal

        if depth > 16:
            return False
        if isinstance(v, Literal):
            return True
        shape = tuple(getattr(getattr(v, "aval", None), "shape", ())
                      or ())
        if not shape:
            return True  # rank-0: one value everywhere
        if d < len(shape) and int(shape[d]) == 1:
            return True
        src, s = scope.producer(v)
        if src is None:
            return False
        prim = src.primitive.name
        if prim == "broadcast_in_dim":
            bd = tuple(src.params.get("broadcast_dimensions", ()) or ())
            if d not in bd:
                return True
            return self._invariant_along(src.invars[0], bd.index(d),
                                         s, depth + 1)
        if prim == "iota":
            dim = src.params.get("dimension")
            return dim is not None and int(dim) != d
        if prim in ("convert_element_type", "copy"):
            return self._invariant_along(src.invars[0], d, s, depth + 1)
        return False

    def _pallas(self, eqn, ins: List[Interval]) -> List[Interval]:
        """Interpret a pallas_call body with ref semantics. The kernel
        jaxpr's invars are the in/out refs (plus scratch); outputs start
        unknown. Like `_scan`, refs that grow across one body execution
        are widened linearly by the grid size before the checked pass —
        sound for the kernels' monotone per-step accumulators."""
        closed = eqn.params.get("jaxpr")
        n_out = len(eqn.outvars)
        if closed is None:
            return [UNKNOWN] * n_out
        body = closed.jaxpr if hasattr(closed, "jaxpr") else closed
        grid = 1
        gm = eqn.params.get("grid_mapping")
        for d in tuple(getattr(gm, "grid", ()) or ()):
            try:
                grid *= int(d)
            except (TypeError, ValueError):
                grid = 0
                break
        args = [as_interval(x) for x in ins]
        # Trailing body invars are the out refs and scratch refs; they
        # have no outer operand, so their contract arrives via seeds
        # (KernelSpec.scratch_seeds, indexed from the first extra invar).
        extra = len(body.invars) - len(args)
        tail = [UNKNOWN] * max(extra, 0)
        for k, bound in (self._scratch_seeds or {}).items():
            if 0 <= k < len(tail):
                tail[k] = Interval(int(bound[0]), int(bound[1]))
        args += tail
        args = args[:len(body.invars)]
        consts = [UNKNOWN] * len(body.constvars)
        prev_grid = getattr(self, "_grid", None)
        self._grid = grid or None
        try:
            silent = IntervalAnalysis(lambda o: None)
            silent._grid = grid or None
            _, env1 = silent.run_env(body, consts, args)
            widened: List[Interval] = []
            for v, a0 in zip(body.invars, args):
                a1 = as_interval(env1.get(v, UNKNOWN))
                if not (a0.known and a1.known):
                    widened.append(UNKNOWN)
                    continue
                grew = a1.lo < a0.lo or a1.hi > a0.hi
                if grew and not grid:
                    widened.append(UNKNOWN)  # unknown trip count
                    continue
                grow_lo = min(a1.lo - a0.lo, 0) * grid
                grow_hi = max(a1.hi - a0.hi, 0) * grid
                widened.append(Interval(a0.lo + grow_lo, a0.hi + grow_hi))
            self.run(body, consts, widened)
        finally:
            self._grid = prev_grid
        return [UNKNOWN] * n_out


_CMP_NEG = {"lt": "ge", "le": "gt", "gt": "le", "ge": "lt", "eq": None}
_CMP_MIRROR = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq"}


def _itemsize(dtype) -> Optional[int]:
    import numpy as np

    try:
        return int(np.dtype(dtype).itemsize)
    except Exception:
        return None


def _trunc_div(a: int, b: int) -> int:
    """lax.div semantics: integer division rounding toward zero."""
    q = abs(a) // abs(b)
    return q if (a >= 0) == (b > 0) else -q
