"""Jaxpr-level analysis primitives for the kueueverify trace engine.

Three capabilities, all operating on the ClosedJaxpr a kernel lowers to:

  * recursive equation iteration (descending into scan/cond/pjit/pallas
    sub-jaxprs) with source-line attribution, so findings anchor to the
    kernel's own file:line and per-line suppressions keep working;
  * a structural signature that is invariant under shape changes —
    primitive sequence, parameter structure (ints stripped), operand
    dtypes — used by TRC03 to prove that two adjacent head-count buckets
    lower to the SAME program (one XLA compile per bucket, no
    shape-specialized divergence);
  * an interval abstract interpreter over the integer equations: every
    value gets a [lo, hi] range seeded from the kernel's input contract
    (sentinels like NO_LIMIT/BIG are 2^62, real quotas are bounded by the
    canonical-unit ceiling), propagated through the arithmetic, and any
    add/sub/mul/sum whose exact result range exceeds the operand dtype is
    an overflow hazard (TRC02). Scan carries are widened linearly by trip
    count, which keeps monotone accumulators finite and sound.

This module imports jax lazily inside functions: the analysis package
itself must stay importable (and the ast/flow engines runnable) on hosts
without jax.
"""

from __future__ import annotations

import re
from typing import Callable, Dict, Iterable, List, Optional, Tuple

INT64_MAX = 2**63 - 1
INT64_MIN = -(2**63)


def _jaxpr_types():
    from jax.core import ClosedJaxpr, Jaxpr
    return Jaxpr, ClosedJaxpr


def sub_jaxprs(eqn) -> Iterable:
    """The raw Jaxprs nested in an equation's params (scan/cond/pjit/
    pallas_call bodies), in a stable order."""
    Jaxpr, ClosedJaxpr = _jaxpr_types()
    for key in sorted(eqn.params, key=str):
        val = eqn.params[key]
        vals = val if isinstance(val, (list, tuple)) else [val]
        for x in vals:
            if isinstance(x, ClosedJaxpr):
                yield x.jaxpr
            elif isinstance(x, Jaxpr):
                yield x


def iter_eqns(jaxpr) -> Iterable:
    """Every equation, depth-first through sub-jaxprs."""
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in sub_jaxprs(eqn):
            yield from iter_eqns(sub)


def eqn_location(eqn) -> Optional[Tuple[str, int]]:
    """(file, line) of the user frame that emitted the equation, or None
    when jax provides no usable traceback."""
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
        if frame is None:
            return None
        return frame.file_name, frame.start_line
    except Exception:
        return None


# ---------------------------------------------------------------------------
# Structural signature (TRC03)
# ---------------------------------------------------------------------------

_DIGITS = re.compile(r"\d+")


def _canon_param(x):
    if isinstance(x, (bool, str, type(None))):
        return x
    if isinstance(x, (int, float)):
        return "#"
    if isinstance(x, (tuple, list)):
        return tuple(_canon_param(i) for i in x)
    Jaxpr, ClosedJaxpr = _jaxpr_types()
    if isinstance(x, ClosedJaxpr):
        return structural_signature(x.jaxpr)
    if isinstance(x, Jaxpr):
        return structural_signature(x)
    # Opaque param objects (dimension numbers, gather specs, dtypes):
    # their repr carries the structure; concrete sizes are stripped.
    return _DIGITS.sub("#", repr(x))


def structural_signature(jaxpr) -> tuple:
    """Shape-free fingerprint of a jaxpr: the primitive sequence, each
    equation's parameter structure with every integer (shape, length,
    axis size) canonicalized away, and the operand/output dtypes. Two
    lowerings of the same kernel at different padded bucket shapes must
    produce EQUAL signatures — anything else means the Python trace took
    a shape-dependent path and the one-compile-per-bucket contract that
    prewarm_idle relies on is broken."""
    out = []
    for eqn in jaxpr.eqns:
        out.append((
            eqn.primitive.name,
            tuple(sorted((str(k), _canon_param(v))
                         for k, v in eqn.params.items())),
            tuple(str(getattr(v.aval, "dtype", "?")) for v in eqn.invars),
            tuple(str(getattr(v.aval, "dtype", "?")) for v in eqn.outvars),
        ))
    return tuple(out)


def first_divergence(sig_a: tuple, sig_b: tuple) -> Optional[Tuple[int, str]]:
    """(index, description) of the first differing equation, or None."""
    for i, (a, b) in enumerate(zip(sig_a, sig_b)):
        if a != b:
            return i, f"equation {i}: {a[0]} vs {b[0]}"
    if len(sig_a) != len(sig_b):
        i = min(len(sig_a), len(sig_b))
        longer = sig_a if len(sig_a) > len(sig_b) else sig_b
        return i, (f"equation count {len(sig_a)} vs {len(sig_b)} "
                   f"(first extra: {longer[i][0]})")
    return None


# ---------------------------------------------------------------------------
# Interval abstract interpretation (TRC02)
# ---------------------------------------------------------------------------


class Interval:
    """[lo, hi] over exact Python ints; None bounds = unknown value."""

    __slots__ = ("lo", "hi")

    def __init__(self, lo: Optional[int], hi: Optional[int]):
        self.lo = lo
        self.hi = hi

    @property
    def known(self) -> bool:
        return self.lo is not None and self.hi is not None

    def union(self, other: "Interval") -> "Interval":
        if not (self.known and other.known):
            return UNKNOWN
        return Interval(min(self.lo, other.lo), max(self.hi, other.hi))

    def __repr__(self):
        return f"[{self.lo}, {self.hi}]"


UNKNOWN = Interval(None, None)


def _dtype_range(dtype) -> Optional[Tuple[int, int]]:
    import numpy as np

    try:
        if np.issubdtype(dtype, np.bool_):
            return (0, 1)
        if np.issubdtype(dtype, np.integer):
            info = np.iinfo(dtype)
            return (int(info.min), int(info.max))
    except Exception:
        pass
    return None  # floats and exotics: not interval-tracked


def default_seed(aval) -> Interval:
    """Input contract when the kernel spec declares nothing: quantities
    are canonical-unit integers well inside the dtype (the schema's
    NO_LIMIT/BIG sentinels must be seeded explicitly by the spec)."""
    import numpy as np

    dtype = getattr(aval, "dtype", None)
    if dtype is None:
        return UNKNOWN
    if np.issubdtype(dtype, np.bool_):
        return Interval(0, 1)
    if np.issubdtype(dtype, np.integer):
        bits = np.iinfo(dtype).bits
        if bits >= 64:
            return Interval(-(2**50), 2**50)
        if bits >= 32:
            return Interval(-(2**28), 2**28)
        return Interval(int(np.iinfo(dtype).min), int(np.iinfo(dtype).max))
    return UNKNOWN


class Overflow:
    """One overflow hazard: the equation whose exact result interval
    escapes its output dtype."""

    __slots__ = ("eqn", "prim", "lo", "hi", "dtype", "location")

    def __init__(self, eqn, prim, lo, hi, dtype, location):
        self.eqn = eqn
        self.prim = prim
        self.lo = lo
        self.hi = hi
        self.dtype = dtype
        self.location = location


def _shape_size(shape) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return max(n, 1)


def _reduced_count(eqn) -> int:
    """How many elements fold into each output element of a reduction."""
    in_shape = getattr(eqn.invars[0].aval, "shape", ())
    out_shape = getattr(eqn.outvars[0].aval, "shape", ())
    return max(_shape_size(in_shape) // _shape_size(out_shape), 1)


class IntervalAnalysis:
    """One pass of abstract interpretation over a closed jaxpr."""

    def __init__(self, on_overflow: Callable[[Overflow], None]):
        self.on_overflow = on_overflow
        self._reported: set = set()

    # -- environment --------------------------------------------------------

    @staticmethod
    def _read(env: Dict, v) -> Interval:
        from jax.core import Literal

        if isinstance(v, Literal):
            try:
                val = int(v.val)
                return Interval(val, val)
            except (TypeError, ValueError, OverflowError):
                return UNKNOWN
        return env.get(v, UNKNOWN)

    def _check(self, eqn, lo: int, hi: int) -> Interval:
        """Flag the equation when [lo, hi] escapes the output dtype; the
        returned interval is clamped so one hazard does not cascade into
        a finding on every downstream consumer."""
        rng = _dtype_range(getattr(eqn.outvars[0].aval, "dtype", None))
        if rng is None:
            return Interval(lo, hi)
        dlo, dhi = rng
        if lo < dlo or hi > dhi:
            key = id(eqn)
            if key not in self._reported:
                self._reported.add(key)
                self.on_overflow(Overflow(
                    eqn, eqn.primitive.name, lo, hi,
                    str(eqn.outvars[0].aval.dtype), eqn_location(eqn)))
            return Interval(max(lo, dlo), min(hi, dhi))
        return Interval(lo, hi)

    # -- the interpreter -----------------------------------------------------

    def run(self, jaxpr, consts: List[Interval],
            args: List[Interval]) -> List[Interval]:
        env: Dict = {}
        for v, iv in zip(jaxpr.constvars, consts):
            env[v] = iv
        for v, iv in zip(jaxpr.invars, args):
            env[v] = iv
        for eqn in jaxpr.eqns:
            outs = self._eqn(eqn, [self._read(env, v) for v in eqn.invars])
            for v, iv in zip(eqn.outvars, outs):
                from jax.core import DropVar

                if not isinstance(v, DropVar):
                    env[v] = iv
        return [self._read(env, v) for v in jaxpr.outvars]

    def _eqn(self, eqn, ins: List[Interval]) -> List[Interval]:
        prim = eqn.primitive.name
        n_out = len(eqn.outvars)

        def allk(*ivs):
            return all(iv.known for iv in ivs)

        if prim in ("add", "sub", "mul"):
            a, b = ins
            if not allk(a, b):
                return [UNKNOWN]
            if prim == "add":
                lo, hi = a.lo + b.lo, a.hi + b.hi
            elif prim == "sub":
                lo, hi = a.lo - b.hi, a.hi - b.lo
            else:
                prods = (a.lo * b.lo, a.lo * b.hi, a.hi * b.lo, a.hi * b.hi)
                lo, hi = min(prods), max(prods)
            return [self._check(eqn, lo, hi)]
        if prim == "neg":
            a = ins[0]
            return [Interval(-a.hi, -a.lo) if a.known else UNKNOWN]
        if prim in ("max", "min"):
            a, b = ins
            if not allk(a, b):
                return [UNKNOWN]
            f = max if prim == "max" else min
            return [Interval(f(a.lo, b.lo), f(a.hi, b.hi))]
        if prim in ("reduce_sum", "cumsum"):
            a = ins[0]
            if not a.known:
                return [UNKNOWN]
            k = _reduced_count(eqn) if prim == "reduce_sum" else \
                _shape_size(getattr(eqn.invars[0].aval, "shape", ()))
            return [self._check(eqn, min(a.lo * k, a.lo),
                                max(a.hi * k, a.hi))]
        if prim in ("reduce_max", "reduce_min"):
            return [ins[0]]
        if prim in ("reduce_and", "reduce_or", "and", "or", "not", "xor",
                    "eq", "ne", "lt", "le", "gt", "ge", "is_finite"):
            return [Interval(0, 1)] * n_out
        if prim == "select_n":
            cases = ins[1:]
            out = cases[0]
            for c in cases[1:]:
                out = out.union(c)
            return [out]
        if prim in ("broadcast_in_dim", "reshape", "squeeze", "transpose",
                    "rev", "slice", "copy", "stop_gradient", "expand_dims",
                    "gather", "dynamic_slice", "reduce_precision"):
            # Value-preserving data movement: the data operand is first;
            # index operands do not affect the value range.
            return [ins[0]] * n_out
        if prim == "dynamic_update_slice":
            return [ins[0].union(ins[1])]
        if prim == "concatenate":
            out = ins[0]
            for iv in ins[1:]:
                out = out.union(iv)
            return [out]
        if prim == "pad":
            return [ins[0].union(ins[1])]
        if prim == "iota":
            size = _shape_size(getattr(eqn.outvars[0].aval, "shape", (1,)))
            return [Interval(0, size - 1)]
        if prim in ("argmax", "argmin"):
            size = _shape_size(getattr(eqn.invars[0].aval, "shape", (1,)))
            return [Interval(0, max(size - 1, 0))]
        if prim == "convert_element_type":
            a = ins[0]
            rng = _dtype_range(getattr(eqn.outvars[0].aval, "dtype", None))
            if rng is None or not a.known:
                return [UNKNOWN if rng is None else Interval(*rng)]
            # Out-of-range conversions wrap; TRC01 owns flagging those.
            return [Interval(max(a.lo, rng[0]), min(a.hi, rng[1]))]
        if prim.startswith("scatter"):
            op, _idx, upd = ins[0], ins[1], ins[2]
            if prim == "scatter-add":
                if not allk(op, upd):
                    return [UNKNOWN]
                k = _shape_size(getattr(eqn.invars[2].aval, "shape", (1,)))
                return [self._check(
                    eqn, op.lo + min(0, upd.lo) * k,
                    op.hi + max(0, upd.hi) * k)]
            return [op.union(upd)]
        if prim == "pjit" or prim == "closed_call" or prim == "core_call":
            sub = eqn.params.get("jaxpr")
            if sub is None:
                return [UNKNOWN] * n_out
            consts = [UNKNOWN] * len(sub.jaxpr.constvars)
            return self.run(sub.jaxpr, consts, ins)
        if prim == "scan":
            return self._scan(eqn, ins)
        if prim == "cond":
            branches = eqn.params.get("branches", ())
            outs = None
            for br in branches:
                res = self.run(br.jaxpr, [UNKNOWN] * len(br.jaxpr.constvars),
                               ins[1:])
                outs = res if outs is None else [
                    a.union(b) for a, b in zip(outs, res)]
            return outs if outs is not None else [UNKNOWN] * n_out
        if prim == "while":
            return [UNKNOWN] * n_out
        return [UNKNOWN] * n_out

    def _scan(self, eqn, ins: List[Interval]) -> List[Interval]:
        """Linear widening: run the body once from the initial carry, then
        extrapolate each carry bound by the trip count and run once more
        for the per-equation overflow checks and the stacked outputs.
        Sound for the kernels' monotone accumulators (usage +=/-= one
        candidate per step bounds total drift by N * per-step range)."""
        p = eqn.params
        length = int(p.get("length", 1))
        num_consts = int(p.get("num_consts", 0))
        num_carry = int(p.get("num_carry", 0))
        body = p["jaxpr"].jaxpr
        consts = ins[:num_consts]
        carry0 = ins[num_consts:num_consts + num_carry]
        xs = ins[num_consts + num_carry:]
        # xs arrive stacked [T, ...]; each step sees one slice with the
        # same value range.
        body_in = consts + carry0 + xs
        silent = IntervalAnalysis(lambda o: None)
        out1 = silent.run(body, [UNKNOWN] * len(body.constvars), body_in)
        carry1 = out1[:num_carry]
        widened: List[Interval] = []
        for c0, c1 in zip(carry0, carry1):
            if not (c0.known and c1.known):
                widened.append(UNKNOWN)
                continue
            grow_lo = min(c1.lo - c0.lo, 0) * length
            grow_hi = max(c1.hi - c0.hi, 0) * length
            widened.append(Interval(c0.lo + grow_lo, c0.hi + grow_hi))
        out2 = self.run(body, [UNKNOWN] * len(body.constvars),
                        consts + widened + xs)
        return out2[:num_carry] + out2[num_carry:]
