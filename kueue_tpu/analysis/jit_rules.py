"""jit-purity (JIT01-03) and retrace-hygiene (RET01-02) rules.

The hot path of this scheduler is a handful of jitted kernels under
`models/`, `ops/`, `solver/` and `parallel/`. Two silent failure modes
repeatedly cost real debugging time:

  * host syncs inside traced code (`.item()`, `float(tracer)`, `np.*` on a
    tracer, `print`) — each one stalls the device pipeline for a full
    device->host round trip, which at tick rate dominates the solve;
  * retraces — unhashable/per-tick-varying statics or Python scalars
    captured into a jitted closure recompile the kernel every tick.

These rules build a jit *reachability* set: functions decorated with
`jax.jit` / `functools.partial(jax.jit, ...)`, functions wrapped by a
`jax.jit(f)` call, and everything those functions call (including callbacks
handed to `lax.scan` / `lax.cond` / `shard_map`), across module boundaries
within the analyzed set. Purity checks then run only inside that set, with
a light taint analysis (parameters are tracers; `.shape`/`.dtype`/`len()`
results are static) to keep false positives near zero.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from kueue_tpu.analysis.core import (
    AnalysisContext, Finding, Rule, Severity, SourceFile, dotted_name,
    finding, register)

_JIT_PATHS = ("models/", "ops/", "solver/", "parallel/", "topology/",
              "hetero/", "transport/", "fuzz/", "fixtures/lint/")

# Names whose call result is host-side static even when fed a tracer.
_UNTAINT_CALLS = {"len", "isinstance", "type", "getattr", "hasattr"}
# Attributes that are static metadata on a tracer.
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "aval"}
# Host-sync builtins when applied to traced values.
_HOST_CAST_CALLS = {"float", "int", "bool", "complex"}
# Receiver methods that mutate the receiver in place.
_MUTATING_METHODS = {"append", "extend", "insert", "add", "update", "pop",
                     "remove", "clear", "setdefault", "popitem"}


# ---------------------------------------------------------------------------
# Module model: functions, imports, jit roots
# ---------------------------------------------------------------------------


class _FuncInfo:
    def __init__(self, qualname: str, node: ast.AST, src: SourceFile,
                 parent: Optional["_FuncInfo"]):
        self.qualname = qualname
        self.node = node
        self.src = src
        self.parent = parent
        self.jit_reachable = False
        # static_argnames/nums attached when this function is a jit root
        self.static_names: Set[str] = set()
        self.static_nums: Set[int] = set()


class _Module:
    """Per-file index: function defs by (qual)name, imports, numpy aliases."""

    def __init__(self, src: SourceFile):
        self.src = src
        self.funcs: Dict[str, _FuncInfo] = {}
        # local name -> (module path, original name) for `from X import Y`
        self.from_imports: Dict[str, Tuple[str, str]] = {}
        self.np_aliases: Set[str] = set()
        self.module_aliases: Dict[str, str] = {}  # local alias -> module path
        self._index()

    def _index(self) -> None:
        tree = self.src.tree
        assert tree is not None
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    local = a.asname or a.name.split(".")[0]
                    self.module_aliases[local] = a.name
                    if a.name == "numpy":
                        self.np_aliases.add(local)
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    local = a.asname or a.name
                    self.from_imports[local] = (node.module, a.name)
                    if node.module == "numpy":
                        # `from numpy import X` — treat X as a numpy call.
                        self.np_aliases.add(local)

        def visit(body: Sequence[ast.stmt], prefix: str,
                  parent: Optional[_FuncInfo]) -> None:
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qn = f"{prefix}{stmt.name}"
                    info = _FuncInfo(qn, stmt, self.src, parent)
                    self.funcs[qn] = info
                    # Innermost definition wins for bare-name lookup; only
                    # set the short name if unset so module-level defs keep
                    # priority for cross-function resolution.
                    self.funcs.setdefault(stmt.name, info)
                    visit(stmt.body, qn + ".", info)
                elif isinstance(stmt, ast.ClassDef):
                    visit(stmt.body, f"{prefix}{stmt.name}.", parent)
                elif isinstance(stmt, (ast.If, ast.Try, ast.With, ast.For,
                                       ast.While)):
                    for field in ("body", "orelse", "finalbody", "handlers"):
                        sub = getattr(stmt, field, None)
                        if not sub:
                            continue
                        for item in sub:
                            if isinstance(item, ast.ExceptHandler):
                                visit(item.body, prefix, parent)
                            else:
                                visit([item], prefix, parent)

        visit(tree.body, "", None)


def _is_jax_jit(node: ast.AST, mod: _Module) -> bool:
    """True when `node` denotes jax.jit (possibly via `from jax import jit`)."""
    name = dotted_name(node)
    if name is None:
        return False
    if name in ("jax.jit", "jit"):
        if name == "jit":
            imp = mod.from_imports.get("jit")
            return imp is not None and imp[0] == "jax"
        return True
    return False


def _partial_of_jit(call: ast.Call, mod: _Module) -> bool:
    fn = dotted_name(call.func)
    if fn not in ("functools.partial", "partial"):
        return False
    return bool(call.args) and _is_jax_jit(call.args[0], mod)


def _extract_statics(call: ast.Call) -> Tuple[Set[str], Set[int]]:
    names: Set[str] = set()
    nums: Set[int] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for lit in ast.walk(kw.value):
                if isinstance(lit, ast.Constant) and isinstance(lit.value, str):
                    names.add(lit.value)
        elif kw.arg == "static_argnums":
            for lit in ast.walk(kw.value):
                if isinstance(lit, ast.Constant) and isinstance(lit.value, int):
                    nums.add(lit.value)
    return names, nums


def _param_names(fn: ast.AST) -> List[str]:
    a = fn.args
    return ([p.arg for p in a.posonlyargs] + [p.arg for p in a.args]
            + [p.arg for p in a.kwonlyargs])


class _Program:
    """Whole-analysis view: all modules, jit roots, reachability closure."""

    def __init__(self, ctx: AnalysisContext):
        self.modules: Dict[str, _Module] = {}
        for f in ctx.files:
            if f.tree is not None:
                self.modules[f.display_path] = _Module(f)
        self._mark_roots()
        self._propagate()

    # -- root discovery ------------------------------------------------------

    def _mark_roots(self) -> None:
        self.roots: List[_FuncInfo] = []
        for mod in self.modules.values():
            tree = mod.src.tree
            for node in ast.walk(tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    for dec in node.decorator_list:
                        statics = self._jit_statics_of(dec, mod)
                        if statics is not None:
                            self._root(mod, node.name, *statics)
                elif isinstance(node, ast.Call):
                    # jax.jit(f, static_*=...): statics live on THIS call;
                    # partial(jax.jit, static_*=...)(f): on the inner call.
                    if _is_jax_jit(node.func, mod):
                        statics = _extract_statics(node)
                    else:
                        statics = self._jit_statics_of(node.func, mod)
                    if statics is not None and node.args:
                        target = node.args[0]
                        if isinstance(target, ast.Name):
                            self._root(mod, target.id, *statics)

    def _jit_statics_of(self, expr: ast.AST, mod: _Module
                        ) -> Optional[Tuple[Set[str], Set[int]]]:
        """statics if `expr` evaluates to a jit transform, else None."""
        if _is_jax_jit(expr, mod):
            return set(), set()
        if isinstance(expr, ast.Call):
            if _partial_of_jit(expr, mod):
                return _extract_statics(expr)
            if _is_jax_jit(expr.func, mod):
                return _extract_statics(expr)
        return None

    def _root(self, mod: _Module, name: str,
              static_names: Set[str], static_nums: Set[int]) -> None:
        info = mod.funcs.get(name)
        if info is None:
            return
        info.jit_reachable = True
        info.static_names |= static_names
        info.static_nums |= static_nums
        self.roots.append(info)

    # -- reachability --------------------------------------------------------

    def _callees(self, info: _FuncInfo) -> List[_FuncInfo]:
        """Functions referenced by name inside `info` (calls and callbacks),
        resolved locally then through `from` imports."""
        mod = self._module_of(info)
        out: List[_FuncInfo] = []
        refs: Set[str] = set()
        for node in ast.walk(info.node):
            if isinstance(node, ast.Call):
                if isinstance(node.func, ast.Name):
                    refs.add(node.func.id)
                # Callback position: lax.scan(step, ...), vmap(f), cond(p, f, g)
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    if isinstance(arg, ast.Name):
                        refs.add(arg.id)
        for name in refs:
            target = mod.funcs.get(name)
            if target is not None:
                out.append(target)
                continue
            imp = mod.from_imports.get(name)
            if imp is None:
                continue
            target_mod = self._find_module(imp[0])
            if target_mod is not None:
                target = target_mod.funcs.get(imp[1])
                if target is not None:
                    out.append(target)
        # Nested defs trace with their parent (lax.scan bodies etc.).
        for fn in mod.funcs.values():
            if fn.parent is info:
                out.append(fn)
        return out

    def _module_of(self, info: _FuncInfo) -> _Module:
        return self.modules[info.src.display_path]

    def _find_module(self, dotted: str) -> Optional[_Module]:
        tail = dotted.replace(".", "/") + ".py"
        for path, mod in self.modules.items():
            if path.endswith(tail):
                return mod
        return None

    def _propagate(self) -> None:
        work = list(self.roots)
        while work:
            info = work.pop()
            for callee in self._callees(info):
                if not callee.jit_reachable:
                    callee.jit_reachable = True
                    work.append(callee)

    def reachable(self) -> List[_FuncInfo]:
        out = []
        for mod in self.modules.values():
            for qn, info in mod.funcs.items():
                if qn == info.qualname and info.jit_reachable:
                    out.append(info)
        return out


def _program(ctx: AnalysisContext) -> _Program:
    prog = getattr(ctx, "_jit_program", None)
    if prog is None:
        prog = _Program(ctx)
        ctx._jit_program = prog
    return prog


# ---------------------------------------------------------------------------
# Taint analysis inside one traced function
# ---------------------------------------------------------------------------


class _Taint:
    """Single forward pass: which local names are tracer-derived."""

    def __init__(self, info: _FuncInfo, mod: _Module):
        self.mod = mod
        statics = set(info.static_names)
        params = _param_names(info.node)
        for i in info.static_nums:
            if i < len(params):
                statics.add(params[i])
        self.tainted: Set[str] = {p for p in params if p not in statics}

    def expr(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            return self.expr(node.value)
        if isinstance(node, ast.Call):
            fn = dotted_name(node.func)
            if fn in _UNTAINT_CALLS:
                return False
            # jnp/jax/lax calls on traced args yield tracers; a method call
            # like x.astype(...) keeps the receiver's taint.
            args = list(node.args) + [kw.value for kw in node.keywords]
            base = (self.expr(node.func.value)
                    if isinstance(node.func, ast.Attribute) else False)
            return base or any(self.expr(a) for a in args)
        if isinstance(node, ast.Subscript):
            return self.expr(node.value) or self.expr(node.slice)
        if isinstance(node, (ast.BinOp,)):
            return self.expr(node.left) or self.expr(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.expr(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self.expr(v) for v in node.values)
        if isinstance(node, ast.Compare):
            return self.expr(node.left) or any(self.expr(c)
                                               for c in node.comparators)
        if isinstance(node, ast.IfExp):
            return (self.expr(node.test) or self.expr(node.body)
                    or self.expr(node.orelse))
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.expr(e) for e in node.elts)
        if isinstance(node, ast.Dict):
            return any(self.expr(v) for v in node.values if v is not None)
        if isinstance(node, ast.Starred):
            return self.expr(node.value)
        if isinstance(node, ast.Slice):
            return any(self.expr(p) for p in
                       (node.lower, node.upper, node.step) if p is not None)
        return False

    def assign(self, target: ast.AST, value_tainted: bool) -> None:
        if isinstance(target, ast.Name):
            if value_tainted:
                self.tainted.add(target.id)
            else:
                self.tainted.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self.assign(e, value_tainted)
        elif isinstance(target, ast.Starred):
            self.assign(target.value, value_tainted)


def _is_none_check(test: ast.AST) -> bool:
    """`x is None` / `x is not None` — pytree-structure checks, static at
    trace time."""
    if isinstance(test, ast.Compare) and len(test.ops) == 1 \
            and isinstance(test.ops[0], (ast.Is, ast.IsNot)):
        comp = test.comparators[0]
        return isinstance(comp, ast.Constant) and comp.value is None
    if isinstance(test, ast.BoolOp):
        return all(_is_none_check(v) for v in test.values)
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _is_none_check(test.operand)
    return False


def _local_names(fn: ast.AST) -> Set[str]:
    """Parameters plus every name bound inside the function body."""
    names = set(_param_names(fn))
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            names.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not fn:
            names.add(node.name)
        elif isinstance(node, (ast.For,)):
            for t in ast.walk(node.target):
                if isinstance(t, ast.Name):
                    names.add(t.id)
        elif isinstance(node, ast.comprehension):
            for t in ast.walk(node.target):
                if isinstance(t, ast.Name):
                    names.add(t.id)
    return names


def _walk_own(fn: ast.AST):
    """Walk a function body without descending into nested defs (nested
    traced functions are analyzed as their own reachable entries)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _walk_own_body(fn: ast.AST):
    """Like _walk_own but skips the decorator list: decorators evaluate
    once at definition time (they configure the transform, e.g. shard_map
    mesh/in_specs) rather than being captured into the trace."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _statements_in_order(fn: ast.AST):
    """Own statements of fn in source order (no nested defs)."""
    out = []

    def rec(body):
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            out.append(stmt)
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if sub:
                    rec(sub)
            for h in getattr(stmt, "handlers", ()) or ():
                rec(h.body)

    rec(fn.body)
    out.sort(key=lambda s: (s.lineno, s.col_offset))
    return out


# ---------------------------------------------------------------------------
# JIT01 — host syncs inside traced code
# ---------------------------------------------------------------------------


def _check_jit01(f: SourceFile, ctx: AnalysisContext):
    prog = _program(ctx)
    mod = prog.modules.get(f.display_path)
    if mod is None:
        return
    for info in prog.reachable():
        if info.src is not f:
            continue
        taint = _run_taint(info, mod)
        for node in _walk_own(info.node):
            if not isinstance(node, ast.Call):
                continue
            fn_name = dotted_name(node.func)
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "item" and not node.args:
                yield finding(JIT01, f, node,
                              "`.item()` forces a device->host sync inside "
                              "jit-traced code; keep the value on device "
                              "(jnp.where / arithmetic) or return it")
                continue
            if fn_name == "print":
                yield finding(JIT01, f, node,
                              "`print` inside jit-traced code runs at trace "
                              "time only (or syncs under debug callbacks); "
                              "use jax.debug.print if output is needed")
                continue
            args = list(node.args) + [kw.value for kw in node.keywords]
            if fn_name in _HOST_CAST_CALLS and args \
                    and any(taint.expr(a) for a in args):
                yield finding(JIT01, f, node,
                              f"`{fn_name}()` on a traced value forces a "
                              "host sync and a concretization error under "
                              "jit; use jnp casts/astype instead")
                continue
            if fn_name is not None and "." in fn_name:
                head = fn_name.split(".")[0]
                if head in mod.np_aliases and any(taint.expr(a) for a in args):
                    yield finding(JIT01, f, node,
                                  f"`{fn_name}` (host numpy) applied to a "
                                  "traced value materializes it on host; "
                                  "use jax.numpy inside jitted code")


def _run_taint(info: _FuncInfo, mod: _Module) -> _Taint:
    taint = _Taint(info, mod)
    for stmt in _statements_in_order(info.node):
        if isinstance(stmt, ast.Assign):
            v = taint.expr(stmt.value)
            for t in stmt.targets:
                taint.assign(t, v)
        elif isinstance(stmt, ast.AugAssign):
            v = taint.expr(stmt.value) or taint.expr(stmt.target)
            taint.assign(stmt.target, v)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            taint.assign(stmt.target, taint.expr(stmt.value))
        elif isinstance(stmt, ast.For):
            taint.assign(stmt.target, taint.expr(stmt.iter))
    return taint


# ---------------------------------------------------------------------------
# JIT02 — Python control flow on traced values
# ---------------------------------------------------------------------------


def _check_jit02(f: SourceFile, ctx: AnalysisContext):
    prog = _program(ctx)
    mod = prog.modules.get(f.display_path)
    if mod is None:
        return
    for info in prog.reachable():
        if info.src is not f:
            continue
        taint = _run_taint(info, mod)
        for node in _walk_own(info.node):
            if isinstance(node, (ast.If, ast.While)) \
                    and not _is_none_check(node.test) \
                    and taint.expr(node.test):
                kind = "if" if isinstance(node, ast.If) else "while"
                yield finding(
                    JIT02, f, node,
                    f"Python `{kind}` on a traced value inside jitted code "
                    "raises a ConcretizationTypeError (or silently "
                    "specializes at trace time); use jnp.where / "
                    "lax.cond / lax.while_loop")
            elif isinstance(node, ast.Assert) and taint.expr(node.test):
                yield finding(
                    JIT02, f, node,
                    "assert on a traced value inside jitted code forces "
                    "concretization; move the check host-side or use "
                    "checkify")


# ---------------------------------------------------------------------------
# JIT03 — mutation of closed-over / global state while tracing
# ---------------------------------------------------------------------------


def _check_jit03(f: SourceFile, ctx: AnalysisContext):
    # Two deliberate exclusions keep this near-zero-FP: `nonlocal` counters
    # over static Python ints (buffer-unpacking helpers advance an offset
    # at trace time — pure metaprogramming), and pallas kernels (ref stores
    # into closed-over/parameter Refs are the pallas output mechanism).
    # What remains — leaking *traced* values into enclosing state — is the
    # bug class: the leaked tracer escapes its trace and either errors or
    # pins the first trace's value forever.
    prog = _program(ctx)
    mod = prog.modules.get(f.display_path)
    if mod is None:
        return
    for info in prog.reachable():
        if info.src is not f:
            continue
        local = _local_names(info.node)
        taint = _run_taint(info, mod)
        for node in _walk_own(info.node):
            if isinstance(node, ast.Global):
                yield finding(
                    JIT03, f, node,
                    f"`global {', '.join(node.names)}` inside jit-traced "
                    "code runs once at trace time, not per call — traced "
                    "functions must be pure")
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                v_tainted = taint.expr(node.value)
                for t in targets:
                    base = t
                    while isinstance(base, (ast.Subscript, ast.Attribute)):
                        base = base.value
                    if isinstance(base, ast.Name) and base.id not in local \
                            and base is not t and v_tainted:
                        yield finding(
                            JIT03, f, t,
                            f"traced value stored into closed-over "
                            f"`{base.id}` inside jit-traced code: the "
                            "tracer escapes its trace (leaked-tracer "
                            "error, or a stale first-trace value); thread "
                            "state through the function instead")
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATING_METHODS:
                base = node.func.value
                while isinstance(base, (ast.Subscript, ast.Attribute)):
                    base = base.value
                args = list(node.args) + [kw.value for kw in node.keywords]
                if isinstance(base, ast.Name) and base.id not in local \
                        and any(taint.expr(a) for a in args):
                    yield finding(
                        JIT03, f, node,
                        f"`.{node.func.attr}()` stores a traced value into "
                        f"closed-over `{base.id}` during tracing — traced "
                        "functions must not mutate external state")


# ---------------------------------------------------------------------------
# RET01 — static_argnames/static_argnums hazards
# ---------------------------------------------------------------------------

_UNHASHABLE_ANNOS = {"list", "List", "dict", "Dict", "set", "Set",
                     "ndarray", "Array", "bytearray"}


def _check_ret01(f: SourceFile, ctx: AnalysisContext):
    prog = _program(ctx)
    mod = prog.modules.get(f.display_path)
    if mod is None:
        return
    for info in prog.roots:
        if info.src is not f:
            continue
        params = _param_names(info.node)
        has_kwargs = info.node.args.kwarg is not None
        for name in sorted(info.static_names):
            if name not in params and not has_kwargs:
                yield finding(
                    RET01, f, info.node,
                    f"static_argnames names `{name}` but "
                    f"`{info.qualname}` has no such parameter — jax raises "
                    "at call time (or silently ignores it on older "
                    "versions)")
        has_vararg = info.node.args.vararg is not None
        for num in sorted(info.static_nums):
            if num >= len(params) and not has_vararg:
                yield finding(
                    RET01, f, info.node,
                    f"static_argnums index {num} is out of range for "
                    f"`{info.qualname}` ({len(params)} parameters)")
        by_name = {}
        a = info.node.args
        for p in a.posonlyargs + a.args + a.kwonlyargs:
            by_name[p.arg] = p
        static_params = set(info.static_names)
        for i in info.static_nums:
            if i < len(params):
                static_params.add(params[i])
        for name in sorted(static_params):
            p = by_name.get(name)
            if p is None or p.annotation is None:
                continue
            anno = p.annotation
            anno_name = dotted_name(anno)
            tail = anno_name.rsplit(".", 1)[-1] if anno_name else None
            if isinstance(anno, ast.Subscript):
                head = dotted_name(anno.value)
                tail = head.rsplit(".", 1)[-1] if head else None
            if tail in _UNHASHABLE_ANNOS:
                yield finding(
                    RET01, f, p,
                    f"static argument `{name}` is annotated `{tail}`: "
                    "unhashable statics raise at call time, and statics "
                    "that vary per tick retrace the kernel every call — "
                    "pass arrays as traced args or use hashable tuples")


# ---------------------------------------------------------------------------
# RET02 — Python scalars captured into jitted closures
# ---------------------------------------------------------------------------


def _check_ret02(f: SourceFile, ctx: AnalysisContext):
    prog = _program(ctx)
    mod = prog.modules.get(f.display_path)
    if mod is None:
        return
    module_names = set(mod.module_aliases) | set(mod.from_imports)
    top_level: Set[str] = set()
    for node in mod.src.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            top_level.add(node.name)
        elif isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                for sub in ast.walk(t):
                    if isinstance(sub, ast.Name):
                        top_level.add(sub.id)
        elif isinstance(node, (ast.If, ast.Try)):
            # try/except import fallbacks and TYPE_CHECKING blocks
            for t in ast.walk(node):
                if isinstance(t, ast.Name) and isinstance(t.ctx, ast.Store):
                    top_level.add(t.id)
    import builtins
    builtin_names = set(dir(builtins))
    for info in prog.roots:
        if info.src is not f or info.parent is None:
            continue
        # A jit root defined inside another function: loads of names local
        # to the enclosing scope are closure captures baked in at trace
        # time; if the enclosing function runs per tick with varying
        # values, every tick retraces.
        local = _local_names(info.node)
        enclosing_locals = _local_names(info.parent.node)
        first_use: Dict[str, ast.Name] = {}
        for node in _walk_own_body(info.node):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                nm = node.id
                if nm in local or nm in module_names or nm in top_level \
                        or nm in builtin_names or nm not in enclosing_locals:
                    continue
                prev = first_use.get(nm)
                if prev is None or (node.lineno, node.col_offset) < \
                        (prev.lineno, prev.col_offset):
                    first_use[nm] = node
        for nm, node in sorted(first_use.items(),
                               key=lambda kv: (kv[1].lineno,
                                               kv[1].col_offset)):
            yield finding(
                RET02, f, node,
                f"jitted closure captures `{nm}` from the enclosing "
                "scope; a different value on a later call silently "
                "retraces — make sure the compiled program is "
                "cached per capture, or pass it as a (static) "
                "argument")


JIT01 = register(Rule(
    id="JIT01", severity=Severity.ERROR,
    summary="host sync (.item()/float()/np.*/print) inside jit-traced code",
    check=_check_jit01, path_fragments=_JIT_PATHS))

JIT02 = register(Rule(
    id="JIT02", severity=Severity.ERROR,
    summary="Python if/while/assert on traced values inside jitted code",
    check=_check_jit02, path_fragments=_JIT_PATHS))

JIT03 = register(Rule(
    id="JIT03", severity=Severity.ERROR,
    summary="mutation of closed-over/global state inside jit-traced code",
    check=_check_jit03, path_fragments=_JIT_PATHS))

RET01 = register(Rule(
    id="RET01", severity=Severity.ERROR,
    summary="static_argnames/static_argnums hazards (missing/unhashable)",
    check=_check_ret01, path_fragments=_JIT_PATHS))

RET02 = register(Rule(
    id="RET02", severity=Severity.WARNING,
    summary="Python values captured into a jitted closure (retrace risk)",
    check=_check_ret02, path_fragments=_JIT_PATHS))
