"""Knob-contract rule (KNOB01): every KUEUE_TPU_* env knob goes through
the registry.

`kueue_tpu/knobs.py` is the single declaration point for the package's
environment knobs: name, kind (kill-switch / debug / tuning), default,
read discipline, doc. The accessors (`knobs.raw` / `knobs.flag`) are the
only sanctioned read path — so a knob cannot ship undocumented, the
README table generates from the registry, and the fuzz lattice can
enumerate kill switches from one place.

KNOB01 enforces the contract from three sides (one rule id, so a single
suppression token covers the whole contract):

  * a raw `os.environ` read of a literal `KUEUE_TPU_*` name anywhere
    outside `knobs.py` — `os.environ.get`, `os.getenv`, subscript, and
    the `from os import environ/getenv` spellings;
  * an accessor call naming a knob the registry does not declare
    (`knobs.flag("KUEUE_TPU_TYPO")` fails at lint time, not as a
    KeyError in a kill-switch drill);
  * a registry entry no analyzed file ever references — dead weight in
    the README table and a lie about the supported surface. This half
    only runs when the registry file itself is in the analyzed set
    (whole-package runs), so analyzing one subpackage cannot
    false-positive every knob it doesn't use.

The registry is recovered from the ANALYZED `knobs.py` when present
(fixtures can carry their own), else parsed once from the package's own
copy on disk — import-free either way, like every ast-engine rule.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set, Tuple

from kueue_tpu.analysis.core import (
    AnalysisContext, Finding, Rule, Severity, SourceFile, dotted_name,
    finding, register)

_PREFIX = "KUEUE_TPU_"
_ACCESSORS = {"raw", "flag", "get"}


def _registry_entries(tree: ast.Module) -> Optional[List[Tuple[str, int]]]:
    """(knob name, line) per Knob(...) inside a REGISTRY assignment, or
    None when the module declares no REGISTRY."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        else:
            continue
        if not any(isinstance(t, ast.Name) and t.id == "REGISTRY"
                   for t in targets):
            continue
        out: List[Tuple[str, int]] = []
        for call in ast.walk(node.value):
            if not isinstance(call, ast.Call):
                continue
            name = dotted_name(call.func)
            if name is None or name.split(".")[-1] != "Knob":
                continue
            knob = None
            if call.args and isinstance(call.args[0], ast.Constant) \
                    and isinstance(call.args[0].value, str):
                knob = call.args[0].value
            for kw in call.keywords:
                if kw.arg == "name" and isinstance(kw.value, ast.Constant):
                    knob = kw.value.value
            if knob is not None:
                out.append((knob, call.lineno))
        return out
    return None


def _is_registry_file(f: SourceFile) -> bool:
    return f.path.name == "knobs.py"


_PACKAGE_REGISTRY: Optional[List[Tuple[str, int]]] = None


def _package_registry() -> List[Tuple[str, int]]:
    """The package's own registry, parsed from disk once — the fallback
    when the analyzed set does not include a knobs.py (single-file runs,
    fixture tests)."""
    global _PACKAGE_REGISTRY
    if _PACKAGE_REGISTRY is None:
        path = Path(__file__).resolve().parent.parent / "knobs.py"
        try:
            tree = ast.parse(path.read_text(encoding="utf-8"))
            _PACKAGE_REGISTRY = _registry_entries(tree) or []
        except (OSError, SyntaxError):
            _PACKAGE_REGISTRY = []
    return _PACKAGE_REGISTRY


def _env_aliases(tree: ast.Module) -> Tuple[Set[str], Set[str]]:
    """(names bound to os.environ, names bound to os.getenv) via
    `from os import environ/getenv [as ...]`."""
    environs: Set[str] = set()
    getenvs: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "os":
            for a in node.names:
                if a.name == "environ":
                    environs.add(a.asname or a.name)
                elif a.name == "getenv":
                    getenvs.add(a.asname or a.name)
    return environs, getenvs


def _knob_literal(node: Optional[ast.AST]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str) \
            and node.value.startswith(_PREFIX):
        return node.value
    return None


def _raw_reads(f: SourceFile) -> Iterable[Tuple[str, ast.AST, str]]:
    """(knob name, node, spelling) for every raw env read of a literal
    KUEUE_TPU_* name in the file."""
    environs, getenvs = _env_aliases(f.tree)
    for node in ast.walk(f.tree):
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            arg = node.args[0] if node.args else None
            if name in ("os.environ.get", "os.getenv") \
                    or (name is not None
                        and (name in getenvs
                             or (name.endswith(".get")
                                 and name[:-len(".get")] in environs))):
                knob = _knob_literal(arg)
                if knob is not None:
                    yield knob, node, name
        elif isinstance(node, ast.Subscript):
            base = dotted_name(node.value)
            if base == "os.environ" or (base is not None
                                        and base in environs):
                knob = _knob_literal(node.slice)
                if knob is not None:
                    yield knob, node, f"{base}[...]"


def _accessor_calls(f: SourceFile) -> Iterable[Tuple[str, ast.AST, str]]:
    """(knob name, node, accessor) for knobs.raw/flag/get calls with a
    literal name."""
    # `from kueue_tpu.knobs import flag` binds the accessor bare.
    bare: Set[str] = set()
    for node in ast.walk(f.tree):
        if isinstance(node, ast.ImportFrom) \
                and node.module == "kueue_tpu.knobs":
            for a in node.names:
                if a.name in _ACCESSORS:
                    bare.add(a.asname or a.name)
    for node in ast.walk(f.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if name is None:
            continue
        parts = name.split(".")
        qualified = (len(parts) >= 2 and parts[-2] == "knobs"
                     and parts[-1] in _ACCESSORS)
        if not qualified and name not in bare:
            continue
        knob = _knob_literal(node.args[0] if node.args else None)
        if knob is not None:
            yield knob, node, parts[-1]


def _check_knob01(ctx: AnalysisContext) -> Iterable[Finding]:
    registry_file = next(
        (f for f in ctx.files
         if _is_registry_file(f) and f.tree is not None
         and _registry_entries(f.tree) is not None), None)
    if registry_file is not None:
        entries = _registry_entries(registry_file.tree) or []
    else:
        entries = _package_registry()
    registered = {name for name, _ in entries}

    referenced: Set[str] = set()
    for f in ctx.files:
        if f.tree is None or f is registry_file:
            continue
        # Any literal mention counts as a read-site reference — accessor
        # calls, the fuzz lattice's subprocess env tuples, drill configs.
        for node in ast.walk(f.tree):
            if isinstance(node, ast.Constant) \
                    and isinstance(node.value, str) \
                    and node.value.startswith(_PREFIX):
                referenced.add(node.value)

        for knob, node, spelling in _raw_reads(f):
            tail = ("" if knob in registered
                    else " — and the registry does not declare it")
            yield finding(
                KNOB01, f, node,
                f"raw `{spelling}` read of {knob} bypasses the knob "
                "registry — declare it in kueue_tpu/knobs.py and read it "
                "through knobs.flag()/knobs.raw() (the registry is what "
                "generates the README table and feeds the kill-switch "
                f"lattice){tail}")
        for knob, node, accessor in _accessor_calls(f):
            if knob not in registered:
                yield finding(
                    KNOB01, f, node,
                    f"knobs.{accessor}({knob!r}) names a knob the "
                    "registry does not declare — add a Knob entry to "
                    "kueue_tpu/knobs.py (kind, default, read discipline, "
                    "doc) or fix the name")

    if registry_file is not None:
        for knob, line in entries:
            if knob not in referenced:
                yield Finding(
                    rule=KNOB01.id, severity=KNOB01.severity,
                    path=registry_file.display_path, line=line, col=0,
                    message=f"registered knob {knob} has no read site in "
                            "the analyzed files — dead registry entries "
                            "document a contract nothing honors; delete "
                            "the entry or wire up the read")


KNOB01 = register(Rule(
    id="KNOB01", severity=Severity.ERROR,
    summary="env knob bypasses or drifts from the kueue_tpu.knobs registry",
    check=_check_knob01, project=True))
