"""Lock-discipline rules (LOCK01, LOCK02) for the controller runtime.

The controller side of this scheduler (cache, queue manager, controllers,
API server) is classic multi-threaded Python. Two hazards have bitten in
past rounds:

  * blocking while holding a lock — a `parallelize` fan-out, subprocess,
    socket/file I/O or an untimed `Condition.wait` inside `with self._lock`
    serializes every other thread behind host-side latency (and the nested
    `parallelize` case can deadlock the shared pool outright);
  * inconsistent guarding — an attribute written under the lock in most
    methods but bare in one is a data race that only shows under load.

LOCK01 walks every `with` block whose context manager looks like a lock
(name contains "lock"/"cond"/"mutex") and flags blocking calls made while
it is held. It does not descend into nested function definitions: those run
later, usually after release.

LOCK02 collects, per class, the set of `self.X` attributes ever assigned
inside a lock block, then flags assignments to the same attributes outside
any lock in other methods. `__init__`/`__post_init__`/`__new__` and methods
that document delegated guarding — a name ending in `_locked`, or a
docstring stating "Caller holds <lock>" (the same two conventions the
THR01 cross-thread engine honors) — are exempt. Warning severity: private
helpers called under the caller's lock are common and legitimate.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from kueue_tpu.analysis.core import (
    AnalysisContext, Rule, Severity, SourceFile, dotted_name, finding,
    register)

_LOCK_PATHS = ("scheduler/", "core/", "queue/", "controllers/", "server/",
               "transport/", "parallel/", "metrics.py", "__main__.py",
               "fixtures/lint/")

_LOCKY = ("lock", "cond", "mutex", "sem")

# Module-qualified calls that block the calling thread.
_BLOCKING_PREFIXES = ("subprocess.", "socket.", "requests.", "urllib.",
                      "shutil.", "http.client.")
_BLOCKING_CALLS = {"time.sleep", "open", "parallelize.until",
                   "parallelize.for_each", "os.system", "input"}
# Bare names that block when imported directly (from ... import until).
_BLOCKING_FROM = {("kueue_tpu.utils.parallelize", "until"),
                  ("kueue_tpu.utils.parallelize", "for_each")}


def _looks_like_lock(expr: ast.AST) -> Optional[str]:
    """Name of the lock-ish context manager, or None."""
    name = dotted_name(expr)
    if isinstance(expr, ast.Call):
        # with self._lock.acquire_timeout(...) or threading.Lock() inline
        name = dotted_name(expr.func)
    if name is None:
        return None
    leaf = name.rsplit(".", 1)[-1].lower()
    if any(k in leaf for k in _LOCKY):
        return name
    return None


def _walk_stopping_at_defs(nodes):
    stack = list(nodes)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _blocking_reason(call: ast.Call, from_imports: Dict[str, Tuple[str, str]]
                     ) -> Optional[str]:
    name = dotted_name(call.func)
    if name is None:
        # method call: cond.wait() with no timeout argument
        if isinstance(call.func, ast.Attribute) and call.func.attr == "wait" \
                and not call.args and not call.keywords:
            recv = dotted_name(call.func.value) or "<expr>"
            return (f"`{recv}.wait()` with no timeout blocks forever while "
                    "the lock of any outer `with` is held")
        return None
    if isinstance(call.func, ast.Attribute) and call.func.attr == "wait" \
            and not call.args and not call.keywords:
        return (f"`{name}.wait()` with no timeout blocks forever while "
                "an outer lock is held")
    if name in _BLOCKING_CALLS:
        return f"`{name}(...)` blocks (I/O or thread fan-out)"
    for prefix in _BLOCKING_PREFIXES:
        if name.startswith(prefix):
            return f"`{name}(...)` blocks on I/O"
    head = name.split(".")[0]
    imp = from_imports.get(head) or from_imports.get(name)
    if imp in _BLOCKING_FROM:
        return f"`{name}(...)` is a parallelize fan-out"
    return None


def _from_imports(tree: ast.Module) -> Dict[str, Tuple[str, str]]:
    out: Dict[str, Tuple[str, str]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                out[a.asname or a.name] = (node.module, a.name)
    return out


def _check_lock01(f: SourceFile, ctx: AnalysisContext):
    imports = _from_imports(f.tree)
    for node in ast.walk(f.tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        lock_name = None
        for item in node.items:
            lock_name = _looks_like_lock(item.context_expr)
            if lock_name:
                break
        if not lock_name:
            continue
        for inner in _walk_stopping_at_defs(node.body):
            if not isinstance(inner, ast.Call):
                continue
            # The lock's own wait IS the release-and-block primitive:
            # `with self._cond: self._cond.wait()` releases while waiting.
            # Only untimed waits on *other* objects are flagged; untimed
            # waits on the held condition get a dedicated message because
            # they still starve the wake-up path if no one ever notifies.
            reason = _blocking_reason(inner, imports)
            if reason is None:
                continue
            recv = None
            if isinstance(inner.func, ast.Attribute):
                recv = dotted_name(inner.func.value)
            if recv is not None and recv == lock_name \
                    and inner.func.attr == "wait":
                yield finding(
                    LOCK01, f, inner,
                    f"untimed `{recv}.wait()` under `with {lock_name}`: "
                    "a missed notify hangs this thread forever — pass a "
                    "timeout and re-check the predicate",
                    severity=Severity.WARNING)
                continue
            yield finding(
                LOCK01, f, inner,
                f"{reason} while `with {lock_name}` is held — move the "
                "blocking call outside the critical section (collect under "
                "the lock, apply after release)")


# ---------------------------------------------------------------------------
# LOCK02 — attributes guarded in some methods, bare in others
# ---------------------------------------------------------------------------

_EXEMPT_METHODS = {"__init__", "__post_init__", "__new__", "__enter__",
                   "__exit__"}

# A docstring saying "Caller holds <the lock>" documents delegated
# guarding — the prose twin of the `*_locked` suffix. \s+ because
# docstrings line-wrap. Shared with the THR01/THR02 thread engine.
_HELD_DOC_RE = re.compile(r"[Cc]aller\s+holds")


def _delegates_guarding(fn: ast.AST) -> bool:
    """True when the method documents that its caller holds the lock
    (`*_locked` name or a `Caller holds ...` docstring)."""
    if fn.name.endswith("_locked"):
        return True
    doc = ast.get_docstring(fn)
    return bool(doc and _HELD_DOC_RE.search(doc))


def _self_attr_writes(fn: ast.AST, self_name: str):
    """(attr, node) for every `self.X = ...` / `self.X op= ...` in fn."""
    for node in _walk_stopping_at_defs(fn.body):
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for t in targets:
            for sub in ast.walk(t):
                if isinstance(sub, ast.Attribute) \
                        and isinstance(sub.value, ast.Name) \
                        and sub.value.id == self_name \
                        and isinstance(sub.ctx, ast.Store):
                    yield sub.attr, sub


def _lock_spans(fn: ast.AST) -> List[Tuple[int, int]]:
    spans = []
    for node in ast.walk(fn):
        if isinstance(node, (ast.With, ast.AsyncWith)) and any(
                _looks_like_lock(i.context_expr) for i in node.items):
            spans.append((node.lineno, node.end_lineno or node.lineno))
    return spans


def _in_spans(line: int, spans: List[Tuple[int, int]]) -> bool:
    return any(lo <= line <= hi for lo, hi in spans)


def _check_lock02(f: SourceFile, ctx: AnalysisContext):
    for cls in ast.walk(f.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        guarded: Set[str] = set()
        per_method: List[Tuple[ast.AST, List[Tuple[str, ast.AST]],
                               List[Tuple[int, int]]]] = []
        for m in methods:
            if not m.args.args:
                continue
            self_name = m.args.args[0].arg
            spans = _lock_spans(m)
            writes = list(_self_attr_writes(m, self_name))
            per_method.append((m, writes, spans))
            for attr, node in writes:
                if _in_spans(node.lineno, spans):
                    guarded.add(attr)
        if not guarded:
            continue
        for m, writes, spans in per_method:
            if m.name in _EXEMPT_METHODS or _delegates_guarding(m):
                continue
            for attr, node in writes:
                if attr in guarded and not _in_spans(node.lineno, spans):
                    yield finding(
                        LOCK02, f, node,
                        f"`self.{attr}` is written under a lock elsewhere "
                        f"in `{cls.name}` but bare in `{m.name}` — take "
                        "the lock here, or document delegated guarding "
                        "(`*_locked` name / `Caller holds <lock>` "
                        "docstring)")


LOCK01 = register(Rule(
    id="LOCK01", severity=Severity.ERROR,
    summary="blocking call (I/O, parallelize, untimed wait) under a held lock",
    check=_check_lock01, path_fragments=_LOCK_PATHS))

LOCK02 = register(Rule(
    id="LOCK02", severity=Severity.WARNING,
    summary="attribute guarded by a lock in some methods but written bare",
    check=_check_lock02, path_fragments=_LOCK_PATHS))
