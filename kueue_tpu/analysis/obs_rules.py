"""Observability-hygiene rule (OBS01).

The tick pipeline has exactly ONE timing source: the span tracer
(`kueue_tpu.tracing.TRACER.phase/span/lock`, `trace_now` for raw
timestamps on the tracer's timebase). The `kueue_tick_phase_seconds`
histogram, bench.py's `phase_means_ms`, and the Chrome-trace export all
derive from it — a raw `time.perf_counter()` / `time.monotonic()`
measurement dropped into scheduler/solver/controller code would feed
one consumer and silently drift from the other two (exactly the
pre-tracer state this rule prevents regressing to).

OBS01 flags, inside the tick-pipeline packages:

  * attribute reads of `time.monotonic` / `time.perf_counter` (and the
    `_ns` variants) through any alias of the `time` module — calls AND
    aliasing assignments both surface as the Attribute node;
  * `from time import perf_counter/monotonic [as ...]` imports.

`time.time()` / `clock()` wall-clock reads are not timing measurements
and stay unflagged. The tracer's own internals are the sanctioned
consumer and carry explicit suppressions; non-measurement uses (e.g. a
monotonic TTL anchor for a health cache) suppress with a justification,
same as the LOCK01 discipline.

controllers/ left this roster when the det engine landed: its monotonic
reads are liveness anchors (degraded-mode stamps, barrier deadlines),
not measurements, and every one needed a justification suppression
under the blanket ban. DET02 now checks the same modules
FLOW-SENSITIVELY — wall-clock may anchor deadlines and elapsed
comparisons freely, and only flows into decision records or sort keys
are flagged — so the six suppressions came out and the real hazard
stayed covered.
"""

from __future__ import annotations

import ast
from typing import Dict, Set

from kueue_tpu.analysis.core import (
    AnalysisContext, Rule, Severity, SourceFile, finding, register)

_OBS_PATHS = ("scheduler/", "solver/", "queue/", "core/",
              "models/", "tracing/", "fixtures/lint/")

_TIMING_FNS = {"monotonic", "perf_counter", "monotonic_ns",
               "perf_counter_ns"}


def _time_aliases(tree: ast.Module) -> Set[str]:
    """Names the `time` module is bound to in this file."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    out.add(a.asname or "time")
    return out


def _check_obs01(f: SourceFile, ctx: AnalysisContext):
    aliases = _time_aliases(f.tree)
    for node in ast.walk(f.tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                if a.name in _TIMING_FNS:
                    yield finding(
                        OBS01, f, node,
                        f"`from time import {a.name}` in the tick "
                        "pipeline — route timing through "
                        "kueue_tpu.tracing (TRACER.phase/span feed the "
                        "phase histogram, bench and the trace export "
                        "from one measurement; trace_now() for raw "
                        "timestamps)")
        elif isinstance(node, ast.Attribute) \
                and isinstance(node.ctx, ast.Load) \
                and node.attr in _TIMING_FNS \
                and isinstance(node.value, ast.Name) \
                and node.value.id in aliases:
            yield finding(
                OBS01, f, node,
                f"raw `{node.value.id}.{node.attr}` timing in the tick "
                "pipeline — use TRACER.phase(name) (metrics + bench + "
                "trace export from one measurement) or TRACER.span/lock; "
                "trace_now() for a raw timestamp on the tracer's "
                "timebase")


OBS01 = register(Rule(
    id="OBS01", severity=Severity.ERROR,
    summary="raw time.monotonic/perf_counter timing bypassing the tracer",
    check=_check_obs01, path_fragments=_OBS_PATHS))
