"""Host-pipeline performance rule (PERF01).

The tick's host glue consumes the batched solver's OUTPUT TENSORS
(`res_flavor`, `ps_ok`, `wl_mode`, ... — the dict `solve_core` returns
and `fetch_outputs` materializes). Reading those element-wise from a
per-workload Python loop is the interpreter-bound shape BENCH_r05
measured at ~5-10us per workload per tensor touch: at the 1k-heads
north-star tick it reintroduces milliseconds of decode/flush latency
that the vectorized paths (np.nonzero / gathers / `batch_usage_csr` /
`csr_gather`) exist to avoid.

PERF01 flags, inside the solver-adjacent packages (scheduler/, solver/,
models/) and the accounting files whose flush/assume paths now consume
the solve's CSR coordinates (core/cache.py, core/snapshot.py):

  * a `for`/`while` loop body subscripting a solver output tensor with
    the loop variable — directly (`out["ps_ok"][w]`) or through a local
    alias (`ps_ok = out["ps_ok"][:n]` ... `ps_ok[w]`);
  * a `for`/`while` loop body calling `dominant_resource_share` — the
    per-candidate/per-iteration dict DRF walk that dominated the fair
    path (BENCH_r04 fair p99 156ms vs 69ms northstar): shares belong on
    the vectorized tensors (models/fair_share.FairShareState,
    ops/fair_preempt) with the dict walk reserved for the referee oracle
    (which carries explanatory suppressions).

Whole-array reads OUTSIDE loops (fancy indexing, reductions) and
`.tolist()` materializations iterated as plain lists are the sanctioned
patterns and stay unflagged — the decode fallback's fill loop walks
`tolist()`ed columns precisely so each tensor is touched once.

The ingest plane extends the roster to `controllers/store.py` and
`server/`: a `for` loop over a batch payload whose body calls the
per-object ingest surface (`.create(...)`, `.submit(...)`,
`decode(...)`/`decode_workload(...)`) re-creates the decode→webhook→
sink fan-out the batch lane (`Store.create_batch` /
`Framework.submit_batch` / `decode_workload_batch`) exists to collapse
— one validation sweep and one dirty-event flush per burst, not per
object. Kill-switch twins keep the loop on purpose and carry an
explanatory suppression.
"""

from __future__ import annotations

import ast
from typing import Set

from kueue_tpu.analysis.core import (
    AnalysisContext, Rule, Severity, SourceFile, finding, register)

_PERF_PATHS = ("scheduler/", "solver/", "models/", "core/cache.py",
               "core/snapshot.py", "hetero/referee.py",
               "controllers/store.py", "server/", "fixtures/lint/")

# The per-object ingest surface: calling any of these once per element
# of a batch payload is the decode→webhook→sink fan-out shape the batch
# lane collapses. Only checked in the ingest files (store/server) so the
# solver packages' unrelated `.submit(...)` idioms stay unflagged.
_INGEST_PATHS = ("controllers/store.py", "server/", "fixtures/lint/")
_INGEST_CALLS = {"create", "submit", "decode", "decode_workload"}

# Per-CQ share functions whose dict-walk cost makes a Python loop around
# them the fair-path hot-spot shape (the KEP-1714 victim-search loop).
_SHARE_WALK_CALLS = {"dominant_resource_share"}

# The batched solve's output pytree keys (models/flavor_fit.solve_core
# `outputs` dict + the derived wl_mode).
_OUTPUT_KEYS = {"res_flavor", "res_mode", "res_borrow", "group_chosen",
                "group_tried", "ps_ok", "ps_mode", "wl_mode"}


def _is_output_tensor_expr(node: ast.expr) -> bool:
    """True for `X["res_flavor"]`-shaped reads (any dict name) and slice
    chains over them (`out["ps_ok"][:n]`)."""
    if not isinstance(node, ast.Subscript):
        return False
    sl = node.slice
    if isinstance(sl, ast.Constant) and isinstance(sl.value, str) \
            and sl.value in _OUTPUT_KEYS:
        return True
    # A slice over an output-tensor expression is still the tensor.
    return _is_output_tensor_expr(node.value)


def _loop_target_names(target: ast.expr) -> Set[str]:
    return {n.id for n in ast.walk(target) if isinstance(n, ast.Name)}


def _check_perf01(f: SourceFile, ctx: AnalysisContext):
    if any(frag in f.display_path for frag in _INGEST_PATHS):
        yield from _check_ingest_loops(f)
    for func in ast.walk(f.tree):
        if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        # Local aliases bound (directly or transitively) to an output
        # tensor: `ps_ok = out["ps_ok"][:n]`; `x = ps_ok` chains too.
        # `.tolist()` / np.* calls break the chain (they leave the
        # tensor world), which is exactly the sanctioned pattern.
        aliases: Set[str] = set()
        changed = True
        while changed:
            changed = False
            for node in ast.walk(func):
                if not isinstance(node, ast.Assign) \
                        or len(node.targets) != 1 \
                        or not isinstance(node.targets[0], ast.Name):
                    continue
                name = node.targets[0].id
                if name in aliases:
                    continue
                value = node.value
                is_alias = _is_output_tensor_expr(value) or (
                    isinstance(value, ast.Name) and value.id in aliases) \
                    or (isinstance(value, ast.Subscript)
                        and isinstance(value.value, ast.Name)
                        and value.value.id in aliases)
                if is_alias:
                    aliases.add(name)
                    changed = True

        for loop in ast.walk(func):
            if isinstance(loop, ast.For):
                loop_vars = _loop_target_names(loop.target)
            elif isinstance(loop, ast.While):
                # While loops index with a manually-advanced counter;
                # flag any alias subscripted by a plain Name.
                loop_vars = None
            else:
                continue
            for sub in ast.walk(loop):
                if not isinstance(sub, ast.Subscript):
                    continue
                base = sub.value
                is_tensor = _is_output_tensor_expr(base) or (
                    isinstance(base, ast.Name) and base.id in aliases)
                if not is_tensor:
                    continue
                idx_names = {n.id for n in ast.walk(sub.slice)
                             if isinstance(n, ast.Name)}
                hit = bool(idx_names & loop_vars) if loop_vars is not None \
                    else bool(idx_names)
                if hit:
                    yield finding(
                        PERF01, f, sub,
                        "per-workload Python loop reads a solver output "
                        "tensor element-wise — gather/reduce it with "
                        "numpy outside the loop (np.nonzero, fancy "
                        "indexing, batch_usage_csr/csr_gather) or "
                        "materialize once with .tolist() and iterate "
                        "the list")

        # Fair-loop shape: a share-value dict walk re-derived inside a
        # loop (per candidate / per while-iteration). Nested loops see
        # the same call several times; flag each call node once.
        seen_calls: Set[int] = set()
        for loop in ast.walk(func):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            for call in ast.walk(loop):
                if not isinstance(call, ast.Call) \
                        or id(call) in seen_calls:
                    continue
                seen_calls.add(id(call))
                fn = call.func
                name = fn.id if isinstance(fn, ast.Name) else (
                    fn.attr if isinstance(fn, ast.Attribute) else None)
                if name in _SHARE_WALK_CALLS:
                    yield finding(
                        PERF01, f, call,
                        "per-iteration dominant_resource_share dict walk "
                        "inside a Python loop — compute shares once on "
                        "the vectorized tensors (models/fair_share."
                        "FairShareState / ops/fair_preempt share-without-"
                        "victim broadcast) and compare arrays instead")


def _check_ingest_loops(f: SourceFile):
    """Per-object ingest loop over a batch payload (store/server only):
    a `for` body calling .create()/.submit()/decode()/decode_workload()
    once per element instead of the batch lane's one-pass sweep."""
    for loop in ast.walk(f.tree):
        if not isinstance(loop, ast.For):
            continue
        for call in ast.walk(loop):
            if not isinstance(call, ast.Call):
                continue
            fn = call.func
            name = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else None)
            if name in _INGEST_CALLS:
                yield finding(
                    PERF01, f, call,
                    f"per-object {name}() inside a Python loop over a "
                    "batch payload — use the batch ingest lane "
                    "(Store.create_batch / Framework.submit_batch / "
                    "decode_workload_batch): one validation sweep and "
                    "one dirty-event flush per burst, not per object")


PERF01 = register(Rule(
    id="PERF01", severity=Severity.ERROR,
    summary="per-workload Python loop over solver output tensors",
    check=_check_perf01, path_fragments=_PERF_PATHS))
