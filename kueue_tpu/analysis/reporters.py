"""Finding reporters: human text and machine JSON."""

from __future__ import annotations

import json
from collections import Counter
from typing import List, Sequence

from kueue_tpu.analysis.core import Finding, Severity, all_rules


def render_text(findings: Sequence[Finding]) -> str:
    lines: List[str] = [f.render() for f in findings]
    by_sev = Counter(f.severity for f in findings)
    errors = by_sev.get(Severity.ERROR, 0)
    warnings = by_sev.get(Severity.WARNING, 0)
    if findings:
        lines.append("")
    lines.append(f"kueuelint: {errors} error(s), {warnings} warning(s)")
    return "\n".join(lines)


def render_json(findings: Sequence[Finding], engine: str = "ast") -> str:
    by_sev = Counter(f.severity for f in findings)
    doc = {
        "tool": "kueuelint",
        "engine": engine,
        "findings": [f.to_dict() for f in findings],
        "counts": {
            "error": by_sev.get(Severity.ERROR, 0),
            "warning": by_sev.get(Severity.WARNING, 0),
        },
    }
    return json.dumps(doc, indent=2, sort_keys=True)


def render_rule_list() -> str:
    lines = []
    for rule in all_rules():
        scope = ("all files" if rule.path_fragments is None
                 else ", ".join(rule.path_fragments))
        lines.append(f"{rule.id}  [{rule.severity.label:7s}] "
                     f"({rule.engine}) {rule.summary}")
        lines.append(f"        scope: {scope}")
    return "\n".join(lines)
