"""Decision-taint rule (TNT01): the knob registry's decision contract.

The fuzz lattice observes, per kill switch, that flipping it preserves
the decision-trail byte identity of whatever the switch does NOT gate
("kill switch => byte identity"). That was an observation; this rule
turns it into a checked CONTRACT. Every knob in `kueue_tpu/knobs.py`
now declares which side of the decision boundary it lives on:

  * `decision=NEUTRAL` — tracing/debug/tuning knobs whose value must
    NEVER reach decision state. A neutral knob may branch (enabling a
    cross-check, a tracer, a drill) but the VALUE may not be stored
    into decision-core objects, passed into decision-record
    constructors, or used in sort keys. The engine proves this by
    taint: accessor reads (`knobs.raw/flag/get`) of neutral knobs are
    sources; attribute stores, program-class constructor arguments,
    and sort keys in the decision core are sinks; branch tests are
    exempt (that is what neutral knobs are FOR).
  * `decision=GATE` — kill switches (and the drill/mutation arms) that
    deliberately select between decision paths, each with its
    registered gate sites (`gates=(path fragment, ...)`). The engine
    enforces that a gate knob is read ONLY at its registered gate
    points — a new read site elsewhere is a contract change that must
    be declared, not an accident that silently widens the switch's
    blast radius (and invalidates the A/B twin that certifies it).

Three checks under one rule id (one suppression token covers the whole
contract, mirroring KNOB01):

  1. registry hygiene, on the analyzed `knobs.py` itself: every Knob
     declares a valid decision; every kill-switch is a GATE; a GATE
     registers at least one gate site; a NEUTRAL registers none;
  2. gate discipline: an accessor call naming a GATE knob in a file
     matching none of its registered gate fragments;
  3. neutral flow: a NEUTRAL knob's value reaching decision state in
     the decision core (taint through locals, intra-procedural, with
     the source→sink path in the message).

Like KNOB01, the registry is recovered from the ANALYZED knobs.py when
present (fixtures can carry their own), else parsed once from the
package's own copy on disk — import-free either way.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set, Tuple

from kueue_tpu.analysis.core import (
    AnalysisContext, Finding, Rule, Severity, SourceFile, dotted_name,
    finding, register)
from kueue_tpu.analysis.det_rules import (
    _CallerLike, _functions, _in_scope, _self_name, DECISION_CORE)
from kueue_tpu.analysis.flow_rules import _Program
from kueue_tpu.analysis.knob_rules import (
    _accessor_calls, _is_registry_file)

_TNT_PATHS = tuple(f"{d}/" for d in DECISION_CORE) + ("fixtures/lint/",)

NEUTRAL = "neutral"
GATE = "gate"


class _Contract:
    """One knob's decision contract as declared in the registry."""

    __slots__ = ("name", "kind", "decision", "gates", "line")

    def __init__(self, name: str, kind: Optional[str],
                 decision: Optional[str], gates: Tuple[str, ...],
                 line: int):
        self.name = name
        self.kind = kind
        self.decision = decision
        self.gates = gates
        self.line = line


def _module_str_constants(tree: ast.Module) -> Dict[str, str]:
    """NAME -> value for module-level `NAME = "literal"` assigns, so
    `decision=GATE` resolves without importing the module."""
    out: Dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            out[node.targets[0].id] = node.value.value
    return out


def _const_str(node: ast.AST, consts: Dict[str, str]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    return None


def _registry_contracts(tree: ast.Module
                        ) -> Optional[List[_Contract]]:
    """Decision contracts per Knob(...) inside a REGISTRY assignment,
    or None when the module declares no REGISTRY."""
    consts = _module_str_constants(tree)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        else:
            continue
        if not any(isinstance(t, ast.Name) and t.id == "REGISTRY"
                   for t in targets):
            continue
        out: List[_Contract] = []
        for call in ast.walk(node.value):
            if not isinstance(call, ast.Call):
                continue
            cname = dotted_name(call.func)
            if cname is None or cname.split(".")[-1] != "Knob":
                continue
            name = kind = decision = None
            gates: Tuple[str, ...] = ()
            pos = ["name", "kind"]
            for i, arg in enumerate(call.args[:2]):
                v = _const_str(arg, consts)
                if pos[i] == "name":
                    name = v
                else:
                    kind = v
            for kw in call.keywords:
                if kw.arg == "name":
                    name = _const_str(kw.value, consts)
                elif kw.arg == "kind":
                    kind = _const_str(kw.value, consts)
                elif kw.arg == "decision":
                    decision = _const_str(kw.value, consts)
                elif kw.arg == "gates" \
                        and isinstance(kw.value, (ast.Tuple, ast.List)):
                    gates = tuple(
                        g for g in (_const_str(e, consts)
                                    for e in kw.value.elts)
                        if g is not None)
            if name is not None:
                out.append(_Contract(name, kind, decision, gates,
                                     call.lineno))
        return out
    return None


_PACKAGE_CONTRACTS: Optional[Dict[str, _Contract]] = None


def _package_contracts() -> Dict[str, _Contract]:
    global _PACKAGE_CONTRACTS
    if _PACKAGE_CONTRACTS is None:
        path = Path(__file__).resolve().parent.parent / "knobs.py"
        try:
            tree = ast.parse(path.read_text(encoding="utf-8"))
            entries = _registry_contracts(tree) or []
        except (OSError, SyntaxError):
            entries = []
        _PACKAGE_CONTRACTS = {c.name: c for c in entries}
    return _PACKAGE_CONTRACTS


# ---------------------------------------------------------------------------
# Check 1 — registry hygiene
# ---------------------------------------------------------------------------


def _registry_findings(f: SourceFile,
                       entries: List[_Contract]) -> Iterable[Finding]:
    for c in entries:
        if c.decision is None:
            yield _at(f, c.line,
                      f"knob {c.name} declares no decision contract — "
                      f"every knob is either decision={NEUTRAL!r} (its "
                      "value never reaches decision state) or "
                      f"decision={GATE!r} with registered gate sites")
            continue
        if c.decision not in (NEUTRAL, GATE):
            yield _at(f, c.line,
                      f"knob {c.name}: decision {c.decision!r} is not "
                      f"{NEUTRAL!r} or {GATE!r}")
            continue
        if c.kind == "kill-switch" and c.decision != GATE:
            yield _at(f, c.line,
                      f"knob {c.name} is a kill-switch but declares "
                      f"decision={c.decision!r} — a kill switch "
                      "selects between decision paths by definition; "
                      "declare it a gate with its gate sites")
        if c.decision == GATE and not c.gates:
            yield _at(f, c.line,
                      f"gate knob {c.name} registers no gate sites — "
                      "list the path fragments where the switch is "
                      "allowed to branch (gates=(...,))")
        if c.decision == NEUTRAL and c.gates:
            yield _at(f, c.line,
                      f"neutral knob {c.name} registers gate sites — "
                      "a neutral knob gates nothing; drop gates= or "
                      "declare it a gate")


def _at(f: SourceFile, line: int, message: str) -> Finding:
    return Finding(rule=TNT01.id, severity=TNT01.severity,
                   path=f.display_path, line=line, col=0,
                   message=message)


# ---------------------------------------------------------------------------
# Check 3 — neutral-knob value flow (intra-procedural taint)
# ---------------------------------------------------------------------------


class _KnobTaint:
    __slots__ = ("knob", "line", "hops")

    def __init__(self, knob: str, line: int,
                 hops: Optional[List[str]] = None):
        self.knob = knob
        self.line = line
        self.hops = hops or []

    def via(self, hop: str) -> "_KnobTaint":
        hops = self.hops + [hop]
        return _KnobTaint(self.knob, self.line, hops[-6:])

    def render(self) -> str:
        return " -> ".join(
            [f"knobs read of {self.knob} (line {self.line})"]
            + self.hops)


def _neutral_read(node: ast.AST, neutral: Set[str],
                  bare: Set[str]) -> Optional[str]:
    """Knob name when `node` is an accessor call reading a neutral
    knob with a literal name."""
    if not isinstance(node, ast.Call) or not node.args:
        return None
    name = dotted_name(node.func)
    if name is None:
        return None
    parts = name.split(".")
    qualified = (len(parts) >= 2 and parts[-2] == "knobs"
                 and parts[-1] in ("raw", "flag", "get"))
    if not qualified and name not in bare:
        return None
    arg = node.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str) \
            and arg.value in neutral:
        return arg.value
    return None


def _bare_accessors(tree: ast.Module) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) \
                and node.module == "kueue_tpu.knobs":
            for a in node.names:
                if a.name in ("raw", "flag", "get"):
                    out.add(a.asname or a.name)
    return out


class _NeutralPass:
    """Taint env for one function: locals carrying neutral-knob values."""

    def __init__(self, fn: ast.AST, neutral: Set[str],
                 bare: Set[str]):
        self.fn = fn
        self.neutral = neutral
        self.bare = bare
        self.env: Dict[str, _KnobTaint] = {}

    def taint_of(self, node: ast.AST) -> Optional[_KnobTaint]:
        knob = _neutral_read(node, self.neutral, self.bare)
        if knob is not None:
            return _KnobTaint(knob, node.lineno)
        if isinstance(node, ast.Name):
            return self.env.get(node.id)
        if isinstance(node, ast.BinOp):
            return self.taint_of(node.left) or self.taint_of(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.taint_of(node.operand)
        if isinstance(node, ast.IfExp):
            # only VALUE positions taint; the test is a branch (exempt)
            return self.taint_of(node.body) or self.taint_of(node.orelse)
        if isinstance(node, ast.Call):
            # int(env) / float(env) conversions keep the taint
            leaf = (dotted_name(node.func) or "").rsplit(".", 1)[-1]
            if leaf in ("int", "float", "str", "bool") and node.args:
                return self.taint_of(node.args[0])
            return None
        if isinstance(node, (ast.List, ast.Tuple, ast.Set)):
            for e in node.elts:
                t = self.taint_of(e)
                if t is not None:
                    return t.via("carried in a container literal")
            return None
        return None

    def run_env(self) -> None:
        for _ in range(2):
            for node in ast.walk(self.fn):
                if isinstance(node, ast.Assign):
                    t = self.taint_of(node.value)
                    if t is None:
                        continue
                    for target in node.targets:
                        if isinstance(target, ast.Name):
                            self.env[target.id] = t.via(
                                f"assigned to `{target.id}` at line "
                                f"{node.lineno}")
                elif isinstance(node, ast.AnnAssign) \
                        and node.value is not None \
                        and isinstance(node.target, ast.Name):
                    t = self.taint_of(node.value)
                    if t is not None:
                        self.env[node.target.id] = t.via(
                            f"assigned to `{node.target.id}` at line "
                            f"{node.lineno}")


def _neutral_flow_findings(f: SourceFile, neutral: Set[str],
                           prog: _Program) -> Iterable[Finding]:
    bare = _bare_accessors(f.tree)
    for cls, fn in _functions(f.tree):
        self_name = _self_name(fn, cls)
        np = _NeutralPass(fn, neutral, bare)
        np.run_env()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    if not isinstance(target, ast.Attribute):
                        continue
                    t = np.taint_of(node.value)
                    if t is None:
                        continue
                    recv = dotted_name(target.value) or "<expr>"
                    yield finding(
                        TNT01, f, node,
                        f"neutral knob value reaches decision state: "
                        f"{t.render()} -> stored to "
                        f"`{recv}.{target.attr}` at line {node.lineno} "
                        f"— {t.knob} is declared decision=neutral, so "
                        "its VALUE must never persist in decision-core "
                        "objects (branch on it instead, or declare the "
                        "knob a gate with this site registered)")
                    break
            elif isinstance(node, ast.Call):
                name = dotted_name(node.func)
                leaf = (name or "").rsplit(".", 1)[-1]
                if leaf[:1].isupper() and leaf in prog.classes:
                    for arg in (list(node.args)
                                + [k.value for k in node.keywords]):
                        t = np.taint_of(arg)
                        if t is not None:
                            yield finding(
                                TNT01, f, node,
                                "neutral knob value reaches decision "
                                f"state: {t.render()} -> `{leaf}(...)` "
                                "constructor argument at line "
                                f"{node.lineno} — {t.knob} is declared "
                                "decision=neutral; decision records "
                                "must not embed it")
                            break
                elif leaf in ("sorted", "sort", "min", "max"):
                    for kw in node.keywords:
                        if kw.arg != "key":
                            continue
                        for sub in ast.walk(kw.value):
                            if isinstance(sub, (ast.Name, ast.Call)):
                                t = np.taint_of(sub)
                                if t is not None:
                                    yield finding(
                                        TNT01, f, node,
                                        "neutral knob value reaches a "
                                        f"sort key: {t.render()} -> "
                                        "`key=` callable at line "
                                        f"{node.lineno} — ordering on "
                                        f"{t.knob} makes the trail a "
                                        "function of an undeclared "
                                        "decision input")
                                    break


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------


def _check_tnt01(ctx: AnalysisContext) -> Iterable[Finding]:
    registry_file = next(
        (f for f in ctx.files
         if _is_registry_file(f) and f.tree is not None
         and _registry_contracts(f.tree) is not None), None)
    if registry_file is not None:
        entries = _registry_contracts(registry_file.tree) or []
        contracts = {c.name: c for c in entries}
        yield from _registry_findings(registry_file, entries)
    else:
        contracts = _package_contracts()

    neutral = {name for name, c in contracts.items()
               if c.decision == NEUTRAL}

    for f in ctx.files:
        if f.tree is None or f is registry_file:
            continue
        posix = f.path.as_posix()
        # Check 2 — gate discipline, every analyzed file.
        for knob, node, accessor in _accessor_calls(f):
            c = contracts.get(knob)
            if c is None or c.decision != GATE:
                continue
            if not any(frag in posix for frag in c.gates):
                sites = ", ".join(c.gates) or "<none>"
                yield finding(
                    TNT01, f, node,
                    f"gate knob {knob} is read outside its registered "
                    f"gate sites ({sites}) — a new gate point widens "
                    "the switch's blast radius and invalidates its A/B "
                    "twin; register the site in knobs.py (gates=...) "
                    "or route the behavior through an existing gate")
        # Check 3 — neutral flow, decision core only.
        if _in_scope(f, _TNT_PATHS, ctx) and neutral:
            prog = _Program([f])
            yield from _neutral_flow_findings(f, neutral, prog)


TNT01 = register(Rule(
    id="TNT01", severity=Severity.ERROR,
    summary="knob decision contract: neutral-knob value reaching "
            "decision state, or gate knob read off its registered "
            "gate sites",
    check=_check_tnt01, project=True, engine="det"))
