"""Cross-thread shared-state rules (THR01, THR02).

LOCK02 checks lock *consistency* per class but is blind to WHICH thread
runs a method: an attribute written bare in a method that only ever runs
on one thread is fine, while the same bare write is a data race the
moment a `threading.Thread(target=self._loop)` executes a reader of it.
Two past incidents motivated making thread identity explicit:

  * the symmetric-sendall deadlock (PR 11): acks are written from the
    READER thread, so two peers pushing large frames into full TCP
    buffers wedged each other — neither reader drained because both
    were stuck in an unbounded `sendall`;
  * the zombie-socket wedge (PR 13): a blocking call issued on a
    service thread that other threads join/flush against turned a
    slow peer into a fleet-wide stall.

This module infers *thread roots* per class — the targets of
`threading.Thread(...)` / `threading.Timer(...)` spawns (`self.method`
or a nested closure), the accept/reader/dialer loops of the transport
layer — and extends LOCK02's guard inference to thread-root
reachability over the class-local call graph (`self.m()` edges and
calls to nested defs). Public methods are the "main" root: the thread
the owner calls the API from.

THR01 (error): an attribute written on one thread root and accessed on
another where some cross-thread access holds no lock. An access counts
as guarded when it sits inside a `with <lock>` span, when the method
name ends in `_locked`, or when the docstring documents the contract
("Caller holds <lock>") — the same conventions LOCK02 honors.
`__init__`-family methods are exempt (they run before any spawn).

THR02 (error): unbounded blocking calls issued from a service thread
root (reader/accept/serve/run loops, timers): `sendall`/`recv` on a
socket in a class that never bounds it with `settimeout(...)`, a
zero-argument `.join()` (Queue.join / Thread.join block forever), and
`fsync` (a stalled disk wedges every thread that joins or flushes
against the service loop). Classes that call `settimeout(<bound>)`
anywhere are recognized as having bounded their socket I/O — the
documented fix for the sendall deadlock.

Both rules are class-local and import-free; cross-object handoffs
(e.g. a channel owned by another class) are out of scope by design —
the owning class is analyzed where the threads are spawned.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set

from kueue_tpu.analysis.core import (
    AnalysisContext, Rule, Severity, SourceFile, dotted_name, finding,
    register)
from kueue_tpu.analysis.lock_rules import (
    _EXEMPT_METHODS, _HELD_DOC_RE, _in_spans, _lock_spans,
    _walk_stopping_at_defs)

_THREAD_PATHS = ("transport/", "parallel/", "controllers/", "server/",
                 "fixtures/lint/")

# Spawn constructors whose arguments name a thread root. Matched on the
# dotted leaf so `threading.Thread`, `Thread`, `threading.Timer` all
# resolve.
_SPAWNERS = {"Thread", "Timer"}

# Thread roots that count as *service* threads for THR02: loops other
# threads hand work to (and block on via join/flush/barrier).
_SERVICE_RE = re.compile(
    r"read|recv|serve|listen|accept|watch|handshake|dispatch|handle"
    r"|loop|run|timer|_on_")


class _Ctx:
    """One execution context: a method body or a nested def's body."""

    __slots__ = ("qual", "leaf", "node", "self_name", "spans", "held",
                 "calls", "spawns", "accesses", "call_nodes", "labels")

    def __init__(self, qual: str, node: ast.AST, self_name: str):
        self.qual = qual
        self.leaf = qual.rsplit(".", 1)[-1]
        self.node = node
        self.self_name = self_name
        self.spans = _lock_spans(node)
        doc = ast.get_docstring(node) or ""
        self.held = (self.leaf.endswith("_locked")
                     or bool(_HELD_DOC_RE.search(doc)))
        self.calls: Set[str] = set()        # quals of class-local callees
        self.spawns: Set[str] = set()       # quals spawned as thread roots
        self.accesses: List = []            # (attr, node, is_write)
        self.call_nodes: List[ast.Call] = []
        self.labels: Set[str] = set()       # thread-root leaves + "main"


def _spawn_target(value: ast.AST, methods: Set[str],
                  visible: Dict[str, str]) -> Optional[str]:
    """Resolve a spawn-constructor argument to a class-local context."""
    if isinstance(value, ast.Attribute) and value.attr in methods:
        return value.attr
    if isinstance(value, ast.Name) and value.id in visible:
        return visible[value.id]
    return None


def _collect(fn: ast.AST, self_name: str, qual: str,
             ctxs: Dict[str, _Ctx], methods: Set[str],
             visible: Dict[str, str]) -> None:
    ctx = ctxs[qual] = _Ctx(qual, fn, self_name)
    body = list(_walk_stopping_at_defs(fn.body))
    local = {n.name: f"{qual}.{n.name}"
             for n in body
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    seen = dict(visible)
    seen.update(local)
    method_call_funcs: Set[int] = set()
    for node in body:
        if not isinstance(node, ast.Call):
            continue
        ctx.call_nodes.append(node)
        func = node.func
        name = dotted_name(func) or ""
        if name.rsplit(".", 1)[-1] in _SPAWNERS:
            for value in list(node.args) + [k.value for k in node.keywords]:
                target = _spawn_target(value, methods, seen)
                if target is not None:
                    ctx.spawns.add(target)
        if isinstance(func, ast.Attribute) \
                and isinstance(func.value, ast.Name) \
                and func.value.id == self_name and func.attr in methods:
            ctx.calls.add(func.attr)
            method_call_funcs.add(id(func))
        elif isinstance(func, ast.Name) and func.id in seen:
            ctx.calls.add(seen[func.id])
    for node in body:
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == self_name \
                and id(node) not in method_call_funcs:
            if isinstance(node.ctx, ast.Store):
                ctx.accesses.append((node.attr, node, True))
            elif isinstance(node.ctx, ast.Load):
                ctx.accesses.append((node.attr, node, False))
    for n in body:
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _collect(n, self_name, f"{qual}.{n.name}", ctxs, methods, seen)


class _ClassModel:
    __slots__ = ("cls", "ctxs", "roots")

    def __init__(self, cls: ast.ClassDef):
        self.cls = cls
        methods = {n.name for n in cls.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        self.ctxs: Dict[str, _Ctx] = {}
        for m in cls.body:
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and m.args.args:
                _collect(m, m.args.args[0].arg, m.name, self.ctxs,
                         methods, {})
        self.roots: Set[str] = set()
        for ctx in self.ctxs.values():
            self.roots |= {t for t in ctx.spawns if t in self.ctxs}
        for root in self.roots:
            self._propagate(root, self.ctxs[root].leaf)
        # Public methods are the main-thread entry points: the owner's
        # calling thread. Private helpers inherit labels only through
        # the call graph (reachable solely from a root == that root's
        # thread; from both == shared).
        for qual, ctx in self.ctxs.items():
            if "." not in qual and qual not in self.roots \
                    and not qual.startswith("_"):
                self._propagate(qual, "main")

    def _propagate(self, start: str, label: str) -> None:
        stack, seen = [start], set()
        while stack:
            qual = stack.pop()
            if qual in seen or qual not in self.ctxs:
                continue
            seen.add(qual)
            self.ctxs[qual].labels.add(label)
            stack.extend(self.ctxs[qual].calls)

    def exempt(self, ctx: _Ctx) -> bool:
        # __init__-family bodies run before any thread is spawned —
        # unless the context itself is (or runs on) a spawned root.
        return (ctx.qual.split(".")[0] in _EXEMPT_METHODS
                and not (ctx.labels - {"main"}))


def _locked(ctx: _Ctx, node: ast.AST) -> bool:
    return ctx.held or _in_spans(node.lineno, ctx.spans)


def _check_thr01(f: SourceFile, actx: AnalysisContext):
    for cls in ast.walk(f.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        model = _ClassModel(cls)
        if not model.roots:
            continue
        by_attr: Dict[str, List] = {}
        for ctx in model.ctxs.values():
            if model.exempt(ctx) or not ctx.labels:
                continue
            for attr, node, is_write in ctx.accesses:
                by_attr.setdefault(attr, []).append((ctx, node, is_write))
        for attr in sorted(by_attr):
            acc = by_attr[attr]
            writes = [a for a in acc if a[2]]
            if not writes:
                continue  # set before spawn (or never in-class): immutable
            labels: Set[str] = set()
            for ctx, _, _ in acc:
                labels |= ctx.labels
            if len(labels) < 2:
                continue  # only ever touched on one thread root
            offenders = [(ctx, node, w) for ctx, node, w in acc
                         if not _locked(ctx, node)]
            if not offenders:
                continue
            offenders.sort(key=lambda a: (not a[2], a[1].lineno))
            ctx, node, is_write = offenders[0]
            kind = "write" if is_write else "read"
            yield finding(
                THR01, f, node,
                f"`self.{attr}` is shared across threads in `{cls.name}` "
                f"(roots: {', '.join(sorted(labels))}) but this {kind} in "
                f"`{ctx.leaf}` holds no lock — guard every cross-thread "
                "access consistently, or document the contract "
                "(`Caller holds <lock>` docstring / `*_locked` name)")


def _class_bounds_sockets(cls: ast.ClassDef) -> bool:
    """True when the class calls `settimeout(<bound>)` anywhere: its
    socket I/O is bounded (a stuck send/recv severs instead of
    wedging), the documented fix for the symmetric-sendall deadlock."""
    for node in ast.walk(cls):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "settimeout" and node.args:
            arg = node.args[0]
            if not (isinstance(arg, ast.Constant) and arg.value is None):
                return True
    return False


def _check_thr02(f: SourceFile, actx: AnalysisContext):
    for cls in ast.walk(f.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        model = _ClassModel(cls)
        if not model.roots:
            continue
        bounded = _class_bounds_sockets(cls)
        for ctx in model.ctxs.values():
            service = sorted(label for label in ctx.labels
                             if label != "main"
                             and _SERVICE_RE.search(label))
            if not service:
                continue
            root = service[0]
            for call in ctx.call_nodes:
                func = call.func
                if isinstance(func, ast.Name) and func.id == "fsync":
                    recv_name = "fsync"
                elif isinstance(func, ast.Attribute):
                    recv_name = dotted_name(func) or func.attr
                else:
                    continue
                attr = recv_name.rsplit(".", 1)[-1]
                if attr == "sendall" and not bounded:
                    yield finding(
                        THR02, f, call,
                        f"unbounded `{recv_name}(...)` on the `{root}` "
                        f"thread of `{cls.name}`: a peer that stops "
                        "draining blocks this service thread forever "
                        "(the symmetric-sendall deadlock) — bound the "
                        "socket with `settimeout(...)` so a stuck send "
                        "severs instead of wedging")
                elif attr == "recv" and not bounded \
                        and isinstance(func, ast.Attribute) \
                        and (dotted_name(func.value) or "").startswith(
                            ctx.self_name + ".") \
                        and not any(k.arg == "timeout"
                                    for k in call.keywords):
                    yield finding(
                        THR02, f, call,
                        f"unbounded `{recv_name}(...)` on the `{root}` "
                        f"thread of `{cls.name}`: a silent peer parks "
                        "this service thread forever — pass a timeout "
                        "or bound the socket with `settimeout(...)`")
                elif attr == "join" and not call.args \
                        and not call.keywords:
                    yield finding(
                        THR02, f, call,
                        f"`{recv_name}()` with no timeout on the "
                        f"`{root}` thread of `{cls.name}`: Queue.join/"
                        "Thread.join block forever if the counterpart "
                        "wedges — a service thread must not make other "
                        "threads' liveness its own; pass a timeout")
                elif attr == "fsync":
                    yield finding(
                        THR02, f, call,
                        f"`{recv_name}(...)` on the `{root}` thread of "
                        f"`{cls.name}`: a stalled disk parks the "
                        "service loop and wedges every thread that "
                        "joins or flushes against it — move durability "
                        "off the service thread or document why the "
                        "stall is survivable")


THR01 = register(Rule(
    id="THR01", severity=Severity.ERROR,
    summary="attribute crosses thread roots with inconsistent/no lock",
    check=_check_thr01, path_fragments=_THREAD_PATHS))

THR02 = register(Rule(
    id="THR02", severity=Severity.ERROR,
    summary="unbounded blocking call on a service thread root",
    check=_check_thr02, path_fragments=_THREAD_PATHS))
