"""kueueverify — trace-level jaxpr verification (TRC01-04).

The ast engine reasons about source text; this engine reasons about what
the kernels actually lower to. Every registered solver kernel (the
traceable preemption engines from `solver/modes.ENGINES`, the batched
flavor-fit solve, and the topology fit search — the host referee and the
C++ batch scan have no jaxpr and are golden-tested instead) is lowered
with `jax.make_jaxpr` at canonical padded bucket shapes and four rule
families run over the equations:

  TRC01  dtype-promotion hazards: a value widened (i32→i64) only to be
         silently truncated back by a scatter/dynamic-update write (the
         `.at[i].set(int64)` on an int32 buffer pattern), a 64-bit
         literal widening a 32-bit tensor, a ref write whose value dtype
         differs from the ref, a sum that promotes its accumulator —
         the exact bug shapes the PR 2 all-engine goldens caught at
         runtime in the Pallas kernel.
  TRC02  sentinel overflow: interval analysis seeds every input from its
         contract (NO_LIMIT/BIG sentinels are 2^62, real quotas bounded
         by the canonical-unit ceiling) and propagates exact ranges
         through the arithmetic; any add/sub/mul/sum whose result range
         escapes the output dtype can wrap on real inputs and silently
         diverge from the host referee. Packed byte-buffer kernels are
         seeded with their wire layout (jaxpr_tools.Packed) so each
         field's contract survives the slice/bitcast unpack chain, and
         Pallas kernels seed their scratch refs from the roster — every
         packed twin is verified directly, not via an unpacked stand-in.
  TRC03  recompile hazards: the same kernel lowered at two ADJACENT
         head-count buckets must produce structurally equal jaxprs
         (modulo shapes) — the one-XLA-compile-per-bucket contract that
         `prewarm_idle` assumes; a shape-dependent Python branch breaks
         it and lands a compile inside a measured tick.
  TRC04  forbidden effects: no io_callback / pure_callback / debug
         callbacks inside a jitted kernel (each is a host round trip on
         the solve's critical path).

Scope: when the analyzed set contains the package's kernel modules, the
built-in roster below runs; any analyzed file (e.g. a test fixture) may
additionally declare its own kernels via a module-level
`KUEUEVERIFY_KERNELS` manifest — a list of dicts with keys `name`,
`build` (bucket -> (fn, args)), and optionally `buckets`, `rules`,
`seeds`, `scratch_seeds`.
Manifest files are IMPORTED (this engine must execute the trace),
unlike everything the ast/flow engines touch.

jax is imported lazily at rule execution, never at module import.
"""

from __future__ import annotations

import dataclasses
import importlib
import importlib.util
from pathlib import Path
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from kueue_tpu.analysis.core import (
    AnalysisContext, Finding, Rule, Severity, SourceFile, register)

ALL_TRC = frozenset({"TRC01", "TRC02", "TRC03", "TRC04"})

_FORBIDDEN_EFFECTS = {
    "io_callback", "pure_callback", "debug_callback", "callback",
    "debug_print", "host_callback_call", "outside_call",
}


@dataclasses.dataclass
class KernelSpec:
    """One kernel in the verification roster.

    `build(bucket)` returns `(fn, args)`; the kernel is lowered as
    `jax.make_jaxpr(fn)(*args)`. `buckets` are two ADJACENT padded sizes
    of the kernel's dynamic axis (TRC03 compares their jaxprs).
    `seeds` overrides the TRC02 input contract by flat arg position
    (negative positions count from the end); a value is a plain
    `(lo, hi)` interval or a `jaxpr_tools.Packed` wire layout (see
    `jaxpr_tools.packed_layout`) for byte-buffer arguments, and the
    whole mapping may be a callable of the bucket when the layout is
    size-dependent. `scratch_seeds` carries the contract of pallas
    out/scratch refs (indexed from the first body invar past the kernel
    operands — they have no outer argument to seed through). Defaults
    come from the dtype contract — see jaxpr_tools.default_seed.
    `anchor` is the source file the kernel lives in; findings whose
    equations carry no usable traceback anchor there."""

    name: str
    anchor: str
    build: Callable[[int], tuple]
    buckets: Tuple[int, int] = (8, 16)
    rules: frozenset = ALL_TRC
    seeds: object = None  # Dict[int, seed] | Callable[[int], Dict[int, seed]]
    scratch_seeds: Optional[Dict[int, Tuple[int, int]]] = None
    optional: bool = False


# ---------------------------------------------------------------------------
# Built-in roster: the registered solver kernels at canonical padded shapes
# ---------------------------------------------------------------------------


def _module_file(module: str) -> str:
    spec = importlib.util.find_spec(module)
    return spec.origin if spec and spec.origin else module


def _build_scan(n: int):
    import numpy as np

    import kueue_tpu.ops  # noqa: F401  (x64 before tracing)
    from kueue_tpu.ops.preemption_scan import _scan_core

    Y, FR = 8, 16
    z64 = lambda s: np.zeros(s, np.int64)  # noqa: E731
    zb = lambda s: np.zeros(s, bool)  # noqa: E731
    args = (z64((Y, FR)), z64((Y, FR)), zb((Y, FR)), z64((Y, FR)),
            z64(FR), zb(FR), z64(FR), zb(FR), z64(FR), zb(FR),
            np.zeros(n, np.int32), z64((n, FR)), np.zeros(n, np.int32),
            np.ones(n, bool),
            np.asarray(True), np.asarray(True), np.asarray(True),
            np.asarray(True), np.asarray(0, np.int32))
    return _scan_core, args


def _build_batch_packed(b: int):
    import functools

    import numpy as np

    import kueue_tpu.ops  # noqa: F401
    from kueue_tpu.ops.preemption_batch import _packed_batch_kernel

    Y, FR, N = 8, 16, 8
    n64 = (3 * b * Y * FR + 3 * b * FR + b * N * FR) * 8
    n32 = (2 * b * N + b) * 4
    n8 = b * Y * FR + 4 * b * FR + b * N + 3 * b
    buf = np.zeros(n64 + n32 + n8, np.uint8)
    fn = functools.partial(_packed_batch_kernel,
                           shapes=(b, Y, FR, N), lending=True)
    return fn, (buf,)


def _build_pallas(n: int):
    import functools

    import numpy as np

    import kueue_tpu.ops  # noqa: F401
    from kueue_tpu.ops import preemption_pallas as pp

    Y, FR, ypad = 4, 8, 8

    def pad2(a, rows):
        return pp._pad_axis(pp._pad_axis(np.atleast_2d(a), 1, pp.LANES),
                            0, rows)

    z = lambda s: np.zeros(s, np.int32)  # noqa: E731
    scalars = np.asarray([n, 1, 1, 1, 0, 0], dtype=np.int32)
    args = (z(n), z(n), scalars,
            pad2(z((Y, FR)), ypad), pad2(z((Y, FR)), ypad),
            pad2(z((Y, FR)), ypad), pad2(z((Y, FR)), ypad),
            pad2(z(FR), 1), pad2(z(FR), 1), pad2(z(FR), 1),
            pad2(z(FR), 1), pad2(z(FR), 1), pad2(z(FR), 1),
            pp._pad_axis(z((n, FR)), 1, pp.LANES))
    fn = functools.partial(pp._pallas_call, n=n, ypad=ypad, interpret=True)
    return fn, args


def _build_flavor_fit(w: int):
    import functools

    import numpy as np

    import kueue_tpu.ops  # noqa: F401
    from kueue_tpu.models.flavor_fit import solve_core

    C, F, R, G, S, K, P = 4, 4, 3, 2, 2, 3, 2
    z64 = lambda s: np.zeros(s, np.int64)  # noqa: E731
    z32 = lambda s: np.zeros(s, np.int32)  # noqa: E731
    zb = lambda s: np.zeros(s, bool)  # noqa: E731
    args = (z64((C, F, R)), z64((C, F, R)), z64((C, F, R)), z64((C, F, R)),
            z64((K, F, R)), z64((K, F, R)), z32(C),
            z32((C, R)), z32((C, G, S)), z32((C, G)),
            zb(C), zb(C), zb(C),
            z32(w), z64((w, P, R)), zb((w, P, R)),
            zb((w, P)), zb((w, P)), zb((w, P, G, S)), z32((w, P, G)))
    fn = functools.partial(solve_core, num_slots=S)
    return fn, args


def _build_flavor_fit_packed(w: int):
    import functools

    import numpy as np

    import kueue_tpu.ops  # noqa: F401
    from kueue_tpu.models.flavor_fit import _solve_kernel_packed

    C, F, R, G, S, K, P = 4, 4, 3, 2, 2, 3, 2
    z64 = lambda s: np.zeros(s, np.int64)  # noqa: E731
    z32 = lambda s: np.zeros(s, np.int32)  # noqa: E731
    zb = lambda s: np.zeros(s, bool)  # noqa: E731
    nb = ((C * F * R + w * P * R) * 8 + (w + w * P * G) * 4
          + w * P * R + 2 * w * P + w * P * G * S)
    statics = (z64((C, F, R)), z64((C, F, R)), z64((C, F, R)),
               z64((C, F, R)), z32(C), z32((C, R)), z32((C, G, S)),
               z32((C, G)), zb(C), zb(C), zb(C))
    fn = functools.partial(_solve_kernel_packed, num_slots=S,
                           shapes=(w, P, R, G, K), fungibility_enabled=True)
    return fn, statics + (None, np.zeros(nb, np.uint8))


def _build_cohort_shard(w: int):
    import functools

    import numpy as np

    import kueue_tpu.ops  # noqa: F401
    from kueue_tpu.parallel.mesh import shard_solve_body

    C, F, R, G, S, K, P = 4, 4, 3, 2, 2, 3, 2
    z64 = lambda s: np.zeros(s, np.int64)  # noqa: E731
    z32 = lambda s: np.zeros(s, np.int32)  # noqa: E731
    zb = lambda s: np.zeros(s, bool)  # noqa: E731
    args = (z64((C, F, R)), z64((C, F, R)), z64((C, F, R)), z64((C, F, R)),
            z32(C), z32((C, R)), z32((C, G, S)), z32((C, G)),
            zb(C), zb(C), zb(C),
            None, z64((C, F, R)),
            z32(w), z64((w, P, R)), zb((w, P, R)),
            zb((w, P)), zb((w, P)), zb((w, P, G, S)), z32((w, P, G)))
    fn = functools.partial(shard_solve_body, num_slots=S, num_cohorts=K,
                           fungibility_enabled=True)
    return fn, args


def _build_flavor_fit_hier(w: int):
    """solve_core with the KEP-79 cohort-forest pytree: the ancestor-path
    T-invariant walk is a materially different jaxpr from the flat-pool
    arithmetic, so it gets its own roster entry (the carried-over "hier
    solve_core in the trace roster" ROADMAP item)."""
    import functools

    import numpy as np

    import kueue_tpu.ops  # noqa: F401
    from kueue_tpu.models.flavor_fit import solve_core

    import jax.numpy as jnp

    C, F, R, G, S, K, P, K2, D = 4, 4, 3, 2, 2, 3, 2, 3, 2
    z64 = lambda s: np.zeros(s, np.int64)  # noqa: E731
    z32 = lambda s: np.zeros(s, np.int32)  # noqa: E731
    zb = lambda s: np.zeros(s, bool)  # noqa: E731
    # One tree level (node 1,2 -> parent 0), every CQ hierarchical. The
    # forest rides in as closure constants (like device_static's pytree),
    # so the tensors must be jax arrays — tracers index them.
    hier = tuple(jnp.asarray(x) for x in (
        z64((K2, F, R)), z64((K2, F, R)), z64((K2, F, R)),
        z32(C), z64((C, F, R)), np.ones(C, bool),
        np.zeros((C, D), np.int32))) + (
        ((jnp.asarray(np.array([1, 2], np.int32)),
          jnp.asarray(np.array([0, 0], np.int32))),),)
    args = (z64((C, F, R)), z64((C, F, R)), z64((C, F, R)), z64((C, F, R)),
            z64((K, F, R)), z64((K, F, R)), z32(C),
            z32((C, R)), z32((C, G, S)), z32((C, G)),
            zb(C), zb(C), zb(C),
            z32(w), z64((w, P, R)), zb((w, P, R)),
            zb((w, P)), zb((w, P)), zb((w, P, G, S)), z32((w, P, G)))
    fn = functools.partial(solve_core, num_slots=S, hier=hier)
    return fn, args


def _build_flavor_fit_hetero(w: int):
    """solve_core with the hetero score override (the `hetero` solve
    mode's rounding jaxpr — argmax over FIT slots plus the first-fit
    twin output)."""
    import functools

    import numpy as np

    import kueue_tpu.ops  # noqa: F401
    from kueue_tpu.models.flavor_fit import solve_core

    C, F, R, G, S, K, P = 4, 4, 3, 2, 2, 3, 2
    z64 = lambda s: np.zeros(s, np.int64)  # noqa: E731
    z32 = lambda s: np.zeros(s, np.int32)  # noqa: E731
    zb = lambda s: np.zeros(s, bool)  # noqa: E731
    args = (z64((C, F, R)), z64((C, F, R)), z64((C, F, R)), z64((C, F, R)),
            z64((K, F, R)), z64((K, F, R)), z32(C),
            z32((C, R)), z32((C, G, S)), z32((C, G)),
            zb(C), zb(C), zb(C),
            z32(w), z64((w, P, R)), zb((w, P, R)),
            zb((w, P)), zb((w, P)), zb((w, P, G, S)), z32((w, P, G)),
            (z64((w, F)), zb(w)))
    fn = functools.partial(
        lambda *a, hetero=None, **kw: solve_core(
            *a[:-1], hetero=a[-1], **kw), num_slots=S)
    return fn, args


def _build_hetero_scores(n: int):
    """The Gavel projected dual iteration (kueue_tpu/hetero/solve.py)."""
    import functools

    import numpy as np

    import kueue_tpu.ops  # noqa: F401
    from kueue_tpu.hetero.solve import hetero_scores_core

    F = 8
    args = (np.zeros((n, F), np.int64), np.zeros(n, np.int64),
            np.zeros(n, bool), np.zeros(F, np.int64))
    fn = functools.partial(hetero_scores_core, iters=4)
    return fn, args


def _build_topology(n: int):
    import functools

    import numpy as np

    import kueue_tpu.ops  # noqa: F401
    from kueue_tpu.topology.fit import solve_topology_core

    T, L, E, D = 2, 2, 8, 4
    args = (np.zeros((T, E), np.int64), np.zeros((T, E), bool),
            np.zeros((T, L, E), np.int32), np.zeros((T, L), np.int32),
            np.full(T, L, np.int32), np.zeros((T, E), np.int64),
            np.zeros(n, np.int32), np.zeros(n, np.int64),
            np.zeros(n, np.int32), np.zeros(n, bool), np.zeros(n, bool))
    fn = functools.partial(solve_topology_core, shapes=(T, L, E, D, n))
    return fn, args


# ---------------------------------------------------------------------------
# TRC02 input contracts for the packed byte-buffer kernels
# ---------------------------------------------------------------------------

# Interval vocabulary of the solver schema (solver/schema.py): quota
# tensors may carry the NO_LIMIT/BIG = 2^62 sentinel; every real
# quantity is a canonical-unit integer far inside its dtype.
_SENTINEL = (0, 2**62)
_CANON64 = (-(2**50), 2**50)
_CANON32 = (-(2**28), 2**28)
_BOOLEAN = (0, 1)


def _batch_packed_seeds(b: int) -> Dict[int, object]:
    """Wire layout of the batch-packed-XLA one-transfer buffer (the
    unpack chain at the top of `_packed_batch_kernel`): the int64 plane
    (usage0, nominal, guaranteed, wl_req, blim, requestable, cand_use —
    nominal and blim carry the NO_LIMIT/BIG sentinel), the int32 plane
    (cand_y, cand_prio, threshold), and the byte plane of bool masks."""
    from kueue_tpu.analysis import jaxpr_tools as jt

    Y, FR, N = 8, 16, 8
    fields = [
        (b * Y * FR, 8, _CANON64),    # usage0
        (b * Y * FR, 8, _SENTINEL),   # nominal
        (b * Y * FR, 8, _CANON64),    # guaranteed
        (b * FR, 8, _CANON64),        # wl_req
        (b * FR, 8, _SENTINEL),       # blim
        (b * FR, 8, _CANON64),        # requestable
        (b * N * FR, 8, _CANON64),    # cand_use
        (b * N, 4, _CANON32),         # cand_y
        (b * N, 4, _CANON32),         # cand_prio
        (b, 4, _CANON32),             # threshold
        (b * Y * FR + 4 * b * FR + b * N + 3 * b, 1, _BOOLEAN),  # masks
    ]
    return {0: jt.packed_layout(fields)}


def _flavor_fit_packed_seeds(w: int) -> Dict[int, object]:
    """Wire layout of the flavor-fit one-transfer buffer (the unpack at
    the top of `_solve_kernel_packed`): i64 usage + requests, i32 cq
    index + resume slots, u8 masks. The buffer is the LAST flat
    argument; the borrow_limit static (position 1) carries the quota
    sentinel."""
    from kueue_tpu.analysis import jaxpr_tools as jt

    C, F, R, G, S, P = 4, 4, 3, 2, 2, 2
    fields = [
        (C * F * R, 8, _CANON64),      # usage
        (w * P * R, 8, _CANON64),      # req
        (w, 4, _CANON32),              # wl_cq
        (w * P * G, 4, _CANON32),      # resume_slot
        (w * P * R, 1, _BOOLEAN),      # has_req
        (w * P, 1, _BOOLEAN),          # podset_valid
        (w * P, 1, _BOOLEAN),          # podset_unsat
        (w * P * G * S, 1, _BOOLEAN),  # elig
    ]
    return {1: _SENTINEL, -1: jt.packed_layout(fields)}


# The Pallas int32 twin runs AFTER `_rescale_int32`: every real quantity
# is proven < (2^31 - 1) / (ypad + 2) before dispatch (ypad = 8 at the
# roster shape — fits_now folds ypad usage rows, the lending credit and
# the request into one int32 sum) and nominal/blim carry I32_SENTINEL
# (2^30) for "no limit".
_PALLAS_BOUND = (2**31 - 1) // 10

_PALLAS_SEEDS = {
    0: (0, 7),                  # cand_y: padded row index < ypad
    1: (-(2**31), 2**31 - 1),   # cand_prio: raw int32 priority
    2: (-(2**15), 2**15),       # scalars (n, mode flags, threshold)
    3: (0, _PALLAS_BOUND),      # usage0
    4: (0, 2**30),              # nominal (I32_SENTINEL for no-limit)
    5: (0, 1),                  # q_def
    6: (0, _PALLAS_BOUND),      # guaranteed
    7: (0, _PALLAS_BOUND),      # wl_req
    8: (0, 1),                  # wl_req_mask
    9: (0, 2**30),              # blim (I32_SENTINEL for no-limit)
    10: (0, 1),                 # blim_def
    11: (0, _PALLAS_BOUND),     # requestable
    12: (0, 1),                 # res_mask
    13: (0, _PALLAS_BOUND),     # cand_use
}
_PALLAS_SCRATCH = {
    2: (0, _PALLAS_BOUND),      # U: usage working copy (clamped writes)
    3: (0, 3),                  # taken: per-candidate verdict enum
    4: (-(2**16), 2**16),       # flags: loop bookkeeping scalars
}


def package_roster() -> List[KernelSpec]:
    """The built-in kernel roster. Preemption engines come from the
    `solver/modes.ENGINES` registry (every `traceable` engine MUST appear
    here — tests/test_engine_coverage.py enforces it); the flavor-fit and
    topology entry points ride along with the same contract.

    TRC02 seeds (by arg position): the nominal/borrow-limit tensors carry
    the NO_LIMIT/BIG = 2^62 sentinel from solver/schema.py; everything
    else defaults to the canonical-unit contract."""
    sentinel = (0, 2**62)
    return [
        KernelSpec(
            name="scan-jax",
            anchor=_module_file("kueue_tpu.ops.preemption_scan"),
            build=_build_scan, buckets=(8, 16),
            seeds={1: sentinel, 6: sentinel}),
        KernelSpec(
            # The whole dynamic side arrives as one byte buffer; the
            # bitcast-aware Packed domain carries the per-field contract
            # through the unpack chain, so TRC02 runs on the packed
            # kernel itself (not an unpacked stand-in).
            name="batch-jax",
            anchor=_module_file("kueue_tpu.ops.preemption_batch"),
            build=_build_batch_packed, buckets=(4, 8),
            seeds=_batch_packed_seeds),
        KernelSpec(
            name="scan-pallas",
            anchor=_module_file("kueue_tpu.ops.preemption_pallas"),
            build=_build_pallas, buckets=(4, 8),
            seeds=_PALLAS_SEEDS, scratch_seeds=_PALLAS_SCRATCH,
            optional=True),
        KernelSpec(
            name="flavor-fit",
            anchor=_module_file("kueue_tpu.models.flavor_fit"),
            build=_build_flavor_fit, buckets=(8, 16),
            seeds={1: sentinel}),
        KernelSpec(
            name="flavor-fit-packed",
            anchor=_module_file("kueue_tpu.models.flavor_fit"),
            build=_build_flavor_fit_packed, buckets=(8, 16),
            seeds=_flavor_fit_packed_seeds),
        KernelSpec(
            name="flavor-fit-hier",
            anchor=_module_file("kueue_tpu.models.flavor_fit"),
            build=_build_flavor_fit_hier, buckets=(8, 16),
            seeds={1: sentinel}),
        KernelSpec(
            # The hetero solve mode's rounding variant of solve_core
            # (score argmax over FIT slots + the first-fit twin output).
            name="flavor-fit-hetero",
            anchor=_module_file("kueue_tpu.models.flavor_fit"),
            build=_build_flavor_fit_hetero, buckets=(8, 16),
            seeds={1: sentinel}),
        KernelSpec(
            # The Gavel score iteration (all-integer dual tatonnement);
            # capacity sums nominal quotas, so it carries the sentinel.
            name="hetero-scores",
            anchor=_module_file("kueue_tpu.hetero.solve"),
            build=_build_hetero_scores, buckets=(8, 16),
            seeds={3: sentinel}),
        KernelSpec(
            # The cohort-sharded per-shard body (parallel/mesh): one
            # shard's compacted block at its per-shard padded bucket —
            # TRC03 across its buckets pins the one-compile-per-bucket
            # contract PER SHARD, and tests/test_shard.py additionally
            # pins that the lowered body is shard-count-independent.
            name="cohort-shard-solve",
            anchor=_module_file("kueue_tpu.parallel.mesh"),
            build=_build_cohort_shard, buckets=(8, 16),
            seeds={1: sentinel}),
        KernelSpec(
            name="topology-fit",
            anchor=_module_file("kueue_tpu.topology.fit"),
            build=_build_topology, buckets=(8, 16)),
    ]


# ---------------------------------------------------------------------------
# Manifest kernels (fixtures/tests)
# ---------------------------------------------------------------------------

_MANIFEST = "KUEUEVERIFY_KERNELS"
_manifest_seq = [0]


def _manifest_specs(f: SourceFile) -> Tuple[List[KernelSpec], Optional[str]]:
    """Import an analyzed file that declares KUEUEVERIFY_KERNELS and read
    its kernel manifest. Returns (specs, import_error)."""
    _manifest_seq[0] += 1
    name = f"_kueueverify_manifest_{_manifest_seq[0]}"
    try:
        spec = importlib.util.spec_from_file_location(name, str(f.path))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
    except Exception as exc:  # a broken manifest is itself a finding
        return [], f"{type(exc).__name__}: {exc}"
    out = []
    for entry in getattr(mod, _MANIFEST, []):
        out.append(KernelSpec(
            name=entry["name"],
            anchor=str(f.path),
            build=entry["build"],
            buckets=tuple(entry.get("buckets", (8, 16))),
            rules=frozenset(entry.get("rules", ALL_TRC)),
            seeds=entry.get("seeds"),
            scratch_seeds=entry.get("scratch_seeds")))
    return out, None


# ---------------------------------------------------------------------------
# Lowering + shared per-context cache
# ---------------------------------------------------------------------------


def _find_source(ctx: AnalysisContext, path: str) -> Optional[SourceFile]:
    try:
        resolved = Path(path).resolve()
    except OSError:
        return None
    cache = getattr(ctx, "_resolved_paths", None)
    if cache is None:
        cache = {}
        for f in ctx.files:
            try:
                cache[f.path.resolve()] = f
            except OSError:
                continue
        ctx._resolved_paths = cache
    return cache.get(resolved)


def _finding(ctx: AnalysisContext, spec: KernelSpec, rule_id: str,
             severity: Severity, loc: Optional[Tuple[str, int]],
             message: str) -> Finding:
    src = _find_source(ctx, loc[0]) if loc else None
    if src is None:
        src = _find_source(ctx, spec.anchor)
    if src is not None:
        path = src.display_path
        line = loc[1] if loc and _find_source(ctx, loc[0]) is src else 1
    else:
        path, line = (loc if loc else (spec.anchor, 1))
    return Finding(rule=rule_id, severity=severity, path=path,
                   line=line, col=0,
                   message=f"[{spec.name}] {message}")


def _active_specs(ctx: AnalysisContext) -> Tuple[List[KernelSpec],
                                                 List[Finding]]:
    """Roster for this analysis run: package kernels whose source file is
    in the analyzed set, plus manifests declared by analyzed files."""
    specs: List[KernelSpec] = []
    findings: List[Finding] = []
    for spec in package_roster():
        if _find_source(ctx, spec.anchor) is not None:
            specs.append(spec)
    for f in ctx.files:
        if f.tree is None or _MANIFEST not in f.text:
            continue
        declares = any(
            getattr(t, "id", None) == _MANIFEST
            for node in f.tree.body if hasattr(node, "targets")
            for t in node.targets)
        if not declares:
            continue
        manifest, err = _manifest_specs(f)
        if err is not None:
            findings.append(Finding(
                rule="PARSE", severity=Severity.ERROR,
                path=f.display_path, line=1, col=0,
                message=f"kernel manifest failed to import: {err}"))
        specs.extend(manifest)
    return specs, findings


def _lower(spec: KernelSpec) -> Dict[int, object]:
    import warnings

    import jax

    out = {}
    for bucket in spec.buckets:
        fn, args = spec.build(bucket)
        with warnings.catch_warnings():
            # The code under analysis may (deliberately, in bad fixtures)
            # trip jax's own deprecation/cast warnings; the analyzer
            # reports findings, not the tracee's warning stream.
            warnings.simplefilter("ignore")
            out[bucket] = jax.make_jaxpr(fn)(*args)
    return out


def _trace_findings(ctx: AnalysisContext) -> Dict[str, List[Finding]]:
    """Lower every active kernel once and run all TRC rules; memoized on
    the context so the four registered rules share one lowering pass."""
    cached = getattr(ctx, "_trace_findings", None)
    if cached is not None:
        return cached
    out: Dict[str, List[Finding]] = {
        "TRC01": [], "TRC02": [], "TRC03": [], "TRC04": [], "PARSE": []}
    specs, manifest_errors = _active_specs(ctx)
    out["PARSE"].extend(manifest_errors)
    for spec in specs:
        try:
            jaxprs = _lower(spec)
        except ImportError:
            if spec.optional:
                continue
            raise
        except Exception as exc:
            out["PARSE"].append(_finding(
                ctx, spec, "PARSE", Severity.ERROR, None,
                f"kernel failed to lower: {type(exc).__name__}: {exc}"))
            continue
        first = jaxprs[spec.buckets[0]]
        if "TRC01" in spec.rules:
            out["TRC01"].extend(_check_trc01(ctx, spec, first))
        if "TRC02" in spec.rules:
            out["TRC02"].extend(
                _check_trc02(ctx, spec, first, spec.buckets[0]))
        if "TRC03" in spec.rules:
            out["TRC03"].extend(_check_trc03(ctx, spec, jaxprs))
        if "TRC04" in spec.rules:
            out["TRC04"].extend(_check_trc04(ctx, spec, first))
    for rule_id, findings in out.items():
        # One source line can emit the same hazard from several lowering
        # contexts (e.g. a helper inlined into both scan phases) — report
        # each (line, message) once.
        seen = set()
        deduped = []
        for fin in findings:
            key = (fin.path, fin.line, fin.message)
            if key not in seen:
                seen.add(key)
                deduped.append(fin)
        out[rule_id] = deduped
    ctx._trace_findings = out
    return out


# ---------------------------------------------------------------------------
# TRC01 — dtype-promotion hazards
# ---------------------------------------------------------------------------


def _int_bits(aval) -> Optional[int]:
    import numpy as np

    dtype = getattr(aval, "dtype", None)
    if dtype is None:
        return None
    try:
        if np.issubdtype(dtype, np.bool_):
            return None
        if np.issubdtype(dtype, np.integer):
            return np.iinfo(dtype).bits
    except Exception:
        pass
    return None


def _check_trc01(ctx, spec, closed) -> List[Finding]:
    from jax.core import Literal

    from kueue_tpu.analysis import jaxpr_tools as jt

    findings: List[Finding] = []

    def emit(eqn, msg):
        findings.append(_finding(ctx, spec, "TRC01", Severity.ERROR,
                                 jt.eqn_location(eqn), msg))

    def walk(jaxpr):
        producers = {}
        for eqn in jaxpr.eqns:
            for v in eqn.outvars:
                producers[v] = eqn

        def widening_convert(v):
            """The producing convert_element_type when `v` is an integer
            widened from a narrower integer (not bool)."""
            src = producers.get(v)
            if src is None or src.primitive.name != "convert_element_type":
                return None
            bi = _int_bits(src.invars[0].aval)
            bo = _int_bits(src.outvars[0].aval)
            if bi is not None and bo is not None and bo > bi:
                return src
            return None

        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            if prim == "convert_element_type":
                # Narrowing write-back: i64 scatter/dus result cast down to
                # the original i32 — the `.at[i].set(int64)` silent cast.
                bi = _int_bits(eqn.invars[0].aval)
                bo = _int_bits(eqn.outvars[0].aval)
                src = producers.get(eqn.invars[0])
                if (bi is not None and bo is not None and bo < bi
                        and src is not None
                        and (src.primitive.name.startswith("scatter")
                             or src.primitive.name == "dynamic_update_slice")
                        and widening_convert(src.invars[0]) is not None):
                    emit(src, f"mixed-dtype write: int{bi} value stored "
                              f"into an int{bo} buffer and silently cast "
                              "back — pin the stored value's dtype "
                              "(the PR 2 Pallas weak-int64 write shape)")
            elif prim in ("add", "sub", "mul", "max", "min"):
                for i, v in enumerate(eqn.invars):
                    if isinstance(v, Literal):
                        continue
                    conv = widening_convert(v)
                    if conv is None:
                        continue
                    other = eqn.invars[1 - i]
                    if isinstance(other, Literal):
                        bo = _int_bits(eqn.outvars[0].aval)
                        bi = _int_bits(conv.invars[0].aval)
                        emit(eqn, f"int{bi} tensor widened to int{bo} by a "
                                  f"{bo}-bit literal in `{prim}` — pin the "
                                  "literal's dtype to the tensor's (weak-"
                                  "literal promotion recompiles and breaks "
                                  "int32-pinned kernels)")
            elif prim == "swap":
                ref_bits = _int_bits(eqn.invars[0].aval)
                val_bits = _int_bits(eqn.invars[1].aval)
                if ref_bits is not None and val_bits is not None \
                        and ref_bits != val_bits:
                    emit(eqn, f"ref write dtype mismatch: int{val_bits} "
                              f"value into an int{ref_bits} ref — the "
                              "Pallas discharge rejects or truncates "
                              "mixed-dtype stores")
            elif prim in ("reduce_sum", "cumsum"):
                bi = _int_bits(eqn.invars[0].aval)
                bo = _int_bits(eqn.outvars[0].aval)
                if bi is not None and bo is not None and bo > bi:
                    emit(eqn, f"sum promotes int{bi} to int{bo} — pin the "
                              "accumulator dtype (int64 sum promotion "
                              "broke the Pallas interpret discharge)")
            for sub in jt.sub_jaxprs(eqn):
                walk(sub)

    walk(closed.jaxpr)
    return findings


# ---------------------------------------------------------------------------
# TRC02 — sentinel/interval overflow
# ---------------------------------------------------------------------------


def _check_trc02(ctx, spec, closed, bucket: int) -> List[Finding]:
    from kueue_tpu.analysis import jaxpr_tools as jt

    findings: List[Finding] = []

    def on_overflow(o: jt.Overflow):
        findings.append(_finding(
            ctx, spec, "TRC02", Severity.ERROR, o.location,
            f"`{o.prim}` result range [{o.lo}, {o.hi}] exceeds {o.dtype} "
            "— can wrap on sentinel-carrying inputs (NO_LIMIT/BIG = 2^62) "
            "and silently diverge from the host referee; rewrite to avoid "
            "the overflowing intermediate (e.g. compare via subtraction)"))

    raw = spec.seeds(bucket) if callable(spec.seeds) else (spec.seeds or {})
    n_args = len(closed.jaxpr.invars)
    seeds = {(k if k >= 0 else n_args + k): v for k, v in raw.items()}
    arg_ivs = []
    for i, v in enumerate(closed.jaxpr.invars):
        if i in seeds:
            s = seeds[i]
            if isinstance(s, (jt.Interval, jt.Packed)):
                arg_ivs.append(s)
            else:
                lo, hi = s
                arg_ivs.append(jt.Interval(lo, hi))
        else:
            arg_ivs.append(jt.default_seed(v.aval))
    const_ivs = []
    for v, val in zip(closed.jaxpr.constvars, closed.consts):
        try:
            import numpy as np

            arr = np.asarray(val)
            if arr.dtype.kind in "iub" and arr.size:
                const_ivs.append(jt.Interval(int(arr.min()), int(arr.max())))
            else:
                const_ivs.append(jt.UNKNOWN)
        except Exception:
            const_ivs.append(jt.UNKNOWN)
    analysis = jt.IntervalAnalysis(on_overflow)
    if spec.scratch_seeds:
        analysis._scratch_seeds = dict(spec.scratch_seeds)
    analysis.run(closed.jaxpr, const_ivs, arg_ivs)
    return findings


# ---------------------------------------------------------------------------
# TRC03 — one compile per bucket
# ---------------------------------------------------------------------------


def bucket_report(specs: Optional[Sequence[KernelSpec]] = None) -> List[dict]:
    """Lower every roster kernel at both buckets and report structural
    equality — the data behind TRC03, exposed for the regression tests
    that pin the one-compile-per-bucket contract per engine."""
    from kueue_tpu.analysis import jaxpr_tools as jt

    out = []
    for spec in (package_roster() if specs is None else specs):
        try:
            jaxprs = _lower(spec)
        except ImportError:
            if spec.optional:
                continue
            raise
        a, b = (jt.structural_signature(jaxprs[n].jaxpr)
                for n in spec.buckets)
        out.append({"kernel": spec.name, "buckets": spec.buckets,
                    "equal": a == b,
                    "divergence": jt.first_divergence(a, b)})
    return out


def _check_trc03(ctx, spec, jaxprs) -> List[Finding]:
    from kueue_tpu.analysis import jaxpr_tools as jt

    b0, b1 = spec.buckets
    sig0 = jt.structural_signature(jaxprs[b0].jaxpr)
    sig1 = jt.structural_signature(jaxprs[b1].jaxpr)
    div = jt.first_divergence(sig0, sig1)
    if div is None:
        return []
    return [_finding(
        ctx, spec, "TRC03", Severity.ERROR, None,
        f"jaxpr structure differs between adjacent buckets {b0} and {b1} "
        f"({div[1]}) — the trace takes a shape-dependent Python path, so "
        "a bucket rotation compiles a DIFFERENT program and prewarm_idle's "
        "one-compile-per-bucket contract is void")]


# ---------------------------------------------------------------------------
# TRC04 — forbidden effects
# ---------------------------------------------------------------------------


def _check_trc04(ctx, spec, closed) -> List[Finding]:
    from kueue_tpu.analysis import jaxpr_tools as jt

    findings = []
    for eqn in jt.iter_eqns(closed.jaxpr):
        if eqn.primitive.name in _FORBIDDEN_EFFECTS:
            findings.append(_finding(
                ctx, spec, "TRC04", Severity.ERROR, jt.eqn_location(eqn),
                f"forbidden effect `{eqn.primitive.name}` inside a jitted "
                "kernel — every callback is a host round trip on the "
                "solve's critical path (and breaks AOT/serialization)"))
    return findings


# ---------------------------------------------------------------------------
# Registration
# ---------------------------------------------------------------------------


def _rule_check(rule_id: str):
    def check(ctx: AnalysisContext):
        found = _trace_findings(ctx)
        # Lowering failures ride along with EVERY trace rule: a roster
        # kernel that no longer lowers must fail the run even under
        # `--select TRC02` / `--disable TRC01` (the driver dedupes the
        # identical findings when several TRC rules run).
        return list(found[rule_id]) + list(found["PARSE"])
    return check


TRC01 = register(Rule(
    id="TRC01", severity=Severity.ERROR,
    summary="jaxpr dtype-promotion hazard (mixed-dtype write, weak-literal "
            "widening, promoted sum)",
    check=_rule_check("TRC01"), project=True, engine="trace"))

TRC02 = register(Rule(
    id="TRC02", severity=Severity.ERROR,
    summary="sentinel overflow: interval analysis proves an arithmetic "
            "result can escape its dtype",
    check=_rule_check("TRC02"), project=True, engine="trace"))

TRC03 = register(Rule(
    id="TRC03", severity=Severity.ERROR,
    summary="recompile hazard: jaxpr structure differs across adjacent "
            "head-count buckets",
    check=_rule_check("TRC03"), project=True, engine="trace"))

TRC04 = register(Rule(
    id="TRC04", severity=Severity.ERROR,
    summary="forbidden effect (io/pure/debug callback) in a jitted kernel",
    check=_rule_check("TRC04"), project=True, engine="trace"))
