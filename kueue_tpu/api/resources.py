"""Integer resource arithmetic.

All quota math in the framework is integer, in canonical units per resource
(reference: pkg/workload/workload.go:245-296):

  * ``cpu``               -> milliCPU
  * everything else       -> absolute units (bytes for memory, count for pods/GPUs)

Quantities may be given as Kubernetes-style strings ("500m", "10Gi", "2k"),
ints, or floats; they are converted once at the API boundary and never again.
"""

from __future__ import annotations

import math
import re
from typing import Union

Quantity = Union[int, float, str]

CPU = "cpu"
MEMORY = "memory"
PODS = "pods"
EPHEMERAL_STORAGE = "ephemeral-storage"

_BINARY_SUFFIXES = {
    "Ki": 1024,
    "Mi": 1024**2,
    "Gi": 1024**3,
    "Ti": 1024**4,
    "Pi": 1024**5,
    "Ei": 1024**6,
}
_DECIMAL_SUFFIXES = {
    "n": 10**-9,
    "u": 10**-6,
    "m": 10**-3,
    "": 1,
    "k": 10**3,
    "M": 10**6,
    "G": 10**9,
    "T": 10**12,
    "P": 10**15,
    "E": 10**18,
}

_QUANTITY_RE = re.compile(
    r"^(?P<sign>[+-]?)(?P<num>\d+(?:\.\d*)?|\.\d+)(?P<suffix>Ki|Mi|Gi|Ti|Pi|Ei|[numkMGTPE]?)$"
)


def parse_quantity(q: Quantity) -> float:
    """Parse a Kubernetes-style quantity into a plain number of base units."""
    if isinstance(q, (int, float)):
        return float(q)
    s = q.strip()
    m = _QUANTITY_RE.match(s)
    if not m:
        raise ValueError(f"invalid quantity {q!r}")
    num = float(m.group("num"))
    suffix = m.group("suffix")
    if suffix in _BINARY_SUFFIXES:
        mult = _BINARY_SUFFIXES[suffix]
    else:
        mult = _DECIMAL_SUFFIXES[suffix]
    val = num * mult
    if m.group("sign") == "-":
        val = -val
    return val


def resource_value(name: str, q: Quantity) -> int:
    """Integer value of a quantity for a resource: milli-units for cpu,
    absolute (rounded-up) units for everything else.

    Mirrors workload.ResourceValue (reference: pkg/workload/workload.go:263-269).
    """
    v = parse_quantity(q)
    if name == CPU:
        return int(math.ceil(v * 1000))
    return int(math.ceil(v))


def format_quantity(name: str, v: int) -> str:
    """Human-readable rendering of an integer resource value (for messages)."""
    if name == CPU:
        if v % 1000 == 0:
            return str(v // 1000)
        return f"{v}m"
    if name in (MEMORY, EPHEMERAL_STORAGE) or name.startswith("hugepages-"):
        for suffix in ("Ei", "Pi", "Ti", "Gi", "Mi", "Ki"):
            unit = _BINARY_SUFFIXES[suffix]
            if v != 0 and v % unit == 0:
                return f"{v // unit}{suffix}"
    return str(v)
