"""Decode reference-format CRD manifests into the object model.

The reference's API surface is YAML applied to the apiserver
(config/components/crd/bases/, examples/). This module is the equivalent
boundary for the embedded runtime: `decode(doc)` turns one
kueue.x-k8s.io/v1beta1 document (or a batch/v1 Job with the queue-name
label) into the corresponding kueue_tpu object, and `load_manifests(path)`
reads a multi-document YAML file, so reference example files like
examples/admin/single-clusterqueue-setup.yaml work unchanged.
"""

from __future__ import annotations

import copy
import itertools
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

from kueue_tpu.api.resources import resource_value
from kueue_tpu.api.types import (
    AdmissionCheck,
    BorrowWithinCohort,
    ClusterQueue,
    ClusterQueuePreemption,
    Container,
    FairSharing,
    FlavorFungibility,
    FlavorQuotas,
    LabelSelector,
    LocalQueue,
    MatchExpression,
    PodSet,
    PodTemplate,
    ResourceFlavor,
    ResourceGroup,
    ResourceQuota,
    Taint,
    Toleration,
    Workload,
    WorkloadPriorityClass,
)

QUEUE_NAME_LABEL = "kueue.x-k8s.io/queue-name"


class DecodeError(ValueError):
    pass


_generated_names = itertools.count(1)


def _meta(doc: Mapping[str, Any]) -> Tuple[str, str]:
    meta = doc.get("metadata") or {}
    name = meta.get("name")
    if not name:
        # metadata.generateName: the apiserver appends a random suffix
        # (the reference's sample manifests use it, e.g.
        # examples/jobs/sample-job.yaml); a monotonic suffix keeps decoded
        # object names deterministic in-process.
        prefix = meta.get("generateName")
        if prefix:
            return f"{prefix}{next(_generated_names):05d}", \
                meta.get("namespace", "default")
        raise DecodeError(f"{doc.get('kind', '?')}: metadata.name or "
                          "metadata.generateName is required")
    return name, meta.get("namespace", "default")


def _match_expressions(exprs: Optional[Sequence[Mapping]]) -> Tuple[MatchExpression, ...]:
    out = []
    for e in exprs or ():
        out.append(MatchExpression(key=e["key"], operator=e["operator"],
                                   values=tuple(e.get("values") or ())))
    return tuple(out)


def _label_selector(sel: Optional[Mapping[str, Any]]) -> LabelSelector:
    if sel is None:
        return LabelSelector.everything()
    return LabelSelector(
        match_labels=tuple(sorted((sel.get("matchLabels") or {}).items())),
        match_expressions=_match_expressions(sel.get("matchExpressions")))


def _tolerations(tols: Optional[Sequence[Mapping]]) -> Tuple[Toleration, ...]:
    out = []
    for t in tols or ():
        out.append(Toleration(
            key=t.get("key", ""), operator=t.get("operator", "Equal"),
            value=t.get("value", ""), effect=t.get("effect", "")))
    return tuple(out)


def _taints(taints: Optional[Sequence[Mapping]]) -> Tuple[Taint, ...]:
    return tuple(Taint(key=t["key"], value=t.get("value", ""),
                       effect=t.get("effect", ""))
                 for t in taints or ())


def _requests(doc: Optional[Mapping[str, Any]]) -> Dict[str, int]:
    return {r: resource_value(r, q) for r, q in (doc or {}).items()}


def _containers(docs: Optional[Sequence[Mapping]]) -> List[Container]:
    out = []
    for c in docs or ():
        res = c.get("resources") or {}
        out.append(Container(name=c.get("name", ""),
                             requests=_requests(res.get("requests")),
                             limits=_requests(res.get("limits"))))
    return out


def _pod_template(doc: Optional[Mapping[str, Any]]) -> Optional[PodTemplate]:
    if doc is None:
        return None
    spec = doc.get("spec") or doc
    return PodTemplate(
        containers=_containers(spec.get("containers")),
        init_containers=_containers(spec.get("initContainers")),
        overhead=_requests(spec.get("overhead")),
        runtime_class_name=spec.get("runtimeClassName"))


def _node_affinity_terms(spec: Mapping[str, Any]) -> Tuple[Tuple[MatchExpression, ...], ...]:
    """requiredDuringSchedulingIgnoredDuringExecution terms (the subset the
    flavor selector replicates, flavorassigner.go:498-542)."""
    affinity = ((spec.get("affinity") or {}).get("nodeAffinity") or {})
    required = affinity.get("requiredDuringSchedulingIgnoredDuringExecution") or {}
    return tuple(_match_expressions(t.get("matchExpressions"))
                 for t in required.get("nodeSelectorTerms") or ())


# -- kueue kinds -------------------------------------------------------------

def _topology_spec(doc: Optional[Mapping[str, Any]]):
    if doc is None:
        return None
    from kueue_tpu.api.types import TopologyLeaf, TopologySpec
    return TopologySpec(
        levels=tuple(doc.get("levels") or ()),
        leaves=tuple(TopologyLeaf(path=tuple(l.get("path") or ()),
                                  capacity=int(l.get("capacity", 1)))
                     for l in doc.get("leaves") or ()))


def _throughput_value(raw: Any, path: str) -> float:
    """Decoder hardening for throughput numbers (hetero scheduling): a
    NaN/inf/negative value would poison the dense score matrix (every
    comparison against NaN is False — the solve would silently fall back
    to slot 0), so malformed manifests are rejected at the boundary."""
    import math
    try:
        val = float(raw)
    except (TypeError, ValueError):
        raise DecodeError(f"{path}: throughput {raw!r} is not a number")
    if math.isnan(val) or math.isinf(val) or val < 0:
        raise DecodeError(
            f"{path}: throughput must be a finite non-negative number, "
            f"got {raw!r}")
    return val


def decode_resource_flavor(doc: Mapping[str, Any]) -> ResourceFlavor:
    name, _ = _meta(doc)
    spec = doc.get("spec") or {}
    speed = spec.get("speedClass")
    if speed is not None:
        # Stricter than the per-podset rule (where 0 means "cannot run
        # here"): a flavor-wide speed class of 0 would mark every
        # workload profiled and every slot unrunnable — the webhook
        # requires > 0, and the decoder enforces the same so manifests
        # that bypass the webhook (store sync, bench) cannot differ.
        speed = _throughput_value(speed, "spec.speedClass")
        if speed == 0:
            raise DecodeError(
                "spec.speedClass: must be a finite positive number, got 0")
    return ResourceFlavor.make(
        name,
        node_labels=spec.get("nodeLabels"),
        node_taints=_taints(spec.get("nodeTaints")),
        tolerations=_tolerations(spec.get("tolerations")),
        topology=_topology_spec(spec.get("topologySpec")),
        speed_class=1.0 if speed is None else speed)


def _flavor_quotas(doc: Mapping[str, Any]) -> FlavorQuotas:
    resources = []
    for r in doc.get("resources") or ():
        rname = r["name"]
        resources.append((rname, ResourceQuota(
            nominal=resource_value(rname, r.get("nominalQuota", 0)),
            borrowing_limit=(None if r.get("borrowingLimit") is None
                             else resource_value(rname, r["borrowingLimit"])),
            lending_limit=(None if r.get("lendingLimit") is None
                           else resource_value(rname, r["lendingLimit"])))))
    return FlavorQuotas(name=doc["name"], resources=tuple(resources))


def decode_cluster_queue(doc: Mapping[str, Any]) -> ClusterQueue:
    name, _ = _meta(doc)
    spec = doc.get("spec") or {}
    groups = tuple(
        ResourceGroup(
            covered_resources=tuple(g.get("coveredResources") or ()),
            flavors=tuple(_flavor_quotas(f) for f in g.get("flavors") or ()))
        for g in spec.get("resourceGroups") or ())
    # ClusterQueue is frozen: collect every optional section first and
    # construct exactly once.
    extra: Dict[str, Any] = {}
    if spec.get("queueingStrategy"):
        extra["queueing_strategy"] = spec["queueingStrategy"]
    p = spec.get("preemption")
    if p:
        bwc = None
        if p.get("borrowWithinCohort"):
            b = p["borrowWithinCohort"]
            bwc = BorrowWithinCohort(
                policy=b.get("policy", "Never"),
                max_priority_threshold=b.get("maxPriorityThreshold"))
        extra["preemption"] = ClusterQueuePreemption(
            reclaim_within_cohort=p.get("reclaimWithinCohort", "Never"),
            within_cluster_queue=p.get("withinClusterQueue", "Never"),
            borrow_within_cohort=bwc)
    ff = spec.get("flavorFungibility")
    if ff:
        extra["flavor_fungibility"] = FlavorFungibility(
            when_can_borrow=ff.get("whenCanBorrow", "Borrow"),
            when_can_preempt=ff.get("whenCanPreempt", "TryNextFlavor"))
    fs = spec.get("fairSharing")
    if fs:
        extra["fair_sharing"] = FairSharing(weight=float(fs.get("weight", 1)))
    return ClusterQueue(
        name=name,
        resource_groups=groups,
        cohort=spec.get("cohort", ""),
        namespace_selector=_label_selector(spec.get("namespaceSelector")),
        admission_checks=tuple(spec.get("admissionChecks") or ()),
        stop_policy=spec.get("stopPolicy", "None"),
        **extra,
    )


def decode_local_queue(doc: Mapping[str, Any]) -> LocalQueue:
    name, namespace = _meta(doc)
    spec = doc.get("spec") or {}
    return LocalQueue(name=name, namespace=namespace,
                      cluster_queue=spec.get("clusterQueue", ""))


def decode_workload_priority_class(doc: Mapping[str, Any]) -> WorkloadPriorityClass:
    name, _ = _meta(doc)
    return WorkloadPriorityClass(name=name, value=int(doc.get("value", 0)))


def decode_admission_check(doc: Mapping[str, Any]) -> AdmissionCheck:
    name, _ = _meta(doc)
    spec = doc.get("spec") or {}
    params = spec.get("parameters")
    return AdmissionCheck(
        name=name,
        controller_name=spec.get("controllerName", ""),
        parameters=(None if params is None else
                    (params.get("apiGroup", ""), params.get("kind", ""),
                     params.get("name", ""))))


def decode_workload(doc: Mapping[str, Any]) -> Workload:
    name, namespace = _meta(doc)
    metadata = doc.get("metadata") or {}
    labels = dict(metadata.get("labels") or {})
    annotations = dict(metadata.get("annotations") or {})
    spec = doc.get("spec") or {}
    pod_sets = []
    for ps in spec.get("podSets") or ():
        template = _pod_template(ps.get("template"))
        ps_spec = (ps.get("template") or {}).get("spec") or {}
        topo_req = ps.get("topologyRequest") or {}
        pod_sets.append(PodSet(
            name=ps.get("name", "main"),
            count=int(ps.get("count", 1)),
            min_count=ps.get("minCount"),
            requests=(template.total_requests() if template else {}),
            node_selector=tuple(sorted(
                (ps_spec.get("nodeSelector") or {}).items())),
            affinity_terms=_node_affinity_terms(ps_spec),
            tolerations=_tolerations(ps_spec.get("tolerations")),
            topology_required=topo_req.get("required"),
            topology_preferred=topo_req.get("preferred"),
            flavor_throughputs=tuple(sorted(
                (fname,
                 _throughput_value(
                     v, f"spec.podSets[{ps.get('name', 'main')}]"
                        f".flavorThroughputs[{fname}]"))
                for fname, v in (ps.get("flavorThroughputs") or {}).items())),
            template=template))
    return Workload(
        name=name, namespace=namespace,
        queue_name=spec.get("queueName", ""),
        labels=labels,
        annotations=annotations,
        pod_sets=pod_sets,
        priority=int(spec.get("priority", 0)),
        priority_class=spec.get("priorityClassName", ""),
        priority_class_source=spec.get("priorityClassSource", ""),
        active=bool(spec.get("active", True)))


# -- batch decode (the vectorized ingest lane) -------------------------------
#
# A submission burst is overwhelmingly N copies of one spec under different
# names (bench arrivals, array jobs, autoscaler ramps). The batch decoder
# parses the first exemplar through the full decoder, then CLONES the decoded
# object for every later doc whose raw spec dict compares equal — one
# quantity-parse/validation-shaped sweep instead of N. The clone is verified
# against a full decode once per template with the same dataclass-equality
# check the digital twin's trusted bulk-ingest lane uses, so a template that
# would not reproduce the per-doc decode silently falls back to it.

# The decoded-spec fields of a Workload; uid/creation_time are auto-assigned
# per object and excluded (two decodes of one doc already differ on them).
_WORKLOAD_SPEC_FIELDS = (
    "name", "namespace", "queue_name", "labels", "annotations", "pod_sets",
    "priority", "priority_class", "priority_class_source", "active")


def workload_spec_equal(a: Workload, b: Workload) -> bool:
    """Dataclass equality over the decoded spec fields (the twin lane's
    bulk-ingest check, PR 17) — uid and creation_time excluded."""
    return all(getattr(a, f) == getattr(b, f) for f in _WORKLOAD_SPEC_FIELDS)


def _clone_pod_template(t: Optional[PodTemplate]) -> Optional[PodTemplate]:
    # Own Container/requests/limits/overhead containers: defaulting and
    # LimitRange adjustment mutate them per workload downstream.
    if t is None:
        return None
    return PodTemplate(
        containers=[Container(name=c.name, requests=dict(c.requests),
                              limits=dict(c.limits)) for c in t.containers],
        init_containers=[
            Container(name=c.name, requests=dict(c.requests),
                      limits=dict(c.limits)) for c in t.init_containers],
        overhead=dict(t.overhead),
        runtime_class_name=t.runtime_class_name)


def _clone_workload(template: Workload, doc: Mapping[str, Any]) -> Workload:
    """A fresh Workload carrying `doc`'s identity/metadata and `template`'s
    decoded spec. Pod sets get their own mutable containers (requests dict,
    template) because default_workload/adjust_resources mutate in place."""
    name, namespace = _meta(doc)
    metadata = doc.get("metadata") or {}
    pod_sets = []
    for ps in template.pod_sets:
        c = copy.copy(ps)
        c.requests = dict(ps.requests)
        c.template = _clone_pod_template(ps.template)
        pod_sets.append(c)
    return Workload(
        name=name, namespace=namespace,
        queue_name=template.queue_name,
        labels=dict(metadata.get("labels") or {}),
        annotations=dict(metadata.get("annotations") or {}),
        pod_sets=pod_sets,
        priority=template.priority,
        priority_class=template.priority_class,
        priority_class_source=template.priority_class_source,
        active=template.active)


def decode_workload_batch(docs: Sequence[Mapping[str, Any]]) -> List[Workload]:
    """Decode a WorkloadList's items in one pass (order preserved).

    Docs whose raw spec dict equals the current template's are cloned from
    its verified decode; anything else (first exemplar, spec change, status
    stanza, generateName) takes the per-doc decoder. Raises DecodeError on
    a non-Workload item."""
    out: List[Workload] = []
    tmpl_spec: Optional[Mapping[str, Any]] = None
    tmpl_wl: Optional[Workload] = None
    for doc in docs:
        kind = doc.get("kind")
        if kind not in (None, "Workload"):
            raise DecodeError(
                f"batch submit: unsupported kind {kind!r} (Workload only)")
        spec = doc.get("spec") or {}
        has_status = bool(doc.get("status"))
        if tmpl_wl is not None and not has_status and spec == tmpl_spec:
            out.append(_clone_workload(tmpl_wl, doc))
            continue
        wl = decode_workload(doc)
        if has_status:
            # Status-bearing docs never become templates: the status is
            # per object, the clone path only reproduces specs.
            decode_workload_status(doc, wl)
        elif (doc.get("metadata") or {}).get("name"):
            # generateName docs cannot template (every _meta call mints a
            # new name, so the verification clone could never match).
            if workload_spec_equal(_clone_workload(wl, doc), wl):
                tmpl_spec, tmpl_wl = spec, wl
        out.append(wl)
    return out


# -- batch/v1 Job (the kubectl-visible job form) -----------------------------

def decode_batch_job(doc: Mapping[str, Any]):
    from kueue_tpu.jobs.batch_job import BatchJob

    name, namespace = _meta(doc)
    labels = (doc.get("metadata") or {}).get("labels") or {}
    spec = doc.get("spec") or {}
    template = _pod_template(spec.get("template"))
    # BatchJob canonicalizes requests itself; hand canonical totals back in
    # suffix form ("1000m") so they round-trip instead of re-scaling.
    requests = {r: (f"{v}m" if r == "cpu" else v)
                for r, v in (template.total_requests() if template else {}).items()}
    return BatchJob(
        name=name, namespace=namespace,
        queue_name=labels.get(QUEUE_NAME_LABEL, ""),
        parallelism=int(spec.get("parallelism", 1)),
        completions=int(spec.get("completions", spec.get("parallelism", 1))),
        requests=requests)


def decode_cohort(doc: Mapping[str, Any]):
    """kueue.x-k8s.io/v1alpha1 Cohort (KEP-79)."""
    from kueue_tpu.api.types import CohortSpec

    name, _ = _meta(doc)
    spec = doc.get("spec") or {}
    groups = tuple(
        ResourceGroup(
            covered_resources=tuple(g.get("coveredResources") or ()),
            flavors=tuple(_flavor_quotas(f) for f in g.get("flavors") or ()))
        for g in spec.get("resourceGroups") or ())
    return CohortSpec(name=name, parent=spec.get("parent") or "",
                      resource_groups=groups)


_DECODERS = {
    "ResourceFlavor": decode_resource_flavor,
    "Cohort": decode_cohort,
    "ClusterQueue": decode_cluster_queue,
    "LocalQueue": decode_local_queue,
    "WorkloadPriorityClass": decode_workload_priority_class,
    "AdmissionCheck": decode_admission_check,
    "Workload": decode_workload,
    "Job": decode_batch_job,
}


def decode(doc: Mapping[str, Any]):
    """Decode one manifest document; returns (kind, object)."""
    kind = doc.get("kind")
    if kind not in _DECODERS:
        raise DecodeError(f"unsupported kind {kind!r} "
                          f"(supported: {', '.join(sorted(_DECODERS))})")
    return kind, _DECODERS[kind](doc)


def load_manifests(path: str) -> List[Tuple[str, object]]:
    """Read a multi-document YAML manifest file (kubectl-apply analog)."""
    import yaml

    out = []
    with open(path) as fh:
        for doc in yaml.safe_load_all(fh):
            if not doc:
                continue
            out.append(decode(doc))
    return out


# -- encoding (object model -> manifest documents) ---------------------------
#
# The reference serves its objects as JSON from the apiserver; the API
# server (kueue_tpu/server/) and the MultiKueue HTTP remote need the same
# wire form, so every kind decodes AND encodes through this module.
# Encodings round-trip: decode(encode(kind, obj)) reproduces the object.

API_VERSION = "kueue.x-k8s.io/v1beta1"


def _quantity(resource: str, value: int):
    """Canonical integer back to a manifest quantity. cpu is tracked in
    milliCPU (workload.go:245-296), so it round-trips in suffix form."""
    return f"{value}m" if resource == "cpu" else value


def _encode_requests(requests: Mapping[str, int]) -> Dict[str, Any]:
    return {r: _quantity(r, v) for r, v in requests.items()}


def _encode_tolerations(tols) -> List[Dict[str, Any]]:
    return [{"key": t.key, "operator": t.operator, "value": t.value,
             "effect": t.effect} for t in tols]


def _encode_match_expressions(exprs) -> List[Dict[str, Any]]:
    return [{"key": e.key, "operator": e.operator, "values": list(e.values)}
            for e in exprs]


def encode_resource_flavor(rf: ResourceFlavor) -> Dict[str, Any]:
    spec: Dict[str, Any] = {
        "nodeLabels": dict(rf.node_labels),
        "nodeTaints": [{"key": t.key, "value": t.value,
                        "effect": t.effect} for t in rf.node_taints],
        "tolerations": _encode_tolerations(rf.tolerations),
    }
    if rf.topology is not None:
        spec["topologySpec"] = {
            "levels": list(rf.topology.levels),
            "leaves": [{"path": list(leaf.path), "capacity": leaf.capacity}
                       for leaf in rf.topology.leaves],
        }
    if rf.speed_class != 1.0:
        spec["speedClass"] = rf.speed_class
    return {
        "apiVersion": API_VERSION, "kind": "ResourceFlavor",
        "metadata": {"name": rf.name},
        "spec": spec,
    }


def _encode_resource_groups(groups) -> List[Dict[str, Any]]:
    out = []
    for g in groups:
        flavors = []
        for f in g.flavors:
            resources = []
            for rname, q in f.resources:
                entry: Dict[str, Any] = {
                    "name": rname, "nominalQuota": _quantity(rname, q.nominal)}
                if q.borrowing_limit is not None:
                    entry["borrowingLimit"] = _quantity(rname, q.borrowing_limit)
                if q.lending_limit is not None:
                    entry["lendingLimit"] = _quantity(rname, q.lending_limit)
                resources.append(entry)
            flavors.append({"name": f.name, "resources": resources})
        out.append({"coveredResources": list(g.covered_resources),
                    "flavors": flavors})
    return out


def encode_cluster_queue(cq: ClusterQueue) -> Dict[str, Any]:
    spec: Dict[str, Any] = {
        "resourceGroups": _encode_resource_groups(cq.resource_groups),
        "queueingStrategy": cq.queueing_strategy,
        "stopPolicy": cq.stop_policy,
    }
    if cq.cohort:
        spec["cohort"] = cq.cohort
    sel = cq.namespace_selector
    if sel.match_labels or sel.match_expressions:
        spec["namespaceSelector"] = {
            "matchLabels": dict(sel.match_labels),
            "matchExpressions": _encode_match_expressions(sel.match_expressions),
        }
    if cq.admission_checks:
        spec["admissionChecks"] = list(cq.admission_checks)
    p = cq.preemption
    preemption: Dict[str, Any] = {
        "reclaimWithinCohort": p.reclaim_within_cohort,
        "withinClusterQueue": p.within_cluster_queue,
    }
    if p.borrow_within_cohort is not None:
        preemption["borrowWithinCohort"] = {
            "policy": p.borrow_within_cohort.policy,
            "maxPriorityThreshold": p.borrow_within_cohort.max_priority_threshold,
        }
    spec["preemption"] = preemption
    spec["flavorFungibility"] = {
        "whenCanBorrow": cq.flavor_fungibility.when_can_borrow,
        "whenCanPreempt": cq.flavor_fungibility.when_can_preempt,
    }
    if cq.fair_sharing is not None:
        spec["fairSharing"] = {"weight": cq.fair_sharing.weight}
    return {"apiVersion": API_VERSION, "kind": "ClusterQueue",
            "metadata": {"name": cq.name}, "spec": spec}


def encode_local_queue(lq: LocalQueue) -> Dict[str, Any]:
    return {"apiVersion": API_VERSION, "kind": "LocalQueue",
            "metadata": {"name": lq.name, "namespace": lq.namespace},
            "spec": {"clusterQueue": lq.cluster_queue}}


def encode_workload_priority_class(pc: WorkloadPriorityClass) -> Dict[str, Any]:
    return {"apiVersion": API_VERSION, "kind": "WorkloadPriorityClass",
            "metadata": {"name": pc.name}, "value": pc.value}


def encode_admission_check(ac: AdmissionCheck) -> Dict[str, Any]:
    spec: Dict[str, Any] = {"controllerName": ac.controller_name}
    if ac.parameters is not None:
        spec["parameters"] = {"apiGroup": ac.parameters[0],
                              "kind": ac.parameters[1],
                              "name": ac.parameters[2]}
    return {"apiVersion": API_VERSION, "kind": "AdmissionCheck",
            "metadata": {"name": ac.name}, "spec": spec}


def encode_cohort(cohort) -> Dict[str, Any]:
    return {"apiVersion": "kueue.x-k8s.io/v1alpha1", "kind": "Cohort",
            "metadata": {"name": cohort.name},
            "spec": {"parent": cohort.parent,
                     "resourceGroups": _encode_resource_groups(
                         cohort.resource_groups)}}


def _encode_pod_set(ps: PodSet) -> Dict[str, Any]:
    # The per-pod totals ride in a single synthetic container so the
    # template round-trips through decode_workload's total_requests().
    spec: Dict[str, Any] = {
        "containers": [{"name": "main",
                        "resources": {"requests": _encode_requests(ps.requests)}}],
    }
    if ps.node_selector:
        spec["nodeSelector"] = dict(ps.node_selector)
    if ps.tolerations:
        spec["tolerations"] = _encode_tolerations(ps.tolerations)
    if ps.affinity_terms:
        spec["affinity"] = {"nodeAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": {
                "nodeSelectorTerms": [
                    {"matchExpressions": _encode_match_expressions(term)}
                    for term in ps.affinity_terms]}}}
    out: Dict[str, Any] = {"name": ps.name, "count": ps.count,
                           "template": {"spec": spec}}
    if ps.min_count is not None:
        out["minCount"] = ps.min_count
    if ps.topology_required is not None:
        out["topologyRequest"] = {"required": ps.topology_required}
    elif ps.topology_preferred is not None:
        out["topologyRequest"] = {"preferred": ps.topology_preferred}
    if ps.flavor_throughputs:
        out["flavorThroughputs"] = dict(ps.flavor_throughputs)
    return out


def _encode_conditions(conditions) -> List[Dict[str, Any]]:
    return [{"type": c.type, "status": "True" if c.status else "False",
             "reason": c.reason, "message": c.message,
             "lastTransitionTime": c.last_transition_time}
            for c in conditions]


def _encode_psa(a) -> Dict[str, Any]:
    out: Dict[str, Any] = {
        "name": a.name, "flavors": dict(a.flavors),
        "resourceUsage": _encode_requests(a.resource_usage),
        "count": a.count}
    ta = a.topology_assignment
    if ta is not None:
        out["topologyAssignment"] = {
            "flavor": ta.flavor, "levels": list(ta.levels),
            "domain": list(ta.domain),
            "counts": [[i, n] for i, n in ta.counts]}
    return out


def encode_workload_status(wl: Workload) -> Dict[str, Any]:
    status: Dict[str, Any] = {"conditions": _encode_conditions(wl.conditions)}
    if wl.admission is not None:
        status["admission"] = {
            "clusterQueue": wl.admission.cluster_queue,
            "podSetAssignments": [
                _encode_psa(a) for a in wl.admission.pod_set_assignments],
        }
    if wl.admission_check_states:
        status["admissionChecks"] = [
            {"name": s.name, "state": s.state, "message": s.message,
             "podSetUpdates": list(s.pod_set_updates)}
            for s in wl.admission_check_states.values()]
    if wl.reclaimable_pods:
        status["reclaimablePods"] = [
            {"name": n, "count": c} for n, c in wl.reclaimable_pods.items()]
    if wl.requeue_state is not None:
        status["requeueState"] = {"count": wl.requeue_state.count,
                                  "requeueAt": wl.requeue_state.requeue_at}
    return status


def encode_workload(wl: Workload, with_status: bool = True) -> Dict[str, Any]:
    doc: Dict[str, Any] = {
        "apiVersion": API_VERSION, "kind": "Workload",
        "metadata": {"name": wl.name, "namespace": wl.namespace,
                     "labels": dict(wl.labels),
                     "annotations": dict(wl.annotations),
                     "uid": wl.uid,
                     "creationTimestamp": wl.creation_time},
        "spec": {"queueName": wl.queue_name,
                 "podSets": [_encode_pod_set(ps) for ps in wl.pod_sets],
                 "priority": wl.priority,
                 "priorityClassName": wl.priority_class,
                 "priorityClassSource": wl.priority_class_source,
                 "active": wl.active},
    }
    if with_status:
        doc["status"] = encode_workload_status(wl)
    return doc


def encode_workload_cloned(wl: Workload,
                           tmpl_doc: Mapping[str, Any]) -> Dict[str, Any]:
    """encode_workload for a workload whose validator-read fields are
    dataclass-equal to `tmpl_doc`'s subject (Store.create_batch's
    exemplar): the podSets stanza — the dominant encode cost — is shared
    structurally from the template document instead of re-encoded.
    Safe because equal pod_sets encode to equal documents and published
    docs are immutable (Store._docs contract); everything identity-side
    (metadata, priority, active, status) is rebuilt per workload, so the
    result is json-identical to encode_workload(wl)."""
    return {
        "apiVersion": API_VERSION, "kind": "Workload",
        "metadata": {"name": wl.name, "namespace": wl.namespace,
                     "labels": dict(wl.labels),
                     "annotations": dict(wl.annotations),
                     "uid": wl.uid,
                     "creationTimestamp": wl.creation_time},
        "spec": {"queueName": wl.queue_name,
                 "podSets": tmpl_doc["spec"]["podSets"],
                 "priority": wl.priority,
                 "priorityClassName": wl.priority_class,
                 "priorityClassSource": wl.priority_class_source,
                 "active": wl.active},
        "status": encode_workload_status(wl),
    }


def decode_workload_status(doc: Mapping[str, Any], wl: Workload) -> Workload:
    """Fold a status stanza back onto a decoded workload (the watch/GET
    client side of encode_workload_status)."""
    from kueue_tpu.api.types import (
        Admission, AdmissionCheckState, Condition, PodSetAssignment,
        RequeueState, TopologyAssignment)

    def _topology_assignment(d):
        if not d:
            return None
        return TopologyAssignment(
            flavor=d.get("flavor", ""),
            levels=tuple(d.get("levels") or ()),
            domain=tuple(d.get("domain") or ()),
            counts=tuple((int(i), int(n)) for i, n in d.get("counts") or ()))

    status = doc.get("status") or {}
    wl.conditions = [
        Condition(type=c["type"], status=c.get("status") == "True",
                  reason=c.get("reason", ""), message=c.get("message", ""),
                  last_transition_time=float(c.get("lastTransitionTime", 0)))
        for c in status.get("conditions") or ()]
    adm = status.get("admission")
    if adm is not None:
        wl.admission = Admission(
            cluster_queue=adm.get("clusterQueue", ""),
            pod_set_assignments=[
                PodSetAssignment(
                    name=a.get("name", "main"),
                    flavors=dict(a.get("flavors") or {}),
                    resource_usage=_requests(a.get("resourceUsage")),
                    count=int(a.get("count", 0)),
                    topology_assignment=_topology_assignment(
                        a.get("topologyAssignment")))
                for a in adm.get("podSetAssignments") or ()])
    wl.admission_check_states = {
        s["name"]: AdmissionCheckState(
            name=s["name"], state=s.get("state", "Pending"),
            message=s.get("message", ""),
            pod_set_updates=list(s.get("podSetUpdates") or ()))
        for s in status.get("admissionChecks") or ()}
    wl.reclaimable_pods = {r["name"]: int(r["count"])
                           for r in status.get("reclaimablePods") or ()}
    rq = status.get("requeueState")
    if rq is not None:
        wl.requeue_state = RequeueState(count=int(rq.get("count", 0)),
                                        requeue_at=rq.get("requeueAt"))
    meta = doc.get("metadata") or {}
    if meta.get("uid"):
        wl.uid = meta["uid"]
    if meta.get("creationTimestamp") is not None:
        try:
            wl.creation_time = float(meta["creationTimestamp"])
        except (TypeError, ValueError):
            pass
    return wl


_ENCODERS = {
    "ResourceFlavor": encode_resource_flavor,
    "Cohort": encode_cohort,
    "ClusterQueue": encode_cluster_queue,
    "LocalQueue": encode_local_queue,
    "WorkloadPriorityClass": encode_workload_priority_class,
    "AdmissionCheck": encode_admission_check,
    "Workload": encode_workload,
}


def encode(kind: str, obj) -> Dict[str, Any]:
    """Encode one object back into its manifest document."""
    if kind not in _ENCODERS:
        raise DecodeError(f"unsupported kind {kind!r} for encoding")
    return _ENCODERS[kind](obj)
