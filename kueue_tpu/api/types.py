"""Object model: the framework's counterpart of the reference CRDs.

These are plain Python dataclasses, not Kubernetes objects: the framework can
be embedded in-process (tests, bench) or fronted by any API layer
(`kueue_tpu.controllers.store` provides a watchable in-memory store).

Reference parity:
  ResourceFlavor        apis/kueue/v1beta1/resourceflavor_types.go
  ClusterQueue          apis/kueue/v1beta1/clusterqueue_types.go
  LocalQueue            apis/kueue/v1beta1/localqueue_types.go
  Workload/PodSet       apis/kueue/v1beta1/workload_types.go
  WorkloadPriorityClass apis/kueue/v1beta1/workloadpriorityclass_types.go

All resource values are canonical integers (see api/resources.py).
"""

from __future__ import annotations

import itertools
import time as _time
from dataclasses import dataclass, field
from enum import IntEnum
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

from kueue_tpu.api.resources import Quantity, resource_value

# ---------------------------------------------------------------------------
# Enums / policies
# ---------------------------------------------------------------------------


class QueueingStrategy:
    STRICT_FIFO = "StrictFIFO"
    BEST_EFFORT_FIFO = "BestEffortFIFO"


class PreemptionPolicy:
    NEVER = "Never"
    LOWER_PRIORITY = "LowerPriority"
    LOWER_OR_NEWER_EQUAL_PRIORITY = "LowerOrNewerEqualPriority"
    ANY = "Any"


class BorrowWithinCohortPolicy:
    NEVER = "Never"
    LOWER_PRIORITY = "LowerPriority"


class FlavorFungibilityPolicy:
    BORROW = "Borrow"
    PREEMPT = "Preempt"
    TRY_NEXT_FLAVOR = "TryNextFlavor"


class StopPolicy:
    NONE = "None"
    HOLD = "Hold"
    HOLD_AND_DRAIN = "HoldAndDrain"


@dataclass(frozen=True)
class BorrowWithinCohort:
    """reference: apis/kueue/v1beta1/clusterqueue_types.go (BorrowWithinCohort)."""

    policy: str = BorrowWithinCohortPolicy.NEVER
    max_priority_threshold: Optional[int] = None


@dataclass(frozen=True)
class ClusterQueuePreemption:
    """reference: apis/kueue/v1beta1/clusterqueue_types.go (ClusterQueuePreemption)."""

    within_cluster_queue: str = PreemptionPolicy.NEVER
    reclaim_within_cohort: str = PreemptionPolicy.NEVER
    borrow_within_cohort: Optional[BorrowWithinCohort] = None


@dataclass(frozen=True)
class FlavorFungibility:
    """Defaults mirror the reference (pkg/cache/clusterqueue.go:174)."""

    when_can_borrow: str = FlavorFungibilityPolicy.BORROW
    when_can_preempt: str = FlavorFungibilityPolicy.TRY_NEXT_FLAVOR


class FairSharingStrategy:
    """Fair-share preemption rules (KEP-1714 S2-a / S2-b)."""

    LESS_THAN_OR_EQUAL_TO_FINAL_SHARE = "LessThanOrEqualToFinalShare"
    LESS_THAN_INITIAL_SHARE = "LessThanInitialShare"


@dataclass(frozen=True)
class FairSharing:
    """Weight-based fair sharing of borrowed capacity (KEP-1714).

    The reference snapshot only designs this (keps/1714-fair-sharing);
    this framework implements it natively. Weight scales the tolerated
    share: a CQ with weight 2 may borrow twice as much as its siblings
    before being considered over-share.
    """

    weight: float = 1.0


# ---------------------------------------------------------------------------
# Label / node selection (host-side string world)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class MatchExpression:
    """A label/node-selector requirement (k8s NodeSelectorRequirement subset)."""

    key: str
    operator: str  # In | NotIn | Exists | DoesNotExist | Gt | Lt
    values: Tuple[str, ...] = ()

    def matches(self, labels: Mapping[str, str]) -> bool:
        has = self.key in labels
        val = labels.get(self.key)
        if self.operator == "In":
            return has and val in self.values
        if self.operator == "NotIn":
            return not has or val not in self.values
        if self.operator == "Exists":
            return has
        if self.operator == "DoesNotExist":
            return not has
        if self.operator == "Gt":
            return has and _is_int(val) and int(val) > int(self.values[0])
        if self.operator == "Lt":
            return has and _is_int(val) and int(val) < int(self.values[0])
        raise ValueError(f"unknown operator {self.operator}")


def _is_int(s: Optional[str]) -> bool:
    if s is None:
        return False
    try:
        int(s)
        return True
    except ValueError:
        return False


@dataclass(frozen=True)
class LabelSelector:
    """k8s metav1.LabelSelector subset; empty selector matches everything."""

    match_labels: Tuple[Tuple[str, str], ...] = ()
    match_expressions: Tuple[MatchExpression, ...] = ()

    @staticmethod
    def everything() -> "LabelSelector":
        return LabelSelector()

    @staticmethod
    def nothing() -> "LabelSelector":
        return LabelSelector(match_expressions=(MatchExpression("__none__", "In", ()),))

    @staticmethod
    def of(**labels: str) -> "LabelSelector":
        return LabelSelector(match_labels=tuple(sorted(labels.items())))

    def matches(self, labels: Mapping[str, str]) -> bool:
        for k, v in self.match_labels:
            if labels.get(k) != v:
                return False
        return all(e.matches(labels) for e in self.match_expressions)


@dataclass(frozen=True)
class Taint:
    key: str
    value: str = ""
    effect: str = "NoSchedule"  # NoSchedule | PreferNoSchedule | NoExecute


@dataclass(frozen=True)
class Toleration:
    key: str = ""
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # empty tolerates all effects

    def tolerates(self, taint: Taint) -> bool:
        if self.effect and self.effect != taint.effect:
            return False
        if self.key == "":
            # Empty key with Exists tolerates everything.
            return self.operator == "Exists"
        if self.key != taint.key:
            return False
        if self.operator == "Exists":
            return True
        return self.value == taint.value


# ---------------------------------------------------------------------------
# ResourceFlavor + topology (slice/rack/host placement hierarchy)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class TopologyLeaf:
    """One lowest-level topology domain (e.g. a host): its path through the
    levels (one value per level, top -> bottom) and its pod-slot capacity."""

    path: Tuple[str, ...]
    capacity: int


@dataclass(frozen=True)
class TopologySpec:
    """Per-flavor placement hierarchy (Kueue Topology-Aware Scheduling).

    `levels` names the domain levels top -> bottom (e.g. ("block", "rack",
    "host")); `leaves` enumerate the lowest-level domains with per-leaf pod
    capacity. A domain at level l is the set of leaves sharing path[:l+1].
    TPU pods are only fast when a PodSet lands inside one contiguous
    domain, which is what `PodSet.topology_required/preferred` ask for.
    """

    levels: Tuple[str, ...]
    leaves: Tuple[TopologyLeaf, ...] = ()

    @staticmethod
    def uniform(levels: Sequence[str], counts: Sequence[int],
                leaf_capacity: int) -> "TopologySpec":
        """A regular tree: counts[i] children per node at level i.
        uniform(("block","rack","host"), (2,2,4), 8) -> 16 hosts of 8 slots."""
        if len(levels) != len(counts):
            raise ValueError("levels and counts must have the same length")
        paths = [()]
        for level, n in zip(levels, counts):
            paths = [p + (f"{level}{i}",) for p in paths for i in range(n)]
        return TopologySpec(
            levels=tuple(levels),
            leaves=tuple(TopologyLeaf(path=p, capacity=leaf_capacity)
                         for p in paths))

    def level_index(self, name: str) -> Optional[int]:
        try:
            return self.levels.index(name)
        except ValueError:
            return None

    def domain_free(self, used: Sequence[int],
                    level: int) -> Dict[Tuple[str, ...], int]:
        """Free pod-slot capacity per domain at `level`, given per-leaf
        occupancy (spec.leaves order; missing/short sequences read as
        empty). The ONE string-world home of leaf->domain aggregation —
        metrics and the preemption victim preference both read it (the
        solver path has its own dense-tensor twin in topology/fit.py)."""
        out: Dict[Tuple[str, ...], int] = {}
        for i, leaf in enumerate(self.leaves):
            u = int(used[i]) if i < len(used) else 0
            key = leaf.path[:level + 1]
            out[key] = out.get(key, 0) + max(leaf.capacity - u, 0)
        return out


@dataclass(frozen=True)
class ResourceFlavor:
    name: str
    node_labels: Tuple[Tuple[str, str], ...] = ()
    node_taints: Tuple[Taint, ...] = ()
    tolerations: Tuple[Toleration, ...] = ()
    # Optional placement hierarchy; None = topology-blind flavor (every
    # existing code path is then byte-identical to the pre-topology build).
    topology: Optional[TopologySpec] = None
    # Relative accelerator speed of this flavor (heterogeneity-aware
    # scheduling, kueue_tpu/hetero): the default throughput a workload
    # gets on this flavor when it declares no per-flavor number of its
    # own. 1.0 (the default) on every flavor means a homogeneous cluster
    # — the hetero solve mode is then a provable no-op.
    speed_class: float = 1.0

    @staticmethod
    def make(name: str, node_labels: Optional[Mapping[str, str]] = None,
             node_taints: Sequence[Taint] = (),
             tolerations: Sequence[Toleration] = (),
             topology: Optional[TopologySpec] = None,
             speed_class: float = 1.0) -> "ResourceFlavor":
        return ResourceFlavor(
            name=name,
            node_labels=tuple(sorted((node_labels or {}).items())),
            node_taints=tuple(node_taints),
            tolerations=tuple(tolerations),
            topology=topology,
            speed_class=speed_class,
        )

    @property
    def labels_dict(self) -> Dict[str, str]:
        return dict(self.node_labels)


# ---------------------------------------------------------------------------
# ClusterQueue quotas
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ResourceQuota:
    """Integer quota for one (flavor, resource); canonical units.

    reference: pkg/cache/clusterqueue.go:106-110 (ResourceQuota).
    """

    nominal: int
    borrowing_limit: Optional[int] = None
    lending_limit: Optional[int] = None

    @staticmethod
    def make(name: str, nominal: Quantity, borrowing_limit: Optional[Quantity] = None,
             lending_limit: Optional[Quantity] = None) -> "ResourceQuota":
        return ResourceQuota(
            nominal=resource_value(name, nominal),
            borrowing_limit=None if borrowing_limit is None else resource_value(name, borrowing_limit),
            lending_limit=None if lending_limit is None else resource_value(name, lending_limit),
        )


@dataclass(frozen=True)
class FlavorQuotas:
    name: str  # flavor name
    resources: Tuple[Tuple[str, ResourceQuota], ...]  # ordered (resource -> quota)

    @staticmethod
    def make(name: str, **quotas: "Quantity | Tuple") -> "FlavorQuotas":
        """FlavorQuotas.make("on-demand", cpu=10, memory="10Gi",
        gpu=(4, 2) )  # (nominal, borrowingLimit) or (nominal, borrow, lend)
        """
        res = []
        for rname, spec in quotas.items():
            rname = rname.replace("_", "-")
            if isinstance(spec, tuple):
                res.append((rname, ResourceQuota.make(rname, *spec)))
            else:
                res.append((rname, ResourceQuota.make(rname, spec)))
        return FlavorQuotas(name=name, resources=tuple(res))

    @property
    def resources_dict(self) -> Dict[str, ResourceQuota]:
        return dict(self.resources)


@dataclass(frozen=True)
class ResourceGroup:
    """An ordered list of flavors covering a set of resources.

    Flavor order is the preference order tried by the assigner
    (reference: apis/kueue/v1beta1/clusterqueue_types.go ResourceGroup).
    """

    covered_resources: Tuple[str, ...]
    flavors: Tuple[FlavorQuotas, ...]


@dataclass(frozen=True)
class CohortSpec:
    """Hierarchical-cohort node (KEP-79, implemented natively from the KEP;
    the reference snapshot only designs it).

    A Cohort named by `ClusterQueue.cohort` need not have a spec — then it
    provides no quota, has no parent, and behaves exactly like the flat
    2-level cohort. With a spec it may carry its own shareable quota
    (`resource_groups`, nominal shared with the whole subtree), a `parent`
    forming the tree, and per-(flavor,resource) borrowing/lending limits:
    borrowingLimit caps how much the whole subtree may borrow from outside
    it; lendingLimit caps how much the rest of the tree may borrow from the
    subtree (keps/79-hierarchical-cohorts/README.md "Design Details")."""

    name: str
    parent: str = ""
    resource_groups: Tuple[ResourceGroup, ...] = ()


@dataclass(frozen=True)
class ClusterQueue:
    name: str
    resource_groups: Tuple[ResourceGroup, ...] = ()
    cohort: str = ""
    queueing_strategy: str = QueueingStrategy.BEST_EFFORT_FIFO
    namespace_selector: LabelSelector = field(default_factory=LabelSelector.everything)
    preemption: ClusterQueuePreemption = field(default_factory=ClusterQueuePreemption)
    flavor_fungibility: FlavorFungibility = field(default_factory=FlavorFungibility)
    admission_checks: Tuple[str, ...] = ()
    stop_policy: str = StopPolicy.NONE
    fair_sharing: Optional[FairSharing] = None


@dataclass(frozen=True)
class LocalQueue:
    name: str
    namespace: str
    cluster_queue: str

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass(frozen=True)
class WorkloadPriorityClass:
    name: str
    value: int


@dataclass(frozen=True)
class AdmissionCheck:
    """A two-phase admission gate definition (KEP-993).

    reference: apis/kueue/v1beta1/admissioncheck_types.go — names the
    controller that drives the check and an optional parameters reference.
    """

    name: str
    controller_name: str
    # (api_group, kind, name) of a controller-specific parameters object,
    # e.g. a ProvisioningRequestConfig.
    parameters: Optional[Tuple[str, str, str]] = None


# ---------------------------------------------------------------------------
# Workload
# ---------------------------------------------------------------------------


@dataclass
class PodSet:
    """A homogeneous set of pods in a Workload.

    `requests` are per-pod; canonical integers are computed on construction.
    reference: apis/kueue/v1beta1/workload_types.go:110-147.
    """

    name: str
    count: int
    requests: Dict[str, int] = field(default_factory=dict)
    min_count: Optional[int] = None  # enables partial admission when set
    node_selector: Tuple[Tuple[str, str], ...] = ()
    # Required node-affinity terms: OR of terms, each term an AND of expressions.
    affinity_terms: Tuple[Tuple[MatchExpression, ...], ...] = ()
    tolerations: Tuple[Toleration, ...] = ()
    # Topology request (TAS): all pods must land within ONE domain at this
    # level of the assigned flavor's topology (`topology_required`), or
    # best-effort pack there, falling back up the hierarchy and finally to
    # unconstrained placement (`topology_preferred`). At most one is set.
    topology_required: Optional[str] = None
    topology_preferred: Optional[str] = None
    # Heterogeneity-aware scheduling (kueue_tpu/hetero): relative
    # throughput of THIS pod set per flavor name — "these pods run at
    # 4.0x the reference speed on flavor B". Flavors not listed fall
    # back to the flavor's `speed_class`. Sorted (flavor, value) pairs
    # so the spec stays hashable for memo keys.
    flavor_throughputs: Tuple[Tuple[str, float], ...] = ()
    # Optional full template; when set, `requests` is derived from it by
    # workload.adjust_resources (pkg/workload/resources.go).
    template: Optional[PodTemplate] = None

    @staticmethod
    def make(name: str, count: int, min_count: Optional[int] = None,
             node_selector: Optional[Mapping[str, str]] = None,
             affinity_terms: Sequence[Sequence[MatchExpression]] = (),
             tolerations: Sequence[Toleration] = (),
             topology_required: Optional[str] = None,
             topology_preferred: Optional[str] = None,
             flavor_throughputs: Optional[Mapping[str, float]] = None,
             **requests: Quantity) -> "PodSet":
        reqs = {r.replace("_", "-"): resource_value(r.replace("_", "-"), q)
                for r, q in requests.items()}
        return PodSet(
            name=name, count=count, requests=reqs, min_count=min_count,
            node_selector=tuple(sorted((node_selector or {}).items())),
            affinity_terms=tuple(tuple(t) for t in affinity_terms),
            tolerations=tuple(tolerations),
            topology_required=topology_required,
            topology_preferred=topology_preferred,
            flavor_throughputs=tuple(
                sorted((flavor_throughputs or {}).items())),
        )


@dataclass
class Container:
    """Resource envelope of one container (k8s core/v1 Container subset).

    `requests`/`limits` are canonical integers keyed by resource name.
    """

    name: str = ""
    requests: Dict[str, int] = field(default_factory=dict)
    limits: Dict[str, int] = field(default_factory=dict)

    @staticmethod
    def make(name: str = "",
             requests: Optional[Mapping[str, Quantity]] = None,
             limits: Optional[Mapping[str, Quantity]] = None) -> "Container":
        return Container(
            name=name,
            requests={r: resource_value(r, q) for r, q in (requests or {}).items()},
            limits={r: resource_value(r, q) for r, q in (limits or {}).items()},
        )


@dataclass
class PodTemplate:
    """The resource-bearing part of a pod template (core/v1 PodSpec subset).

    Job integrations attach one per PodSet so the resource-adjustment
    pipeline (reference: pkg/workload/resources.go AdjustResources) can fold
    RuntimeClass overhead, LimitRange defaults and limits->requests
    defaulting before the per-pod totals are computed
    (pkg/util/limitrange/limitrange.go TotalRequests).
    """

    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)
    overhead: Dict[str, int] = field(default_factory=dict)
    runtime_class_name: Optional[str] = None

    def total_requests(self) -> Dict[str, int]:
        """total = max(sum(containers), max(initContainers)) + overhead
        (limitrange.go:83-101)."""
        total: Dict[str, int] = {}
        for c in self.containers:
            for r, v in c.requests.items():
                total[r] = total.get(r, 0) + v
        for c in self.init_containers:
            for r, v in c.requests.items():
                if v > total.get(r, 0):
                    total[r] = v
        for r, v in self.overhead.items():
            total[r] = total.get(r, 0) + v
        return total


# Condition types (reference: apis/kueue/v1beta1/workload_types.go conditions)
CONDITION_QUOTA_RESERVED = "QuotaReserved"
CONDITION_ADMITTED = "Admitted"
CONDITION_EVICTED = "Evicted"
CONDITION_FINISHED = "Finished"
CONDITION_PODS_READY = "PodsReady"

# Eviction reasons
EVICTED_BY_PREEMPTION = "Preempted"
EVICTED_BY_PODS_READY_TIMEOUT = "PodsReadyTimeout"
EVICTED_BY_ADMISSION_CHECK = "AdmissionCheck"
EVICTED_BY_CLUSTER_QUEUE_STOPPED = "ClusterQueueStopped"
EVICTED_BY_DEACTIVATION = "InactiveWorkload"


# Conditions are mutated in place by set_condition so that Workload's
# _cond_memo index (built on object identity) stays valid across updates.
@dataclass
class Condition:  # kueuelint: disable=API02
    type: str
    status: bool
    reason: str = ""
    message: str = ""
    last_transition_time: float = 0.0


@dataclass(frozen=True)
class TopologyAssignment:
    """The topology domain a PodSet was packed into at admission.

    `levels`/`domain` identify the chosen domain (a prefix of the flavor's
    topology levels and the matching path values); `counts` records the
    per-leaf pod distribution as (leaf index into the flavor's
    TopologySpec.leaves, pods) pairs — what the ledger charges and
    releases."""

    flavor: str
    levels: Tuple[str, ...]
    domain: Tuple[str, ...]
    counts: Tuple[Tuple[int, int], ...]


@dataclass
class PodSetAssignment:
    name: str
    flavors: Dict[str, str]  # resource -> flavor name
    resource_usage: Dict[str, int]  # per-pod-set totals
    count: int
    # Set when the podset carried a topology request and the assigned
    # flavor declares a topology (None otherwise).
    topology_assignment: Optional[TopologyAssignment] = None


@dataclass
class Admission:
    cluster_queue: str
    pod_set_assignments: List[PodSetAssignment] = field(default_factory=list)


@dataclass
class AdmissionCheckState:
    name: str
    state: str  # Pending | Ready | Retry | Rejected
    message: str = ""
    pod_set_updates: List[dict] = field(default_factory=list)


@dataclass(frozen=True)
class RequeueState:
    count: int = 0
    requeue_at: Optional[float] = None


_uid_counter = itertools.count(1)


@dataclass
class Workload:
    name: str
    namespace: str = "default"
    queue_name: str = ""  # LocalQueue name
    # metadata.labels analog (e.g. the MultiKueue origin label on mirrors).
    labels: Dict[str, str] = field(default_factory=dict)
    # metadata.annotations analog (e.g. provreq.kueue.x-k8s.io/* parameters
    # passed through to ProvisioningRequests).
    annotations: Dict[str, str] = field(default_factory=dict)
    pod_sets: List[PodSet] = field(default_factory=list)
    priority: int = 0
    priority_class: str = ""
    priority_class_source: str = ""  # "kueue.x-k8s.io/workloadpriorityclass" or pod PC
    creation_time: float = field(default_factory=_time.time)
    uid: str = ""
    active: bool = True

    # Status
    conditions: List[Condition] = field(default_factory=list)
    admission: Optional[Admission] = None
    reclaimable_pods: Dict[str, int] = field(default_factory=dict)  # podset name -> count
    admission_check_states: Dict[str, AdmissionCheckState] = field(default_factory=dict)
    requeue_state: Optional[RequeueState] = None

    def __post_init__(self) -> None:
        if not self.uid:
            self.uid = f"uid-{next(_uid_counter):08d}"
        # name/namespace are identity (never reassigned); precompute the
        # cache key once — it is read on every usage-accounting mutation.
        self._key = f"{self.namespace}/{self.name}"
        # In-place condition mutation counter: set_condition (and the
        # scheduler's unrolled twin) bump it, so memos derived from
        # condition STATE (queue-ordering timestamp) can key on
        # (conditions identity, len, this) — identity+len alone only
        # detect wholesale replacement and appends.
        self._cond_mut = 0

    # -- condition helpers (reference: pkg/workload/workload.go:369-505) ----

    @property
    def key(self) -> str:
        return self._key

    def _cond_map(self) -> dict:
        # Dict index over the conditions list, rebuilt when the list is
        # appended to or replaced wholesale (decode_workload_status):
        # condition lookups run several times per admission on the hot
        # path. Condition objects are mutated in place by set_condition,
        # which keeps membership — and therefore the index — intact.
        conds = self.conditions
        memo = getattr(self, "_cond_memo", None)
        if memo is None or memo[0] is not conds or memo[1] != len(conds):
            memo = (conds, len(conds), {c.type: c for c in conds})
            self._cond_memo = memo
        return memo[2]

    def find_condition(self, ctype: str) -> Optional[Condition]:
        return self._cond_map().get(ctype)

    def condition_true(self, ctype: str) -> bool:
        c = self._cond_map().get(ctype)
        return c is not None and c.status

    def set_condition(self, ctype: str, status: bool, reason: str = "",
                      message: str = "", now: Optional[float] = None) -> None:
        now = _time.time() if now is None else now
        self._cond_mut += 1
        c = self.find_condition(ctype)
        if c is None:
            self.conditions.append(
                Condition(ctype, status, reason, message, last_transition_time=now))
        else:
            if c.status != status:
                c.last_transition_time = now
            c.status, c.reason, c.message = status, reason, message

    @property
    def has_quota_reservation(self) -> bool:
        return self.condition_true(CONDITION_QUOTA_RESERVED)

    @property
    def is_admitted(self) -> bool:
        return self.condition_true(CONDITION_ADMITTED)

    @property
    def is_evicted(self) -> bool:
        return self.condition_true(CONDITION_EVICTED)

    @property
    def is_finished(self) -> bool:
        return self.condition_true(CONDITION_FINISHED)

    def quota_reserved_time(self, now: float) -> float:
        c = self.find_condition(CONDITION_QUOTA_RESERVED)
        if c is None or not c.status:
            return now
        return c.last_transition_time

    def can_be_partially_admitted(self) -> bool:
        return any(ps.min_count is not None and ps.min_count < ps.count
                   for ps in self.pod_sets)
