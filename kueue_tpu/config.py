"""Runtime configuration (counterpart of reference apis/config/v1beta1 +
pkg/config).

One Configuration object drives the runtime: waitForPodsReady gating and
requeuing backoff (apis/config/v1beta1/configuration_types.go), queue
visibility, and the fair-sharing knobs this framework implements natively
(KEP-1714).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple

from kueue_tpu.api.types import FairSharingStrategy

REQUEUING_TIMESTAMP_EVICTION = "Eviction"
REQUEUING_TIMESTAMP_CREATION = "Creation"

# Base/factor of the PodsReady requeue backoff
# (reference: core/workload_controller.go:393-399).
BACKOFF_BASE_SECONDS = 1.0
BACKOFF_FACTOR = 1.41284738


@dataclass(frozen=True)
class RequeuingStrategy:
    timestamp: str = REQUEUING_TIMESTAMP_EVICTION
    # None = endless requeueing; otherwise deactivate after this many
    # requeues (workload_controller.go:373-384).
    backoff_limit_count: Optional[int] = None


@dataclass(frozen=True)
class WaitForPodsReady:
    enable: bool = False
    timeout_seconds: float = 300.0
    # Block new admissions while any admitted workload is not PodsReady
    # (KEP-349 all-or-nothing).
    block_admission: bool = True
    requeuing_strategy: RequeuingStrategy = field(default_factory=RequeuingStrategy)


@dataclass(frozen=True)
class FairSharingConfig:
    enable: bool = False
    preemption_strategies: Tuple[str, ...] = (
        FairSharingStrategy.LESS_THAN_OR_EQUAL_TO_FINAL_SHARE,
        FairSharingStrategy.LESS_THAN_INITIAL_SHARE,
    )


@dataclass(frozen=True)
class QueueVisibility:
    max_count: int = 10
    update_interval_seconds: float = 5.0


@dataclass(frozen=True)
class Configuration:
    namespace: str = "kueue-system"
    wait_for_pods_ready: Optional[WaitForPodsReady] = None
    fair_sharing: Optional[FairSharingConfig] = None
    queue_visibility: QueueVisibility = field(default_factory=QueueVisibility)


def requeue_backoff_seconds(requeue_count: int) -> float:
    """Backoff before an evicted-by-PodsReady workload requeues:
    base * factor^(n-1) (workload_controller.go:393-404, jitter omitted)."""
    return BACKOFF_BASE_SECONDS * (BACKOFF_FACTOR ** max(0, requeue_count - 1))
