"""Runtime configuration (counterpart of reference apis/config/v1beta1 +
pkg/config).

One Configuration object drives the runtime. It can be built directly, or
loaded from a YAML/dict document in the reference's on-disk format
(camelCase keys, `--config` file of cmd/kueue/main.go:102-105): `load()`
parses, `set_defaults()` applies the defaulting of
apis/config/v1beta1/defaults.go:30-50, and `validate_configuration()`
enforces the rules of pkg/config/validation.go:47-127.

Knobs that only exist to configure Kubernetes transport (webhook TLS
certs, client QPS/burst, bind addresses) are accepted and carried so
reference config files load unchanged, but the in-process runtime has no
TLS/apiserver boundary to apply them to; see PARITY.md for the explicit
mapping.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional, Tuple

from kueue_tpu.api.types import FairSharingStrategy

REQUEUING_TIMESTAMP_EVICTION = "Eviction"
REQUEUING_TIMESTAMP_CREATION = "Creation"

# Base/factor of the PodsReady requeue backoff
# (reference: core/workload_controller.go:393-399).
BACKOFF_BASE_SECONDS = 1.0
BACKOFF_FACTOR = 1.41284738

# Defaults (apis/config/v1beta1/defaults.go:30-58).
DEFAULT_NAMESPACE = "kueue-system"
DEFAULT_PODS_READY_TIMEOUT_SECONDS = 300.0
DEFAULT_QUEUE_VISIBILITY_UPDATE_INTERVAL_SECONDS = 5.0
DEFAULT_CLUSTER_QUEUES_MAX_COUNT = 10
DEFAULT_JOB_FRAMEWORK = "batch"
DEFAULT_MULTIKUEUE_GC_INTERVAL_SECONDS = 60.0
DEFAULT_MULTIKUEUE_ORIGIN = "multikueue"
DEFAULT_MULTIKUEUE_WORKER_LOST_TIMEOUT_SECONDS = 15 * 60.0
DEFAULT_LEADER_ELECTION_ID = "c1f6bfd2.kueue.x-k8s.io"
DEFAULT_LEASE_DURATION_SECONDS = 15.0
DEFAULT_RENEW_DEADLINE_SECONDS = 10.0
DEFAULT_RETRY_PERIOD_SECONDS = 2.0

# Validation bounds (pkg/config/validation.go:30-32).
QUEUE_VISIBILITY_MAX_COUNT_LIMIT = 4000
QUEUE_VISIBILITY_MIN_UPDATE_INTERVAL_SECONDS = 1.0


class ConfigurationError(ValueError):
    """Raised by validate_configuration / load on an invalid document."""

    def __init__(self, errors: List[str]):
        self.errors = list(errors)
        super().__init__("; ".join(errors))


@dataclass(frozen=True)
class RequeuingStrategy:
    timestamp: str = REQUEUING_TIMESTAMP_EVICTION
    # None = endless requeueing; otherwise deactivate after this many
    # requeues (workload_controller.go:373-384).
    backoff_limit_count: Optional[int] = None


@dataclass(frozen=True)
class WaitForPodsReady:
    enable: bool = False
    timeout_seconds: float = DEFAULT_PODS_READY_TIMEOUT_SECONDS
    # Block new admissions while any admitted workload is not PodsReady
    # (KEP-349 all-or-nothing). Reference defaults this to `enable`.
    block_admission: bool = True
    requeuing_strategy: RequeuingStrategy = field(default_factory=RequeuingStrategy)


@dataclass(frozen=True)
class FairSharingConfig:
    enable: bool = False
    preemption_strategies: Tuple[str, ...] = (
        FairSharingStrategy.LESS_THAN_OR_EQUAL_TO_FINAL_SHARE,
        FairSharingStrategy.LESS_THAN_INITIAL_SHARE,
    )


@dataclass(frozen=True)
class QueueVisibility:
    max_count: int = DEFAULT_CLUSTER_QUEUES_MAX_COUNT
    update_interval_seconds: float = DEFAULT_QUEUE_VISIBILITY_UPDATE_INTERVAL_SECONDS


@dataclass(frozen=True)
class PodIntegrationOptions:
    """Namespace/pod label selectors scoping the pod-group integration
    (configuration_types.go PodIntegrationOptions). Selectors are full
    metav1.LabelSelector analogs (matchLabels + matchExpressions)."""
    namespace_selector: Optional["LabelSelector"] = None
    pod_selector: Optional["LabelSelector"] = None


@dataclass(frozen=True)
class Integrations:
    # None = every registered integration (the embedded-library default);
    # a config file without an `integrations` section gets the reference
    # default of batch only (defaults.go:141-143).
    frameworks: Optional[Tuple[str, ...]] = None
    pod_options: Optional[PodIntegrationOptions] = None

    def enables(self, kind: str) -> bool:
        return self.frameworks is None or kind in self.frameworks


@dataclass(frozen=True)
class MultiKueueConfig:
    """MultiKueue controller knobs (configuration_types.go MultiKueue)."""
    gc_interval_seconds: float = DEFAULT_MULTIKUEUE_GC_INTERVAL_SECONDS
    origin: str = DEFAULT_MULTIKUEUE_ORIGIN
    worker_lost_timeout_seconds: float = DEFAULT_MULTIKUEUE_WORKER_LOST_TIMEOUT_SECONDS


@dataclass(frozen=True)
class TPUSolverConfig:
    """TPU solve-path knobs — this build's extension to the reference
    Configuration (the north-star gRPC/JAX boundary of SURVEY §2.5).

    `enable` None (the default) means auto: the device solve path turns on
    when an accelerator backend is present and falls back to the pure host
    referee on CPU-only hosts — the TPU path is the default of a
    TPU-native framework, not an opt-in. `pipeline_depth` > 1 keeps that
    many ticks' device solves in flight while older ticks complete
    host-side (admission-safe via the scheduler's staleness
    re-validation); 1 is the reference-equivalent synchronous mode.
    `preemption_engine` selects the minimal-preemptions engine: None/
    "auto" = the batched C++ scan whenever the solver runs (host referee
    otherwise), "host" = force the per-entry host referee, "native" =
    force the C++ batch engine, "jax"/"pallas" = one packed XLA dispatch
    per round."""
    enable: Optional[bool] = None
    pipeline_depth: int = 1
    preemption_engine: Optional[str] = None
    # Multi-chip scale-out (parallel/mesh.py): shard every solve over a
    # jax.sharding.Mesh of this many devices (CQ usage partitioned with
    # on-device cohort psum/all_gather over ICI; workload batch
    # data-parallel). 0/1 = single-device; -1 = all visible devices.
    shard_devices: int = 0
    # Cohort-sharded solve (parallel/mesh.CohortMesh — the production
    # scale-out path): the batch is partitioned by cohort hash into
    # per-shard compacted blocks, one device each, with NO collectives;
    # the admit cycle goes two-phase (optimistic per-shard, global
    # lending-clamp reconcile) for hierarchical trees the hash splits.
    # 0/1 = single-device; -1 = all visible devices. Kill switch:
    # KUEUE_TPU_NO_SHARD=1.
    cohort_shards: int = 0
    # Flavor-assignment solve mode (solver/modes.SOLVE_MODES): "default"
    # = the reference's ordered first-fit; "hetero" = Gavel-style
    # max-effective-throughput scoring over the same quota constraints
    # (kueue_tpu/hetero). Kill switch: KUEUE_TPU_NO_HETERO=1.
    mode: str = "default"


@dataclass(frozen=True)
class TransportConfig:
    """Replica transport (kueue_tpu/transport) — how scheduler replicas
    and the coordinator talk.

    `mode` "pipe" keeps the single-machine multiprocessing pipes;
    "socket" runs the length-prefixed framed reconcile protocol over
    TCP (per-host state dirs + coordinator-owned journal replication,
    the multi-host deployment). `listen` is the coordinator's bind
    address ("host:port", port 0 = ephemeral); `peers` carries the
    replica hosts' advertised addresses (accepted and carried for
    real multi-machine deployments; the single-binary CLI spawns its
    replicas locally and they dial `listen`). `faults` is a drill-only
    injection spec ("delay_ms=5,delay_p=0.5,drop_p=0.01,seed=7").
    Kill switch: KUEUE_TPU_NO_SOCKET=1 forces pipe mode."""
    mode: str = "pipe"
    listen: str = "127.0.0.1:0"
    peers: Tuple[str, ...] = ()
    faults: str = ""

    def listen_addr(self) -> Tuple[str, int]:
        host, _, port = self.listen.rpartition(":")
        return (host or "127.0.0.1", int(port))


@dataclass(frozen=True)
class LeaderElectionConfig:
    """Lease-based leader election for HA replicas
    (configv1alpha1.LeaderElectionConfiguration; defaults.go:37-44)."""
    enable: bool = False
    resource_name: str = DEFAULT_LEADER_ELECTION_ID
    lease_duration_seconds: float = DEFAULT_LEASE_DURATION_SECONDS
    renew_deadline_seconds: float = DEFAULT_RENEW_DEADLINE_SECONDS
    retry_period_seconds: float = DEFAULT_RETRY_PERIOD_SECONDS


@dataclass(frozen=True)
class MetricsConfig:
    """controller-runtime metrics options we honor (the bind address is
    transport config the embedded build has no server for; the reference
    knob enableClusterQueueResources gates the optional per-CQ quota
    gauges, configuration_types.go:135-138)."""

    enable_cluster_queue_resources: bool = False


@dataclass(frozen=True)
class Configuration:
    namespace: str = DEFAULT_NAMESPACE
    # Reconcile jobs submitted with no queue name: suspended until queued
    # (configuration_types.go ManageJobsWithoutQueueName).
    manage_jobs_without_queue_name: bool = False
    wait_for_pods_ready: Optional[WaitForPodsReady] = None
    fair_sharing: Optional[FairSharingConfig] = None
    queue_visibility: QueueVisibility = field(default_factory=QueueVisibility)
    integrations: Integrations = field(default_factory=Integrations)
    multikueue: MultiKueueConfig = field(default_factory=MultiKueueConfig)
    leader_election: LeaderElectionConfig = field(default_factory=LeaderElectionConfig)
    tpu_solver: TPUSolverConfig = field(default_factory=TPUSolverConfig)
    transport: TransportConfig = field(default_factory=TransportConfig)
    metrics: MetricsConfig = field(default_factory=MetricsConfig)
    # Transport-only reference knobs, carried opaquely (see module doc).
    extra: Dict[str, Any] = field(default_factory=dict)


def requeue_backoff_seconds(requeue_count: int) -> float:
    """Backoff before an evicted-by-PodsReady workload requeues:
    base * factor^(n-1) (workload_controller.go:393-404, jitter omitted)."""
    return BACKOFF_BASE_SECONDS * (BACKOFF_FACTOR ** max(0, requeue_count - 1))


# -- loading (pkg/config/config.go:150-170 analog) ---------------------------

_TRANSPORT_KEYS = (
    "webhook", "metrics", "health", "pprofBindAddress", "controller",
    "internalCertManagement", "clientConnection", "apiVersion", "kind",
)


def _duration_seconds(v: Any, default: float, field_name: str = "") -> float:
    """Accept numbers (seconds) or k8s duration strings ("5m", "30s")."""
    where = f"{field_name}: " if field_name else ""
    if v is None:
        return default
    if isinstance(v, bool):
        raise ConfigurationError([f"{where}invalid duration {v!r}"])
    if isinstance(v, (int, float)):
        return float(v)
    s = str(v).strip()
    if not s:
        raise ConfigurationError([f"{where}invalid duration {v!r}"])
    units = {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0}
    total, num = 0.0, ""
    i = 0
    try:
        while i < len(s):
            ch = s[i]
            if ch.isdigit() or ch == ".":
                num += ch
                i += 1
                continue
            unit = ch
            if s[i:i + 2] == "ms":
                unit, i = "ms", i + 1
            i += 1
            if not num or unit not in units:
                raise ValueError(s)
            total += float(num) * units[unit]
            num = ""
        if num:  # bare number
            total += float(num)
    except ValueError:
        raise ConfigurationError([f"{where}invalid duration {s!r}"])
    return total


def _decode_selector(sel: Optional[Mapping[str, Any]]) -> Optional["LabelSelector"]:
    """Decode a metav1.LabelSelector document (matchLabels AND
    matchExpressions — the reference's canonical podOptions default is
    expression-based)."""
    from kueue_tpu.api.types import LabelSelector, MatchExpression

    if sel is None:
        return None
    return LabelSelector(
        match_labels=tuple(sorted((sel.get("matchLabels") or {}).items())),
        match_expressions=tuple(
            MatchExpression(key=e["key"], operator=e["operator"],
                            values=tuple(e.get("values") or ()))
            for e in sel.get("matchExpressions") or ()))


def from_dict(doc: Mapping[str, Any]) -> Configuration:
    """Build a Configuration from a reference-format document (camelCase),
    applying defaulting. Raises ConfigurationError on invalid fields."""
    doc = dict(doc or {})

    wfpr = None
    if doc.get("waitForPodsReady") is not None:
        w = doc["waitForPodsReady"]
        enable = bool(w.get("enable", False))
        rs = w.get("requeuingStrategy") or {}
        wfpr = WaitForPodsReady(
            enable=enable,
            timeout_seconds=_duration_seconds(
                w.get("timeout"), DEFAULT_PODS_READY_TIMEOUT_SECONDS,
                "waitForPodsReady.timeout"),
            # BlockAdmission defaults to Enable (defaults.go:118-124).
            block_admission=bool(w.get("blockAdmission", enable)),
            requeuing_strategy=RequeuingStrategy(
                timestamp=rs.get("timestamp", REQUEUING_TIMESTAMP_EVICTION),
                backoff_limit_count=rs.get("backoffLimitCount"),
            ))

    fair = None
    if doc.get("fairSharing") is not None:
        f = doc["fairSharing"]
        strategies = tuple(f.get("preemptionStrategies") or
                           FairSharingConfig().preemption_strategies)
        fair = FairSharingConfig(enable=bool(f.get("enable", False)),
                                 preemption_strategies=strategies)

    qv = QueueVisibility()
    if doc.get("queueVisibility") is not None:
        q = doc["queueVisibility"]
        cq = q.get("clusterQueues") or {}
        qv = QueueVisibility(
            max_count=int(cq.get("maxCount", DEFAULT_CLUSTER_QUEUES_MAX_COUNT)),
            update_interval_seconds=float(q.get(
                "updateIntervalSeconds",
                DEFAULT_QUEUE_VISIBILITY_UPDATE_INTERVAL_SECONDS)))

    # Config files get the reference default (batch only, defaults.go:141-143).
    integrations = Integrations(frameworks=(DEFAULT_JOB_FRAMEWORK,))
    if doc.get("integrations") is not None:
        it = doc["integrations"]
        # An explicitly empty list stays empty so validation rejects it
        # (validation.go "cannot be empty"); only absence defaults.
        raw_fw = it.get("frameworks")
        frameworks = (tuple(raw_fw) if raw_fw is not None
                      else (DEFAULT_JOB_FRAMEWORK,))
        po = None
        if it.get("podOptions") is not None:
            po = PodIntegrationOptions(
                namespace_selector=_decode_selector(
                    it["podOptions"].get("namespaceSelector")),
                pod_selector=_decode_selector(
                    it["podOptions"].get("podSelector")))
        integrations = Integrations(frameworks=frameworks, pod_options=po)

    mk = MultiKueueConfig()
    if doc.get("multiKueue") is not None:
        m = doc["multiKueue"]
        mk = MultiKueueConfig(
            gc_interval_seconds=_duration_seconds(
                m.get("gcInterval"), DEFAULT_MULTIKUEUE_GC_INTERVAL_SECONDS,
                "multiKueue.gcInterval"),
            origin=m.get("origin") or DEFAULT_MULTIKUEUE_ORIGIN,
            worker_lost_timeout_seconds=_duration_seconds(
                m.get("workerLostTimeout"),
                DEFAULT_MULTIKUEUE_WORKER_LOST_TIMEOUT_SECONDS,
                "multiKueue.workerLostTimeout"))

    ts = TPUSolverConfig()
    if doc.get("tpuSolver") is not None:
        t = doc["tpuSolver"]
        enable = t.get("enable")
        ts = TPUSolverConfig(
            enable=None if enable is None else bool(enable),
            pipeline_depth=int(t.get("pipelineDepth", 1)),
            preemption_engine=t.get("preemptionEngine"),
            shard_devices=int(t.get("shardDevices", 0)),
            cohort_shards=int(t.get("cohortShards", 0)),
            mode=t.get("mode") or "default")

    tr = TransportConfig()
    if doc.get("transport") is not None:
        t = doc["transport"]
        tr = TransportConfig(
            mode=t.get("mode") or "pipe",
            listen=t.get("listen") or "127.0.0.1:0",
            peers=tuple(t.get("peers") or ()),
            faults=t.get("faults") or "")

    mc = MetricsConfig()
    if isinstance(doc.get("metrics"), dict):
        mc = MetricsConfig(enable_cluster_queue_resources=bool(
            doc["metrics"].get("enableClusterQueueResources", False)))

    le = LeaderElectionConfig()
    if doc.get("leaderElection") is not None:
        l = doc["leaderElection"]
        le = LeaderElectionConfig(
            enable=bool(l.get("leaderElect", False)),
            resource_name=l.get("resourceName") or DEFAULT_LEADER_ELECTION_ID,
            lease_duration_seconds=_duration_seconds(
                l.get("leaseDuration"), DEFAULT_LEASE_DURATION_SECONDS,
                "leaderElection.leaseDuration"),
            renew_deadline_seconds=_duration_seconds(
                l.get("renewDeadline"), DEFAULT_RENEW_DEADLINE_SECONDS,
                "leaderElection.renewDeadline"),
            retry_period_seconds=_duration_seconds(
                l.get("retryPeriod"), DEFAULT_RETRY_PERIOD_SECONDS,
                "leaderElection.retryPeriod"))

    cfg = Configuration(
        namespace=doc.get("namespace") or DEFAULT_NAMESPACE,
        manage_jobs_without_queue_name=bool(
            doc.get("manageJobsWithoutQueueName", False)),
        wait_for_pods_ready=wfpr,
        fair_sharing=fair,
        queue_visibility=qv,
        integrations=integrations,
        multikueue=mk,
        leader_election=le,
        tpu_solver=ts,
        transport=tr,
        metrics=mc,
        extra={k: doc[k] for k in _TRANSPORT_KEYS if k in doc},
    )
    errors = validate_configuration(cfg)
    if errors:
        raise ConfigurationError(errors)
    return cfg


def load(path: str) -> Configuration:
    """Load a configuration file (YAML, reference --config format)."""
    import yaml

    with open(path) as fh:
        doc = yaml.safe_load(fh) or {}
    if not isinstance(doc, dict):
        raise ConfigurationError([f"config file {path} is not a mapping"])
    return from_dict(doc)


# -- validation (pkg/config/validation.go) -----------------------------------

def known_frameworks() -> Tuple[str, ...]:
    from kueue_tpu.controllers import jobframework
    import kueue_tpu.jobs  # noqa: F401  (registers integrations)
    return tuple(sorted(jobframework.integrations()))


def validate_configuration(cfg: Configuration) -> List[str]:
    errors: List[str] = []

    # waitForPodsReady (validation.go:56-73)
    wfpr = cfg.wait_for_pods_ready
    if wfpr is not None and wfpr.enable:
        rs = wfpr.requeuing_strategy
        if rs.timestamp not in (REQUEUING_TIMESTAMP_EVICTION,
                                REQUEUING_TIMESTAMP_CREATION):
            errors.append(
                "waitForPodsReady.requeuingStrategy.timestamp: unsupported "
                f"value {rs.timestamp!r} (want Eviction or Creation)")
        if rs.backoff_limit_count is not None and rs.backoff_limit_count < 0:
            errors.append(
                "waitForPodsReady.requeuingStrategy.backoffLimitCount: "
                "must not be negative")
        if wfpr.timeout_seconds <= 0:
            errors.append("waitForPodsReady.timeout: must be positive")

    # queueVisibility (validation.go:75-90)
    qv = cfg.queue_visibility
    if qv.max_count > QUEUE_VISIBILITY_MAX_COUNT_LIMIT:
        errors.append(
            f"queueVisibility.clusterQueues.maxCount: must be less than "
            f"{QUEUE_VISIBILITY_MAX_COUNT_LIMIT}")
    if qv.update_interval_seconds < QUEUE_VISIBILITY_MIN_UPDATE_INTERVAL_SECONDS:
        errors.append(
            "queueVisibility.updateIntervalSeconds: must be greater than or "
            f"equal to {QUEUE_VISIBILITY_MIN_UPDATE_INTERVAL_SECONDS:g}")

    # integrations (validation.go:92-127)
    if cfg.integrations.frameworks is not None and not cfg.integrations.frameworks:
        errors.append("integrations.frameworks: cannot be empty")
    elif cfg.integrations.frameworks is not None:
        known = known_frameworks()
        for fw in cfg.integrations.frameworks:
            if fw not in known:
                errors.append(
                    f"integrations.frameworks: unknown framework {fw!r} "
                    f"(known: {', '.join(known)})")
        if "podgroup" in cfg.integrations.frameworks:
            po = cfg.integrations.pod_options
            if po is None:
                errors.append(
                    "integrations.podOptions: cannot be empty when the pod "
                    "integration is enabled")
            elif po.namespace_selector is None:
                errors.append(
                    "integrations.podOptions.namespaceSelector: a namespace "
                    "selector is required")
            else:
                # Never reconcile kube-system or the controller namespace
                # (validation.go prohibitedNamespaces): the selector must
                # NOT match either namespace, whether it is expressed as
                # matchLabels or matchExpressions.
                for prohibited in ("kube-system", cfg.namespace):
                    if po.namespace_selector.matches(
                            {"kubernetes.io/metadata.name": prohibited}):
                        errors.append(
                            "integrations.podOptions.namespaceSelector: "
                            f"must not match the {prohibited!r} namespace")

    # fairSharing preemption strategies (reference validates the enum)
    if cfg.fair_sharing is not None:
        known_strategies = (FairSharingStrategy.LESS_THAN_OR_EQUAL_TO_FINAL_SHARE,
                            FairSharingStrategy.LESS_THAN_INITIAL_SHARE)
        for s in cfg.fair_sharing.preemption_strategies:
            if s not in known_strategies:
                errors.append(
                    f"fairSharing.preemptionStrategies: unsupported value "
                    f"{s!r} (want one of: {', '.join(known_strategies)})")

    # multiKueue
    if cfg.multikueue.gc_interval_seconds < 0:
        errors.append("multiKueue.gcInterval: must not be negative")
    if cfg.multikueue.worker_lost_timeout_seconds < 0:
        errors.append("multiKueue.workerLostTimeout: must not be negative")

    # tpuSolver
    if cfg.tpu_solver.pipeline_depth < 1:
        errors.append("tpuSolver.pipelineDepth: must be >= 1")
    if cfg.tpu_solver.preemption_engine not in (None, "auto", "host",
                                                "native", "jax", "pallas"):
        errors.append("tpuSolver.preemptionEngine: must be one of "
                      "auto, host, native, jax, pallas (or omitted for auto)")
    if cfg.tpu_solver.shard_devices < -1:
        errors.append("tpuSolver.shardDevices: must be -1 (all devices), "
                      "0/1 (single device), or a positive device count")
    if cfg.tpu_solver.cohort_shards < -1:
        errors.append("tpuSolver.cohortShards: must be -1 (all devices), "
                      "0/1 (single device), or a positive shard count")
    if cfg.tpu_solver.cohort_shards not in (0, 1) \
            and cfg.tpu_solver.shard_devices not in (0, 1):
        errors.append("tpuSolver.cohortShards and tpuSolver.shardDevices "
                      "are mutually exclusive sharding modes")
    # Solve mode: only REGISTERED modes pass (solver/modes.SOLVE_MODES —
    # the registry the kueueverify roster and the coverage meta-test are
    # pinned to), so a typo'd or unregistered mode fails at config load,
    # not silently at the first tick.
    from kueue_tpu.solver.modes import solve_mode_names
    if cfg.tpu_solver.mode not in solve_mode_names():
        errors.append(
            f"tpuSolver.mode: unknown solve mode {cfg.tpu_solver.mode!r} "
            f"(registered modes: {', '.join(solve_mode_names())})")
    if cfg.tpu_solver.mode == "hetero" \
            and cfg.tpu_solver.shard_devices not in (0, 1):
        errors.append("tpuSolver.mode: hetero runs single-device or over "
                      "cohortShards — shardDevices is not a supported "
                      "combination")

    # transport
    tr = cfg.transport
    if tr.mode not in ("pipe", "socket"):
        errors.append("transport.mode: must be pipe or socket")
    try:
        tr.listen_addr()
    except (ValueError, TypeError):
        errors.append(
            f"transport.listen: invalid address {tr.listen!r} "
            "(want host:port, port 0 for ephemeral)")
    if tr.faults:
        from kueue_tpu.transport.faults import parse_fault_env
        try:
            parse_fault_env(tr.faults)
        except ValueError as exc:
            errors.append(f"transport.faults: {exc}")

    # leaderElection
    le = cfg.leader_election
    if le.enable:
        if le.lease_duration_seconds <= le.renew_deadline_seconds:
            errors.append("leaderElection.leaseDuration: must be greater "
                          "than renewDeadline")
        if le.renew_deadline_seconds <= le.retry_period_seconds:
            errors.append("leaderElection.renewDeadline: must be greater "
                          "than retryPeriod")
    return errors
