"""Lifecycle plumbing: the in-memory API runtime and reconciler logic."""

from kueue_tpu.controllers.runtime import Framework
