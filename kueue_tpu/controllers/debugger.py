"""State dumper (counterpart of reference pkg/debugger/debugger.go:41-64).

Dumps the full admitted-state cache and the pending queues as a plain dict
(JSON-serializable); optionally registered on SIGUSR2 like the reference.
"""

from __future__ import annotations

import json
import signal
import sys
from typing import Dict

from kueue_tpu.core.cache import Cache
from kueue_tpu.queue.manager import Manager


class Dumper:
    def __init__(self, cache: Cache = None, queues: Manager = None,
                 events=None, explain=None, reconcile=None):
        # cache/queues may be None in replica mode: the parent process
        # owns no scheduler slice — only the coordinator's reconcile
        # state (the `reconcile` provider below).
        self.cache = cache
        self.queues = queues
        # Optional extras: the Framework's EventRecorder (occupancy /
        # drop accounting), the scheduler's ExplainStore (last
        # admission decision per workload), and the replica runtime's
        # reconcile info provider (barrier round + coordinator epoch +
        # per-shard-group backlog depth).
        self.events = events
        self.explain = explain
        self.reconcile = reconcile

    def dump(self) -> Dict:
        cache_dump = {}
        for name, cq in (self.cache.cluster_queues.items()
                         if self.cache is not None else ()):
            cache_dump[name] = {
                "cohort": cq.cohort_name,
                "usage": {f: dict(r) for f, r in cq.usage.items()},
                "admittedWorkloads": sorted(cq.workloads),
                "allocatableGeneration": cq.allocatable_generation,
                "active": cq.active(),
            }
        queue_dump = {}
        for name, cq in (self.queues.cluster_queues.items()
                         if self.queues is not None else ()):
            queue_dump[name] = {
                "active": [wi.key for wi in cq.heap.items()],
                "inadmissible": sorted(cq.inadmissible),
                "popCycle": cq.pop_cycle,
            }
        out = {"cache": cache_dump, "queues": queue_dump}
        if self.reconcile is not None:
            out["reconcile"] = self.reconcile()
        if self.events is not None:
            out["events"] = {
                "occupancy": self.events.occupancy,
                "capacity": self.events.capacity,
                "dropped": self.events.dropped,
            }
        if self.explain is not None:
            out["explain"] = {
                "workloads": self.explain.occupancy,
                "lastDecisions": self.explain.snapshot(limit=100),
            }
        return out

    def dump_json(self) -> str:
        return json.dumps(self.dump(), indent=2, sort_keys=True)

    def listen_for_signal(self) -> None:
        """SIGUSR2 -> dump to stderr (debugger.go ListenForSignal)."""
        signal.signal(signal.SIGUSR2,
                      lambda *_: print(self.dump_json(), file=sys.stderr))
