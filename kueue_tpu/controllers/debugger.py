"""State dumper (counterpart of reference pkg/debugger/debugger.go:41-64).

Dumps the full admitted-state cache and the pending queues as a plain dict
(JSON-serializable); optionally registered on SIGUSR2 like the reference.
"""

from __future__ import annotations

import json
import signal
import sys
from typing import Dict

from kueue_tpu.core.cache import Cache
from kueue_tpu.queue.manager import Manager


class Dumper:
    def __init__(self, cache: Cache, queues: Manager):
        self.cache = cache
        self.queues = queues

    def dump(self) -> Dict:
        cache_dump = {}
        for name, cq in self.cache.cluster_queues.items():
            cache_dump[name] = {
                "cohort": cq.cohort_name,
                "usage": {f: dict(r) for f, r in cq.usage.items()},
                "admittedWorkloads": sorted(cq.workloads),
                "allocatableGeneration": cq.allocatable_generation,
                "active": cq.active(),
            }
        queue_dump = {}
        for name, cq in self.queues.cluster_queues.items():
            queue_dump[name] = {
                "active": [wi.key for wi in cq.heap.items()],
                "inadmissible": sorted(cq.inadmissible),
                "popCycle": cq.pop_cycle,
            }
        return {"cache": cache_dump, "queues": queue_dump}

    def dump_json(self) -> str:
        return json.dumps(self.dump(), indent=2, sort_keys=True)

    def listen_for_signal(self) -> None:
        """SIGUSR2 -> dump to stderr (debugger.go ListenForSignal)."""
        signal.signal(signal.SIGUSR2,
                      lambda *_: print(self.dump_json(), file=sys.stderr))
