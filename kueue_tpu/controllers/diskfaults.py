"""Injectable DISK faults for durable-journal drills.

The seeded-schedule twin of transport/faults.py, aimed at the failures
a real fleet's disks actually throw at an append-only journal:

  * enospc — the append raises OSError(ENOSPC) before any byte lands
    (a full volume). The event is lost exactly as an unacknowledged
    write is lost; the error is counted in
    kueue_journal_write_errors_total, never swallowed.
  * fsync  — the data write lands but fsync raises (EIO). The line MAY
    survive a crash; durability of that one record is unknown — which
    is precisely what replay's torn/complete distinction absorbs.
  * torn   — only a PREFIX of the line reaches the file and the writer
    "crashes" (TornWrite raised after the partial write). This is the
    power-cut mid-append shape; attach-time replay must truncate the
    torn tail and recover every complete record.

A `DiskFaultPlan` is a pure function of (seed, path, append ordinal),
so a drill replays bit-identically — same discipline as the transport
plans, same reason (the soak and the regression fixtures must be
reproducible).

Opt-in only: `KUEUE_TPU_DISK_FAULTS="enospc_p=0.01,torn_p=0.005,
fsync_p=0.01,seed=7"` (or a plan passed to `Journal(faults=...)`).
"""

from __future__ import annotations

import errno
import random
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional

# Append dispositions.
PASS = "pass"
ENOSPC = "enospc"
FSYNC = "fsync"
TORN = "torn"


class TornWrite(OSError):
    """A torn trailing write: the partial prefix is on disk and the
    writer is considered crashed for this record (the injection's
    stand-in for power loss mid-append)."""


@dataclass(frozen=True)
class DiskFaultPlan:
    seed: int = 0
    enospc_prob: float = 0.0
    fsync_prob: float = 0.0
    torn_prob: float = 0.0

    @property
    def active(self) -> bool:
        return (self.enospc_prob > 0 or self.fsync_prob > 0
                or self.torn_prob > 0)

    def injector(self, path: str) -> Optional["DiskFaultInjector"]:
        return DiskFaultInjector(self, path) if self.active else None

    def to_dict(self) -> Dict[str, float]:
        return {"seed": self.seed, "enospc_prob": self.enospc_prob,
                "fsync_prob": self.fsync_prob, "torn_prob": self.torn_prob}

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> Optional["DiskFaultPlan"]:
        if not d:
            return None
        return cls(seed=int(d.get("seed", 0)),
                   enospc_prob=float(d.get("enospc_prob", 0.0)),
                   fsync_prob=float(d.get("fsync_prob", 0.0)),
                   torn_prob=float(d.get("torn_prob", 0.0)))


def parse_disk_fault_env(spec: Optional[str]) -> Optional[DiskFaultPlan]:
    """Parse `KUEUE_TPU_DISK_FAULTS` ("enospc_p=0.01,fsync_p=0.02,
    torn_p=0.005,seed=7"); None/empty disables."""
    if not spec:
        return None
    keys = {"enospc_p": "enospc_prob", "fsync_p": "fsync_prob",
            "torn_p": "torn_prob", "seed": "seed"}
    kw: Dict[str, float] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, val = part.partition("=")
        field_name = keys.get(name.strip())
        if field_name is None:
            raise ValueError(
                f"KUEUE_TPU_DISK_FAULTS: unknown knob {name.strip()!r} "
                f"(known: {', '.join(sorted(keys))})")
        kw[field_name] = float(val)
    if "seed" in kw:
        kw["seed"] = int(kw["seed"])
    plan = DiskFaultPlan(**kw)
    return plan if plan.active else None


@dataclass
class DiskFaultStats:
    enospc: int = 0
    fsyncs: int = 0
    torn: int = 0
    schedule: List[str] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {"enospc": self.enospc, "fsyncs": self.fsyncs,
                "torn": self.torn}


class DiskFaultInjector:
    """Per-journal deterministic fault schedule (crc32-of-path mixed
    with the plan seed — never `hash()`, which is salted per process)."""

    def __init__(self, plan: DiskFaultPlan, path: str):
        self.plan = plan
        self.path = path
        self._rnd = random.Random(
            plan.seed * 1_000_003
            + zlib.crc32(str(path).encode("utf-8")))
        self.stats = DiskFaultStats()

    def next_action(self) -> str:
        """Disposition for the next append. Draw order fixed (enospc,
        torn, fsync) so the schedule reproduces."""
        rnd = self._rnd
        plan = self.plan
        action = PASS
        if rnd.random() < plan.enospc_prob:
            action = ENOSPC
        elif rnd.random() < plan.torn_prob:
            action = TORN
        elif rnd.random() < plan.fsync_prob:
            action = FSYNC
        stats = self.stats
        if action == ENOSPC:
            stats.enospc += 1
        elif action == TORN:
            stats.torn += 1
        elif action == FSYNC:
            stats.fsyncs += 1
        stats.schedule.append(action)
        return action

    def torn_prefix_len(self, line_len: int) -> int:
        """How many bytes of the line land before the 'power cut' (at
        least 1, never the whole line + newline)."""
        return max(1, int(self._rnd.random() * line_len))

    def enospc_error(self) -> OSError:
        return OSError(errno.ENOSPC, "No space left on device (injected)")

    def fsync_error(self) -> OSError:
        return OSError(errno.EIO, "fsync failed (injected)")
