"""Durable state: an append-only journal behind the Store.

The reference externalizes every decision to the Kubernetes apiserver
(etcd) and rebuilds its caches on startup — the cache re-lists admitted
workloads per ClusterQueue (cache.go:295-328) and the queue manager
re-adopts pending ones (queue/manager.go:121-134). This module is that
durability boundary for the embedded runtime: every Store event appends a
JSON line (the manifest format of api/serialization, so journals are
kubectl-shaped and human-readable); on boot the journal replays into a
fresh Store BEFORE the controllers attach, and the StoreAdapter's initial
watch replay rebuilds the Framework — admitted workloads re-account their
quota, pending ones re-queue (Framework.restore_workload).

The journal self-compacts: when the live object count falls below half
the journal's line count (and the journal has grown past a floor), the
file is atomically rewritten as a snapshot of current state.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Optional

from kueue_tpu import knobs
from kueue_tpu.api import serialization
from kueue_tpu.controllers import store as store_mod
from kueue_tpu.controllers.store import DELETED, Event, Store
from kueue_tpu.tracing import TRACER

# Replay/snapshot kind order: referenced-before-referencing (a workload's
# admission names a ClusterQueue; a LocalQueue names a ClusterQueue...).
KIND_ORDER = (
    store_mod.KIND_RESOURCE_FLAVOR,
    store_mod.KIND_COHORT,
    store_mod.KIND_CLUSTER_QUEUE,
    store_mod.KIND_LOCAL_QUEUE,
    store_mod.KIND_WORKLOAD_PRIORITY_CLASS,
    store_mod.KIND_ADMISSION_CHECK,
    store_mod.KIND_WORKLOAD,
)

COMPACT_MIN_LINES = 2000


class Journal:
    """Append-only event log attached to a Store."""

    def __init__(self, path: str, fsync: Optional[bool] = None,
                 faults=None):
        from kueue_tpu.controllers.diskfaults import parse_disk_fault_env

        self.path = path
        self.fsync = (knobs.flag("KUEUE_TPU_DURABLE_FSYNC")
                      if fsync is None else fsync)
        self._lock = threading.Lock()
        self._file = None
        self._lines = 0
        self._store: Optional[Store] = None
        self._owner_lock_file = None
        # Seeded disk-fault injection (diskfaults.py): a DiskFaultPlan,
        # a prebuilt injector, or the KUEUE_TPU_DISK_FAULTS env knob.
        # None (the default, env unset) injects nothing.
        if faults is None:
            faults = parse_disk_fault_env(
                knobs.raw("KUEUE_TPU_DISK_FAULTS"))
        self.faults = (faults.injector(path)
                       if faults is not None and hasattr(faults, "injector")
                       else faults)
        # Durability bookkeeping for torn-tail repair: the file offset
        # after the last append KNOWN to be complete. A failed append
        # truncates back to it before the next record, so a torn prefix
        # can never glue onto a later line.
        self._good_offset = 0
        self._dirty_tail = False
        self.write_errors = 0
        self.replay_skipped = 0
        self.torn_tail_recovered = 0
        # Bootstrap evidence: journal lines applied by _replay on attach
        # (the rejoin-cost number the snapshot-shipping path minimizes).
        self.replayed_lines = 0
        # Deferred-sync bookkeeping (_record_batch): an injected fsync
        # failure drawn mid-batch surfaces at the batch flush, exactly
        # like a real one would.
        self._pending_fsync_error: Optional[OSError] = None
        # Replication tap (transport/replication.py): every recorded
        # line is mirrored as ("append", line), every compaction as
        # ("reset", [lines]) — the multi-host runtime ships these
        # segment ops to the coordinator so fail-over does not need to
        # read this host's filesystem. None = no replication.
        self.sink = None

    # -- boot ---------------------------------------------------------------

    def attach(self, store: Store) -> int:
        """Replay any existing journal into `store`, compact, then start
        recording its events. Returns the number of objects restored.
        Call BEFORE controllers watch the store, so their initial watch
        replay sees the recovered state.

        The journal is SINGLE-WRITER: an exclusive flock is held for its
        lifetime, so a second process attaching the same path fails fast
        instead of corrupting it. HA replicas share ONE state dir (the
        etcd analog) but DEFER the attach until they hold the leader
        lease (__main__.tick_once) — the standby replays the dead
        leader's journal at takeover and only then becomes the writer."""
        import fcntl

        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        # Acquire the flock on a local handle first: the open+flock I/O
        # happens with no lock held, and the attribute publish (which
        # close() reads under self._lock) is guarded.
        owner = open(self.path + ".owner", "a+")
        try:
            fcntl.flock(owner.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            owner.close()
            raise RuntimeError(
                f"state journal {self.path} is owned by another process "
                "(journals are single-writer; an elected replica attaches "
                "only after taking the lease, so this clears once the "
                "previous owner exits)")
        with self._lock:
            self._owner_lock_file = owner
        self._store = store
        restored = self._replay(store)
        self._compact(store)
        for kind in KIND_ORDER:
            store.watch(kind, self._record, send_initial=False,
                        batch=self._record_batch)
        return restored

    def _replay(self, store: Store) -> int:
        if not os.path.exists(self.path):
            return 0
        import sys

        from kueue_tpu.metrics import REGISTRY

        # Parse with byte offsets so a torn TRAILING line can be
        # truncated off the file (not just skipped: a skipped-but-kept
        # torn prefix would glue onto the next append and corrupt BOTH
        # records), while a torn/corrupt MID-file line — which cannot be
        # a crash artifact of append-only writing — is skipped, counted
        # and logged, never silently absorbed.
        parsed = []  # (start_offset, entry_or_None)
        offset = 0
        with open(self.path, "rb") as f:
            for raw in f:
                start = offset
                offset += len(raw)
                text = raw.decode("utf-8", errors="replace").strip()
                if not text:
                    continue
                try:
                    parsed.append((start, json.loads(text)))
                except ValueError:
                    parsed.append((start, None))
        torn_at = None
        if parsed and parsed[-1][1] is None:
            torn_at, _ = parsed.pop()
        for start, entry in parsed:
            if entry is None:
                self.replay_skipped += 1
                REGISTRY.journal_write_errors_total.inc("corrupt-replay")
                print(f"kueue-tpu: journal {self.path}: skipped a "
                      f"corrupt mid-file line at byte {start}",
                      file=sys.stderr, flush=True)
                continue
            self._apply(store, entry)
            self.replayed_lines += 1
        if torn_at is not None:
            # The crash-mid-append artifact: the record was never
            # acknowledged, so dropping it is correct — and truncating
            # it keeps the file appendable (no glued lines).
            with open(self.path, "r+b") as f:
                f.truncate(torn_at)
            self.torn_tail_recovered += 1
            print(f"kueue-tpu: journal {self.path}: truncated a torn "
                  f"trailing line at byte {torn_at}",
                  file=sys.stderr, flush=True)
        return sum(len(store.list(kind)) for kind in KIND_ORDER)

    @staticmethod
    def _apply(store: Store, entry: dict) -> None:
        kind = entry["kind"]
        if entry["type"] == DELETED:
            store.delete(kind, entry["key"])
            return
        doc = entry["object"]
        _, obj = serialization.decode(doc)
        if kind == store_mod.KIND_WORKLOAD and doc.get("status"):
            # decode() is spec-only (the apiserver ignores status on
            # create); a journal replay restores the recorded status too —
            # that is the whole point of the durability boundary.
            serialization.decode_workload_status(doc, obj)
        key = store_mod._obj_key(kind, obj)
        if store.get(kind, key) is None:
            store.create(kind, obj)
        else:
            # Replays carry already-validated state; status writes bypass
            # spec-update immutability exactly as the original did.
            store.update_status(kind, obj)

    # -- recording ------------------------------------------------------------

    def _record(self, ev: Event) -> None:
        entry = {"type": ev.type, "kind": ev.kind, "key": ev.key}
        if ev.type != DELETED:
            entry["object"] = serialization.encode(ev.kind, ev.obj)
        line = json.dumps(entry, separators=(",", ":"))
        with TRACER.lock(self._lock, "journal.lock_wait"):
            try:
                self._append_locked(line)
            except OSError as exc:
                # The record is LOST (exactly as an unacknowledged
                # write is lost in a crash) — but the error is counted
                # and logged, never swallowed, and the tail is marked
                # dirty so a torn prefix can never glue onto the next
                # append.
                self._dirty_tail = True
                self._note_write_error(exc)
                return
            self._lines += 1
            if self.sink is not None:
                self.sink(("append", line))
            if self._lines >= COMPACT_MIN_LINES and self._store is not None:
                live = sum(len(self._store.list(k)) for k in KIND_ORDER)
                if live * 2 < self._lines:
                    try:
                        self._compact_locked(self._store)
                    except OSError as exc:
                        # A failed compaction (ENOSPC on the tmp file)
                        # leaves the journal as it was; surface + retry
                        # at the next threshold crossing.
                        self._note_write_error(exc, reason="compact")

    def _record_batch(self, events) -> None:
        """Batched recording (Store.create_batch): encode every line
        first, then ONE lock acquisition, buffered appends, and one
        flush/fsync for the whole burst instead of per line. The
        per-line fault draw (_append_locked) is unchanged — a seeded
        fault plan injects at the same records either way — and error
        handling stays per line: a failed record is lost and counted,
        the rest of the batch still lands."""
        lines = []
        for ev in events:
            entry = {"type": ev.type, "kind": ev.kind, "key": ev.key}
            if ev.type != DELETED:
                entry["object"] = serialization.encode(ev.kind, ev.obj)
            lines.append(json.dumps(entry, separators=(",", ":")))
        with TRACER.lock(self._lock, "journal.lock_wait"):
            appended = False
            for line in lines:
                try:
                    self._append_locked(line, sync=False)
                except OSError as exc:
                    self._dirty_tail = True
                    self._note_write_error(exc)
                    continue
                appended = True
                self._lines += 1
                if self.sink is not None:
                    self.sink(("append", line))
            if appended:
                self._flush_locked()
            if self._lines >= COMPACT_MIN_LINES and self._store is not None:
                live = sum(len(self._store.list(k)) for k in KIND_ORDER)
                if live * 2 < self._lines:
                    try:
                        self._compact_locked(self._store)
                    except OSError as exc:
                        self._note_write_error(exc, reason="compact")

    def _flush_locked(self) -> None:
        """One flush (and fsync, when configured) for a whole batch; a
        deferred injected fsync failure surfaces here."""
        if self._file is None:
            return
        try:
            self._file.flush()
        except OSError as exc:
            self._dirty_tail = True
            self._note_write_error(exc)
            return
        if self.fsync:
            with TRACER.span("journal.fsync"):
                err, self._pending_fsync_error = \
                    self._pending_fsync_error, None
                try:
                    if err is not None:
                        raise err
                    os.fsync(self._file.fileno())
                except OSError as exc:
                    self._note_write_error(exc, reason="fsync")

    def _append_locked(self, line: str, sync: bool = True) -> None:
        """One fault-injectable append. Caller holds _lock; raises
        OSError when the record did not (completely) land. sync=False
        (the batch path) buffers the write and defers flush/fsync to
        _flush_locked — one disk round trip per burst."""
        from kueue_tpu.controllers import diskfaults

        if self._file is None:
            # Serializing append I/O is this lock's purpose: entries
            # must hit the journal in event order.
            self._file = open(self.path, "a", encoding="utf-8")
            self._good_offset = self._file.tell()
        if self._dirty_tail:
            self._repair_tail_locked()
        injector = self.faults
        action = injector.next_action() if injector is not None \
            else diskfaults.PASS
        with TRACER.span("journal.append") as sp:
            if action == diskfaults.ENOSPC:
                raise injector.enospc_error()
            if action == diskfaults.TORN:
                prefix = (line + "\n")[:injector.torn_prefix_len(
                    len(line))]
                self._file.write(prefix)
                self._file.flush()
                raise diskfaults.TornWrite(
                    f"torn write after {len(prefix)} bytes (injected)")
            self._file.write(line + "\n")
            if sync:
                self._file.flush()
                if self.fsync:
                    with TRACER.span("journal.fsync"):
                        try:
                            if action == diskfaults.FSYNC:
                                raise injector.fsync_error()
                            os.fsync(self._file.fileno())
                        except OSError as exc:
                            # The data write landed; only this record's
                            # DURABILITY is unknown. Count it, keep it —
                            # replay's complete/torn distinction absorbs
                            # whichever way the disk went.
                            self._note_write_error(exc, reason="fsync")
            elif self.fsync and action == diskfaults.FSYNC:
                self._pending_fsync_error = injector.fsync_error()
            sp.set("bytes", len(line) + 1)
        self._good_offset = self._file.tell()

    def _repair_tail_locked(self) -> None:
        """Truncate back to the last known-complete append (a previous
        failed write may have left a torn prefix)."""
        self._file.flush()
        self._file.truncate(self._good_offset)
        self._dirty_tail = False

    def _note_write_error(self, exc: OSError,
                          reason: Optional[str] = None) -> None:
        import errno
        import sys

        from kueue_tpu.controllers.diskfaults import TornWrite
        from kueue_tpu.metrics import REGISTRY

        if reason is None:
            if isinstance(exc, TornWrite):
                reason = "torn"
            elif getattr(exc, "errno", None) == errno.ENOSPC:
                reason = "enospc"
            else:
                reason = "os-error"
        self.write_errors += 1
        REGISTRY.journal_write_errors_total.inc(reason)
        print(f"kueue-tpu: journal {self.path} write failed "
              f"({reason}): {exc}", file=sys.stderr, flush=True)

    # -- compaction -----------------------------------------------------------

    def _compact(self, store: Store) -> None:
        with self._lock:
            self._compact_locked(store)

    def _compact_locked(self, store: Store) -> None:
        """Atomically rewrite the journal as a snapshot of current state."""
        tmp = f"{self.path}.{os.getpid()}.tmp"
        lines = 0
        snapshot = [] if self.sink is not None else None
        with open(tmp, "w", encoding="utf-8") as f:
            for kind in KIND_ORDER:
                for obj in store.list(kind):
                    entry = {"type": store_mod.ADDED, "kind": kind,
                             "key": store_mod._obj_key(kind, obj),
                             "object": serialization.encode(kind, obj)}
                    line = json.dumps(entry, separators=(",", ":"))
                    f.write(line + "\n")
                    if snapshot is not None:
                        snapshot.append(line)
                    lines += 1
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        if self._file is not None:
            self._file.close()
        self._file = open(self.path, "a", encoding="utf-8")
        self._good_offset = self._file.tell()
        self._dirty_tail = False
        self._lines = lines
        if snapshot is not None:
            self.sink(("reset", snapshot))

    def detach(self) -> None:
        """Stop recording (unhook the store watchers) and release the
        journal: the single-writer flock clears, so another process —
        or another replica adopting this shard group — can attach. Used
        by the live group-migration path: the releasing owner detaches
        BEFORE deleting the group's objects from its framework, so the
        deletion storm is never journaled and the file keeps the final
        state for the adopter's replay."""
        with self._lock:
            store = self._store
        if store is not None:
            for kind in KIND_ORDER:
                store.unwatch(kind, self._record)
        self.close()
        self._store = None

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None
            if self._owner_lock_file is not None:
                self._owner_lock_file.close()
                self._owner_lock_file = None
