"""Job-integration framework: the generic job <-> Workload sync engine.

Counterpart of reference pkg/controller/jobframework/: a `GenericJob`
protocol with optional capability seams (interface.go:32-114 —
JobWithReclaimablePods, JobWithCustomStop, JobWithFinalize, JobWithSkip,
JobWithPriorityClass, ComposableJob, prebuilt workloads), an integration
registry keyed by job type (integrationmanager.go:44-95), and the
reconciler state machine (reconciler.go:159-440) that guarantees a single
matching Workload per job (ensureOneWorkload dedup + finish-stale,
reconciler.go:478-579), creates Workloads from job pod sets, starts jobs
on admission (injecting the assigned flavors' node selectors and
tolerations, pkg/podset), stops them on eviction (restoring templates),
and propagates Finished / PodsReady / reclaimable-pod updates.

Jobs here are host-side orchestration objects (a TPU training run, a batch
process); "running" means the framework invoked the job's `run` hook with
the admitted placement info.
"""

from __future__ import annotations

import abc
import enum
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Type

from kueue_tpu.api.types import (
    PodSet,
    PodSetAssignment,
    ResourceFlavor,
    Workload,
)
from kueue_tpu.controllers.provisioning import PROV_REQ_ANNOTATION_PREFIX


@dataclass
class PodSetInfo:
    """Placement info merged into a pod template at start and restored at
    stop (reference: pkg/podset/podset.go:50-165)."""

    name: str
    count: int
    node_selector: Dict[str, str] = field(default_factory=dict)
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    tolerations: List = field(default_factory=list)


def podset_infos_from_admission(
        wl: Workload, flavors: Dict[str, ResourceFlavor]) -> List[PodSetInfo]:
    """Build per-PodSet placement info from the admission's flavor
    assignment (reference: jobframework/reconciler.go startJob ->
    getPodSetsInfoFromStatus)."""
    infos: List[PodSetInfo] = []
    for psa in wl.admission.pod_set_assignments:
        info = PodSetInfo(name=psa.name, count=psa.count)
        for flavor_name in psa.flavors.values():
            flavor = flavors.get(flavor_name)
            if flavor is None:
                continue
            info.node_selector.update(flavor.labels_dict)
            info.tolerations.extend(flavor.tolerations)
        infos.append(info)
    return infos


class StopReason(enum.Enum):
    """Why a job is being stopped (interface.go:66-73)."""

    WORKLOAD_DELETED = "WorkloadDeleted"
    WORKLOAD_EVICTED = "WorkloadEvicted"
    NO_MATCHING_WORKLOAD = "NoMatchingWorkload"
    NOT_ADMITTED = "NotAdmitted"


class GenericJob(abc.ABC):
    """The integration contract (reference: jobframework/interface.go:32-55)."""

    @property
    @abc.abstractmethod
    def name(self) -> str: ...

    @property
    def namespace(self) -> str:
        return "default"

    @property
    def annotations(self) -> Dict[str, str]:
        """Object metadata annotations; provreq.kueue.x-k8s.io/* entries are
        copied onto the Workload (reconciler.go:808)."""
        return {}

    @property
    @abc.abstractmethod
    def queue_name(self) -> str:
        """The LocalQueue this job targets (kueue.x-k8s.io/queue-name)."""

    @abc.abstractmethod
    def is_suspended(self) -> bool: ...

    @abc.abstractmethod
    def suspend(self) -> None: ...

    @abc.abstractmethod
    def run(self, podset_infos: Sequence[PodSetInfo]) -> None:
        """Unsuspend with the admitted placement."""

    @abc.abstractmethod
    def restore(self, podset_infos: Sequence[PodSetInfo]) -> None:
        """Undo placement info on stop."""

    @abc.abstractmethod
    def pod_sets(self) -> List[PodSet]: ...

    @abc.abstractmethod
    def finished(self) -> Tuple[bool, bool]:
        """(finished, success)."""

    def pods_ready(self) -> bool:
        return False

    def is_active(self) -> bool:
        """Any pods still running (drives stopJob)."""
        return not self.is_suspended()

    # Optional capabilities (interface.go:56-114).

    def reclaimable_pods(self) -> Dict[str, int]:
        """JobWithReclaimablePods."""
        return {}

    def priority_class(self) -> str:
        """JobWithPriorityClass."""
        return ""

    def priority(self) -> int:
        return 0

    def prebuilt_workload(self) -> Optional[str]:
        """Name of a pre-created Workload this job binds to instead of
        constructing one (the kueue.x-k8s.io/prebuilt-workload-name label,
        interface.go PrebuiltWorkloadFor); None = construct normally.
        The default honors a `prebuilt_name` attribute so integrations can
        carry the label value without overriding."""
        return getattr(self, "prebuilt_name", None)


class JobWithCustomStop(abc.ABC):
    """Jobs with a custom stop procedure (interface.go:75-80). `stop` must
    be idempotent and returns whether this call stopped the job."""

    @abc.abstractmethod
    def stop(self, podset_infos: Sequence[PodSetInfo], stop_reason: StopReason,
             event_msg: str) -> bool: ...


class JobWithFinalize(abc.ABC):
    """Jobs needing custom finalization after they finish
    (interface.go:82-87)."""

    @abc.abstractmethod
    def finalize(self) -> None: ...


class JobWithSkip(abc.ABC):
    """Jobs whose reconciliation is conditionally skipped
    (interface.go:89-93)."""

    @abc.abstractmethod
    def skip(self) -> bool: ...


class ComposableJob(abc.ABC):
    """Jobs assembled out of multiple API objects (interface.go:99-114) —
    the pod-group integration is the canonical implementation."""

    @abc.abstractmethod
    def construct_composable_workload(self) -> Optional[Workload]:
        """Assemble the Workload from all members; None = not yet
        constructable (e.g. the group is awaiting members)."""

    @abc.abstractmethod
    def find_matching_workloads(self, owned: Sequence[Workload],
                                ) -> Tuple[Optional[Workload], List[Workload]]:
        """(match, to_delete) among the job's owned workloads."""

    def list_child_workloads(self, owned: Sequence[Workload]) -> List[Workload]:
        return list(owned)


# -- integration registry (integrationmanager.go) ---------------------------

_INTEGRATIONS: Dict[str, Type[GenericJob]] = {}


def register_integration(kind: str):
    def wrap(cls: Type[GenericJob]):
        if kind in _INTEGRATIONS:
            raise ValueError(f"integration {kind} already registered")
        _INTEGRATIONS[kind] = cls
        cls.kind = kind
        return cls
    return wrap


def integrations() -> Dict[str, Type[GenericJob]]:
    return dict(_INTEGRATIONS)


def kind_of(job: GenericJob) -> Optional[str]:
    """Registered integration kind of a job instance (exact class first,
    then subclass match — the registry lookup of integrationmanager.go)."""
    for kind, cls in _INTEGRATIONS.items():
        if type(job) is cls:
            return kind
    for kind, cls in _INTEGRATIONS.items():
        if isinstance(job, cls):
            return kind
    return None


def _podset_shape(ps) -> Tuple:
    return (ps.name, ps.count, tuple(sorted(ps.requests.items())))


def equivalent_to_workload(job: GenericJob, wl: Workload) -> bool:
    """Job <-> workload podset equivalence (reconciler.go
    equivalentToWorkload): the workload's spec podsets must match the
    job's, modulo partial admission — a started job may run with the
    admission's reduced counts (expectedRunningPodSets)."""
    jps = [_podset_shape(p) for p in job.pod_sets()]
    wps = [_podset_shape(p) for p in wl.pod_sets]
    if jps == wps:
        return True
    if wl.has_quota_reservation and wl.admission is not None:
        admitted_counts = {psa.name: psa.count
                          for psa in wl.admission.pod_set_assignments}
        # Spec podset requests are per-pod; only counts scale under
        # partial admission.
        scaled = [(p.name, admitted_counts.get(p.name, p.count),
                   tuple(sorted(p.requests.items())))
                  for p in wl.pod_sets]
        if jps == scaled:
            return True
    return False


def find_matching_workloads_default(
        job: GenericJob, owned: Sequence[Workload],
) -> Tuple[Optional[Workload], List[Workload]]:
    """First equivalent workload wins; the rest are duplicates to delete
    (reconciler.go FindMatchingWorkloads :581-600). Shared by the
    reconciler's non-composable branch and composable implementations that
    want the default policy."""
    match = None
    to_delete = []
    for w in owned:
        if match is None and equivalent_to_workload(job, w):
            match = w
        else:
            to_delete.append(w)
    return match, to_delete


# -- per-job webhook validation (jobframework/validation.go + the
# per-framework *_webhook.go files) ------------------------------------------


def _dns1123_errors(value: str, what: str) -> List[str]:
    from kueue_tpu.webhooks.validation import is_dns1123_subdomain
    if not is_dns1123_subdomain(value):
        return [f"{what}: {value!r} must be a DNS-1123 subdomain"]
    return []


def validate_job_create(job: GenericJob) -> List[str]:
    """Create-time rules (jobframework/validation.go
    ValidateCreateForQueueName): queue name and prebuilt-workload name
    must be valid CRD names."""
    errs: List[str] = []
    if job.queue_name:
        errs += _dns1123_errors(job.queue_name,
                                "metadata.labels[kueue.x-k8s.io/queue-name]")
    prebuilt = job.prebuilt_workload()
    if prebuilt:
        errs += _dns1123_errors(
            prebuilt,
            "metadata.labels[kueue.x-k8s.io/prebuilt-workload-name]")
    return errs


def job_update_guard(job: GenericJob) -> dict:
    """The fields the update webhooks pin (captured at submit time)."""
    return {
        "queue_name": job.queue_name,
        "prebuilt": job.prebuilt_workload(),
        "priority_class": job.priority_class(),
    }


def validate_job_update(guard: dict, job: GenericJob) -> List[str]:
    """Update-time rules (jobframework/validation.go
    ValidateUpdateForQueueName / ...ForWorkloadPriorityClassName, plus the
    per-framework `validate_update` hook — e.g. batch/Job forbids
    parallelism changes of an unsuspended partial-admission job,
    job_webhook.go:147-160): returns reasons, empty == allowed. `guard`
    is the last-admitted state from job_update_guard."""
    errs: List[str] = []
    if not job.is_suspended() and job.queue_name != guard["queue_name"]:
        errs.append("metadata.labels[kueue.x-k8s.io/queue-name]: "
                    "immutable while the job is not suspended")
    if job.prebuilt_workload() != guard["prebuilt"]:
        errs.append("metadata.labels[kueue.x-k8s.io/prebuilt-workload-name]: "
                    "field is immutable")
    if job.priority_class() != guard["priority_class"]:
        errs.append(
            "metadata.labels[kueue.x-k8s.io/workload-priority-class]: "
            "field is immutable")
    hook = getattr(job, "validate_update", None)
    if hook is not None:
        errs += hook(guard)
    return errs


@dataclass
class _JobState:
    job: GenericJob
    owned: List[str] = field(default_factory=list)   # workload keys
    finalized: bool = False
    guard: Optional[dict] = None
    last_rejection: Optional[str] = None


class JobReconciler:
    """The job <-> workload state machine (reconciler.go:159-440).

    Driven by the runtime after every scheduling tick and on job events.
    Guarantees the single-workload invariant per job: duplicate or
    non-equivalent workloads are deleted (finish-stale), a running job
    without a matching workload is stopped, and a suspended unreserved
    workload is updated in place to match the job
    (ensureOneWorkload, reconciler.go:478-579).
    """

    def __init__(self, framework):
        self.fw = framework
        self._states: Dict[str, _JobState] = {}

    @staticmethod
    def job_key(job: GenericJob) -> str:
        return f"{job.namespace}/{job.name}"

    # Back-compat introspection used by tests/integrations. Read-only
    # view — mutate through submit/adopt_workload/delete/forget.
    @property
    def jobs(self) -> Dict[str, Tuple[GenericJob, str]]:
        return {k: (s.job, s.owned[0] if s.owned else "")
                for k, s in self._states.items()}

    def forget(self, job_key: str) -> None:
        """Stop tracking a job WITHOUT deleting its workloads (the caller
        already disposed of them — e.g. a MultiKueue worker garbage-
        collecting a mirror and its bound remote job together)."""
        self._states.pop(job_key, None)

    def submit(self, job: GenericJob) -> Optional[Workload]:
        """Admit a job into the queueing system: default-suspend it,
        register it, and run one reconcile pass (which creates the
        Workload — reconciler.go handleJobWithNoWorkload).

        Jobs of a non-enabled integration are rejected
        (integrationmanager.go:44-76: only configured integrations are set
        up). Jobs without a queue name are only managed when
        manageJobsWithoutQueueName is set (reconciler.go:173-180); when it
        is off they are left alone (returns None, job unsuspended)."""
        cfg = self.fw.config
        kind = kind_of(job)
        if kind is not None and not cfg.integrations.enables(kind):
            raise ValueError(
                f"integration {kind!r} is not enabled in "
                f"integrations.frameworks {cfg.integrations.frameworks}")
        if not job.queue_name:
            if not cfg.manage_jobs_without_queue_name:
                return None
            # Managed but unqueued: held suspended, no workload until a
            # queue is assigned.
            if not job.is_suspended():
                job.suspend()
            return None
        errs = validate_job_create(job)
        if errs:
            from kueue_tpu.webhooks import ValidationError
            raise ValidationError(errs)
        if not job.is_suspended():
            job.suspend()
        state = self._states.setdefault(self.job_key(job), _JobState(job=job))
        state.job = job
        state.guard = job_update_guard(job)
        self.reconcile_job(job)
        wl_key = state.owned[0] if state.owned else None
        return self.fw.workloads.get(wl_key) if wl_key else None

    def adopt_workload(self, job: GenericJob, wl: Workload) -> None:
        """Register an externally created workload as owned by `job` (the
        owner-reference indexing of reconciler.go FindMatchingWorkloads;
        also how duplicates enter and get deduped)."""
        state = self._states.setdefault(self.job_key(job), _JobState(job=job))
        if wl.key not in state.owned:
            state.owned.append(wl.key)

    def delete(self, job: GenericJob) -> None:
        state = self._states.pop(self.job_key(job), None)
        if state is None:
            return
        for key in state.owned:
            wl = self.fw.workloads.get(key)
            if wl is not None:
                self.fw.delete_workload(wl)
        self._finalize(state)

    def reconcile(self) -> None:
        """One pass of the job state machine over all tracked jobs."""
        for state in list(self._states.values()):
            self.reconcile_job(state.job)

    # -- the per-job state machine (reconciler.go:159-440) ------------------

    def reconcile_job(self, job: GenericJob) -> None:
        state = self._states.get(self.job_key(job))
        if state is None:
            return

        # 0. JobWithSkip: reconciliation conditionally skipped
        #    (reconciler.go:177-181).
        if isinstance(job, JobWithSkip) and job.skip():
            return

        # 0.1 Per-job update webhook (jobframework/validation.go + the
        # per-framework *_webhook.go rules): an invalid mutation is the
        # analog of a denied apiserver write — surface it (once per
        # distinct rejection) and do not act on the new state. Completion
        # still proceeds: a denied write must not wedge finalization.
        # A legal mutation refreshes the guard.
        if state.guard is not None:
            errs = validate_job_update(state.guard, job)
            if errs:
                message = "; ".join(errs)
                if message != state.last_rejection:
                    state.last_rejection = message
                    events = getattr(self.fw, "events", None)
                    if events is not None:
                        from kueue_tpu import events as events_mod
                        events.event(
                            self.job_key(job), events_mod.WARNING,
                            "UpdateRejected", message, now=self.fw.clock())
                wl = next((self.fw.workloads[k] for k in state.owned
                           if k in self.fw.workloads), None)
                if wl is not None and wl.is_finished:
                    self._finalize(state)
                    return
                done, success = job.finished()
                if done:
                    if wl is not None and not wl.is_finished:
                        self.fw.finish(wl, success=success)
                    self._finalize(state)
                    return
                # Quota-safety transitions still run on the (old) state —
                # the reference's denied write leaves reconciliation
                # operating normally: an evicted or reservation-less
                # running job must still be stopped.
                if wl is not None and wl.is_evicted \
                        and not job.is_suspended():
                    evicted = wl.find_condition("Evicted")
                    self._stop_job(job, wl, StopReason.WORKLOAD_EVICTED,
                                   evicted.message if evicted else "")
                elif not job.is_suspended() and (
                        wl is None or (not wl.is_admitted
                                       and not wl.has_quota_reservation)):
                    self._stop_job(job, wl, StopReason.NOT_ADMITTED,
                                   "Not admitted by cluster queue")
                return
            state.last_rejection = None
            state.guard = job_update_guard(job)

        # 1. Single-workload invariant (reconciler.go:270 ensureOneWorkload).
        wl = self._ensure_one_workload(state, job)

        # 1.1 Workload finished -> finalize the job (reconciler.go:276-285).
        if wl is not None and wl.is_finished:
            self._finalize(state)
            return

        # 2. Job finished -> propagate onto the workload, finalize
        #    (reconciler.go:300-317).
        done, success = job.finished()
        if done:
            if wl is not None and not wl.is_finished:
                self.fw.finish(wl, success=success)
            self._finalize(state)
            return

        # 3. No workload -> create one (reconciler.go:319-331).
        if wl is None:
            self._handle_no_workload(state, job)
            return

        # 4. Sync reclaimable pods (KEP-78 dynamic reclaim,
        #    reconciler.go:333-350). A rejected update (webhook:
        #    shrinking/out-of-range counts) is dropped, like a denied SSA
        #    patch in the reference.
        reclaimable = job.reclaimable_pods()
        if reclaimable and reclaimable != wl.reclaimable_pods:
            from kueue_tpu.webhooks import ValidationError
            try:
                self.fw.update_reclaimable_pods(wl, reclaimable)
            except ValidationError:
                pass

        # 5. PodsReady condition from the job (reconciler.go:352-366).
        if job.pods_ready() and not wl.condition_true("PodsReady"):
            self.fw.mark_pods_ready(wl)

        # 6. Evicted -> stop the job (reconciler.go:368-384).
        if wl.is_evicted and not job.is_suspended():
            evicted = wl.find_condition("Evicted")
            self._stop_job(job, wl, StopReason.WORKLOAD_EVICTED,
                           evicted.message if evicted else "")
            return

        # 7. Admitted -> start the job (reconciler.go:386-404).
        if wl.is_admitted and job.is_suspended():
            infos = podset_infos_from_admission(
                wl, self.fw.cache.resource_flavors)
            job.run(infos)
            return

        # 7.1 Queue change while suspended (reconciler.go:406-416).
        if job.is_suspended() and not wl.has_quota_reservation \
                and wl.queue_name != job.queue_name:
            self.fw.move_workload_queue(wl, job.queue_name)
            return

        # 8. Deactivated workload -> evict (reconciler.go:419-426).
        if not wl.active and not wl.is_evicted:
            from kueue_tpu.api.types import EVICTED_BY_DEACTIVATION
            self.fw.evict_workload(
                wl, reason=EVICTED_BY_DEACTIVATION,
                message="The workload is deactivated")
            return

        # 9. Job unsuspended without admission -> hold it
        #    (reconciler.go:428-437).
        if not job.is_suspended() and not wl.is_admitted \
                and not wl.has_quota_reservation:
            self._stop_job(job, wl, StopReason.NOT_ADMITTED,
                           "Not admitted by cluster queue")

    # -- ensureOneWorkload (reconciler.go:478-579) ---------------------------

    def _ensure_one_workload(self, state: _JobState,
                             job: GenericJob) -> Optional[Workload]:
        prebuilt = job.prebuilt_workload()
        if prebuilt is not None:
            wl = self.fw.workloads.get(f"{job.namespace}/{prebuilt}")
            if wl is None:
                return None
            if wl.key not in state.owned:
                state.owned.append(wl.key)
            if not equivalent_to_workload(job, wl) and not wl.is_finished:
                # ensurePrebuiltWorkloadInSync: finish it, out of sync.
                self.fw.finish(wl, success=False, reason="OutOfSync")
                return None
            return wl

        owned = [self.fw.workloads[k] for k in state.owned
                 if k in self.fw.workloads]
        state.owned = [w.key for w in owned]
        if isinstance(job, ComposableJob):
            match, to_delete = job.find_matching_workloads(owned)
        else:
            match, to_delete = find_matching_workloads_default(job, owned)

        to_update = None
        if match is None and to_delete and job.is_suspended() \
                and not to_delete[0].has_quota_reservation:
            # A suspended job's unreserved stale workload is updated in
            # place instead of recreated (reconciler.go:517-521).
            to_update = to_delete.pop(0)

        if match is None and not job.is_suspended() and not job.finished()[0]:
            # Running with no matching workload: all bets are off — stop
            # (reconciler.go:523-545).
            w = to_delete[0] if len(to_delete) == 1 else None
            msg = ("No matching Workload; restoring pod templates according "
                   "to existent Workload") if w is not None else \
                "Missing Workload; unable to restore pod templates"
            self._stop_job(job, w, StopReason.NO_MATCHING_WORKLOAD, msg)

        # Delete duplicate / non-equivalent workloads (finish-stale,
        # reconciler.go:547-572).
        for w in to_delete:
            state.owned.remove(w.key)
            self.fw.delete_workload(w)
        if to_delete:
            # The reference returns an error to requeue; the next reconcile
            # pass recreates. Surface the same "nothing matched this pass".
            return match

        if to_update is not None:
            return self._update_workload_to_match(state, job, to_update)
        return match

    def _update_workload_to_match(self, state: _JobState, job: GenericJob,
                                  wl: Workload) -> Workload:
        """updateWorkloadToMatchJob (reconciler.go:649-668): refresh the
        suspended, unreserved workload's podsets to the job's, re-running
        the same priority-class resolution and resource adjustment the
        creation path applies (a refreshed workload must not diverge from
        an identical freshly-submitted one)."""
        wl.pod_sets = list(job.pod_sets())
        wl.priority = job.priority()
        wl.priority_class = job.priority_class()
        self.fw.requeue_updated_workload(wl)
        return wl

    def _handle_no_workload(self, state: _JobState, job: GenericJob) -> None:
        """Create the job's workload (reconciler.go handleJobWithNoWorkload).
        ComposableJobs may defer (group awaiting members); prebuilt-bound
        jobs never construct — they wait for their workload to appear
        (reconciler.go:481-496)."""
        if job.prebuilt_workload() is not None:
            return
        if isinstance(job, ComposableJob):
            wl = job.construct_composable_workload()
            if wl is None:
                return
        else:
            wl = Workload(
                name=f"job-{job.name}",
                namespace=job.namespace,
                queue_name=job.queue_name,
                # FilterProvReqAnnotations (reconciler.go:808): only the
                # provisioning-parameter annotations flow onto the Workload.
                annotations={k: v for k, v in job.annotations.items()
                             if k.startswith(PROV_REQ_ANNOTATION_PREFIX)},
                pod_sets=list(job.pod_sets()),
                priority=job.priority(),
                priority_class=job.priority_class(),
            )
        if wl.key not in state.owned:
            state.owned.append(wl.key)
        self.fw.submit(wl)

    # -- stop / finalize -----------------------------------------------------

    def _stop_job(self, job: GenericJob, wl: Optional[Workload],
                  reason: StopReason, message: str) -> None:
        """stopJob (reconciler.go:670-713): JobWithCustomStop runs the
        integration's own procedure; the default suspends and restores
        placement info."""
        infos: List[PodSetInfo] = []
        if wl is not None and wl.admission is not None:
            infos = podset_infos_from_admission(
                wl, self.fw.cache.resource_flavors)
        if isinstance(job, JobWithCustomStop):
            job.stop(infos, reason, message)
            return
        if not job.is_suspended():
            job.suspend()
        job.restore(infos)

    def _finalize(self, state: _JobState) -> None:
        """finalizeJob (reconciler.go:715-723): JobWithFinalize hook, once."""
        if state.finalized:
            return
        job = state.job
        if isinstance(job, JobWithFinalize):
            job.finalize()
        state.finalized = True
