"""Job-integration framework: the generic job <-> Workload sync engine.

Counterpart of reference pkg/controller/jobframework/: a `GenericJob`
protocol (interface.go:32-114), an integration registry keyed by job type
(integrationmanager.go:44-95), and the reconciler state machine
(reconciler.go:159-440) that creates Workloads from job pod sets, starts
jobs on admission (injecting the assigned flavors' node selectors and
tolerations, pkg/podset), stops them on eviction (restoring templates), and
propagates Finished / PodsReady / reclaimable-pod updates.

Jobs here are host-side orchestration objects (a TPU training run, a batch
process); "running" means the framework invoked the job's `run` hook with
the admitted placement info.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Type

from kueue_tpu.api.types import (
    PodSet,
    PodSetAssignment,
    ResourceFlavor,
    Workload,
)
from kueue_tpu.controllers.provisioning import PROV_REQ_ANNOTATION_PREFIX


@dataclass
class PodSetInfo:
    """Placement info merged into a pod template at start and restored at
    stop (reference: pkg/podset/podset.go:50-165)."""

    name: str
    count: int
    node_selector: Dict[str, str] = field(default_factory=dict)
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    tolerations: List = field(default_factory=list)


def podset_infos_from_admission(
        wl: Workload, flavors: Dict[str, ResourceFlavor]) -> List[PodSetInfo]:
    """Build per-PodSet placement info from the admission's flavor
    assignment (reference: jobframework/reconciler.go startJob ->
    getPodSetsInfoFromStatus)."""
    infos: List[PodSetInfo] = []
    for psa in wl.admission.pod_set_assignments:
        info = PodSetInfo(name=psa.name, count=psa.count)
        for flavor_name in psa.flavors.values():
            flavor = flavors.get(flavor_name)
            if flavor is None:
                continue
            info.node_selector.update(flavor.labels_dict)
            info.tolerations.extend(flavor.tolerations)
        infos.append(info)
    return infos


class GenericJob(abc.ABC):
    """The integration contract (reference: jobframework/interface.go:32-55)."""

    @property
    @abc.abstractmethod
    def name(self) -> str: ...

    @property
    def namespace(self) -> str:
        return "default"

    @property
    def annotations(self) -> Dict[str, str]:
        """Object metadata annotations; provreq.kueue.x-k8s.io/* entries are
        copied onto the Workload (reconciler.go:808)."""
        return {}

    @property
    @abc.abstractmethod
    def queue_name(self) -> str:
        """The LocalQueue this job targets (kueue.x-k8s.io/queue-name)."""

    @abc.abstractmethod
    def is_suspended(self) -> bool: ...

    @abc.abstractmethod
    def suspend(self) -> None: ...

    @abc.abstractmethod
    def run(self, podset_infos: Sequence[PodSetInfo]) -> None:
        """Unsuspend with the admitted placement."""

    @abc.abstractmethod
    def restore(self, podset_infos: Sequence[PodSetInfo]) -> None:
        """Undo placement info on stop."""

    @abc.abstractmethod
    def pod_sets(self) -> List[PodSet]: ...

    @abc.abstractmethod
    def finished(self) -> Tuple[bool, bool]:
        """(finished, success)."""

    def pods_ready(self) -> bool:
        return False

    def is_active(self) -> bool:
        """Any pods still running (drives stopJob)."""
        return not self.is_suspended()

    # Optional capabilities (interface.go:56-114).

    def reclaimable_pods(self) -> Dict[str, int]:
        return {}

    def priority_class(self) -> str:
        return ""

    def priority(self) -> int:
        return 0


# -- integration registry (integrationmanager.go) ---------------------------

_INTEGRATIONS: Dict[str, Type[GenericJob]] = {}


def register_integration(kind: str):
    def wrap(cls: Type[GenericJob]):
        if kind in _INTEGRATIONS:
            raise ValueError(f"integration {kind} already registered")
        _INTEGRATIONS[kind] = cls
        cls.kind = kind
        return cls
    return wrap


def integrations() -> Dict[str, Type[GenericJob]]:
    return dict(_INTEGRATIONS)


def kind_of(job: GenericJob) -> Optional[str]:
    """Registered integration kind of a job instance (exact class first,
    then subclass match — the registry lookup of integrationmanager.go)."""
    for kind, cls in _INTEGRATIONS.items():
        if type(job) is cls:
            return kind
    for kind, cls in _INTEGRATIONS.items():
        if isinstance(job, cls):
            return kind
    return None


class JobReconciler:
    """The job <-> workload state machine (reconciler.go:159-440).

    Driven by the runtime after every scheduling tick and on job events.
    """

    def __init__(self, framework):
        self.fw = framework
        # job key -> (job, workload key)
        self.jobs: Dict[str, Tuple[GenericJob, str]] = {}

    @staticmethod
    def job_key(job: GenericJob) -> str:
        return f"{job.namespace}/{job.name}"

    def submit(self, job: GenericJob) -> Optional[Workload]:
        """Admit a job into the queueing system: default-suspend it and
        create its Workload (reconciler.go handleJobWithNoWorkload).

        Jobs of a non-enabled integration are rejected
        (integrationmanager.go:44-76: only configured integrations are set
        up). Jobs without a queue name are only managed when
        manageJobsWithoutQueueName is set (reconciler.go:173-180); when it
        is off they are left alone (returns None, job unsuspended)."""
        cfg = self.fw.config
        kind = kind_of(job)
        if kind is not None and not cfg.integrations.enables(kind):
            raise ValueError(
                f"integration {kind!r} is not enabled in "
                f"integrations.frameworks {cfg.integrations.frameworks}")
        if not job.queue_name:
            if not cfg.manage_jobs_without_queue_name:
                return None
            # Managed but unqueued: held suspended, no workload until a
            # queue is assigned.
            if not job.is_suspended():
                job.suspend()
            return None
        if not job.is_suspended():
            job.suspend()
        wl = Workload(
            name=f"job-{job.name}",
            namespace=job.namespace,
            queue_name=job.queue_name,
            # FilterProvReqAnnotations (reconciler.go:808): only the
            # provisioning-parameter annotations flow onto the Workload.
            annotations={k: v for k, v in job.annotations.items()
                         if k.startswith(PROV_REQ_ANNOTATION_PREFIX)},
            pod_sets=list(job.pod_sets()),
            priority=job.priority(),
            priority_class=job.priority_class(),
        )
        self.jobs[self.job_key(job)] = (job, wl.key)
        self.fw.submit(wl)
        return wl

    def delete(self, job: GenericJob) -> None:
        entry = self.jobs.pop(self.job_key(job), None)
        if entry is None:
            return
        wl = self.fw.workloads.get(entry[1])
        if wl is not None:
            self.fw.delete_workload(wl)

    def reconcile(self) -> None:
        """One pass of the job state machine over all tracked jobs."""
        for job, wl_key in list(self.jobs.values()):
            wl = self.fw.workloads.get(wl_key)
            if wl is None:
                continue

            # 1. Propagate Finished (reconciler.go step 2).
            done, success = job.finished()
            if done and not wl.is_finished:
                self.fw.finish(wl)
                continue
            if wl.is_finished:
                continue

            # 2. Sync reclaimable pods (step 4; KEP-78 dynamic reclaim).
            # A rejected update (webhook: shrinking/out-of-range counts) is
            # dropped, like a denied SSA patch in the reference.
            reclaimable = job.reclaimable_pods()
            if reclaimable and reclaimable != wl.reclaimable_pods:
                from kueue_tpu.webhooks import ValidationError
                try:
                    self.fw.update_reclaimable_pods(wl, reclaimable)
                except ValidationError:
                    pass

            # 3. PodsReady condition from the job (step 5).
            if job.pods_ready() and not wl.condition_true("PodsReady"):
                self.fw.mark_pods_ready(wl)

            # 4. Evicted -> stop the job (step 6).
            if wl.is_evicted and not job.is_suspended():
                self._stop_job(job, wl)
                continue

            # 5. Admitted -> start the job (step 7).
            if wl.is_admitted and job.is_suspended():
                infos = podset_infos_from_admission(
                    wl, self.fw.cache.resource_flavors)
                job.run(infos)

            # 6. Job unsuspended without admission -> hold it (step 8).
            if not job.is_suspended() and not wl.is_admitted \
                    and not wl.has_quota_reservation:
                self._stop_job(job, wl)

    def _stop_job(self, job: GenericJob, wl: Workload) -> None:
        infos = []
        if wl.admission is not None:
            infos = podset_infos_from_admission(
                wl, self.fw.cache.resource_flavors)
        job.suspend()
        job.restore(infos)
