"""Lease-based leader election for HA replicas.

Counterpart of the reference's controller-runtime leader election wiring
(cmd/kueue/main.go manager options + apis/config/v1beta1/defaults.go:37-44:
lease ``c1f6bfd2.kueue.x-k8s.io``, 15s lease / 10s renew / 2s retry) and of
``pkg/controller/core/leader_aware_reconciler.go``: non-leading replicas do
not reconcile — they requeue events for one lease duration so nothing is
missed across a fail-over, keeping hot-standby replicas' webhooks serving
while only the leader mutates state.

The lease itself is the in-process analog of a coordination.k8s.io Lease:
a shared `Lease` record in a `LeaseStore` that candidates acquire by
compare-and-swap on (holder, renew deadline). kube-style semantics: a
candidate may take the lease when it is unheld or its previous holder's
lease duration elapsed without renewal; the holder renews every retry
period and abdicates by zeroing the holder identity.
"""

from __future__ import annotations

import threading
import time as _time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

from kueue_tpu.config import LeaderElectionConfig


def _count_transition(name: str) -> None:
    """Every holder change bumps kueue_lease_transitions_total — the
    audit-trail twin of the lease's own transitions field (the metric
    is per-process and monotonic; the field is the cross-process epoch
    source)."""
    from kueue_tpu.metrics import REGISTRY

    REGISTRY.lease_transitions_total.inc(name)


@dataclass
class Lease:
    name: str
    holder: str = ""
    acquire_time: float = 0.0
    renew_time: float = 0.0
    lease_duration_seconds: float = 15.0
    # Incremented on every holder change (Lease.spec.leaseTransitions).
    transitions: int = 0


class LeaseStore:
    """Shared lease records; the CAS point all candidates race on."""

    def __init__(self):
        self._leases: Dict[str, Lease] = {}
        self._lock = threading.Lock()

    def try_acquire_or_renew(self, name: str, identity: str,
                             lease_duration: float, now: float) -> bool:
        with self._lock:
            lease = self._leases.get(name)
            if lease is None:
                lease = Lease(name=name)
                self._leases[name] = lease
            if lease.holder == identity:
                lease.renew_time = now
                lease.lease_duration_seconds = lease_duration
                return True
            expired = (not lease.holder or
                       now >= lease.renew_time + lease.lease_duration_seconds)
            if not expired:
                return False
            lease.holder = identity
            lease.acquire_time = now
            lease.renew_time = now
            lease.lease_duration_seconds = lease_duration
            lease.transitions += 1
            _count_transition(name)
            return True

    def release(self, name: str, identity: str) -> None:
        with self._lock:
            lease = self._leases.get(name)
            if lease is not None and lease.holder == identity:
                lease.holder = ""

    def holder(self, name: str) -> str:
        with self._lock:
            lease = self._leases.get(name)
            return lease.holder if lease is not None else ""

    def transitions(self, name: str) -> int:
        """Holder-change count (Lease.spec.leaseTransitions) — the
        multi-host coordinator's barrier-round EPOCH source: every
        takeover bumps it, so journal entries and reconcile rounds are
        attributable to exactly one coordinator incarnation."""
        with self._lock:
            lease = self._leases.get(name)
            return lease.transitions if lease is not None else 0


class FileLeaseStore:
    """Cross-process lease records in a shared state directory.

    The reference's lease lives in the apiserver (etcd) — the substrate
    every HA replica shares. This build's shared substrate is the durable
    state directory (controllers/durable.py), so the lease is a JSON file
    there: the compare-and-swap runs under an fcntl lock and lands with an
    atomic rename, giving the same kube semantics (take when unheld or
    expired, renew by holder, abdicate by zeroing) across processes on the
    shared mount. Same interface as LeaseStore."""

    def __init__(self, path: str):
        self.path = path
        self._lockpath = path + ".lock"

    def _rmw(self, fn):
        """Read-modify-write the lease file under an exclusive flock;
        `fn(leases: dict) -> (result, dirty)` may mutate the dict in
        place — the file is rewritten only when dirty (a standby's failed
        acquire and pure reads must not generate write traffic on the
        shared mount)."""
        import fcntl
        import json
        import os

        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(self._lockpath, "a+") as lockf:
            fcntl.flock(lockf.fileno(), fcntl.LOCK_EX)
            leases: Dict[str, dict] = {}
            try:
                with open(self.path, "r", encoding="utf-8") as f:
                    leases = json.load(f)
            except (OSError, ValueError):
                pass
            result, dirty = fn(leases)
            if dirty:
                tmp = f"{self.path}.{os.getpid()}.tmp"
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump(leases, f)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self.path)
            return result

    def try_acquire_or_renew(self, name: str, identity: str,
                             lease_duration: float, now: float) -> bool:
        def cas(leases):
            lease = leases.setdefault(name, {
                "holder": "", "acquire_time": 0.0, "renew_time": 0.0,
                "lease_duration_seconds": lease_duration, "transitions": 0})
            if lease["holder"] == identity:
                lease["renew_time"] = now
                lease["lease_duration_seconds"] = lease_duration
                return True, True
            expired = (not lease["holder"] or now >= lease["renew_time"]
                       + lease["lease_duration_seconds"])
            if not expired:
                return False, False
            lease.update(holder=identity, acquire_time=now, renew_time=now,
                         lease_duration_seconds=lease_duration,
                         transitions=lease["transitions"] + 1)
            _count_transition(name)
            return True, True
        return self._rmw(cas)

    def release(self, name: str, identity: str) -> None:
        def rel(leases):
            lease = leases.get(name)
            if lease is not None and lease["holder"] == identity:
                lease["holder"] = ""
                return None, True
            return None, False
        self._rmw(rel)

    def holder(self, name: str) -> str:
        def read(leases):
            lease = leases.get(name)
            return (lease["holder"] if lease is not None else ""), False
        return self._rmw(read)

    def transitions(self, name: str) -> int:
        def read(leases):
            lease = leases.get(name)
            return (lease["transitions"] if lease is not None else 0), False
        return self._rmw(read)


class LeaderElector:
    """One replica's view of the election.

    Drive it with `step()` from the replica's main loop (or `run()` on a
    thread): each step renews when leading or retries acquisition when not,
    spaced by the configured retry period. `is_leader()` answers the
    question the manager's Elected() channel answers in the reference;
    leadership is lost implicitly once the renew deadline passes without a
    successful renewal.
    """

    def __init__(self, store: LeaseStore, identity: str,
                 config: Optional[LeaderElectionConfig] = None,
                 clock: Callable[[], float] = _time.time,
                 on_started_leading: Optional[Callable[[], None]] = None,
                 on_stopped_leading: Optional[Callable[[], None]] = None):
        self.store = store
        self.identity = identity
        self.config = config or LeaderElectionConfig(enable=True)
        self.clock = clock
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._last_renew = 0.0
        self._last_attempt = -float("inf")
        self._leading = False
        self._stop = threading.Event()
        # is_leader()/step() may race when run() drives the election on
        # a thread while the replica's main loop keeps asking is_leader:
        # both mutate _leading (and could double-fire the transition
        # callbacks). RLock, not Lock: a callback may call back into the
        # elector from under it.
        self._lock = threading.RLock()

    def is_leader(self) -> bool:
        with self._lock:
            if not self._leading:
                return False
            now = self.clock()
            if now >= self._last_renew + self.config.renew_deadline_seconds:
                # Failed to renew within the deadline: no longer leading
                # even though the lease record may not have been taken
                # over yet.
                self._set_leading_locked(False)
            return self._leading

    def step(self) -> bool:
        """Attempt one acquire/renew if the retry period elapsed; returns
        current leadership."""
        with self._lock:
            now = self.clock()
            if now - self._last_attempt < self.config.retry_period_seconds:
                return self.is_leader()
            self._last_attempt = now
        # The store CAS can block (file lock, lease-service RPC): keep it
        # outside the lock so a concurrent is_leader() never waits on I/O.
        ok = self.store.try_acquire_or_renew(
            self.config.resource_name, self.identity,
            self.config.lease_duration_seconds, now)
        with self._lock:
            if ok:
                self._last_renew = now
            self._set_leading_locked(ok or self.is_leader())
            return self._leading

    def step_now(self) -> bool:
        """step() with the retry-period throttle bypassed — the
        coordinator takeover path cannot wait a retry period to rejoin
        the election mid-barrier."""
        with self._lock:
            self._last_attempt = -float("inf")
        return self.step()

    def release(self) -> None:
        """Voluntarily abdicate (graceful shutdown)."""
        self.store.release(self.config.resource_name, self.identity)
        with self._lock:
            self._set_leading_locked(False)

    def _set_leading_locked(self, leading: bool) -> None:
        """Flip leadership and fire the transition callback (under the
        caller's _lock, so concurrent flips cannot double-fire it)."""
        if leading and not self._leading:
            self._leading = True
            if self.on_started_leading:
                self.on_started_leading()
        elif not leading and self._leading:
            self._leading = False
            if self.on_stopped_leading:
                self.on_stopped_leading()

    # -- threaded driving (optional) ----------------------------------------

    def run(self) -> threading.Thread:
        def loop():
            while not self._stop.is_set():
                self.step()
                self._stop.wait(self.config.retry_period_seconds)
        t = threading.Thread(target=loop, daemon=True)
        t.start()
        return t

    def stop(self) -> None:
        self._stop.set()


class LeaderAwareReconciler:
    """Decorator that delays reconciles on non-leading replicas
    (core/leader_aware_reconciler.go:46-74).

    `reconcile(key)` returns either the delegate's result (when leading) or
    a requeue-after of one lease duration so no event is missed across the
    period a fail-over can take. Deleted objects are discarded instead of
    requeued indefinitely (the IgnoreNotFound branch).
    """

    def __init__(self, elector: LeaderElector, delegate: Callable[[str], object],
                 exists: Callable[[str], bool]):
        self.elector = elector
        self.delegate = delegate
        self.exists = exists

    def reconcile(self, key: str):
        if self.elector.is_leader():
            return self.delegate(key)
        if not self.exists(key):
            return None  # discard: object is gone
        return RequeueAfter(self.elector.config.lease_duration_seconds)


@dataclass(frozen=True)
class RequeueAfter:
    seconds: float
