"""MultiKueue: multi-cluster workload dispatch.

Counterpart of reference pkg/controller/admissionchecks/multikueue/: for a
workload that reserved quota locally and whose ClusterQueue carries a
MultiKueue AdmissionCheck, mirror the workload onto every worker cluster
(multikueue/workload.go:56-300), keep the first cluster that reserves
quota, delete the mirror from the rest, sync remote Finished back, and
garbage-collect orphans. Worker loss is handled with reconnect accounting
and a workerLostTimeout before requeueing
(multikueuecluster.go:64-188, config defaults.go:49).

The remote boundary is the `RemoteClient` protocol; `InProcessRemote` wraps
another Framework instance (the envtest-style two-cluster simulation used
by the reference's integration tests), while a production deployment can
implement it over gRPC.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kueue_tpu.api.types import AdmissionCheckState, Workload

MULTIKUEUE_CHECK_CONTROLLER = "kueue.x-k8s.io/multikueue"
# Binds a remote job to its already-mirrored workload instead of creating
# a second one (the reference's prebuilt-workload jobframework support).
PREBUILT_WORKLOAD_LABEL = "kueue.x-k8s.io/prebuilt-workload-name"
QUEUE_NAME_LABEL = "kueue.x-k8s.io/queue-name"
DEFAULT_WORKER_LOST_TIMEOUT = 15 * 60.0
DEFAULT_GC_INTERVAL = 60.0
DEFAULT_ORIGIN = "multikueue"
# Label stamped on remote mirrors so GC only touches objects this manager
# created — survives manager restarts, unlike in-memory dispatch state
# (reference: multikueue constants.go MultiKueueOriginLabel).
ORIGIN_LABEL = "kueue.x-k8s.io/multikueue-origin"

# Reconnect backoff for lost workers (multikueuecluster.go:64-69).
RECONNECT_BASE_SECONDS = 5.0
RECONNECT_MAX_SECONDS = 300.0


@dataclass
class MultiKueueConfig:
    """reference: apis/kueue/v1alpha1 MultiKueueConfig — names the worker
    clusters one MultiKueue AdmissionCheck dispatches to."""

    name: str
    clusters: Tuple[str, ...] = ()


@dataclass
class MultiKueueCluster:
    """reference: apis/kueue/v1alpha1 MultiKueueCluster — one worker
    cluster's connection spec plus its Active condition mirror.

    `kubeconfig_ref` is (location_type, location): the reference reads a
    kubeconfig from a secret or path (multikueuecluster.go:423-453); the
    embedded runtime resolves it through a client factory instead.
    """

    name: str
    kubeconfig_ref: Tuple[str, str] = ("Path", "")
    # Status mirror
    active: bool = False
    active_reason: str = "Inactive"
    active_message: str = ""
    failed_connection_attempts: int = 0
    next_reconnect_at: Optional[float] = None


class RemoteError(Exception):
    """A transient remote failure (worker unreachable, timeout, 5xx).
    Reconcile passes catch it and retry the workload next pass — one bad
    worker must not crash the manager's tick loop (the reference records
    per-cluster errors and requeues, multikueuecluster.go:139-188)."""


class RemoteRejected(Exception):
    """A permanent remote rejection (4xx other than 409-conflict, e.g. a
    worker-side webhook validation failure): re-POSTing the same payload
    can never succeed, so the controller records the rejection per worker
    instead of retrying every pass; when every worker rejects, the
    admission check goes Rejected with the server's message."""


class RemoteClient(abc.ABC):
    """A connection to one worker cluster."""

    @abc.abstractmethod
    def connected(self) -> bool: ...

    @abc.abstractmethod
    def create_workload(self, wl: Workload) -> None: ...

    @abc.abstractmethod
    def delete_workload(self, key: str) -> None: ...

    @abc.abstractmethod
    def get_status(self, key: str) -> Optional[dict]:
        """{'quota_reserved': bool, 'admitted': bool, 'finished': bool,
        'success': bool} or None if absent."""

    def list_workload_keys(self) -> List[str]:
        """Keys of remote workloads this manager created (GC support)."""
        return []

    # Job-adapter seam (reference: multikueue jobAdapter): create the job
    # object on the worker next to the mirrored workload, read its status
    # back. Manifest-shaped so it carries over any transport.
    def create_job(self, manifest: dict, wl: Workload) -> None:
        raise NotImplementedError

    def get_job(self, namespace: str, name: str) -> Optional[dict]:
        """{'ready': int, 'succeeded': int, 'failed': any} or None."""
        return None


class JobAdapter(abc.ABC):
    """Per-framework remote job sync (reference: multikueue jobAdapter,
    batchjob_adapter.go / jobset_adapter.go): creates the *job* object on
    the worker alongside the mirrored workload and copies remote job status
    back once the remote is reserving."""

    @abc.abstractmethod
    def sync_job(self, client: RemoteClient, local_job, wl: Workload) -> None:
        """Ensure the job exists remotely (create on first call)."""

    @abc.abstractmethod
    def copy_status_remote_to_local(self, client: RemoteClient, local_job,
                                    wl: Workload) -> None: ...


class InProcessRemote(RemoteClient):
    """A worker cluster hosted by another Framework instance in-process
    (the envtest-style two-cluster simulation of test/integration/multikueue)."""

    def __init__(self, framework, queue_name: str = "main"):
        self.fw = framework
        self.queue_name = queue_name
        self._up = True
        self._created: set = set()
        # Origin label value stamped on mirrors (set by the controller on
        # add_cluster; multiKueue.origin config).
        self.origin = DEFAULT_ORIGIN
        # name -> remote GenericJob (job adapter surface)
        self.jobs: Dict[str, object] = {}

    def set_connected(self, up: bool) -> None:
        self._up = up

    def connected(self) -> bool:
        return self._up

    def create_workload(self, wl: Workload) -> None:
        import copy
        remote = Workload(
            name=wl.name, namespace=wl.namespace, queue_name=self.queue_name,
            labels={ORIGIN_LABEL: self.origin},
            pod_sets=copy.deepcopy(wl.pod_sets), priority=wl.priority,
            creation_time=wl.creation_time)
        self.fw.submit(remote)
        self._created.add(remote.key)

    def delete_workload(self, key: str) -> None:
        wl = self.fw.workloads.get(key)
        if wl is not None:
            self.fw.delete_workload(wl)
        self._created.discard(key)
        # Adapter-created remote jobs bound to this mirror go with it
        # (the remote job is owned by the mirrored workload).
        for job_key, (job, wl_key) in list(self.fw.job_reconciler.jobs.items()):
            if wl_key == key:
                self.fw.job_reconciler.forget(job_key)
                self.jobs.pop(job_key, None)

    def get_status(self, key: str) -> Optional[dict]:
        wl = self.fw.workloads.get(key)
        if wl is None:
            return None
        return {
            "quota_reserved": wl.has_quota_reservation,
            "admitted": wl.is_admitted,
            "finished": wl.is_finished,
            "success": wl.is_finished,
        }

    def list_workload_keys(self) -> List[str]:
        """Mirrors this manager owns: found by the origin label (so GC
        works across manager restarts), unioned with in-memory bookkeeping
        for mirrors created before a label scheme change."""
        by_label = {k for k, w in self.fw.workloads.items()
                    if w.labels.get(ORIGIN_LABEL) == self.origin}
        return sorted(by_label | {k for k in self._created
                                  if k in self.fw.workloads})

    def create_job(self, manifest: dict, wl: Workload) -> None:
        """Decode the job manifest into this worker's runtime and bind it
        to the already-mirrored workload (the prebuilt-workload binding the
        HTTP server does for out-of-process workers)."""
        from kueue_tpu.api import serialization
        _, job = serialization.decode(manifest)
        key = f"{job.namespace}/{job.name}"
        if key in self.jobs:
            return
        self.jobs[key] = job
        # The remote job reuses the mirrored workload rather than creating
        # a second one (managed-by semantics, workload.go:232-300), via the
        # jobframework's prebuilt-workload seam (reconciler.go:481-496).
        job.prebuilt_name = wl.name
        self.fw.job_reconciler.submit(job)

    def get_job(self, namespace: str, name: str) -> Optional[dict]:
        remote = self.jobs.get(f"{namespace}/{name}")
        if remote is None:
            return None
        return {"ready": remote.ready_pods, "succeeded": remote.succeeded,
                "failed": remote.failed}


class BatchJobAdapter(JobAdapter):
    """batch/Job adapter (reference: multikueue/batchjob_adapter.go): mirrors
    a local BatchJob onto the worker as a batch/v1 manifest and copies
    remote counters back. Transport-agnostic: works against any
    RemoteClient implementing the create_job/get_job seam (in-process or
    HTTP)."""

    @staticmethod
    def _job_key(local_job) -> str:
        return f"{local_job.namespace}/{local_job.name}"

    def sync_job(self, client: RemoteClient, local_job, wl: Workload) -> None:
        from kueue_tpu.api.serialization import _encode_requests

        queue = getattr(client, "queue_name", "main")
        requests = wl.pod_sets[0].requests if wl.pod_sets else {}
        manifest = {
            "apiVersion": "batch/v1", "kind": "Job",
            "metadata": {
                "name": local_job.name, "namespace": local_job.namespace,
                "labels": {
                    QUEUE_NAME_LABEL: queue,
                    # Bind to the mirrored workload instead of creating a
                    # second one (prebuilt-workload semantics).
                    PREBUILT_WORKLOAD_LABEL: wl.name,
                },
            },
            "spec": {
                "parallelism": local_job.original_parallelism,
                "completions": local_job.completions,
                "template": {"spec": {"containers": [{
                    "name": "main",
                    "resources": {"requests": _encode_requests(requests)}}]}},
            },
        }
        client.create_job(manifest, wl)

    def copy_status_remote_to_local(self, client: RemoteClient, local_job,
                                    wl: Workload) -> None:
        status = client.get_job(local_job.namespace, local_job.name)
        if status is None:
            return
        local_job.ready_pods = status["ready"]
        local_job.succeeded = status["succeeded"]
        local_job.failed = status["failed"]


@dataclass
class _Dispatch:
    created_on: List[str] = field(default_factory=list)
    # worker name -> rejection message for permanent create failures
    # (never re-POSTed; see RemoteRejected).
    rejected_on: Dict[str, str] = field(default_factory=dict)
    kept_on: Optional[str] = None
    lost_since: Optional[float] = None
    # Remote job status is polled (jobs have no watch stream), so throttle
    # it — otherwise every reconcile pass costs one round-trip per running
    # job and a slow worker stalls the tick loop.
    next_job_poll_at: float = 0.0


class MultiKueueController:
    """Drives MultiKueue-type AdmissionChecks against worker clusters."""

    def __init__(self, framework, check_name: str = "multikueue",
                 worker_lost_timeout: Optional[float] = None,
                 client_factory=None):
        self.fw = framework
        self.check_name = check_name
        # Wired from the Configuration file (multiKueue section,
        # apis/config defaults.go:46-49) unless explicitly overridden.
        runtime_cfg = getattr(framework, "config", None)
        mk_cfg = runtime_cfg.multikueue if runtime_cfg is not None else None
        if worker_lost_timeout is None:
            worker_lost_timeout = (
                mk_cfg.worker_lost_timeout_seconds
                if mk_cfg is not None else DEFAULT_WORKER_LOST_TIMEOUT)
        # gcInterval throttles the remote-orphan sweep; 0 disables it
        # (configuration_types.go MultiKueue.GCInterval). The origin label
        # value marks mirrors as ours.
        self.gc_interval = (mk_cfg.gc_interval_seconds
                            if mk_cfg is not None else DEFAULT_GC_INTERVAL)
        self.origin = mk_cfg.origin if mk_cfg is not None else DEFAULT_ORIGIN
        self._next_gc_at = 0.0
        self.clusters: Dict[str, RemoteClient] = {}
        self.cluster_specs: Dict[str, MultiKueueCluster] = {}
        self.configs: Dict[str, MultiKueueConfig] = {}
        # check name -> MultiKueueConfig name (AdmissionCheck parameters ref)
        self.check_configs: Dict[str, str] = {}
        # job kind -> JobAdapter (batchjob_adapter.go / jobset_adapter.go)
        self.adapters: Dict[str, JobAdapter] = {}
        # MultiKueueCluster name -> RemoteClient or None (factory connects;
        # multikueuecluster.go:423-453 builds clients from kubeconfigs)
        self.client_factory = client_factory
        self.worker_lost_timeout = worker_lost_timeout
        self._dispatches: Dict[str, _Dispatch] = {}
        # Only specs registered through add_cluster_spec are factory-managed;
        # directly-registered clients (add_cluster) keep their client object
        # across transient disconnects so worker-lost-timeout applies.
        self._factory_managed: set = set()

    def add_cluster(self, name: str, client: RemoteClient) -> None:
        """Directly register a connected worker (tests / embedded use)."""
        self.clusters[name] = client
        if hasattr(client, "origin"):
            client.origin = self.origin
        self.cluster_specs.setdefault(
            name, MultiKueueCluster(name=name, active=True, active_reason="Active"))

    def remove_cluster(self, name: str) -> None:
        self.clusters.pop(name, None)
        self.cluster_specs.pop(name, None)

    def add_cluster_spec(self, spec: MultiKueueCluster) -> None:
        """Register a worker by spec; the client factory connects it with
        exponential backoff (multikueuecluster.go:64-69,139-188)."""
        self.cluster_specs[spec.name] = spec
        self._factory_managed.add(spec.name)

    def add_config(self, config: MultiKueueConfig) -> None:
        self.configs[config.name] = config

    def register_adapter(self, kind: str, adapter: JobAdapter) -> None:
        self.adapters[kind] = adapter

    def _clusters_for_check(self) -> Dict[str, RemoteClient]:
        """The worker set this check dispatches to: all clusters when no
        config is bound, the configured subset when one is, and NONE when
        the bound MultiKueueConfig is missing (the check is inactive in the
        reference until its config resolves)."""
        config_name = self.check_configs.get(self.check_name)
        if config_name is None:
            return self.clusters
        config = self.configs.get(config_name)
        if config is None:
            return {}
        return {n: c for n, c in self.clusters.items()
                if n in config.clusters}

    def _configured_cluster_names(self) -> set:
        """Every worker the check is configured to dispatch to, connected
        or not — the denominator for "all workers rejected". Using the
        live-connection dict would let one rejecting worker + one
        transiently disconnected worker permanently deactivate a workload
        the disconnected worker would have accepted."""
        config_name = self.check_configs.get(self.check_name)
        if config_name is None:
            return set(self.cluster_specs) | set(self.clusters)
        config = self.configs.get(config_name)
        if config is None:
            return set()
        return set(config.clusters)

    def reconcile_clusters(self) -> None:
        """Connection lifecycle for spec-registered workers: try the
        factory, track the Active condition, back off exponentially on
        failure (the multikueuecluster reconciler)."""
        if self.client_factory is None:
            return
        now = self.fw.clock()
        for name, spec in self.cluster_specs.items():
            if name not in self._factory_managed:
                continue
            client = self.clusters.get(name)
            if client is not None and client.connected():
                spec.active = True
                spec.active_reason = "Active"
                spec.failed_connection_attempts = 0
                spec.next_reconnect_at = None
                continue
            spec.active = False
            if spec.next_reconnect_at is not None \
                    and now < spec.next_reconnect_at:
                continue
            client = self.client_factory(spec)
            if client is not None and client.connected():
                if hasattr(client, "origin"):
                    client.origin = self.origin
                self.clusters[name] = client
                spec.active = True
                spec.active_reason = "Active"
                spec.failed_connection_attempts = 0
                spec.next_reconnect_at = None
            else:
                self.clusters.pop(name, None)
                spec.failed_connection_attempts += 1
                spec.active_reason = "ClientConnectionFailed"
                backoff = min(
                    RECONNECT_BASE_SECONDS
                    * 2 ** (spec.failed_connection_attempts - 1),
                    RECONNECT_MAX_SECONDS)
                spec.next_reconnect_at = now + backoff

    def reconcile(self) -> None:
        self.reconcile_clusters()
        now = self.fw.clock()
        # One O(jobs) sweep builds the reverse workload->job map for the
        # whole pass (vs a scan per reconciled workload).
        jobs_by_wl = {
            wl_key: (getattr(type(job), "kind", None), job)
            for job, wl_key in self.fw.job_reconciler.jobs.values()
        }
        for wl in list(self.fw.workloads.values()):
            cq = self.fw.cache.cluster_queues.get(
                wl.admission.cluster_queue if wl.admission else "")
            if cq is None or self.check_name not in cq.admission_checks:
                continue
            if wl.is_finished:
                self._gc(wl.key)
                continue
            if not wl.has_quota_reservation:
                continue
            try:
                self._reconcile_workload(wl, now, jobs_by_wl)
            except RemoteError:
                continue  # transient worker failure; retry next pass
        # GC dispatches whose local workload disappeared (part of the
        # normal reconcile, like wlReconciler's not-found branch) ...
        for key in list(self._dispatches):
            if key not in self.fw.workloads:
                self._gc(key)
        # ... and remote orphans no dispatch owns, on the configured GC
        # cadence; interval 0 disables (multikueuecluster.go:476-500 runs
        # as a gcInterval-periodic runnable).
        if self.gc_interval > 0 and now >= self._next_gc_at:
            self._next_gc_at = now + self.gc_interval
            owned = set(self._dispatches)
            for client in self.clusters.values():
                if not client.connected():
                    continue
                try:
                    for key in client.list_workload_keys():
                        if key not in owned:
                            client.delete_workload(key)
                except RemoteError:
                    continue  # next GC sweep retries


    def _reconcile_workload(self, wl: Workload, now: float,
                            jobs_by_wl: Dict[str, tuple]) -> None:
        d = self._dispatches.setdefault(wl.key, _Dispatch())
        workers = self._clusters_for_check()
        kind, local_job = jobs_by_wl.get(wl.key, (None, None))
        adapter = self.adapters.get(kind) if kind else None

        # Create the mirror (workload + job via the adapter) on every
        # connected worker (workload.go:232-300).
        if d.kept_on is None:
            for name, client in workers.items():
                if name in d.created_on or name in d.rejected_on \
                        or not client.connected():
                    continue
                try:
                    client.create_workload(wl)
                except RemoteRejected as exc:
                    d.rejected_on[name] = str(exc)
                    continue
                if adapter is not None and local_job is not None:
                    adapter.sync_job(client, local_job, wl)
                d.created_on.append(name)
            configured = self._configured_cluster_names()
            if configured and not d.created_on \
                    and configured <= set(d.rejected_on):
                # Every worker permanently rejected the mirror: surface the
                # rejection on the check instead of silently re-POSTing
                # forever (ADVICE r2: 422-style webhook rejections).
                state = wl.admission_check_states.get(self.check_name)
                if state is None or state.state != "Rejected":
                    wl.admission_check_states[self.check_name] = \
                        AdmissionCheckState(
                            name=self.check_name, state="Rejected",
                            message=next(iter(d.rejected_on.values())))
                    self._note_check_changed(wl)
                return
            if not wl.admission_check_states.get(self.check_name):
                wl.admission_check_states[self.check_name] = \
                    AdmissionCheckState(name=self.check_name, state="Pending",
                                        message="dispatched to workers")
                self._note_check_changed(wl)

        # First worker to reserve quota wins (workload.go:94-148).
        statuses = {}
        for name in list(d.created_on):
            client = self.clusters.get(name)
            if client is None or not client.connected():
                continue
            statuses[name] = client.get_status(wl.key)

        if d.kept_on is None:
            winner = next((n for n, s in statuses.items()
                           if s and s["quota_reserved"]), None)
            if winner is not None:
                d.kept_on = winner
                for name in d.created_on:
                    if name != winner:
                        client = self.clusters.get(name)
                        if client is not None and client.connected():
                            client.delete_workload(wl.key)
                d.created_on = [winner]
                wl.admission_check_states[self.check_name] = \
                    AdmissionCheckState(
                        name=self.check_name, state="Ready",
                        message=f'The workload got reservation on "{winner}"')
                self._note_check_changed(wl)
            return

        # Kept worker: watch status (remote watch analog).
        status = statuses.get(d.kept_on)
        client = self.clusters.get(d.kept_on)
        if client is None or not client.connected() or status is None:
            # Worker lost: wait out the timeout, then retry the whole
            # dispatch (multikueuecluster.go workerLostTimeout).
            if d.lost_since is None:
                d.lost_since = now
            elif now - d.lost_since >= self.worker_lost_timeout:
                self._dispatches[wl.key] = _Dispatch()
                wl.admission_check_states[self.check_name] = \
                    AdmissionCheckState(name=self.check_name, state="Retry",
                                        message="Reserving remote lost")
                self._note_check_changed(wl)
            return
        d.lost_since = None
        if adapter is not None and local_job is not None \
                and now >= d.next_job_poll_at:
            # Remote job status flows back while the remote runs
            # (jobAdapter.CopyStatusRemoteObject). The poll cadence is the
            # transport's call: free for in-process workers, throttled for
            # HTTP ones.
            d.next_job_poll_at = now + getattr(
                client, "job_status_poll_interval", 0.0)
            adapter.copy_status_remote_to_local(client, local_job, wl)
        if status["finished"]:
            self.fw.finish(wl)
            self._gc(wl.key)

    def _note_check_changed(self, wl) -> None:
        note = getattr(self.fw, "note_check_state_changed", None)
        if note is not None:
            note(wl)

    def _gc(self, key: str) -> None:
        d = self._dispatches.pop(key, None)
        if d is None:
            return
        for name in d.created_on:
            client = self.clusters.get(name)
            if client is not None and client.connected():
                try:
                    client.delete_workload(key)
                except RemoteError:
                    pass  # orphan; the periodic GC sweep catches it
