"""MultiKueue: multi-cluster workload dispatch.

Counterpart of reference pkg/controller/admissionchecks/multikueue/: for a
workload that reserved quota locally and whose ClusterQueue carries a
MultiKueue AdmissionCheck, mirror the workload onto every worker cluster
(multikueue/workload.go:56-300), keep the first cluster that reserves
quota, delete the mirror from the rest, sync remote Finished back, and
garbage-collect orphans. Worker loss is handled with reconnect accounting
and a workerLostTimeout before requeueing
(multikueuecluster.go:64-188, config defaults.go:49).

The remote boundary is the `RemoteClient` protocol; `InProcessRemote` wraps
another Framework instance (the envtest-style two-cluster simulation used
by the reference's integration tests), while a production deployment can
implement it over gRPC.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kueue_tpu.api.types import AdmissionCheckState, Workload

MULTIKUEUE_CHECK_CONTROLLER = "kueue.x-k8s.io/multikueue"
DEFAULT_WORKER_LOST_TIMEOUT = 15 * 60.0


class RemoteClient(abc.ABC):
    """A connection to one worker cluster."""

    @abc.abstractmethod
    def connected(self) -> bool: ...

    @abc.abstractmethod
    def create_workload(self, wl: Workload) -> None: ...

    @abc.abstractmethod
    def delete_workload(self, key: str) -> None: ...

    @abc.abstractmethod
    def get_status(self, key: str) -> Optional[dict]:
        """{'quota_reserved': bool, 'admitted': bool, 'finished': bool,
        'success': bool} or None if absent."""


class InProcessRemote(RemoteClient):
    """A worker cluster hosted by another Framework instance in-process."""

    def __init__(self, framework, queue_name: str = "main"):
        self.fw = framework
        self.queue_name = queue_name
        self._up = True

    def set_connected(self, up: bool) -> None:
        self._up = up

    def connected(self) -> bool:
        return self._up

    def create_workload(self, wl: Workload) -> None:
        import copy
        remote = Workload(
            name=wl.name, namespace=wl.namespace, queue_name=self.queue_name,
            pod_sets=copy.deepcopy(wl.pod_sets), priority=wl.priority,
            creation_time=wl.creation_time)
        self.fw.submit(remote)

    def delete_workload(self, key: str) -> None:
        wl = self.fw.workloads.get(key)
        if wl is not None:
            self.fw.delete_workload(wl)

    def get_status(self, key: str) -> Optional[dict]:
        wl = self.fw.workloads.get(key)
        if wl is None:
            return None
        return {
            "quota_reserved": wl.has_quota_reservation,
            "admitted": wl.is_admitted,
            "finished": wl.is_finished,
            "success": wl.is_finished,
        }


@dataclass
class _Dispatch:
    created_on: List[str] = field(default_factory=list)
    kept_on: Optional[str] = None
    lost_since: Optional[float] = None


class MultiKueueController:
    """Drives MultiKueue-type AdmissionChecks against worker clusters."""

    def __init__(self, framework, check_name: str = "multikueue",
                 worker_lost_timeout: float = DEFAULT_WORKER_LOST_TIMEOUT):
        self.fw = framework
        self.check_name = check_name
        self.clusters: Dict[str, RemoteClient] = {}
        self.worker_lost_timeout = worker_lost_timeout
        self._dispatches: Dict[str, _Dispatch] = {}

    def add_cluster(self, name: str, client: RemoteClient) -> None:
        self.clusters[name] = client

    def remove_cluster(self, name: str) -> None:
        self.clusters.pop(name, None)

    def reconcile(self) -> None:
        now = self.fw.clock()
        for wl in list(self.fw.workloads.values()):
            cq = self.fw.cache.cluster_queues.get(
                wl.admission.cluster_queue if wl.admission else "")
            if cq is None or self.check_name not in cq.admission_checks:
                continue
            if wl.is_finished:
                self._gc(wl.key)
                continue
            if not wl.has_quota_reservation:
                continue
            self._reconcile_workload(wl, now)
        # GC dispatches whose local workload disappeared
        # (multikueuecluster.go:476-500).
        for key in list(self._dispatches):
            if key not in self.fw.workloads:
                self._gc(key)

    def _reconcile_workload(self, wl: Workload, now: float) -> None:
        d = self._dispatches.setdefault(wl.key, _Dispatch())

        # Create the mirror on every connected worker (workload.go:232-300).
        if d.kept_on is None:
            for name, client in self.clusters.items():
                if name not in d.created_on and client.connected():
                    client.create_workload(wl)
                    d.created_on.append(name)
            if not wl.admission_check_states.get(self.check_name):
                wl.admission_check_states[self.check_name] = \
                    AdmissionCheckState(name=self.check_name, state="Pending",
                                        message="dispatched to workers")

        # First worker to reserve quota wins (workload.go:94-148).
        statuses = {}
        for name in list(d.created_on):
            client = self.clusters.get(name)
            if client is None or not client.connected():
                continue
            statuses[name] = client.get_status(wl.key)

        if d.kept_on is None:
            winner = next((n for n, s in statuses.items()
                           if s and s["quota_reserved"]), None)
            if winner is not None:
                d.kept_on = winner
                for name in d.created_on:
                    if name != winner:
                        client = self.clusters.get(name)
                        if client is not None and client.connected():
                            client.delete_workload(wl.key)
                d.created_on = [winner]
                wl.admission_check_states[self.check_name] = \
                    AdmissionCheckState(
                        name=self.check_name, state="Ready",
                        message=f'The workload got reservation on "{winner}"')
            return

        # Kept worker: watch status (remote watch analog).
        status = statuses.get(d.kept_on)
        client = self.clusters.get(d.kept_on)
        if client is None or not client.connected() or status is None:
            # Worker lost: wait out the timeout, then retry the whole
            # dispatch (multikueuecluster.go workerLostTimeout).
            if d.lost_since is None:
                d.lost_since = now
            elif now - d.lost_since >= self.worker_lost_timeout:
                self._dispatches[wl.key] = _Dispatch()
                wl.admission_check_states[self.check_name] = \
                    AdmissionCheckState(name=self.check_name, state="Retry",
                                        message="Reserving remote lost")
            return
        d.lost_since = None
        if status["finished"]:
            self.fw.finish(wl)
            self._gc(wl.key)

    def _gc(self, key: str) -> None:
        d = self._dispatches.pop(key, None)
        if d is None:
            return
        for name in d.created_on:
            client = self.clusters.get(name)
            if client is not None and client.connected():
                client.delete_workload(key)
