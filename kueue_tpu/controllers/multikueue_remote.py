"""MultiKueue HTTP remote: cross-process worker-cluster client.

The reference's MultiKueue reaches worker clusters through their
apiservers: a per-cluster `remoteClient` built from a kubeconfig, with
watch-based workload mirroring and reconnect backoff
(multikueuecluster.go:73-260). `HTTPRemote` is that client against a
worker running `python -m kueue_tpu --serve --port N` (the
`kueue_tpu.server.APIServer` surface): workloads and jobs are created
over the wire as manifest JSON, and a chunked watch stream mirrors remote
workload status into the manager process so `get_status` is served from
the mirror, not a per-reconcile poll.

Transport-agnostic job sync: `RemoteClient.create_job`/`get_job` are the
jobAdapter seam (batchjob_adapter.go); both `InProcessRemote` and
`HTTPRemote` implement them, so the same `BatchJobAdapter` drives an
embedded or an out-of-process worker. Remote jobs are bound to the
already-mirrored workload with the `kueue.x-k8s.io/prebuilt-workload-name`
label, exactly like the reference keeps the remote job from spawning a
second workload (jobframework prebuilt-workload support).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional

from kueue_tpu.api import serialization
from kueue_tpu.api.types import Workload
from kueue_tpu.controllers.multikueue import (
    ORIGIN_LABEL,
    RemoteClient,
    RemoteError,
    RemoteRejected,
)

WORKLOADS_PATH = "/apis/kueue.x-k8s.io/v1beta1/namespaces/{ns}/workloads"
JOBS_PATH = "/apis/batch/v1/namespaces/{ns}/jobs"

# connected() probes are cached briefly so a reconcile pass costs one
# round-trip, not one per workload.
_HEALTH_CACHE_SECONDS = 1.0


class HTTPRemote(RemoteClient):
    """A worker cluster behind the kueue_tpu API server."""

    # Remote job counters are polled (no watch stream for jobs); the
    # controller throttles copy_status to this cadence per dispatch.
    job_status_poll_interval = 1.0

    def __init__(self, base_url: str, queue_name: str = "main",
                 timeout: float = 5.0, watch: bool = True):
        self.base_url = base_url.rstrip("/")
        self.queue_name = queue_name
        self.timeout = timeout
        self.origin = "multikueue"
        self._created: set = set()
        self._health_at = 0.0
        self._health = False
        self._closed = threading.Event()
        # key -> status dict, fed by the watch stream.
        self._mirror: Dict[str, dict] = {}
        self._watch_live = threading.Event()
        self._watch_thread: Optional[threading.Thread] = None
        if watch:
            self._watch_thread = threading.Thread(
                target=self._watch_loop, daemon=True)
            self._watch_thread.start()

    # -- transport ---------------------------------------------------------

    def _request(self, method: str, path: str,
                 body: Optional[dict] = None) -> dict:
        """One JSON round-trip. Transport failures (unreachable, timeout,
        5xx) become RemoteError so a reconcile pass retries instead of
        crashing; HTTP client errors (4xx) re-raise as HTTPError for the
        caller to interpret (404 absent, 409 already-exists)."""
        data = None if body is None else json.dumps(body).encode()
        req = urllib.request.Request(
            self.base_url + path, data=data, method=method,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as exc:
            if exc.code >= 500:
                raise RemoteError(f"{method} {path}: {exc}") from exc
            raise
        except (urllib.error.URLError, OSError, ValueError) as exc:
            raise RemoteError(f"{method} {path}: {exc}") from exc

    def close(self) -> None:
        self._closed.set()

    # -- RemoteClient ------------------------------------------------------

    def connected(self) -> bool:
        # TTL anchor for the health-probe cache, not a latency
        # measurement — nothing for the tracer to aggregate.
        now = time.monotonic()
        if now - self._health_at < _HEALTH_CACHE_SECONDS:
            return self._health
        try:
            req = urllib.request.Request(self.base_url + "/healthz")
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                self._health = resp.status == 200
        except (urllib.error.URLError, OSError, ValueError):
            self._health = False
        self._health_at = now
        return self._health

    def create_workload(self, wl: Workload) -> None:
        mirror = serialization.encode_workload(wl, with_status=False)
        mirror["metadata"]["labels"][ORIGIN_LABEL] = self.origin
        mirror["spec"]["queueName"] = self.queue_name
        try:
            self._request("POST", WORKLOADS_PATH.format(ns=wl.namespace),
                          mirror)
        except urllib.error.HTTPError as exc:
            if exc.code != 409:  # 409 = already mirrored
                # Non-conflict 4xx (e.g. a worker-side webhook rejection)
                # is permanent: the same payload can never succeed, so the
                # controller must stop re-POSTing and surface the message.
                try:
                    body = json.loads(exc.read() or b"{}")
                    detail = (body.get("message")
                              if isinstance(body, dict) else None) or str(exc)
                except Exception:
                    detail = str(exc)
                raise RemoteRejected(
                    f"create workload {wl.key}: {detail}") from exc
        self._created.add(wl.key)

    def delete_workload(self, key: str) -> None:
        ns, _, name = key.partition("/")
        try:
            self._request(
                "DELETE", WORKLOADS_PATH.format(ns=ns) + f"/{name}")
        except urllib.error.HTTPError as exc:
            if exc.code != 404:
                raise RemoteError(f"delete workload {key}: {exc}") from exc
        except RemoteError:
            pass  # worker unreachable; GC retries on the next sweep
        self._created.discard(key)
        self._mirror.pop(key, None)

    def get_status(self, key: str) -> Optional[dict]:
        if self._watch_live.is_set():
            return self._mirror.get(key)
        ns, _, name = key.partition("/")
        try:
            doc = self._request(
                "GET", WORKLOADS_PATH.format(ns=ns) + f"/{name}")
        except urllib.error.HTTPError as exc:
            if exc.code == 404:
                return None
            raise RemoteError(f"get workload {key}: {exc}") from exc
        except RemoteError:
            return None  # worker lost; the lost-timeout path handles it
        return self._status_from_doc(doc)

    def list_workload_keys(self) -> List[str]:
        try:
            resp = self._request(
                "GET",
                "/apis/kueue.x-k8s.io/v1beta1/workloads"
                f"?labelSelector={ORIGIN_LABEL}={self.origin}")
        except (RemoteError, urllib.error.HTTPError):
            return []
        keys = []
        for item in resp.get("items", ()):
            meta = item.get("metadata") or {}
            keys.append(f"{meta.get('namespace', 'default')}/{meta['name']}")
        return sorted(keys)

    # -- job adapter seam --------------------------------------------------

    def create_job(self, manifest: dict, wl: Workload) -> None:
        ns = (manifest.get("metadata") or {}).get("namespace", "default")
        try:
            self._request("POST", JOBS_PATH.format(ns=ns), manifest)
        except urllib.error.HTTPError as exc:
            if exc.code != 409:
                raise RemoteError(f"create job: {exc}") from exc

    def get_job(self, namespace: str, name: str) -> Optional[dict]:
        try:
            doc = self._request(
                "GET", JOBS_PATH.format(ns=namespace) + f"/{name}")
        except urllib.error.HTTPError as exc:
            if exc.code == 404:
                return None
            raise RemoteError(f"get job {namespace}/{name}: {exc}") from exc
        except RemoteError:
            return None
        status = doc.get("status") or {}
        return {"ready": int(status.get("ready") or 0),
                "succeeded": int(status.get("succeeded") or 0),
                "failed": status.get("failed") or 0}

    # -- watch mirroring (multikueuecluster.go:190-230) --------------------

    @staticmethod
    def _status_from_doc(doc: dict) -> dict:
        conditions = {c.get("type"): c.get("status") == "True"
                      for c in (doc.get("status") or {}).get("conditions") or ()}
        finished = conditions.get("Finished", False)
        return {"quota_reserved": conditions.get("QuotaReserved", False),
                "admitted": conditions.get("Admitted", False),
                "finished": finished, "success": finished}

    def _watch_loop(self) -> None:
        """Maintain the status mirror off the server's watch stream,
        reconnecting with a capped backoff (multikueuecluster.go:64-69)."""
        backoff = 0.2
        while not self._closed.is_set():
            try:
                req = urllib.request.Request(
                    self.base_url
                    + "/apis/kueue.x-k8s.io/v1beta1/watch/workloads")
                with urllib.request.urlopen(req, timeout=30) as resp:
                    # The initial ADDED replay is staged and only swapped
                    # into the live mirror at the server's BOOKMARK marker:
                    # going live mid-replay would serve mirror-misses for
                    # workloads that exist on the worker and spuriously
                    # start the lost_since timer after every reconnect.
                    # If the server never sends a BOOKMARK, get_status
                    # falls back to per-key GETs — correct, just unmirrored.
                    staging: Dict[str, dict] = {}
                    backoff = 0.2
                    for raw in resp:
                        if self._closed.is_set():
                            return
                        line = raw.strip()
                        if not line:
                            continue  # heartbeat
                        ev = json.loads(line)
                        if ev.get("type") == "BOOKMARK":
                            # Lock-free publish: rebinding the attribute
                            # is atomic under the GIL, and the stream
                            # keeps mutating the now-live dict only by
                            # whole-value replacement — readers never
                            # observe a half-built status.
                            self._mirror = staging  # kueuelint: disable=THR01
                            self._watch_live.set()
                            continue
                        obj = ev.get("object") or {}
                        meta = obj.get("metadata") or {}
                        key = (f"{meta.get('namespace', 'default')}"
                               f"/{meta.get('name')}")
                        if ev.get("type") == "DELETED":
                            staging.pop(key, None)
                        else:
                            staging[key] = self._status_from_doc(obj)
            except (urllib.error.URLError, OSError, ValueError):
                pass
            self._watch_live.clear()
            if self._closed.wait(backoff):
                return
            backoff = min(backoff * 2, 5.0)


def http_client_factory(spec) -> Optional[HTTPRemote]:
    """Client factory for spec-registered clusters whose kubeconfig_ref
    carries a base URL: ("URL", "http://host:port[?queue=name]")."""
    location_type, location = spec.kubeconfig_ref
    if location_type != "URL" or not location:
        return None
    queue = "main"
    if "?queue=" in location:
        location, _, queue = location.partition("?queue=")
    client = HTTPRemote(location, queue_name=queue)
    if not client.connected():
        client.close()
        return None
    return client
