"""Provisioning admission-check controller.

Counterpart of reference pkg/controller/admissionchecks/provisioning/: for
every workload with QuotaReserved whose ClusterQueue carries a provisioning
AdmissionCheck, create a ProvisioningRequest against a capacity provider
(the cluster-autoscaler analog -- here a pluggable callback that brings up
TPU slices/nodepools), track its outcome with bounded retries
(controller.go:793+), flip the check state, and inject the provisioned
placement into the workload's PodSetUpdates (controller.go:549-560).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from kueue_tpu.api.types import AdmissionCheckState, Workload

PROVISIONING_CHECK_CONTROLLER = "kueue.x-k8s.io/provisioning-request"


@dataclass
class ProvisioningRequestConfig:
    """reference: apis/kueue/v1beta1/provisioningrequestconfig_types.go:25-58."""

    name: str
    provisioning_class: str = "queued-provisioning.gke.io"
    parameters: Dict[str, str] = field(default_factory=dict)
    max_retries: int = 3


@dataclass
class ProvisioningRequest:
    name: str
    workload_key: str
    provisioning_class: str
    parameters: Dict[str, str]
    pod_sets: List[dict]
    state: str = "Pending"  # Pending | Provisioned | Failed
    attempt: int = 1
    node_selector: Dict[str, str] = field(default_factory=dict)


class ProvisioningController:
    """Drives check states for provisioning-type AdmissionChecks."""

    def __init__(self, framework,
                 provider: Optional[Callable[[ProvisioningRequest], None]] = None):
        self.fw = framework
        # The capacity provider observes requests and flips their state
        # (cluster-autoscaler analog). Default provider provisions
        # instantly.
        self.provider = provider or self._instant_provider
        self.configs: Dict[str, ProvisioningRequestConfig] = {}
        # check name -> config name
        self.checks: Dict[str, str] = {}
        self.requests: Dict[str, ProvisioningRequest] = {}
        self._seq = itertools.count(1)

    @staticmethod
    def _instant_provider(req: ProvisioningRequest) -> None:
        req.state = "Provisioned"

    def register_check(self, check_name: str,
                       config: ProvisioningRequestConfig) -> None:
        self.configs[config.name] = config
        self.checks[check_name] = config.name

    def reconcile(self) -> None:
        for wl in list(self.fw.workloads.values()):
            if not wl.has_quota_reservation or wl.is_finished or wl.is_evicted:
                continue
            cq = self.fw.cache.cluster_queues.get(
                wl.admission.cluster_queue if wl.admission else "")
            if cq is None:
                continue
            for check_name in cq.admission_checks:
                if check_name not in self.checks:
                    continue
                self._reconcile_check(wl, check_name)

    def _reconcile_check(self, wl: Workload, check_name: str) -> None:
        config = self.configs[self.checks[check_name]]
        state = wl.admission_check_states.get(check_name)
        if state is not None and state.state in ("Ready", "Rejected"):
            return
        key = f"{wl.key}/{check_name}"
        req = self.requests.get(key)
        if req is None:
            req = ProvisioningRequest(
                name=f"prov-{next(self._seq):06d}",
                workload_key=wl.key,
                provisioning_class=config.provisioning_class,
                parameters=dict(config.parameters),
                pod_sets=[{"name": psa.name, "count": psa.count,
                           "requests": dict(psa.resource_usage)}
                          for psa in wl.admission.pod_set_assignments],
            )
            self.requests[key] = req
            wl.admission_check_states[check_name] = AdmissionCheckState(
                name=check_name, state="Pending",
                message=f"Created ProvisioningRequest {req.name}")
        self.provider(req)
        if req.state == "Provisioned":
            updates = [{"name": ps["name"],
                        "nodeSelector": dict(req.node_selector)}
                       for ps in req.pod_sets]
            wl.admission_check_states[check_name] = AdmissionCheckState(
                name=check_name, state="Ready",
                message=f"ProvisioningRequest {req.name} provisioned",
                pod_set_updates=updates)
        elif req.state == "Failed":
            if req.attempt >= config.max_retries:
                wl.admission_check_states[check_name] = AdmissionCheckState(
                    name=check_name, state="Rejected",
                    message=f"ProvisioningRequest {req.name} failed "
                            f"after {req.attempt} attempts")
            else:
                # Retry with a fresh request (controller.go backoff+retry).
                req.attempt += 1
                req.state = "Pending"
                wl.admission_check_states[check_name] = AdmissionCheckState(
                    name=check_name, state="Retry",
                    message=f"ProvisioningRequest {req.name} failed; "
                            f"attempt {req.attempt}")
