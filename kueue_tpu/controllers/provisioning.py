"""Provisioning admission-check controller.

Counterpart of reference pkg/controller/admissionchecks/provisioning/: for
every workload with QuotaReserved whose ClusterQueue carries a provisioning
AdmissionCheck, create a ProvisioningRequest against a capacity provider
(the cluster-autoscaler analog — here a pluggable callback that brings up
TPU slices/nodepools), track its outcome with bounded retries and
exponential backoff (controller.go:220-320,788-806), flip the check state
(syncCheckStates, controller.go:465-546), and inject the provisioned
placement into the workload's PodSetUpdates (podSetUpdates,
controller.go:549-560).

Semantics carried over:
- managedResources filtering: only pod sets requesting a managed resource
  need provisioning; when none do, the check is Ready with
  "the provisioning request is not needed" (reqIsNeeded/requiredPodSets,
  controller.go:389-417).
- request naming `<workload>-<check>-<attempt>` with the attempt suffix as
  the retry counter (GetProvisioningRequestName, controller.go:738-744).
- retry: a Failed request is retried up to MaxRetries(3) times after an
  exponential backoff of MinBackoffSeconds(60)*2^(attempt-1) capped at
  30min; past that the check is Rejected with the failure message. Like the
  reference snapshot (syncCheckStates sets Pending "Retrying after
  failure", controller.go:496-507), the workload keeps its quota
  reservation through the backoff window rather than being evicted.
- workload annotations `provreq.kueue.x-k8s.io/<param>` are passed into the
  request parameters (passProvReqParams, controller.go:455-463).
- an inactive check (no config) reports Pending
  "the check is not active" (CheckInactiveMessage).
- Ready checks carry PodSetUpdates annotating each pod set with
  `cluster-autoscaler.kubernetes.io/consume-provisioning-request`.
- requests of finished/evicted workloads, and superseded attempts, are
  garbage-collected (deleteUnusedProvisioningRequests, controller.go:189+).
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from kueue_tpu.api.types import AdmissionCheckState, Workload
from kueue_tpu.events import EventRecorder

PROVISIONING_CHECK_CONTROLLER = "kueue.x-k8s.io/provisioning-request"
PROV_REQ_ANNOTATION_PREFIX = "provreq.kueue.x-k8s.io/"
CONSUMES_ANNOTATION_KEY = \
    "cluster-autoscaler.kubernetes.io/consume-provisioning-request"
CHECK_INACTIVE_MESSAGE = "the check is not active"
NO_REQUEST_NEEDED = "the provisioning request is not needed"
MAX_RETRIES = 3
MIN_BACKOFF_SECONDS = 60
MAX_BACKOFF_SECONDS = 30 * 60


@dataclass
class ProvisioningRequestConfig:
    """reference: apis/kueue/v1beta1/provisioningrequestconfig_types.go:25-58."""

    name: str
    provisioning_class: str = "queued-provisioning.gke.io"
    parameters: Dict[str, str] = field(default_factory=dict)
    # Only pod sets requesting one of these resources are provisioned; empty
    # means all pod sets.
    managed_resources: Tuple[str, ...] = ()


@dataclass
class ProvisioningRequest:
    name: str
    workload_key: str
    check_name: str
    provisioning_class: str
    parameters: Dict[str, str]
    pod_sets: List[dict]
    state: str = "Pending"  # Pending | Provisioned | Failed
    attempt: int = 1
    failure_message: str = ""
    failed_at: float = 0.0
    # Provider extension: node placement for the provisioned capacity.
    node_selector: Dict[str, str] = field(default_factory=dict)


def backoff_seconds(attempt: int) -> float:
    """MinBackoffSeconds * 2^(attempt-1), capped (controller.go:788-806)."""
    d = MIN_BACKOFF_SECONDS
    for _ in range(1, attempt):
        d *= 2
        if d >= MAX_BACKOFF_SECONDS:
            return MAX_BACKOFF_SECONDS
    return d


class ProvisioningController:
    """Drives check states for provisioning-type AdmissionChecks."""

    def __init__(self, framework,
                 provider: Optional[Callable[[ProvisioningRequest], None]] = None,
                 clock: Callable[[], float] = _time.time,
                 recorder: Optional[EventRecorder] = None):
        self.fw = framework
        # The capacity provider observes requests and flips their state
        # (cluster-autoscaler analog). Default provider provisions
        # instantly.
        self.provider = provider or self._instant_provider
        self.clock = clock
        self.recorder = recorder or getattr(framework, "events", None) \
            or EventRecorder()
        self.configs: Dict[str, ProvisioningRequestConfig] = {}
        # check name -> config name
        self.checks: Dict[str, str] = {}
        # request name -> request
        self.requests: Dict[str, ProvisioningRequest] = {}

    @staticmethod
    def _instant_provider(req: ProvisioningRequest) -> None:
        req.state = "Provisioned"

    def register_check(self, check_name: str,
                       config: Optional[ProvisioningRequestConfig] = None
                       ) -> None:
        """An AdmissionCheck handled by this controller; without a config it
        is inactive (reports CheckInactiveMessage)."""
        if config is not None:
            self.configs[config.name] = config
            self.checks[check_name] = config.name
        else:
            self.checks[check_name] = ""

    def update_config(self, config: ProvisioningRequestConfig) -> None:
        self.configs[config.name] = config

    # -- naming (controller.go:738-744) -------------------------------------

    @staticmethod
    def request_name(wl: Workload, check_name: str, attempt: int) -> str:
        return f"{wl.name}-{check_name}-{attempt}"

    def _latest_request(self, wl: Workload,
                        check_name: str) -> Optional[ProvisioningRequest]:
        best = None
        for req in self.requests.values():
            if req.workload_key == wl.key and req.check_name == check_name:
                if best is None or req.attempt > best.attempt:
                    best = req
        return best

    # -- reconcile -----------------------------------------------------------

    def reconcile(self) -> None:
        live_keys = set()
        for wl in list(self.fw.workloads.values()):
            if not wl.has_quota_reservation or wl.is_finished or wl.is_evicted:
                continue
            cq = self.fw.cache.cluster_queues.get(
                wl.admission.cluster_queue if wl.admission else "")
            if cq is None:
                continue
            live_keys.add(wl.key)
            for check_name in cq.admission_checks:
                if check_name not in self.checks:
                    continue
                self._reconcile_check(wl, check_name)
        # GC requests owned by workloads no longer holding quota
        # (deleteUnusedProvisioningRequests analog).
        for name in [n for n, r in self.requests.items()
                     if r.workload_key not in live_keys]:
            del self.requests[name]

    def _required_podsets(self, wl: Workload,
                          config: ProvisioningRequestConfig) -> List[str]:
        """Pod sets that request a managed resource (controller.go:393-407)."""
        if not config.managed_resources:
            return [ps.name for ps in wl.pod_sets]
        managed = set(config.managed_resources)
        return [ps.name for ps in wl.pod_sets
                if managed.intersection(ps.requests)]

    def _set_state(self, wl: Workload, check_name: str, state: str,
                   message: str, pod_set_updates=None) -> None:
        prev = wl.admission_check_states.get(check_name)
        if prev is not None and prev.state == state \
                and prev.message == message:
            return
        wl.admission_check_states[check_name] = AdmissionCheckState(
            name=check_name, state=state, message=message,
            pod_set_updates=pod_set_updates)
        note = getattr(self.fw, "note_check_state_changed", None)
        if note is not None:
            note(wl)
        if prev is not None and prev.state != state:
            self.recorder.event(
                wl.key, "Normal", "AdmissionCheckUpdated",
                f"Admission check {check_name} updated state from "
                f"{prev.state} to {state}" + (
                    f" with message {message}" if message else ""))

    def _reconcile_check(self, wl: Workload, check_name: str) -> None:
        config = self.configs.get(self.checks.get(check_name, ""))
        if config is None:
            # Inactive check (controller.go:474-479).
            self._set_state(wl, check_name, "Pending", CHECK_INACTIVE_MESSAGE)
            return
        required = self._required_podsets(wl, config)
        state = wl.admission_check_states.get(check_name)
        if not required:
            # No managed resources requested (controller.go:480-486); like
            # the reference, only a non-Ready state is rewritten, so a Ready
            # check keeps its PodSetUpdates across config changes.
            if state is None or state.state != "Ready":
                self._set_state(wl, check_name, "Ready", NO_REQUEST_NEEDED)
            return
        if state is not None and state.state in ("Ready", "Rejected"):
            return

        req = self._latest_request(wl, check_name)
        should_create = req is None
        attempt = req.attempt if req is not None else 1
        if req is not None and req.state == "Failed" \
                and attempt <= MAX_RETRIES:
            if self.clock() - req.failed_at >= backoff_seconds(attempt):
                should_create = True
                attempt += 1
        if should_create:
            req = self._create_request(wl, check_name, config, required,
                                       attempt)

        # Only in-flight requests are shown to the provider: a recorded
        # Failed/Provisioned attempt is immutable, so the backoff clock and
        # the attempt history can't be bypassed by a re-drive.
        if req.state == "Pending":
            self.provider(req)
            if req.state == "Failed" and not req.failed_at:
                req.failed_at = self.clock()

        # syncCheckStates (controller.go:465-546).
        if req.state == "Failed":
            if req.attempt <= MAX_RETRIES:
                self._set_state(
                    wl, check_name, "Pending",
                    f"Retrying after failure: {req.failure_message}")
            else:
                self._set_state(wl, check_name, "Rejected",
                                req.failure_message)
        elif req.state == "Provisioned":
            updates = []
            for ps in req.pod_sets:
                update = {"name": ps["name"],
                          "annotations": {CONSUMES_ANNOTATION_KEY: req.name}}
                if req.node_selector:
                    update["nodeSelector"] = dict(req.node_selector)
                updates.append(update)
            self._set_state(
                wl, check_name, "Ready",
                f"ProvisioningRequest {req.name} provisioned",
                pod_set_updates=updates)
        else:
            self._set_state(wl, check_name, "Pending",
                            f"Waiting for ProvisioningRequest {req.name}")

    def _create_request(self, wl: Workload, check_name: str,
                        config: ProvisioningRequestConfig,
                        required: List[str],
                        attempt: int) -> ProvisioningRequest:
        parameters = dict(config.parameters)
        # passProvReqParams (controller.go:455-463).
        for key, val in wl.annotations.items():
            if key.startswith(PROV_REQ_ANNOTATION_PREFIX):
                parameters[key[len(PROV_REQ_ANNOTATION_PREFIX):]] = val
        psa_by_name = {psa.name: psa
                       for psa in wl.admission.pod_set_assignments}
        pod_sets = []
        for ps in wl.pod_sets:
            if ps.name not in required:
                continue
            psa = psa_by_name.get(ps.name)
            pod_sets.append({
                "name": ps.name,
                "count": psa.count if psa is not None else ps.count,
                "requests": dict(psa.resource_usage) if psa is not None
                else dict(ps.requests),
            })
        # Superseded attempts are deleted, keeping only the active/last
        # request per (workload, check) — deleteUnusedProvisioningRequests
        # (controller.go:189-215).
        for old in [n for n, r in self.requests.items()
                    if r.workload_key == wl.key
                    and r.check_name == check_name]:
            del self.requests[old]
        name = self.request_name(wl, check_name, attempt)
        req = ProvisioningRequest(
            name=name, workload_key=wl.key, check_name=check_name,
            provisioning_class=config.provisioning_class,
            parameters=parameters, pod_sets=pod_sets, attempt=attempt)
        self.requests[name] = req
        self.recorder.event(
            wl.key, "Normal", "ProvisioningRequestCreated",
            f'Created ProvisioningRequest: "{name}"')
        return req
