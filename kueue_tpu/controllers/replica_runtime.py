"""Multi-process replica runtime: shard-group worker processes + the
coordinator barrier driving the cross-replica commit protocol.

`ReplicaRuntime(n)` owns N workers — real ``multiprocessing`` (spawn)
processes in production, in-process threads in loopback mode (the
decision-identity goldens' transport; the protocol and the code are the
same, only the channel differs). Each worker owns the FULL vertical
slice for its shard groups: its own queue `Manager`, `Cache`,
`SnapshotMirror`, `WorkloadArena`/`AdmittedArena`, nominate cache and
`BatchSolver` (each `Framework` binds its own arenas to its own queue
and cache sinks — per-process arena binding falls out of construction),
plus one `Store` + durable `Journal` per shard group it owns, fed by the
runtime's partitioned watch routing (`parallel.replica.GroupMap` — the
PR 7 cohort hash, so flat cohorts are replica-complete).

The tick is a barrier protocol:

  parent: "tick" to every live worker
  worker: runs its local Framework tick; the scheduler's admission
          cycle ships its split-root candidates (or the worker an empty
          round) and BLOCKS on the verdict reply
  parent: collects one round per live worker, has the lease-holding
          Coordinator replay all candidates in global cycle order
          against the merged lending-clamp state, answers per-replica
          commit/revoke verdicts
  worker: applies verdicts, flushes, requeues, syncs status into its
          group journals, replies "done" with the tick's evidence
          (admissions, revocations, reconcile RTTs, RSS)

Fail-over: a worker death is detected at the next barrier; the
lease-holding parent reassigns its shard groups to a survivor, which
attaches the dead worker's per-group journals (`Journal.attach` — the
flock clears when the process dies) and replays them: admitted
workloads re-account quota, pending ones re-queue, exactly the PR 2 HA
takeover per partition.

Kill switches: ``KUEUE_TPU_REPLICAS=N`` opts the CLI in,
``KUEUE_TPU_NO_REPLICA=1`` forces single-process regardless.
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Dict, List, Optional, Tuple

from kueue_tpu import knobs
from kueue_tpu.controllers.store import (
    ADDED,
    DELETED,
    MODIFIED,
    KIND_ADMISSION_CHECK,
    KIND_CLUSTER_QUEUE,
    KIND_COHORT,
    KIND_LOCAL_QUEUE,
    KIND_RESOURCE_FLAVOR,
    KIND_WORKLOAD,
    KIND_WORKLOAD_PRIORITY_CLASS,
    Store,
    StoreAdapter,
)
from kueue_tpu.parallel.replica import (
    SOLO_PREFIX,
    Coordinator,
    GroupMap,
    ReplicaChannel,
    ReplicaContext,
    group_key,
    group_of,
)
from kueue_tpu.transport.faults import FaultPlan, parse_fault_env
from kueue_tpu.transport.replication import JournalReplicator, host_state_dir
from kueue_tpu.transport.socket_channel import (
    PEER_RESTART,
    ChannelListener,
    SocketChannel,
    WorkerDiedError,
)
from kueue_tpu.transport.watchdog import BarrierStallError, barrier_deadline

_ROUND_TIMEOUT = float(knobs.raw("KUEUE_TPU_ROUND_TIMEOUT"))


def transport_from_env(default: str = "pipe") -> str:
    """The configured replica transport: KUEUE_TPU_TRANSPORT, with the
    KUEUE_TPU_NO_SOCKET=1 kill switch forcing pipes regardless."""
    if knobs.flag("KUEUE_TPU_NO_SOCKET"):
        return "pipe"
    mode = knobs.raw("KUEUE_TPU_TRANSPORT") or default
    return mode if mode in ("pipe", "socket") else default


def replicas_from_env() -> int:
    """The configured replica count: KUEUE_TPU_REPLICAS, with
    KUEUE_TPU_NO_REPLICA=1 forcing single-process (0)."""
    if knobs.flag("KUEUE_TPU_NO_REPLICA"):
        return 0
    try:
        return int(knobs.raw("KUEUE_TPU_REPLICAS") or 0)
    except ValueError:
        return 0


def _rss_bytes() -> int:
    """Current resident set of THIS process (/proc/self/statm)."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except Exception:
        return 0


class WorkerDied(RuntimeError):
    pass


class _QueueChan:
    """Loopback transport: a pair of in-process queues."""

    def __init__(self, out_q: "queue.Queue", in_q: "queue.Queue"):
        self._out = out_q
        self._in = in_q

    def send(self, msg) -> None:
        self._out.put(msg)

    def recv(self, timeout: Optional[float] = None):
        try:
            return self._in.get(timeout=timeout)
        except queue.Empty:
            raise WorkerDied("loopback worker did not answer in time")


class _PipeChan:
    """Cross-process transport: a multiprocessing duplex pipe."""

    def __init__(self, conn):
        self._conn = conn
        # A closed pipe raises IMMEDIATELY on every recv (EOF, not
        # timeout): the worker's degraded loop must tell the two apart
        # or a dead parent becomes a zero-delay busy-spin.
        self._closed = False

    def send(self, msg) -> None:
        self._conn.send(msg)

    def recv(self, timeout: Optional[float] = None):
        if timeout is not None and not self._conn.poll(timeout):
            raise WorkerDied("worker pipe did not answer in time")
        try:
            return self._conn.recv()
        except (EOFError, OSError):
            self._closed = True
            raise WorkerDied("worker pipe closed")


# ---------------------------------------------------------------------------
# Worker (runs in the replica process / loopback thread)
# ---------------------------------------------------------------------------


class ReplicaWorker:
    """One replica's vertical slice + its side of the tick barrier."""

    chan: ReplicaChannel

    def __init__(self, worker_id: int, opts: dict, chan: ReplicaChannel):
        from kueue_tpu.config import Configuration, TPUSolverConfig
        from kueue_tpu.controllers.runtime import Framework

        self.worker_id = worker_id
        self.opts = opts
        self.chan = chan
        self.host_id = opts.get("host_id") or f"host-{worker_id}"
        # Journal replication (per-host state dirs): each group's
        # journal tap appends segment ops here; the tick's done reply
        # ships + clears them (transport/replication.py).
        self.replicate = bool(opts.get("replicate"))
        self._seg: Dict[int, list] = {}
        self.cq_gid: Dict[str, int] = {}     # cq name -> owning group
        # The parent ships its own barrier deadline so both sides of
        # the watchdog agree (a bench that raises the parent's round
        # timeout must raise the workers' verdict wait too, or a fast
        # worker times out on its slow siblings' phase A).
        self._barrier_deadline = float(
            opts.get("barrier_deadline")
            or barrier_deadline(_ROUND_TIMEOUT))
        self._dispatches_seen = 0
        # Degraded safe mode (fleet deployments): after this many
        # seconds of coordinator silence — and a failed re-election
        # probe — the worker drops to journaled shard-local admission.
        # None (the default for single-machine runs) keeps the PR 11
        # behavior: coordinator loss surfaces as a BarrierStallError.
        self._degraded_after = opts.get("degraded_after")
        self._degraded_interval = float(
            opts.get("degraded_tick_interval")
            or (min(float(self._degraded_after), 0.05)
                if self._degraded_after else 0.05))
        self._state_dir = opts.get("state_dir")
        self.degraded = False
        self.degraded_epoch = 0
        self._degraded_windows = 0
        self._degraded_ticks = 0
        self._degraded_admitted: List[Tuple[str, str]] = []
        self._degraded_t0: Optional[float] = None
        self._last_epoch = int(opts.get("epoch", 0) or 0)
        self._lease_probe = opts.get("lease_probe")  # callable or None
        self.revoked_total = 0
        # Dirty-cohort micro-ticks between barriers (opt-in: they
        # intentionally reorder vs the barrier-paced trail) and the
        # eager-encode predispatch (identity-preserving: abandoned on
        # any state-changing message). Both are the PR 9 barrier-stall
        # fix: a replica blocked behind a slow sibling keeps doing
        # useful work instead of idling.
        self._micro_enabled = bool(opts.get("microtick"))
        self._eager = bool(opts.get("eager_encode")) \
            and not knobs.flag("KUEUE_TPU_NO_EAGER_ENCODE")
        self._predispatched = None
        self.predispatch_used = 0
        self.predispatch_abandoned = 0
        self.micro_admitted: List[Tuple[str, str]] = []
        self.micro_preempted: List[str] = []
        self.microticks_run = 0
        # Last-shipped watermarks: the barrier done reply carries
        # DELTAS for every micro/predispatch counter (micro_admitted
        # already drains), so the coordinator's per-tick stats never
        # mix per-tick and lifetime semantics.
        self._microticks_sent = 0
        self._predispatch_sent = (0, 0)
        # Seeded slow-worker drill: sleep this long inside every tick
        # (the laggard the barrier-stall drill measures against).
        self._drill_slow_s = float(opts.get("drill_slow_s") or 0.0)
        batch_solver = None
        if opts.get("solver", True):
            from kueue_tpu.models.flavor_fit import BatchSolver

            batch_solver = BatchSolver(shards=opts.get("cohort_shards"))
        cfg = Configuration(tpu_solver=TPUSolverConfig(
            enable=False,  # never probe: the solver is decided above
            preemption_engine=opts.get("engine") or None))
        # Depth 1: the commit protocol's barrier runs INSIDE the cycle,
        # so overlapping ticks would stack barriers (and the sharded-mesh
        # argument applies — there is no host-link latency to hide).
        self.fw = Framework(batch_solver=batch_solver, config=cfg,
                            pipeline_depth=1)
        self.groups: Dict[int, tuple] = {}   # gid -> (store, adapter, journal)
        self.wl_gid: Dict[str, int] = {}     # workload key -> owning group
        # GHOST members: split-tree ClusterQueues another replica owns,
        # mirrored cache-only (never in the queue manager) so this
        # replica's nomination math sees the WHOLE tree — quota rows
        # from the routed specs, usage from the pre-tick exchange.
        self.ghost_cqs: set = set()
        self.rctx = ReplicaContext(submit=self._submit_round,
                                   usage_provider=self._cache_split_usage)
        self.rctx.on_stall = self._maybe_degrade
        # The runtime's pre-tick exchange is the authoritative usage
        # channel; rounds ship none (a ghost view must never overwrite
        # its owner's).
        self.rctx.ship_usage = False
        self.fw.scheduler.replica_ctx = self.rctx
        self._usage_memo = None
        self.tick_admitted: List[Tuple[str, str]] = []
        self.tick_preempted: List[str] = []
        orig_admit = self.fw.scheduler.apply_admission
        orig_preempt = self.fw.scheduler.apply_preemption

        def apply_admission(wl):
            ok = orig_admit(wl)
            if ok:
                self.tick_admitted.append(
                    (wl.key, wl.admission.cluster_queue))
            return ok

        def apply_preemption(wl, msg):
            self.tick_preempted.append(wl.key)
            return orig_preempt(wl, msg)

        self.fw.scheduler.apply_admission = apply_admission
        self.fw.scheduler.apply_preemption = apply_preemption

    # -- groups -------------------------------------------------------------

    def add_group(self, gid: int, journal_path: Optional[str] = None,
                  ) -> int:
        """Own a shard group: its Store + StoreAdapter into this
        worker's framework, plus the per-group durable journal when a
        state dir is configured. Attaching an existing journal REPLAYS
        it (restart recovery / fail-over adoption) — the adapter is
        already watching, so replayed events rebuild the framework:
        admitted workloads re-account quota, pending ones re-queue."""
        from kueue_tpu.controllers.durable import Journal

        store = Store()
        adapter = StoreAdapter(store, self.fw)
        journal = None
        restored = 0
        if journal_path:
            journal = Journal(journal_path)
            if self.replicate:
                # Tap BEFORE attach: the attach-time compaction ships a
                # ("reset", snapshot) op, so the coordinator's replica
                # copy starts from exactly this journal's content.
                journal.sink = \
                    lambda op, _g=gid: self._seg.setdefault(
                        _g, []).append(op)
            restored = journal.attach(store)
        self.groups[gid] = (store, adapter, journal)
        return restored

    # -- the commit-protocol round ------------------------------------------

    def _submit_round(self, payload: dict) -> List[bool]:
        self.chan.send(("round", {"replica": self.worker_id,
                                  "tick": 0, **payload}))
        try:
            msg = self.chan.recv(timeout=self._barrier_deadline)
        except (WorkerDied, WorkerDiedError):
            # The coordinator missed the barrier: surface WHO and WHICH
            # round instead of blocking this replica forever (the
            # watchdog half of the commit protocol — the parent has the
            # matching deadline for replicas).
            raise BarrierStallError(
                "coordinator", wid=self.worker_id, pid=os.getpid(),
                host=self.host_id, round_no=self.rctx.rounds,
                phase="verdicts", timeout_s=self._barrier_deadline)
        if msg[0] != "verdicts":
            raise RuntimeError(
                f"replica protocol violation: expected verdicts, "
                f"got {msg[0]!r}")
        return list(msg[1])

    def _root_of(self, cohort: str) -> str:
        specs = self.fw.cache.cohort_specs
        seen = set()
        node = cohort
        while True:
            spec = specs.get(node)
            parent = spec.parent if spec is not None else ""
            if not parent or node in seen:
                return node
            seen.add(node)
            node = parent

    def _cache_split_usage(self) -> Dict[str, dict]:
        """This replica's OWNED split-root members' usage from the live
        cache (ghosts excluded — their usage belongs to their owner),
        shipped at the pre-tick exchange (cache-side Cohort objects
        carry no parent links, so roots walk the specs)."""
        split = self.rctx.split_roots
        if not split:
            return {}
        cache = self.fw.cache
        key = (cache.structure_version, split, len(self.ghost_cqs))
        memo = self._usage_memo
        if memo is None or memo[0] != key:
            names = [
                cq.name for cq in cache.cluster_queues.values()
                if cq.cohort_name
                and cq.name not in self.ghost_cqs
                and self._root_of(cq.cohort_name) in split]
            memo = self._usage_memo = (key, names)
        cqs = cache.cluster_queues
        return {
            name: {f: dict(res) for f, res in cqs[name].usage.items()}
            for name in memo[1] if name in cqs}

    def _local_journal_path(self, gid: int) -> Optional[str]:
        """Where THIS worker journals shard group `gid` when the
        parent cannot name a path on our disk (remote join: journals
        are host-local by construction)."""
        if not self._state_dir:
            return None
        os.makedirs(self._state_dir, exist_ok=True)
        return os.path.join(self._state_dir, f"journal-g{gid}.jsonl")

    # -- degraded safe mode ---------------------------------------------------
    #
    # The coordinator is dead (watchdog silence past `degraded_after`)
    # and the re-election probe failed: this replica keeps serving what
    # it can PROVE safe alone. Flat cohorts are replica-complete by the
    # shard-group hash, so their quota math never needed the
    # coordinator — those heads keep admitting shard-locally. Split
    # roots park with an explain reason. Every degraded tick's verdicts
    # are journaled with a degraded-epoch stamp; the rejoin reconcile
    # replays the window against the merged state (quota is never
    # oversubscribed; revocations are allowed and counted).

    def _maybe_degrade(self) -> bool:
        """ReplicaContext.on_stall: a live round missed the barrier
        deadline — park and degrade (True) or surface the stall
        (False)?"""
        if self.degraded:
            return True
        if self._degraded_after is None:
            return False
        if self._coordinator_presumed_dead():
            self._enter_degraded("barrier-stall")
            return True
        return False

    def _coordinator_presumed_dead(self) -> bool:
        """One re-election probe. Without a lease seam (local pipe /
        loopback workers), silence past the deadline is the only
        signal (presume dead) — which is why `degraded_after` is OFF
        by default for local deployments and an operator who sets it
        must size it above the longest legitimate idle gap between
        coordinator messages. Joined workers probe the lease service:
        a reachable service whose lease is held means the coordinator
        (or a successor) is alive — keep waiting."""
        probe = self._lease_probe
        if probe is None:
            return True
        try:
            return not probe()
        except Exception:
            return True

    def _enter_degraded(self, why: str) -> None:
        import sys
        import time as _time

        from kueue_tpu.metrics import REGISTRY

        self.degraded = True
        self.rctx.degraded = True
        self._degraded_windows += 1
        self.degraded_epoch = self._last_epoch + 1
        # Wall-clock window bookkeeping (liveness evidence), not tick-
        # phase timing — the tracer may be disabled in a degraded
        # worker and the window must still measure.
        self._degraded_t0 = _time.monotonic()
        REGISTRY.coordinator_degraded.set(self.host_id, value=1.0)
        self._djournal({"event": "enter",
                        "degraded_epoch": self.degraded_epoch,
                        "why": why, "host": self.host_id})
        print(f"kueue-tpu: replica {self.worker_id} ({self.host_id}) "
              f"entered DEGRADED admission ({why}): flat cohorts admit "
              "shard-locally, split roots park",
              file=sys.stderr, flush=True)

    def _exit_degraded(self, why: str) -> None:
        import sys
        import time as _time

        from kueue_tpu.metrics import REGISTRY

        if not self.degraded:
            return
        self.degraded = False
        self.rctx.degraded = False
        REGISTRY.coordinator_degraded.set(self.host_id, value=0.0)
        now = _time.monotonic()
        dur = now - (self._degraded_t0 or now)
        self._djournal({"event": "exit",
                        "degraded_epoch": self.degraded_epoch,
                        "why": why, "ticks": self._degraded_ticks,
                        "duration_s": round(dur, 3),
                        "host": self.host_id})
        print(f"kueue-tpu: replica {self.worker_id} ({self.host_id}) "
              f"left degraded admission after {self._degraded_ticks} "
              f"ticks ({why})", file=sys.stderr, flush=True)

    def _degraded_tick(self) -> None:
        """One self-paced tick of the safe mode: the same Framework
        tick, with the replica context parking every split-root
        candidate locally instead of shipping a round."""
        from kueue_tpu.metrics import REGISTRY

        self.tick_admitted.clear()
        self.tick_preempted.clear()
        parked0 = self.rctx.parked
        self.fw.tick()
        self.rctx.flush_tick()
        self._degraded_ticks += 1
        if self.tick_admitted:
            REGISTRY.degraded_admissions_total.inc(
                self.host_id, by=float(len(self.tick_admitted)))
            self._degraded_admitted.extend(self.tick_admitted)
        # Degraded verdicts are durable like every other admission:
        # status syncs into the group journals, and the degraded
        # journal stamps the window's trail with its epoch.
        for _store, adapter, _journal in self.groups.values():
            adapter.sync_status()
        self._djournal({
            "event": "tick", "degraded_epoch": self.degraded_epoch,
            "tick": self._degraded_ticks,
            "admitted": [list(p) for p in self.tick_admitted],
            "parked": self.rctx.parked - parked0,
            "host": self.host_id})

    def _degraded_journal_path(self) -> Optional[str]:
        if not self._state_dir:
            return None
        os.makedirs(self._state_dir, exist_ok=True)
        return os.path.join(self._state_dir,
                            f"degraded-{self.host_id}.jsonl")

    def _djournal(self, entry: dict) -> None:
        import json as _json

        path = self._degraded_journal_path()
        if path is None:
            return
        try:
            with open(path, "a", encoding="utf-8") as f:
                f.write(_json.dumps(entry, separators=(",", ":")) + "\n")
        except OSError as exc:
            import sys

            from kueue_tpu.metrics import REGISTRY

            REGISTRY.journal_write_errors_total.inc("degraded-journal")
            print(f"kueue-tpu: degraded journal write failed: {exc}",
                  file=sys.stderr, flush=True)

    def _handle_rejoin(self, epoch: int,
                       caps: Optional[dict] = None) -> None:
        """The coordinator is back: leave safe mode, resolve any
        oversubscription against the merged capacity it shipped
        (revocations counted, newest-first), and answer with the
        degraded window's full evidence."""
        import time as _time

        was = self.degraded
        now = _time.monotonic()
        dur = (now - self._degraded_t0) \
            if (was and self._degraded_t0) else 0.0
        if was:
            self._exit_degraded("rejoin")
        self._last_epoch = int(epoch)
        revoked = self._revoke_oversubscribed(caps) if caps else []
        report = {
            "replica": self.worker_id, "host": self.host_id,
            "was_degraded": bool(was or self._degraded_ticks),
            "degraded_epoch": self.degraded_epoch,
            "windows": self._degraded_windows,
            "ticks": self._degraded_ticks,
            "admitted": [list(p) for p in self._degraded_admitted],
            "parked": self.rctx.parked,
            "revoked": revoked,
            "duration_s": round(dur, 3),
            "usage": {name: {f: dict(r) for f, r in cq.usage.items()}
                      for name, cq in
                      self.fw.cache.cluster_queues.items()
                      if name not in self.ghost_cqs},
        }
        self._djournal({"event": "rejoin", "epoch": int(epoch),
                        "revoked": revoked, "host": self.host_id})
        # The report consumed this window's accumulators.
        self._degraded_admitted = []
        self._degraded_ticks = 0
        self.rctx.parked = 0
        self.chan.send(("degraded_report", report))

    def _revoke_oversubscribed(self, caps: dict) -> List[str]:
        """Replay the degraded window against the merged capacity: for
        every cohort root whose total usage exceeds the CURRENT nominal
        capacity the coordinator shipped, evict this window's newest
        degraded admissions until it fits again. Evictions requeue, so
        a revoked workload re-admits against the new quota the moment
        it fits — a journaled revocation, never a silent loss."""
        roots = caps.get("roots") or {}
        cq_root = caps.get("cq_root") or {}
        cache = self.fw.cache

        def over(root: str) -> bool:
            cap = roots.get(root)
            if cap is None:
                return False  # the coordinator models no cap: trust it
            total: Dict[str, dict] = {}
            for name, cq in cache.cluster_queues.items():
                if name in self.ghost_cqs or cq_root.get(name) != root:
                    continue
                for f, res in cq.usage.items():
                    d = total.setdefault(f, {})
                    for rname, val in res.items():
                        d[rname] = d.get(rname, 0) + val
            for f, res in total.items():
                for rname, val in res.items():
                    if val > cap.get(f, {}).get(rname, 0):
                        return True
            return False

        revoked: List[str] = []
        for key, cq_name in reversed(self._degraded_admitted):
            root = cq_root.get(cq_name)
            if root is None or not over(root):
                continue
            wl = self.fw.workloads.get(key)
            if wl is None or not wl.is_admitted:
                continue
            self.fw.evict_workload(
                wl, reason="DegradedRejoinRevoked",
                message="degraded-window admission revoked by the "
                        "rejoin reconcile (merged capacity shrank)")
            revoked.append(key)
        if revoked:
            self.revoked_total += len(revoked)
            for _store, adapter, _journal in self.groups.values():
                adapter.sync_status()
        return revoked

    # -- message loop --------------------------------------------------------

    def run(self) -> Optional[str]:
        while True:
            try:
                if self.degraded:
                    msg = self.chan.recv(timeout=self._degraded_interval)
                elif self._degraded_after is not None:
                    msg = self.chan.recv(timeout=self._degraded_after)
                else:
                    msg = self.chan.recv()
            except (WorkerDied, WorkerDiedError):
                if self._degraded_after is None \
                        or getattr(self.chan, "_closed", False):
                    raise
                # Coordinator silence past the deadline: probe the
                # election once, then drop to (or continue) journaled
                # shard-local admission. A predispatched tick must be
                # abandoned first — the degraded self-ticks run the
                # framework directly, and its popped heads would
                # otherwise sit in limbo for the whole window.
                if self._predispatched is not None:
                    self.fw.abandon_predispatch(self._predispatched)
                    self._predispatched = None
                    self.predispatch_abandoned += 1
                if self.degraded:
                    self._degraded_tick()
                elif self._coordinator_presumed_dead():
                    self._enter_degraded("recv-timeout")
                continue
            if msg == PEER_RESTART:
                if self._predispatched is not None:
                    # The re-join handshake mutates state outside this
                    # loop (group drops/adoptions); a stale predispatch
                    # must not survive into the new incarnation.
                    self.fw.abandon_predispatch(self._predispatched)
                    self._predispatched = None
                    self.predispatch_abandoned += 1
                # The coordinator came back as a NEW incarnation: the
                # old conversation is void; the join driver
                # (worker_join_main) re-handshakes from scratch.
                return "peer-restart"
            op = msg[0]
            if self._predispatched is not None \
                    and op not in ("tick", "pretick"):
                # Anything but the tick command (or the read-only
                # pre-tick usage exchange) can change this worker's
                # inputs: the predispatched tick is no longer provably
                # what a lazy tick would compute — abandon it (heads
                # restored unchanged; only device work is wasted).
                self.fw.abandon_predispatch(self._predispatched)
                self._predispatched = None
                self.predispatch_abandoned += 1
            if self.degraded:
                if op == "verdicts":
                    continue  # stale reply from the dead incarnation
                # Any other coordinator message means it is back. The
                # rejoin op exits the window itself (it measures it);
                # everything else resumes normal service first.
                if op != "rejoin":
                    self._exit_degraded(f"coordinator message ({op})")
            if op == "objs":
                self._apply_batch(msg[1])
                self._maybe_microtick()
            elif op == "tick":
                if len(msg) > 3:
                    self._last_epoch = int(msg[3])
                self._tick(want_status=len(msg) > 2 and bool(msg[2]))
            elif op == "pretick":
                self.chan.send(("usage", self._cache_split_usage()))
            elif op == "ghost_usage":
                for name, usage in msg[1].items():
                    if name in self.ghost_cqs:
                        self.fw.cache.set_external_usage(name, usage)
            elif op == "ghost_cq":
                self._apply_ghost(msg[1])
            elif op == "split":
                self.rctx.split_roots = frozenset(msg[1])
                self._usage_memo = None
            elif op == "adopt":
                self._adopt(msg[1], msg[2],
                            msg[3] if len(msg) > 3 else None)
            elif op == "release":
                self._release(msg[1],
                              want_entries=bool(msg[2])
                              if len(msg) > 2 else True)
            elif op == "synth":
                self.chan.send(("synth_done", self._synth(msg[1])))
            elif op == "gc":
                # Off-window GC maintenance: the bench calls this at the
                # warmup/measured boundary so warmup survivors (admission
                # conditions, assignments) freeze too and the measured
                # window starts with an empty gen-2 scan set.
                self.chan.send(("gc_done", self._gc_settle()))
            elif op == "finish":
                self._finish(msg[1], msg[2])
            elif op == "finish_many":
                for key in msg[1]:
                    self._finish(key, True)
            elif op == "submit_many":
                self._submit_many(msg[1])
                self._maybe_microtick()
            elif op == "delete_wl":
                self._delete(msg[1])
            elif op == "rejoin":
                self._handle_rejoin(msg[1],
                                    msg[2] if len(msg) > 2 else None)
            elif op == "dump":
                self.chan.send(("dump", self._dump()))
            elif op == "trace":
                from kueue_tpu.tracing import TRACER

                self.chan.send(("trace", os.getpid(),
                                TRACER.export_chrome(
                                    slowest_only=len(msg) > 1
                                    and bool(msg[1])),
                                self.host_id))
            elif op == "stop":
                self._close()
                self.chan.send(("stopped", self.worker_id))
                return

    def _maybe_microtick(self) -> None:
        """Dirty-cohort micro-tick between barriers: arrivals routed to
        this worker admit NOW instead of waiting out a slow sibling's
        barrier stall — flat cohorts are replica-complete by the shard
        hash, so their quota math never needed the coordinator (the same
        soundness argument as degraded-mode admission, without the
        outage). Micro admissions are journaled via the group status
        sync and reported in the next barrier reply."""
        if not self._micro_enabled or self.degraded:
            return
        if not self.fw.queues.has_dirty_cohorts():
            return
        before = len(self.tick_admitted)
        before_p = len(self.tick_preempted)
        n = self.fw.microtick()
        moved = len(self.tick_admitted) > before \
            or len(self.tick_preempted) > before_p
        if moved:
            self.microticks_run += 1
            # Micro admissions AND preemptions report separately from
            # the barrier tick's (they happened BETWEEN ticks, and the
            # tick clears its own accumulators at start).
            self.micro_admitted.extend(self.tick_admitted[before:])
            del self.tick_admitted[before:]
            self.micro_preempted.extend(self.tick_preempted[before_p:])
            del self.tick_preempted[before_p:]
            for _store, adapter, _journal in self.groups.values():
                adapter.sync_status()

    def _tick(self, want_status: bool = False) -> None:
        from kueue_tpu.tracing import TRACER, trace_now

        if self._drill_slow_s:
            import time as _time

            _time.sleep(self._drill_slow_s)  # the seeded laggard drill
        self.tick_admitted.clear()
        self.tick_preempted.clear()
        m = self.fw.scheduler.metrics
        rev0 = m.reconcile_revocations
        t0 = trace_now()
        with TRACER.span("replica.tick") as sp:
            pre = self._predispatched
            self._predispatched = None
            if pre is not None:
                n = self.fw.tick_prepared(pre)
                if getattr(self.fw, "predispatch_consumed", False):
                    # Eager encode paid off: this tick's ingest/encode/
                    # solve already ran during the previous barrier's
                    # idle window.
                    self.predispatch_used += 1
                else:
                    # A backoff expired in between: tick_prepared
                    # abandoned the predispatch and ran the lazy path.
                    self.predispatch_abandoned += 1
            else:
                n = self.fw.tick()
            # Barrier discipline: exactly one round per tick. A tick
            # whose cycle never submitted (no heads, quiescent replay,
            # all-NoFit) submits the empty round here — carrying this
            # replica's split-root usage for the others' gating.
            self.rctx.flush_tick()
            sp.set("replica", self.worker_id)
            sp.set("admitted", n)
        changed: Optional[list] = [] if want_status else None
        for store, adapter, _journal in self.groups.values():
            adapter.sync_status(collect=changed)
        status_docs = None
        if changed:
            # Only a Store-fed deployment (the parent serves GET/watch)
            # asks for these; direct-driven runs (bench, goldens) ship
            # nothing.
            from kueue_tpu.api import serialization

            status_docs = [serialization.encode(KIND_WORKLOAD, wl)
                           for wl in changed]
        self.fw.prewarm_idle()
        solver = getattr(self.fw.scheduler, "batch_solver", None)
        dispatches = None
        if solver is not None:
            total = getattr(solver, "dispatches", 0)
            dispatches = total - self._dispatches_seen
            self._dispatches_seen = total
        micro_pairs, self.micro_admitted = self.micro_admitted, []
        micro_evicted, self.micro_preempted = self.micro_preempted, []
        microticks_delta = self.microticks_run - self._microticks_sent
        self._microticks_sent = self.microticks_run
        pd_delta = [self.predispatch_used - self._predispatch_sent[0],
                    self.predispatch_abandoned - self._predispatch_sent[1]]
        self._predispatch_sent = (self.predispatch_used,
                                  self.predispatch_abandoned)
        self.chan.send(("done", {
            "admitted": list(self.tick_admitted),
            # Between-barrier micro-tick preemptions fold into the
            # tick's eviction evidence (they are real evictions the
            # drivers' bookkeeping must see).
            "preempted": list(self.tick_preempted) + micro_evicted,
            "n": n,
            # Between-barrier micro-tick admissions since the last done
            # (already journaled via the group status sync). Every
            # micro/predispatch counter here is a since-last-done DELTA.
            "micro_admitted": [list(p) for p in micro_pairs],
            "microticks": microticks_delta,
            "predispatch": pd_delta,
            "revocations": m.reconcile_revocations - rev0,
            "rtt": self.rctx.drain_rtt(),
            "rss": _rss_bytes(),
            "tick_s": trace_now() - t0,
            "status_docs": status_docs,
            # The elastic-scaling signal: pending backlog per owned
            # shard group (feeds kueue_replica_backlog_depth).
            "backlog": [[gid, depth] for gid, depth
                        in sorted(self._backlog_by_group().items())],
            # Journal replication segments (per-host mode; empty lists
            # stripped to keep the barrier reply lean).
            "segments": self._drain_segments(),
            "dispatches": dispatches,
            "pid": os.getpid(),
            "host": self.host_id,
        }))
        if self._eager and not self.degraded:
            # Barrier idle window: start the NEXT tick's encode now
            # instead of waiting out a slow sibling — any state-changing
            # message before the next tick command abandons it (the
            # run-loop guard), keeping decisions byte-identical.
            self._predispatched = self.fw.predispatch()

    def _apply_batch(self, entries) -> None:
        from kueue_tpu.controllers.durable import Journal

        for gid, entry in entries:
            group = self.groups.get(gid)
            if group is None:
                continue
            store = group[0]
            if entry["kind"] == KIND_WORKLOAD:
                if entry["type"] == DELETED:
                    self.wl_gid.pop(entry["key"], None)
                else:
                    self.wl_gid[entry["key"]] = gid
            elif entry["kind"] == KIND_CLUSTER_QUEUE:
                if entry["type"] == DELETED:
                    self.cq_gid.pop(entry["key"], None)
                else:
                    self.cq_gid[entry["key"]] = gid
            if entry["type"] == DELETED:
                store.delete(entry["kind"], entry["key"])
            else:
                # The journal replay applier IS the routing applier: the
                # wire format is journal lines, so a routed event and a
                # replayed one rebuild identically.
                Journal._apply(store, entry)

    def _submit_many(self, specs) -> None:
        """Bulk arrivals constructed worker-side (the bench's churn
        path: shipping compact tuples instead of encoded manifests keeps
        the parent out of the per-workload serialization business)."""
        from kueue_tpu.api.types import PodSet, Workload

        wls = [Workload(
            name=s["name"], namespace=s.get("namespace", "default"),
            queue_name=s["queue"], priority=s.get("priority", 0),
            creation_time=s["creation_time"],
            pod_sets=[PodSet.make(
                "ps0", count=s.get("count", 1), cpu=s.get("cpu", 1),
                memory=f"{s.get('memory_gi', 1)}Gi")])
            for s in specs]
        if knobs.flag("KUEUE_TPU_NO_BATCH_INGEST"):
            for wl in wls:  # kill-switch twin of the batch lane
                self.fw.submit(wl)
            return
        # Specs were built from trusted tuples above; validate=False is
        # the bulk-ingest lane submit() itself documents.
        self.fw.submit_batch(wls, validate=False)

    def _finish(self, key: str, delete: bool) -> None:
        wl = self.fw.workloads.get(key)
        if wl is None:
            return
        self.fw.finish(wl)
        if delete:
            self._delete(key)

    def _delete(self, key: str) -> None:
        gid = self.wl_gid.pop(key, None)
        if gid is not None and gid in self.groups:
            self.groups[gid][0].delete(KIND_WORKLOAD, key)
            return
        wl = self.fw.workloads.get(key)
        if wl is not None:
            self.fw.delete_workload(wl)

    def _backlog_by_group(self) -> Dict[int, int]:
        """Pending-workload depth per OWNED shard group — the elastic
        signal. Store-routed ClusterQueues map through cq_gid; direct-
        loaded ones (bench synth) fall back to the cohort hash, which is
        the same function the router uses."""
        out: Dict[int, int] = {}
        n_groups = self.opts.get("n_groups", 1)
        qm = self.fw.queues
        cache_cqs = self.fw.cache.cluster_queues
        for name in qm.cluster_queues:
            if name in self.ghost_cqs:
                continue
            gid = self.cq_gid.get(name)
            if gid is None:
                cq = cache_cqs.get(name)
                cohort = cq.cohort_name if cq is not None else None
                # Memoize: the mapping is static per CQ, and at 10k CQs
                # re-hashing every tick is measurable barrier work.
                gid = self.cq_gid[name] = group_of(
                    group_key(name, cohort), n_groups)
            out[gid] = out.get(gid, 0) + qm.pending(name)
        return out

    def _drain_segments(self) -> list:
        """Ship + clear the journal segment ops buffered since the last
        barrier reply (JSON-safe [[gid, ops], ...])."""
        if not self._seg:
            return []
        out = [[gid, ops] for gid, ops in sorted(self._seg.items()) if ops]
        self._seg = {}
        return out

    def _release(self, gid: int, want_entries: bool = True) -> None:
        """Give up a shard group for migration (parent-requested):
        `_drop_group` does the work; the reply carries the snapshot."""
        self.chan.send(("released", gid,
                        self._drop_group(gid, want_entries)))

    def _drop_group(self, gid: int, want_entries: bool = True) -> dict:
        """Detach a shard group from this worker: journal released (the
        flock clears, recording stops), objects snapshotted (the
        journal-free migration channel — built only when asked;
        journal-backed adoption never reads it), then every
        group-routed object deleted from this framework — the DELETE
        events fan through the adapter, releasing quota and pruning
        queues. Admin kinds stay: they are broadcast to every group and
        shared by the framework. Used by the migration protocol AND by
        a rejoin assignment that took a group away (first-join-wins
        conflict resolution keeps the single-owner invariant)."""
        from kueue_tpu.api import serialization
        from kueue_tpu.controllers.store import _obj_key

        group = self.groups.pop(gid, None)
        if group is None:
            return {"ops": [], "entries": []}
        store, _adapter, journal = group
        ops = self._seg.pop(gid, [])
        if journal is not None:
            journal.detach()
        entries = []
        from kueue_tpu.controllers.durable import KIND_ORDER

        if want_entries:
            for kind in KIND_ORDER:
                for obj in store.list(kind):
                    entries.append({
                        "type": ADDED, "kind": kind,
                        "key": _obj_key(kind, obj),
                        "object": serialization.encode(kind, obj)})
        for kind in (KIND_WORKLOAD, KIND_LOCAL_QUEUE, KIND_CLUSTER_QUEUE):
            for key in [_obj_key(kind, obj) for obj in store.list(kind)]:
                store.delete(kind, key)
        for key in [k for k, g in self.wl_gid.items() if g == gid]:
            del self.wl_gid[key]
        for key in [k for k, g in self.cq_gid.items() if g == gid]:
            del self.cq_gid[key]
        self._usage_memo = None
        return {"ops": ops, "entries": entries}

    def _apply_ghost(self, entry: dict) -> None:
        """Mirror a remote split-tree member into the CACHE only: its
        quota rows join this replica's tree math, its usage arrives via
        the pre-tick exchange, and the queue manager never learns it —
        ghosts are never scheduled here."""
        from kueue_tpu.api import serialization

        cache = self.fw.cache
        if entry["type"] == DELETED:
            if entry["key"] in self.ghost_cqs:
                self.ghost_cqs.discard(entry["key"])
                cache.delete_cluster_queue(entry["key"])
            self._usage_memo = None
            return
        _, spec = serialization.decode(entry["object"])
        if spec.name in cache.cluster_queues:
            if spec.name not in self.ghost_cqs:
                return  # owned locally: the routed store event rules
            cache.update_cluster_queue(spec)
        else:
            cache.add_cluster_queue(spec)
        self.ghost_cqs.add(spec.name)
        self._usage_memo = None

    def _adopt(self, gid: int, journal_path: Optional[str],
               seed: Optional[dict] = None) -> None:
        # A journal may re-create ClusterQueues this replica holds as
        # ghosts: purge every ghost first (the replay re-adds the now-
        # owned ones; the parent re-routes the rest at the next ghost
        # sync) so the adapter's create never collides.
        for name in sorted(self.ghost_cqs):
            self.fw.cache.delete_cluster_queue(name)
        self.ghost_cqs.clear()
        self._usage_memo = None
        if journal_path is None and self._state_dir \
                and seed and seed.get("lines") is not None:
            # Remote adoption: the parent cannot name a path on THIS
            # host's disk — seed the replicated lines into our own
            # state dir instead.
            journal_path = self._local_journal_path(gid)
        if seed and seed.get("lines") is not None and journal_path:
            # Per-host fail-over/migration: seed THIS host's local
            # journal from the coordinator's replicated copy, then
            # attach-replay it like any restart.
            try:
                self._write_seed(journal_path, seed)
            except OSError as exc:
                # A snapshot seed that did not land whole must NOT be
                # attach-replayed — truncation machinery would silently
                # drop live objects. Report; the parent falls back to
                # shipping raw history (lossless).
                self.chan.send(
                    ("adopt_err", gid, f"snapshot-write-torn: {exc}"))
                return
        try:
            restored = self.add_group(gid, journal_path)
        except RuntimeError as exc:
            # The dead owner's flock may outlive it for a moment (or the
            # process is not dead after all): report, parent retries.
            self.chan.send(("adopt_err", gid, str(exc)))
            return
        if seed and seed.get("entries"):
            # Journal-less migration: the releasing owner's snapshot
            # entries rebuild the group through the routing applier.
            self._apply_batch([(gid, e) for e in seed["entries"]])
            restored += len(seed["entries"])
        self.chan.send(("adopted", gid, restored))

    def _write_seed(self, journal_path: str, seed: dict) -> None:
        """Write the shipped seed lines into this host's journal file.

        Snapshot seeds get the extra care raw-history seeds do not need:
        a compacted snapshot has NO redundancy, so a torn or short write
        here silently loses live objects that raw replay would have
        recovered. The write is therefore (a) fault-injectable via
        KUEUE_TPU_SNAPSHOT_BOOT_FAULTS — the lattice's torn-snapshot
        drill arms it — and (b) read back and verified line-for-line
        before attach is allowed to replay it."""
        from kueue_tpu.controllers import diskfaults

        os.makedirs(os.path.dirname(journal_path) or ".", exist_ok=True)
        lines = seed["lines"]
        snapshot = bool((seed.get("bootstrap") or {}).get("snapshot"))
        injector = None
        if snapshot:
            plan = diskfaults.parse_disk_fault_env(
                knobs.raw("KUEUE_TPU_SNAPSHOT_BOOT_FAULTS"))
            if plan is not None:
                injector = plan.injector(journal_path)
        with open(journal_path, "w", encoding="utf-8") as f:
            for line in lines:
                data = line + "\n"
                if injector is not None:
                    action = injector.next_action()
                    if action == diskfaults.ENOSPC:
                        raise injector.enospc_error()
                    if action == diskfaults.TORN:
                        f.write(data[:injector.torn_prefix_len(len(data))])
                        f.flush()
                        raise diskfaults.TornWrite(
                            f"torn snapshot seed write: {journal_path}")
                f.write(data)
            f.flush()
        if snapshot:
            with open(journal_path, "r", encoding="utf-8") as f:
                written = [ln.rstrip("\n") for ln in f if ln.strip()]
            if written != list(lines):
                raise OSError(
                    f"snapshot seed verification failed: wrote "
                    f"{len(lines)} lines, read back {len(written)}")

    def _synth(self, kw: dict) -> dict:
        """Generate this worker's slice of a synthetic cluster LOCALLY
        (deterministic seed, cohort-hash filter) — the 1M-backlog bench
        loads without piping a million encoded workloads through the
        parent. Store-less (bench mode): objects go straight into the
        framework, exactly `synthetic_framework`'s semantics."""
        from kueue_tpu.utils.synthetic import synthetic_objects

        n_groups = self.opts.get("n_groups", 1)
        mine = set(self.groups)
        num_cohorts = kw.get("num_cohorts", 100)

        def cq_filter(c: int) -> bool:
            cohort = f"cohort-{c % num_cohorts}" if num_cohorts > 0 else None
            return group_of(group_key(f"cq-{c}", cohort), n_groups) in mine

        flavors, cqs, lqs, admitted, pending, cohort_specs = \
            synthetic_objects(cq_filter=cq_filter, **kw)
        for rf in flavors:
            self.fw.create_resource_flavor(rf)
        for spec in cohort_specs:
            self.fw.create_cohort(spec)
        for cq in cqs:
            self.fw.create_cluster_queue(cq)
        for lq in lqs:
            self.fw.create_local_queue(lq)
        for wl in admitted:
            self.fw.workloads[wl.key] = wl
            self.fw.cache.add_or_update_workload(wl)
        for wl in pending:
            self.fw.submit(wl)
        self._gc_settle()
        return {"cqs": len(cqs), "pending": len(pending),
                "admitted": len(admitted)}

    @staticmethod
    def _gc_settle() -> int:
        """Collect, then FREEZE the survivors out of the cyclic GC's
        scan set. A 250k-workload slice is ~2.7M long-lived objects; a
        gen-2 pass over them is a multi-second stop anywhere in the
        window, and at a barrier ANY worker's pause stalls the whole
        tick — N workers multiply the odds a given tick eats one.
        Frozen objects still free by refcount when workloads churn out;
        only cycle garbage among them would persist, and the bulk-load
        objects are acyclic API dataclasses."""
        import gc

        gc.collect()
        gc.freeze()
        return gc.get_freeze_count()

    def _dump(self) -> dict:
        # Ghosts are other replicas' state: reporting them would let an
        # empty mirror shadow the owner's real view in the merged dump.
        ghosts = self.ghost_cqs
        admitted = {name: sorted(cq.workloads)
                    for name, cq in self.fw.cache.cluster_queues.items()
                    if name not in ghosts}
        pending = {name: self.fw.queues.pending(name)
                   for name in self.fw.queues.cluster_queues}
        usage = {name: {f: dict(r) for f, r in cq.usage.items()}
                 for name, cq in self.fw.cache.cluster_queues.items()
                 if name not in ghosts}
        return {"admitted": admitted, "pending": pending, "usage": usage,
                "workloads": len(self.fw.workloads)}

    def _close(self) -> None:
        for _store, _adapter, journal in self.groups.values():
            if journal is not None:
                journal.close()
        self.fw.scheduler.close()


def _worker_main(conn, worker_id: int, opts: dict) -> None:
    """Spawn-mode entry point (module top level: picklable under the
    spawn start method). Rebuilds the feature-gate state the parent
    shipped, then runs the worker loop until stop/EOF. `conn` is the
    multiprocessing pipe end (pipe transport) or None (socket
    transport — the worker dials opts["connect"] and identifies itself
    with its worker id)."""
    from kueue_tpu import features

    try:
        for gate, val in (opts.get("gates") or {}).items():
            try:
                features.set_enabled(gate, val)
            except KeyError:
                pass
        if opts.get("trace"):
            from kueue_tpu.tracing import TRACER

            TRACER.configure(enabled=True)
        if conn is None:
            chan: ReplicaChannel = SocketChannel.connect(
                tuple(opts["connect"]), cid=worker_id,
                plan=FaultPlan.from_dict(opts.get("faults")),
                name=f"worker-{worker_id}")
        else:
            chan = _PipeChan(conn)
        worker = ReplicaWorker(worker_id, opts, chan)
        for gid, journal_path in opts.get("groups", ()):
            worker.add_group(gid, journal_path)
        worker.run()
    except (EOFError, OSError, KeyboardInterrupt,
            WorkerDied, WorkerDiedError):
        pass


def worker_join_main(addr, state_dir: Optional[str] = None,
                     tls_cafile: Optional[str] = None,
                     auth_token: Optional[str] = None,
                     node: Optional[str] = None,
                     join_timeout: float = 60.0,
                     degraded_after: Optional[float] = 5.0) -> int:
    """`python -m kueue_tpu --join HOST:PORT`: the worker-only fleet
    entry point. Dials the REMOTE coordinator (TLS + auth token when
    configured), identifies via a join hello, receives its shard-group
    assignment + admin-object seed over the channel, and runs the
    worker loop. Survives coordinator restarts: the channel's session
    ids surface the new incarnation, the worker re-joins carrying the
    shard groups it already owns, and the degraded window it served in
    between is reported to the rejoin reconcile. Returns only on stop
    (0) or an unrecoverable join failure (1)."""
    import socket as socket_mod
    import sys

    from kueue_tpu import features
    from kueue_tpu.config import LeaderElectionConfig
    from kueue_tpu.transport.lease_channel import ChannelLeaseStore

    node = node or f"{socket_mod.gethostname()}-{os.getpid()}"
    tls_ctx = None
    if tls_cafile:
        from kueue_tpu.transport.security import client_tls_context

        tls_ctx = client_tls_context(tls_cafile)
    addr = (addr[0], int(addr[1]))
    chan = SocketChannel.connect(
        addr, cid=f"join/{node}", name=f"join-{node}",
        auth_token=auth_token, tls_context=tls_ctx,
        restart_markers=True)
    lease_name = LeaderElectionConfig().resource_name
    lease_store: List[Optional[ChannelLeaseStore]] = [None]

    def lease_probe() -> bool:
        """True iff a live coordinator holds the lease: reachable
        lease service + non-empty holder. The service rides the
        coordinator's own listener, so 'unreachable' and 'dead
        coordinator' coincide — which is the point."""
        if lease_store[0] is None:
            lease_store[0] = ChannelLeaseStore(
                addr, identity=f"probe-{node}", tls_context=tls_ctx,
                auth_token=auth_token,
                timeout=min(2.0, degraded_after or 2.0))
        store = lease_store[0]
        holder = store.holder(lease_name)
        return bool(holder) and store.available

    worker: Optional[ReplicaWorker] = None
    try:
        while True:
            chan.send(("join", {
                "node": node, "pid": os.getpid(),
                "groups": sorted(worker.groups)
                if worker is not None else []}))
            msg = None
            while True:
                try:
                    msg = chan.recv(timeout=join_timeout)
                except (WorkerDied, WorkerDiedError):
                    print(f"kueue-tpu: --join: no assignment from "
                          f"{addr[0]}:{addr[1]} within {join_timeout:g}s",
                          file=sys.stderr, flush=True)
                    return 1
                if msg == PEER_RESTART:
                    break  # raced a coordinator restart: re-greet
                if isinstance(msg, (tuple, list)) and msg \
                        and msg[0] == "assign":
                    break
            if msg == PEER_RESTART:
                continue
            _, wid, opts, gids = msg
            for gate, val in (opts.get("gates") or {}).items():
                try:
                    features.set_enabled(gate, val)
                except KeyError:
                    pass
            opts = {**opts, "state_dir": state_dir}
            if degraded_after is not None:
                opts["degraded_after"] = degraded_after
            if worker is None:
                worker = ReplicaWorker(wid, opts, chan)
                worker._lease_probe = lease_probe
            else:
                # Re-assigned by a new coordinator incarnation: adopt
                # the (possibly new) id and epoch; the framework state
                # and owned groups are live and stay.
                worker.worker_id = wid
                worker._last_epoch = int(opts.get("epoch", 0) or 0)
            restored = 0
            # A rejoin assignment is AUTHORITATIVE both ways: groups
            # the new coordinator gave to another claimant (it failed
            # over before the restart; first-join-wins resolved against
            # us) must be dropped here, or the same group would live on
            # two workers and double-count usage.
            for gid in [g for g in sorted(worker.groups)
                        if g not in gids]:
                worker._drop_group(gid, want_entries=False)
                print(f"kueue-tpu: --join: dropped shard group {gid} "
                      "(reassigned elsewhere)", file=sys.stderr,
                      flush=True)
            for gid in gids:
                if gid not in worker.groups:
                    restored += worker.add_group(
                        gid, worker._local_journal_path(gid))
            chan.send(("joined", wid, restored))
            print(f"kueue-tpu: joined coordinator at "
                  f"{addr[0]}:{addr[1]} as worker {wid} "
                  f"(groups {sorted(worker.groups)})",
                  file=sys.stderr, flush=True)
            if worker.run() != "peer-restart":
                return 0
            print("kueue-tpu: --join: coordinator restarted; "
                  "re-joining", file=sys.stderr, flush=True)
    except (EOFError, OSError, KeyboardInterrupt,
            WorkerDied, WorkerDiedError):
        return 0
    finally:
        if lease_store[0] is not None:
            lease_store[0].close()


# ---------------------------------------------------------------------------
# Parent runtime
# ---------------------------------------------------------------------------


class _WorkerHandle:
    """Parent-side handle: the channel plus liveness/kill control.

    Transport matrix: spawn x {pipe, socket} and loopback x {queue,
    socket}. The socket variants exercise the full framed reliable
    channel (the loopback-socket pair is the "two emulated hosts on one
    machine" harness: real TCP framing, reconnects and faults, no
    process overhead)."""

    chan: ReplicaChannel

    def __init__(self, wid: int, spawn: bool, opts: dict,
                 groups: List[tuple],
                 listener: Optional[ChannelListener] = None):
        self.wid = wid
        self.alive = True
        self.spawn = spawn
        self.remote = False
        self.host_id = opts.get("host_id") or f"host-{wid}"
        self.pid: Optional[int] = None
        # Parent-side sends come from the runtime lock AND the watch
        # fan-out writer threads; a mp.Pipe connection is not safe for
        # concurrent writers, so every send serializes here (queue and
        # socket transports lock internally — this is belt-and-braces
        # for them, load-bearing for pipes).
        self._send_lock = threading.Lock()
        # True once a worker_error message arrived: the worker CRASHED
        # with a real exception — the watchdog must report that, not a
        # "stall" (the loopback thread may still be microseconds from
        # exiting when the parent reads the error).
        self.crashed = False
        if listener is not None:
            self.chan = listener.endpoint(wid, name=f"replica-{wid}")
        if spawn:
            import multiprocessing

            ctx = multiprocessing.get_context("spawn")
            if listener is not None:
                self.proc = ctx.Process(
                    target=_worker_main,
                    args=(None, wid, {**opts, "groups": groups}),
                    daemon=True)
                self.proc.start()
            else:
                parent_conn, child_conn = ctx.Pipe()
                self.proc = ctx.Process(
                    target=_worker_main,
                    args=(child_conn, wid, {**opts, "groups": groups}),
                    daemon=True)
                self.proc.start()
                child_conn.close()
                self.chan = _PipeChan(parent_conn)
            self.pid = self.proc.pid
            self.thread = None
        else:
            if listener is not None:
                addr = listener.address
                worker_chan = None  # dialed inside the thread
            else:
                to_worker: "queue.Queue" = queue.Queue()
                to_parent: "queue.Queue" = queue.Queue()
                self.chan = _QueueChan(to_worker, to_parent)
                worker_chan = _QueueChan(to_parent, to_worker)
            self.proc = None
            self.pid = os.getpid()

            def run():
                chan = worker_chan
                try:
                    if chan is None:
                        chan = SocketChannel.connect(
                            addr, cid=wid,
                            plan=FaultPlan.from_dict(opts.get("faults")),
                            name=f"worker-{wid}")
                    worker = ReplicaWorker(wid, opts, chan)
                    for gid, journal_path in groups:
                        worker.add_group(gid, journal_path)
                    worker.run()
                except (WorkerDied, WorkerDiedError):
                    pass
                except Exception as exc:  # surface, never hang the barrier
                    if chan is not None:
                        chan.send(("worker_error", wid, repr(exc)))

            self.thread = threading.Thread(
                target=run, name=f"replica-{wid}", daemon=True)
            self.thread.start()

    @classmethod
    def remote(cls, wid: int, chan, host_id: str,
               pid: Optional[int] = None) -> "_WorkerHandle":
        """A worker that JOINED over the wire (`--join`): the handle is
        just its listener endpoint — no process or thread to supervise.
        Liveness is protocol liveness: a remote worker that misses a
        barrier deadline is declared dead by the watchdog exactly as a
        stalled local process is (its shard groups then fail over via
        the replicated journals)."""
        self = cls.__new__(cls)
        self.wid = wid
        self.alive = True
        self.spawn = False
        self.remote = True
        self.host_id = host_id
        self.pid = pid
        self.crashed = False
        self.chan = chan
        self.proc = None
        self.thread = None
        self._send_lock = threading.Lock()
        return self

    def send(self, msg) -> None:
        with self._send_lock:
            self.chan.send(msg)

    def recv(self, timeout: Optional[float] = None):
        try:
            msg = self.chan.recv(timeout=timeout)
        except WorkerDiedError as exc:
            # Transport-level timeout/close -> the runtime's own type.
            raise WorkerDied(str(exc))
        if msg and msg[0] == "worker_error":
            self.alive = False
            self.crashed = True
            raise WorkerDied(f"replica {msg[1]} crashed: {msg[2]}")
        return msg

    def is_alive(self) -> bool:
        if not self.alive:
            return False
        if self.remote:
            return True  # liveness is decided at the barrier
        if self.proc is not None:
            return self.proc.is_alive()
        return self.thread.is_alive()

    def os_alive(self) -> bool:
        """Is the underlying process/thread still RUNNING (stalled
        counts as alive — the watchdog's stall-vs-crash distinction)?"""
        if self.remote:
            return self.chan.connected if hasattr(
                self.chan, "connected") else False
        if self.proc is not None:
            return self.proc.is_alive()
        return self.thread is not None and self.thread.is_alive()

    def kill(self) -> None:
        self.alive = False
        if self.remote:
            try:
                self.chan.send(("stop",))
            except Exception:
                pass
            return
        if self.proc is not None:
            self.proc.kill()
            self.proc.join(timeout=10)
        else:
            # Loopback threads die cooperatively: stop closes the
            # journals (releasing the flocks exactly like process death).
            self.chan.send(("stop",))
            deadline_chan = self.chan
            try:
                while True:
                    msg = deadline_chan.recv(timeout=10)
                    if msg[0] == "stopped":
                        break
            except (WorkerDied, WorkerDiedError):
                pass


class ReplicaRuntime:
    """N shard-group replicas + the lease-holding coordinator barrier.

    The parent routes API objects by the cohort hash (`GroupMap`),
    drives the tick barrier, arbitrates split-root candidates through
    the `Coordinator`, reassigns a dead replica's shard groups (journal
    replay on the adopter), and merges per-process trace rings into one
    Chrome trace."""

    def __init__(self, replicas: int, spawn: bool = False,
                 state_dir: Optional[str] = None,
                 engine: Optional[str] = None, solver: bool = True,
                 lease_store=None, identity: Optional[str] = None,
                 trace: bool = False, transport: Optional[str] = None,
                 listen: Optional[tuple] = None,
                 per_host: Optional[bool] = None,
                 faults: Optional[FaultPlan] = None,
                 n_groups: Optional[int] = None,
                 remote: bool = False, join_timeout: float = 60.0,
                 degraded_after: Optional[float] = None,
                 tls_cert: Optional[str] = None,
                 tls_key: Optional[str] = None,
                 auth_token: Optional[str] = None,
                 microtick: bool = False,
                 eager_encode: Optional[bool] = None,
                 drill_slow: Optional[Dict[int, float]] = None):
        from kueue_tpu import features
        from kueue_tpu.config import LeaderElectionConfig
        from kueue_tpu.controllers.leaderelection import (
            FileLeaseStore, LeaderElector, LeaseStore)

        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        self.n = replicas
        self.spawn = spawn
        self.remote = remote
        if remote and transport != "socket":
            transport = "socket"  # remote workers only exist on the wire
        self.degraded_after = degraded_after
        self.state_dir = state_dir
        # An EXPLICIT transport argument wins over the generic
        # KUEUE_TPU_TRANSPORT default; only the documented kill switch
        # (KUEUE_TPU_NO_SOCKET=1) overrides it.
        if transport is None:
            self.transport = transport_from_env("pipe")
        elif knobs.flag("KUEUE_TPU_NO_SOCKET"):
            self.transport = "pipe"
        else:
            self.transport = transport if transport in ("pipe", "socket") \
                else "pipe"
        if remote and self.transport != "socket":
            # The KUEUE_TPU_NO_SOCKET=1 kill switch forced pipes, but
            # remote workers only exist on the wire: fail loudly
            # instead of crashing later on a listener that was never
            # created.
            raise RuntimeError(
                "remote worker join requires the socket transport; "
                "unset KUEUE_TPU_NO_SOCKET or drop --remote-workers")
        # Per-host state: each replica journals in its OWN directory
        # (the socket transport's default — real hosts share nothing)
        # with coordinator-owned replication; pipe mode keeps PR 9's
        # shared-directory layout unless opted in.
        self.per_host = (self.transport == "socket") \
            if per_host is None else per_host
        if faults is None and self.transport == "socket":
            faults = parse_fault_env(knobs.raw("KUEUE_TPU_FAULTS"))
        self.faults = faults
        self.listener: Optional[ChannelListener] = None
        self._join_q: "queue.Queue" = queue.Queue()
        self.tls_cert = tls_cert
        self.auth_token = auth_token
        server_tls = None
        if tls_cert and tls_key:
            from kueue_tpu.transport.security import server_tls_context

            server_tls = server_tls_context(tls_cert, tls_key)
        if self.transport == "socket":
            host, port = listen or ("127.0.0.1", 0)
            self.listener = ChannelListener(
                host, port, plan=faults, tls_context=server_tls,
                auth_token=auth_token,
                on_hello=self._on_join_hello if remote else None)
        self.replicator: Optional[JournalReplicator] = None
        if self.per_host and state_dir:
            self.replicator = JournalReplicator(
                os.path.join(state_dir, "coordinator-replica"))
        n_groups = replicas if not n_groups or n_groups < replicas \
            else n_groups
        self.n_groups = n_groups
        self.gmap = GroupMap(n_groups)
        if lease_store is None:
            lease_store = FileLeaseStore(
                os.path.join(state_dir, "leases.json")) \
                if state_dir else LeaseStore()
        self.elector = LeaderElector(
            lease_store, identity=identity or f"coordinator-{os.getpid()}",
            config=LeaderElectionConfig(enable=True))
        self.elector.step()
        # Lease arbitration rides the control-plane port: any channel
        # whose cid starts with "lease/" gets the CAS — the workers'
        # re-election probe, a standby coordinator's ChannelLeaseStore,
        # and the no-shared-fs equivalence suite all dial this.
        self.lease_service = None
        if self.listener is not None:
            from kueue_tpu.transport.lease_channel import LeaseService

            self.lease_service = LeaseService(lease_store).attach(
                self.listener)
        self.coordinator = Coordinator(
            journal_path=os.path.join(state_dir, "coordinator.jsonl")
            if state_dir else None,
            epoch=self._lease_transitions())
        # Worker-side dirty-cohort micro-ticks between barriers: OFF by
        # default — every decision-identity golden compares against the
        # barrier-paced trail, and micro-ticks intentionally reorder.
        # The serve CLI opts in; the invariant oracles (quota high-water,
        # journal replay) cover the reordered mode in the fuzz lattice.
        self.microtick = microtick
        # Eager encode at the barrier (the PR 9 slow-worker-stall fix):
        # a replica that finishes its tick early predispatches its NEXT
        # tick's ingest+encode+solve instead of idling — abandoned (and
        # therefore decision-identical) whenever any state-changing
        # message lands first. KUEUE_TPU_NO_EAGER_ENCODE=1 kills it.
        if eager_encode is None:
            eager_encode = not knobs.flag("KUEUE_TPU_NO_EAGER_ENCODE")
        self.eager_encode = eager_encode
        opts = {
            "engine": engine,
            "solver": solver,
            "n_groups": n_groups,
            "microtick": microtick,
            "eager_encode": eager_encode,
            "barrier_deadline": barrier_deadline(_ROUND_TIMEOUT),
            "replicate": self.replicator is not None,
            "connect": list(self.listener.address)
            if self.listener is not None else None,
            "faults": faults.to_dict() if faults is not None else None,
            "degraded_after": degraded_after,
            "epoch": self.coordinator.epoch,
            "auth_token": auth_token,
            # Spawned workers run their own TRACER; loopback threads
            # share this process's (already configured by the caller).
            "trace": trace and spawn,
            "gates": {g: features.enabled(g) for g in features.all_gates()}
            if (spawn or remote) else None,
        }
        self._opts = opts
        if remote:
            # Fleet mode: the replicas are REMOTE processes that dial
            # in (`python -m kueue_tpu --join HOST:PORT`); the join
            # wait runs at the END of construction (it needs the admin
            # spec retention below for rejoin seeding).
            self.group_owner: Dict[int, int] = {}
            self.workers: List[_WorkerHandle] = []
        else:
            self.group_owner = {
                g: g % replicas for g in range(n_groups)}
            self.workers = [
                _WorkerHandle(w, spawn,
                              {**opts, "host_id": f"host-{w}",
                               "drill_slow_s": (drill_slow or {}).get(w),
                               "state_dir": self._worker_state_dir(
                                   f"host-{w}")},
                              groups=[(g, self._journal_path(g, wid=w))
                                      for g in range(n_groups)
                                      if g % replicas == w],
                              listener=self.listener)
                for w in range(replicas)
            ]
        self.pen: Dict[str, List[tuple]] = {}   # "ns/lq" -> queued entries
        self.wl_group: Dict[str, int] = {}
        self._cq_specs: Dict[str, object] = {}
        # Admin specs retained for coordinator REBUILD at fail-over (a
        # new incarnation cannot read the dead one's memory).
        self._flavor_specs: Dict[str, object] = {}
        self._cohort_spec_objs: Dict[str, object] = {}
        self._ghost_sent: set = set()            # (wid, cq name)
        self.tick_no = 0
        self._last_split = frozenset()
        self._lock = threading.RLock()
        self.round_timeout = barrier_deadline(_ROUND_TIMEOUT)
        self.stats_last: dict = {}
        self.backlog_last: Dict[int, int] = {}
        self.stall_count = 0
        # Surfaced-error hook for barrier stalls (stderr by default; a
        # deployment can swap in structured logging).
        self.on_stall = lambda err: print(
            f"kueue-tpu: {err}", file=__import__("sys").stderr, flush=True)
        self._coord_kill_pending = False
        self.failover_evidence: Optional[dict] = None
        self.degraded_evidence: Optional[dict] = None
        # Rejoin-cost evidence from the last snapshot-shipped adoption
        # (history_lines vs shipped lines; reconcile_info surfaces it).
        self.bootstrap_evidence: Optional[dict] = None
        # Sharded watch fan-out (submit_fanout): per-worker writer
        # queues + threads, created lazily per wid. Encode+send of a
        # submission burst leave the caller's lock; flush_fanout() is
        # the ordering barrier before any synchronous send.
        self._fanout_queues: Dict[int, "queue.Queue"] = {}
        self._fanout_threads: Dict[int, threading.Thread] = {}
        if remote:
            self._await_joins(replicas, join_timeout)
        # Set by ReplicaStoreBridge: the parent deployment's read-surface
        # Store. When present, each tick asks workers for the statuses
        # they published this round and mirrors them here so GET/watch
        # clients see admission state (None = direct-driven, zero cost).
        # The echo guard holds the MIRRORING thread's ident — a global
        # boolean would also swallow a concurrent HTTP thread's create
        # landing between two update_status calls.
        self.status_store = None
        self._applying_status: Optional[int] = None

    def _lease_transitions(self) -> int:
        """The coordinator epoch source: how many times the lease has
        changed hands."""
        try:
            return self.elector.store.transitions(
                self.elector.config.resource_name)
        except AttributeError:
            return 0

    def _journal_path(self, gid: int,
                      wid: Optional[int] = None) -> Optional[str]:
        """Where shard group `gid`'s journal lives. Per-host mode keys
        by the OWNING worker's private host directory (pass `wid` when
        ownership is mid-change); shared mode keeps one flat dir."""
        if not self.state_dir:
            return None
        if self.per_host:
            if wid is None:
                wid = self.group_owner.get(gid, gid % self.n)
            d = host_state_dir(self.state_dir, f"host-{wid}")
            return os.path.join(d, f"journal-g{gid}.jsonl")
        os.makedirs(self.state_dir, exist_ok=True)
        return os.path.join(self.state_dir, f"journal-g{gid}.jsonl")

    def _worker_state_dir(self, host_id: str) -> Optional[str]:
        """Where one worker keeps its own non-group durable state (the
        degraded journal): its host dir in per-host mode, the shared
        dir otherwise, None without a state dir."""
        if not self.state_dir:
            return None
        if self.per_host:
            return host_state_dir(self.state_dir, host_id)
        os.makedirs(self.state_dir, exist_ok=True)
        return self.state_dir

    # -- remote worker join (the --join fleet path) ---------------------------

    def _on_join_hello(self, cid, chan) -> None:
        if isinstance(cid, str) and cid.startswith("join/"):
            self._join_q.put((cid, chan))

    def _await_joins(self, n: int, timeout: float) -> None:
        """Collect N remote workers: each dials the listener, greets
        with ("join", {node, pid, groups}) and receives ("assign", wid,
        opts, gids) + the admin-object seed back. A REJOINING worker
        (the coordinator restarted, not the worker) reports the shard
        groups it already owns and keeps them — its framework state is
        live and its journals are local; reassigning would orphan
        both."""
        import sys
        import time as _time

        addr = self.listener.address
        print(f"kueue-tpu: coordinator listening on "
              f"{addr[0]}:{addr[1]}; waiting for {n} workers to --join",
              file=sys.stderr, flush=True)
        # Join-wait deadline arithmetic, not tick-phase timing.
        deadline = _time.monotonic() + timeout
        joined: List[tuple] = []  # (cid, chan, info)
        while len(joined) < n:
            remaining = deadline \
                - _time.monotonic()
            if remaining <= 0:
                raise RuntimeError(
                    f"fleet join timed out: {len(joined)}/{n} workers "
                    f"joined within {timeout:g}s")
            try:
                cid, chan = self._join_q.get(timeout=remaining)
            except queue.Empty:
                continue
            try:
                msg = chan.recv(timeout=min(10.0, max(remaining, 0.1)))
            except WorkerDiedError:
                continue
            if not isinstance(msg, (tuple, list)) or not msg \
                    or msg[0] != "join":
                continue
            joined.append((cid, chan, msg[1] or {}))
        # Group assignment: rejoiners keep their reported groups; the
        # rest round-robin over the remaining workers. CONFLICTING
        # claims (a group failed over to worker B before the restart,
        # then both A and B rejoin reporting it) resolve first-join-
        # wins deterministically — and the loser DROPS the group when
        # its assignment comes back without it (worker_join_main),
        # preserving the single-owner invariant.
        taken: Dict[int, int] = {}
        for idx, (_cid, _chan, info) in enumerate(joined):
            for g in info.get("groups") or ():
                taken.setdefault(int(g), idx)
        assigns: Dict[int, List[int]] = {i: [] for i in range(n)}
        for g, idx in taken.items():
            assigns[idx].append(g)
        free = [g for g in range(self.n_groups) if g not in taken]
        for g in free:
            idx = min(assigns, key=lambda i: (len(assigns[i]), i))
            assigns[idx].append(g)
        for wid, (cid, chan, info) in enumerate(joined):
            host = info.get("node") or str(cid)[len("join/"):]
            handle = _WorkerHandle.remote(wid, chan, host_id=host,
                                          pid=info.get("pid"))
            gids = sorted(assigns[wid])
            handle.send(("assign", wid,
                         {**self._opts, "host_id": host}, gids))
            reply = handle.recv(timeout=self.round_timeout
                                if hasattr(self, "round_timeout")
                                else 60.0)
            if reply[0] != "joined":
                raise RuntimeError(
                    f"fleet join protocol violation from {host}: "
                    f"{reply[0]!r}")
            self.workers.append(handle)
            for g in gids:
                self.group_owner[g] = wid
            self._seed_admin(handle, gids)
            print(f"kueue-tpu: worker {wid} joined from {host} "
                  f"(pid {info.get('pid')}, groups {gids}, "
                  f"restored {reply[2] if len(reply) > 2 else 0})",
                  file=__import__("sys").stderr, flush=True)

    def _seed_admin(self, handle: "_WorkerHandle",
                    gids: List[int]) -> None:
        """Ship the retained admin specs to a late joiner: flavors and
        cohorts to every owned group (each group journal must stay
        self-contained), ClusterQueues to the group they hash to.
        Workload/LocalQueue state rides the group journals (local
        replay or the coordinator's replicated copy) — never this
        seed."""
        if not gids:
            return
        batch: List[tuple] = []
        for rf in self._flavor_specs.values():
            entry = self._entry(KIND_RESOURCE_FLAVOR, rf)
            batch.extend((g, entry) for g in gids)
        for spec in self._cohort_spec_objs.values():
            entry = self._entry(KIND_COHORT, spec)
            batch.extend((g, entry) for g in gids)
        for name, spec in self._cq_specs.items():
            gid = self.gmap.cq_group.get(name)
            if gid in gids:
                batch.append((gid, self._entry(KIND_CLUSTER_QUEUE,
                                               spec)))
        if batch:
            handle.send(("objs", batch))

    # -- degraded window: rejoin + catch-up reconcile -------------------------

    def _root_caps(self) -> dict:
        """The merged capacity view for the rejoin reconcile: nominal
        quota per cohort root (milli-unit resolution, straight off the
        retained CURRENT specs) + each ClusterQueue's root. Degraded
        windows admit against possibly-stale local specs; replaying
        their verdicts against THIS map is what makes
        quota-never-oversubscribed an invariant rather than a hope."""
        cq_root: Dict[str, str] = {}
        roots: Dict[str, dict] = {}
        for name, spec in self._cq_specs.items():
            cohort = self.gmap.cq_cohort.get(name) or spec.cohort
            root = (self.gmap.root_of(cohort) if cohort
                    else f"{SOLO_PREFIX}{name}")
            cq_root[name] = root
            dst = roots.setdefault(root, {})
            for rg_ in spec.resource_groups:
                for fq in rg_.flavors:
                    d = dst.setdefault(fq.name, {})
                    for rname, quota in fq.resources:
                        d[rname] = d.get(rname, 0) + quota.nominal
        return {"roots": roots, "cq_root": cq_root}

    def rejoin(self) -> dict:
        """Catch-up reconcile after a degraded window (or a coordinator
        restart): every live worker leaves safe mode, replays its
        degraded admissions against the merged capacity map (revoking
        newest-first where the window oversubscribed — counted, never
        silent), and reports the window's evidence. Returns the
        aggregated evidence block."""
        caps = self._root_caps()
        with self._lock:
            live = [w for w in self.workers if w.alive]
            for w in live:
                w.send(("rejoin", self.coordinator.epoch, caps))
            reports = []
            for w in live:
                deadline_misses = 0
                while True:
                    try:
                        msg = w.recv(timeout=self.round_timeout)
                    except WorkerDied:
                        w.alive = False
                        break
                    if msg[0] == "degraded_report":
                        reports.append(msg[1])
                        break
                    # Stale barrier traffic from the degraded window
                    # (an unanswered round, a late done): drain it.
                    deadline_misses += 1
                    if deadline_misses > 64:
                        w.alive = False
                        break
            evidence = self._fold_degraded_reports(reports)
            self.degraded_evidence = evidence
            return evidence

    def _fold_degraded_reports(self, reports: List[dict]) -> dict:
        return {
            "workers": len(reports),
            "degraded_workers": sum(
                1 for r in reports if r.get("was_degraded")),
            "degraded_window_ticks": max(
                (r.get("ticks", 0) for r in reports), default=0),
            "degraded_admissions": sum(
                len(r.get("admitted") or ()) for r in reports),
            "parked": sum(r.get("parked", 0) for r in reports),
            "rejoin_revocations": sum(
                len(r.get("revoked") or ()) for r in reports),
            "revoked_keys": sorted(
                k for r in reports for k in (r.get("revoked") or ())),
            "window_s": max(
                (r.get("duration_s", 0.0) for r in reports),
                default=0.0),
            "epoch": self.coordinator.epoch,
            "reports": reports,
        }

    def degraded_window(self, seconds: float) -> None:
        """Drill hook: the coordinator goes silent for `seconds` while
        the workers' own deadlines fire and they self-tick in safe mode
        (requires the runtime to have been built with
        `degraded_after`). Call `rejoin()` afterwards to run the
        catch-up reconcile."""
        import time as _time

        if self.degraded_after is None:
            raise RuntimeError(
                "degraded_window needs ReplicaRuntime(degraded_after=…)")
        _time.sleep(seconds)

    # -- routing -------------------------------------------------------------

    def _owner(self, gid: int) -> Optional[_WorkerHandle]:
        wid = self.group_owner.get(gid)
        if wid is None:
            return None
        w = self.workers[wid]
        return w if w.alive else None

    def _entry(self, kind: str, obj, ev_type: str = ADDED,
               key: Optional[str] = None) -> dict:
        from kueue_tpu.api import serialization
        from kueue_tpu.controllers.store import _obj_key

        entry = {"type": ev_type, "kind": kind,
                 "key": key if key is not None else _obj_key(kind, obj)}
        if ev_type != DELETED:
            entry["object"] = serialization.encode(kind, obj)
        return entry

    def _broadcast(self, kind: str, obj, ev_type: str = ADDED,
                   key: Optional[str] = None) -> None:
        """Admin kinds go to EVERY shard group (each group journal is
        self-contained: a takeover replay needs the flavors/cohorts its
        workloads reference)."""
        entry = self._entry(kind, obj, ev_type, key=key)
        with self._lock:
            by_worker: Dict[int, list] = {}
            for gid, wid in self.group_owner.items():
                by_worker.setdefault(wid, []).append((gid, entry))
            for wid, batch in by_worker.items():
                if self.workers[wid].alive:
                    self.workers[wid].send(("objs", batch))

    def _send_group(self, gid: int, kind: str, obj,
                    ev_type: str = ADDED,
                    key: Optional[str] = None) -> None:
        with self._lock:
            w = self._owner(gid)
            if w is not None:
                w.send(("objs",
                        [(gid, self._entry(kind, obj, ev_type, key=key))]))

    def _resplit(self) -> None:
        split = self.gmap.recompute_split()
        if split != self._last_split:
            self._last_split = split
            self.coordinator.set_split(split)
            with self._lock:
                for w in self.workers:
                    if w.alive:
                        w.send(("split", sorted(split)))
        self._sync_ghosts()

    def _sync_ghosts(self) -> None:
        """Route every split-root member's SPEC to each replica that
        owns a sibling subtree (cache-only ghost): quota rows complete
        the remote tree math, usage follows via the pre-tick exchange.
        Idempotent — only not-yet-sent (worker, cq) pairs ship."""
        if not self._last_split:
            return
        with self._lock:
            by_root: Dict[str, list] = {}
            for name, spec in self._cq_specs.items():
                cohort = self.gmap.cq_cohort.get(name)
                if not cohort:
                    continue
                root = self.gmap.root_of(cohort)
                if root in self._last_split:
                    by_root.setdefault(root, []).append(name)
            for root, members in by_root.items():
                wids = set()
                for name in members:
                    gid = self.gmap.cq_group.get(name)
                    wid = self.group_owner.get(gid)
                    if wid is not None and self.workers[wid].alive:
                        wids.add(wid)
                for name in members:
                    owner = self.group_owner.get(self.gmap.cq_group[name])
                    entry = None
                    for wid in wids:
                        if wid == owner or (wid, name) in self._ghost_sent:
                            continue
                        if entry is None:
                            entry = self._entry(KIND_CLUSTER_QUEUE,
                                                self._cq_specs[name])
                        self.workers[wid].send(("ghost_cq", entry))
                        self._ghost_sent.add((wid, name))

    # -- admin API (the partitioned watch stream) ----------------------------

    def create_resource_flavor(self, rf) -> None:
        self._flavor_specs[rf.name] = rf
        self.coordinator.note_flavor(rf)
        self._broadcast(KIND_RESOURCE_FLAVOR, rf)

    def create_cohort(self, spec) -> None:
        self.gmap.note_cohort(spec.name, spec.parent)
        self._cohort_spec_objs[spec.name] = spec
        self.coordinator.note_cohort(spec)
        self._broadcast(KIND_COHORT, spec)
        self._resplit()

    def create_cluster_queue(self, spec) -> None:
        gid = self.gmap.place_cq(spec.name, spec.cohort)
        self.coordinator.note_cluster_queue(spec)
        self._cq_specs[spec.name] = spec
        self._send_group(gid, KIND_CLUSTER_QUEUE, spec)
        self._resplit()

    def create_local_queue(self, lq) -> None:
        gid = self.gmap.place_lq(lq.key, lq.cluster_queue)
        if gid is None:
            # LocalQueue for a not-yet-seen CQ: place by the CQ name so
            # the pair reunites once the CQ arrives with the same hash.
            gid = self.gmap.place_cq(lq.cluster_queue, None)
        self._send_group(gid, KIND_LOCAL_QUEUE, lq)
        for key, queued in list(self.pen.items()):
            if key == lq.key:
                del self.pen[key]
                for kind, obj in queued:
                    self.submit(obj)

    def create_workload_priority_class(self, pc) -> None:
        self._broadcast(KIND_WORKLOAD_PRIORITY_CLASS, pc)

    def create_admission_check(self, ac) -> None:
        self._broadcast(KIND_ADMISSION_CHECK, ac)

    def submit(self, wl) -> None:
        lq_key = f"{wl.namespace}/{wl.queue_name}"
        cq = self.gmap.lq_cq.get(lq_key)
        if cq is None:
            # Hold until the LocalQueue appears (the manager's own
            # unknown-queue pen, one level up).
            self.pen.setdefault(lq_key, []).append((KIND_WORKLOAD, wl))
            return
        gid = self.gmap.cq_group.get(cq)
        if gid is None:
            gid = self.gmap.place_cq(cq, None)
        self.wl_group[wl.key] = gid
        self._send_group(gid, KIND_WORKLOAD, wl)

    def submit_fanout(self, wls) -> None:
        """Sharded watch fan-out for a submission burst: route every
        workload under ONE lock acquisition, then hand each owner's
        slice to that owner's dedicated writer queue — encode + channel
        write happen on per-worker threads, so the parent Store's watch
        stream never serializes N workers' sockets through this lock.
        flush_fanout() is the ordering barrier before any synchronous
        send (tick, finish, adopt) to the same workers."""
        if knobs.flag("KUEUE_TPU_NO_BATCH_INGEST"):
            for wl in wls:  # kill-switch twin of the fan-out lane
                self.submit(wl)
            return
        by_wid: Dict[int, list] = {}
        with self._lock:
            for wl in wls:
                lq_key = f"{wl.namespace}/{wl.queue_name}"
                cq = self.gmap.lq_cq.get(lq_key)
                if cq is None:
                    self.pen.setdefault(lq_key, []).append(
                        (KIND_WORKLOAD, wl))
                    continue
                gid = self.gmap.cq_group.get(cq)
                if gid is None:
                    gid = self.gmap.place_cq(cq, None)
                self.wl_group[wl.key] = gid
                wid = self.group_owner.get(gid)
                if wid is None or not self.workers[wid].alive:
                    continue  # reassigned at the next barrier, like submit
                by_wid.setdefault(wid, []).append((gid, wl))
            for wid, items in by_wid.items():
                self._fanout_queue(wid).put(items)

    def _fanout_queue(self, wid: int) -> "queue.Queue":
        # Callers hold self._lock, so lazy creation never races.
        q = self._fanout_queues.get(wid)
        if q is None:
            q = self._fanout_queues[wid] = queue.Queue()
            t = threading.Thread(
                target=self._fanout_run, args=(wid, q),
                name=f"watch-fanout-{wid}", daemon=True)
            self._fanout_threads[wid] = t
            t.start()
        return q

    def _fanout_run(self, wid: int, q: "queue.Queue") -> None:
        while True:
            items = q.get()
            try:
                if items is None:
                    return
                batch = [(gid, self._entry(KIND_WORKLOAD, wl))
                         for gid, wl in items]
                w = self.workers[wid]
                if w.alive:
                    try:
                        w.send(("objs", batch))
                    except Exception as exc:
                        # Worker death surfaces at the next barrier; the
                        # writer thread must outlive a dead socket or
                        # every future flush_fanout() wedges on join().
                        import sys

                        print(f"kueue-tpu: watch fan-out to replica "
                              f"{wid} failed: {exc!r}", file=sys.stderr,
                              flush=True)
            finally:
                q.task_done()

    def flush_fanout(self) -> None:
        """Barrier: every burst handed to the writer threads is encoded
        and on the wire. Per-worker channel bytes stay ordered because
        each worker has exactly one writer thread and synchronous sends
        flush first."""
        for q in list(self._fanout_queues.values()):
            q.join()

    def finish(self, key: str, cq: Optional[str] = None,
               delete: bool = True) -> None:
        gid = self.wl_group.pop(key, None)
        if gid is None and cq is not None:
            gid = self.gmap.cq_group.get(cq)
        if gid is None:
            return
        with self._lock:
            w = self._owner(gid)
            if w is not None:
                w.send(("finish", key, delete))

    def finish_many(self, pairs) -> None:
        """Bulk completion flux: `pairs` is [(key, cq), ...]; one message
        per owning replica."""
        by_gid: Dict[int, list] = {}
        for key, cq in pairs:
            gid = self.wl_group.pop(key, None)
            if gid is None:
                gid = self.gmap.cq_group.get(cq)
            if gid is not None:
                by_gid.setdefault(gid, []).append(key)
        with self._lock:
            for gid, keys in by_gid.items():
                w = self._owner(gid)
                if w is not None:
                    w.send(("finish_many", keys))

    def submit_many(self, specs) -> None:
        """Bulk arrivals as compact spec tuples (see
        ReplicaWorker._submit_many); routed by each spec's LocalQueue."""
        by_gid: Dict[int, list] = {}
        for s in specs:
            lq_key = f"{s.get('namespace', 'default')}/{s['queue']}"
            cq = self.gmap.lq_cq.get(lq_key)
            gid = self.gmap.cq_group.get(cq) if cq is not None else None
            if gid is not None:
                by_gid.setdefault(gid, []).append(s)
        with self._lock:
            for gid, batch in by_gid.items():
                w = self._owner(gid)
                if w is not None:
                    w.send(("submit_many", batch))

    def delete_workload(self, key: str) -> None:
        gid = self.wl_group.pop(key, None)
        if gid is None:
            return
        with self._lock:
            w = self._owner(gid)
            if w is not None:
                w.send(("delete_wl", key))

    def apply_event(self, kind: str, ev_type: str, obj=None,
                    key: Optional[str] = None) -> None:
        """Route ONE watch event (the partitioned Store stream): admin
        kinds broadcast to every shard group, ClusterQueues/LocalQueues/
        Workloads go to their cohort-hash group, split-root membership
        and ghost mirrors resync after structural changes. ADDED events
        reuse the create_* paths, so a Store-driven deployment and a
        directly-driven one (tests, bench) take identical routes."""
        if key is None and obj is not None:
            from kueue_tpu.controllers.store import _obj_key

            key = _obj_key(kind, obj)
        if kind == KIND_RESOURCE_FLAVOR:
            if ev_type == DELETED:
                self._flavor_specs.pop(key, None)
                self.coordinator.note_flavor(key, deleted=True)
                self._broadcast(kind, obj, DELETED, key=key)
            else:
                self.create_resource_flavor(obj)
        elif kind == KIND_COHORT:
            if ev_type == DELETED:
                self.gmap.drop_cohort(key)
                self._cohort_spec_objs.pop(key, None)
                self.coordinator.note_cohort(key, deleted=True)
                self._broadcast(kind, obj, DELETED, key=key)
                self._resplit()
            else:
                self.create_cohort(obj)
        elif kind == KIND_CLUSTER_QUEUE:
            if ev_type == DELETED:
                gid = self.gmap.cq_group.get(key)
                with self._lock:
                    # Purge the ghost mirrors BEFORE the owning group's
                    # delete: a sibling replica must not keep scheduling
                    # tree math against a removed member's quota.
                    for wid, name in sorted(self._ghost_sent):
                        if name == key and self.workers[wid].alive:
                            self.workers[wid].send(
                                ("ghost_cq", {"type": DELETED,
                                              "key": key}))
                    self._ghost_sent = {
                        (wid, name) for wid, name in self._ghost_sent
                        if name != key}
                if gid is not None:
                    self._send_group(gid, kind, obj, DELETED, key=key)
                self.gmap.drop_cq(key)
                self._cq_specs.pop(key, None)
                self.coordinator.note_cluster_queue(key, deleted=True)
                self._resplit()
            elif ev_type == MODIFIED:
                gid = self.gmap.place_cq(obj.name, obj.cohort)
                self.coordinator.note_cluster_queue(obj)
                self._cq_specs[obj.name] = obj
                self._send_group(gid, kind, obj, MODIFIED)
                with self._lock:
                    # Drop the sent-markers so _sync_ghosts re-ships the
                    # UPDATED spec to every sibling replica mirroring it.
                    self._ghost_sent = {
                        (wid, name) for wid, name in self._ghost_sent
                        if name != obj.name}
                self._resplit()
            else:
                self.create_cluster_queue(obj)
        elif kind == KIND_LOCAL_QUEUE:
            if ev_type == DELETED:
                cq = self.gmap.lq_cq.pop(key, None)
                gid = self.gmap.cq_group.get(cq) if cq else None
                if gid is not None:
                    self._send_group(gid, kind, obj, DELETED, key=key)
            elif ev_type == MODIFIED:
                gid = self.gmap.place_lq(key, obj.cluster_queue)
                if gid is not None:
                    self._send_group(gid, kind, obj, MODIFIED)
            else:
                self.create_local_queue(obj)
        elif kind == KIND_WORKLOAD:
            if ev_type == DELETED:
                self.delete_workload(key)
            elif ev_type == MODIFIED:
                gid = self.wl_group.get(key)
                if gid is not None:
                    self._send_group(gid, kind, obj, MODIFIED)
                else:
                    self.submit(obj)
            else:
                self.submit(obj)
        elif kind in (KIND_WORKLOAD_PRIORITY_CLASS, KIND_ADMISSION_CHECK):
            self._broadcast(kind, obj, ev_type, key=key)

    def load_synthetic(self, **kwargs) -> dict:
        """Distributed synthetic load: every worker generates (and
        keeps) only its own cohort-hash slice from the shared seed; the
        parent registers the routing formula without materializing a
        single workload object."""
        num_cqs = kwargs.get("num_cqs", 1000)
        num_cohorts = kwargs.get("num_cohorts", 100)
        for c in range(num_cqs):
            cohort = f"cohort-{c % num_cohorts}" if num_cohorts > 0 else None
            self.gmap.place_cq(f"cq-{c}", cohort)
            self.gmap.lq_cq[f"default/lq-{c}"] = f"cq-{c}"
        self._resplit()
        with self._lock:
            live = [w for w in self.workers if w.alive]
            for w in live:
                w.send(("synth", kwargs))
            totals: Dict[str, int] = {}
            for w in live:
                msg = w.recv(timeout=max(self.round_timeout, 1800))
                assert msg[0] == "synth_done", msg
                for k, v in msg[1].items():
                    totals[k] = totals.get(k, 0) + v
        return totals

    def gc_settle(self) -> int:
        """Barrier GC maintenance on every live worker (collect +
        freeze; see ReplicaWorker._gc_settle): call at a window
        boundary so no measured tick pays a gen-2 pass over millions of
        long-lived backlog objects. Returns the total frozen count."""
        with self._lock:
            live = [w for w in self.workers if w.alive]
            for w in live:
                w.send(("gc",))
            frozen = 0
            for w in live:
                msg = w.recv(timeout=self.round_timeout)
                assert msg[0] == "gc_done", msg
                frozen += msg[1]
        return frozen

    # -- the tick barrier ----------------------------------------------------

    def _barrier_recv(self, w: _WorkerHandle, phase: str, want: str,
                      stalls: List[dict]):
        """One barrier wait on one replica. A miss surfaces as a
        BarrierStallError naming the pid/host/round (the watchdog), is
        counted, and — when the process is STALLED rather than dead
        (SIGSTOP, wedged GC) — the process is killed so its journal
        flocks clear and the group reassignment can actually proceed
        (previously a stopped worker kept its flocks and adoption
        retried silently forever). Returns the payload or None."""
        from kueue_tpu.metrics import REGISTRY

        try:
            msg = w.recv(timeout=self.round_timeout)
            if msg[0] != want:
                raise WorkerDied(
                    f"protocol violation from replica {w.wid}: "
                    f"{msg[0]!r}")
            return msg
        except WorkerDied as exc:
            stalled = w.os_alive() and not w.crashed
            err = BarrierStallError(
                "replica", wid=w.wid, pid=w.pid, host=w.host_id,
                round_no=self.tick_no, phase=phase,
                timeout_s=self.round_timeout)
            w.alive = False
            if stalled:
                self.stall_count += 1
                REGISTRY.replica_barrier_stalls_total.inc(str(w.wid))
                stalls.append(err.to_dict())
                self.on_stall(err)
                if w.proc is not None:
                    # A stalled process still holds its flocks; clear
                    # them so the adopters are not wedged behind it.
                    w.proc.kill()
            else:
                stalls.append({**err.to_dict(), "who": "replica-death",
                               "error": str(exc)})
            return None

    def tick(self) -> dict:
        """One barrier tick across every live replica; returns the
        aggregated evidence. Dead replicas are detected here and their
        shard groups reassigned (journal replay on the adopter) BEFORE
        the tick runs; stalled ones surface through the watchdog."""
        from kueue_tpu.metrics import REGISTRY
        from kueue_tpu.tracing import TRACER

        with self._lock:
            # Ordering barrier: every fan-out burst must be on the wire
            # before the tick message (new bursts can't start — routing
            # needs this lock).
            self.flush_fanout()
            empty = {"admitted": [], "preempted": [], "n": 0,
                     "revocations": 0, "rtt": [], "rss": _rss_bytes(),
                     "tick_s": [], "stalls": [], "dispatches": 0,
                     "micro_admitted": 0, "microticks": 0,
                     "predispatch": [0, 0]}
            stalls: List[dict] = []
            self.tick_no += 1
            self.elector.step()
            if not self.elector.is_leader():
                return {**empty, "skipped": "not-leader"}
            self._reassign_dead()
            live = [w for w in self.workers if w.alive]
            if not live:
                return {**empty, "skipped": "no-replicas"}
            # Pre-tick usage exchange: every replica ships its OWNED
            # split-root members' usage; the merged map refreshes the
            # ghosts (remote members in each replica's cache) AND feeds
            # the coordinator's round — one authoritative view per tick,
            # exactly the state a single-process snapshot would hold.
            merged: Dict[str, dict] = {}
            if self._last_split:
                for w in live:
                    w.send(("pretick",))
                for w in live:
                    msg = self._barrier_recv(w, "pretick", "usage", stalls)
                    if msg is not None:
                        merged.update(msg[1])
                live = [w for w in live if w.alive]
                if merged:
                    for w in live:
                        w.send(("ghost_usage", merged))
            for w in live:
                w.send(("tick", self.tick_no,
                        self.status_store is not None,
                        self.coordinator.epoch))
            rounds = []
            for w in live:
                msg = self._barrier_recv(w, "round", "round", stalls)
                if msg is not None:
                    rounds.append(msg[1])
            with TRACER.span("reconcile.round") as sp:
                verdicts = self.coordinator.run_round(rounds, usage=merged)
                if self._coord_kill_pending:
                    # Mid-window coordinator death drill: the previous
                    # incarnation arbitrated + journaled this round but
                    # never answered; a newly elected incarnation must
                    # resume the barrier, not stall it.
                    self._coord_kill_pending = False
                    verdicts = self._coordinator_takeover(
                        rounds, merged, verdicts)
                sp.set("round", self.coordinator.rounds)
                sp.set("epoch", self.coordinator.epoch)
                sp.set("candidates",
                       sum(len(r.get("candidates", ())) for r in rounds))
            REGISTRY.reconcile_round_epoch.set(
                value=self.coordinator.epoch)
            stats = {"admitted": [], "preempted": [], "n": 0,
                     "revocations": 0, "rtt": [], "rss": _rss_bytes(),
                     "tick_s": [], "stalls": stalls, "dispatches": 0,
                     "micro_admitted": 0, "microticks": 0,
                     "predispatch": [0, 0]}
            status_batches: list = []
            backlog: Dict[int, int] = {}
            for w in live:
                if not w.alive:
                    continue
                w.send(("verdicts", verdicts.get(w.wid, [])))
            for w in live:
                if not w.alive:
                    continue
                msg = self._barrier_recv(w, "done", "done", stalls)
                if msg is None:
                    continue
                d = msg[1]
                stats["admitted"].extend(
                    [tuple(pair) for pair in d["admitted"]])
                # Between-barrier micro-tick admissions fold into the
                # same admitted evidence (they are real admissions the
                # drivers' bookkeeping must see), counted separately.
                micro = [tuple(pair)
                         for pair in d.get("micro_admitted") or ()]
                stats["admitted"].extend(micro)
                stats["micro_admitted"] += len(micro)
                stats["microticks"] += d.get("microticks") or 0
                pd = d.get("predispatch") or (0, 0)
                stats["predispatch"][0] += pd[0]
                stats["predispatch"][1] += pd[1]
                stats["preempted"].extend(d["preempted"])
                stats["n"] += d["n"] + len(micro)
                stats["revocations"] += d["revocations"]
                stats["rtt"].extend(d["rtt"])
                stats["rss"] += d["rss"]
                stats["tick_s"].append(d["tick_s"])
                stats["dispatches"] += d.get("dispatches") or 0
                for gid, depth in d.get("backlog") or ():
                    backlog[int(gid)] = backlog.get(int(gid), 0) \
                        + int(depth)
                if self.replicator is not None:
                    for gid, ops in d.get("segments") or ():
                        self.replicator.submit(int(gid), ops)
                if d.get("status_docs"):
                    status_batches.extend(d["status_docs"])
            for gid, depth in backlog.items():
                REGISTRY.replica_backlog_depth.set(
                    str(gid), value=float(depth))
            self.backlog_last = backlog
            stats["backlog"] = backlog
            self.stats_last = stats
        # Status mirror OUTSIDE self._lock: update_status takes the
        # parent Store's lock, and Store watch callbacks (an HTTP POST
        # holding Store._lock in _notify) take self._lock in the bridge
        # routing — applying under both would be a lock-order inversion
        # that deadlocks the deployment.
        if status_batches:
            self._apply_status_docs(status_batches)
        return stats

    def _apply_status_docs(self, docs) -> None:
        """Mirror worker-published workload statuses into the parent's
        read-surface Store (the /status subresource write). The bridge's
        echo guard keeps the resulting MODIFIED events from routing back
        to the workers as takeover replays."""
        from kueue_tpu.api import serialization

        store = self.status_store
        if store is None:
            return
        self._applying_status = threading.get_ident()
        try:
            for doc in docs:
                _, obj = serialization.decode(doc)
                if doc.get("status"):
                    serialization.decode_workload_status(doc, obj)
                try:
                    store.update_status(KIND_WORKLOAD, obj)
                except KeyError:
                    # Deleted from the parent store while the worker's
                    # publish was in flight.
                    pass
        finally:
            self._applying_status = None

    def _adopt_seed(self, gid: int, to_wid: int,
                    released: Optional[dict] = None):
        """(journal_path, seed) for adopting `gid` on worker `to_wid`:
        per-host mode ships the coordinator's replicated journal lines
        (the adopter cannot read the old owner's disk); shared-dir mode
        hands over the released/orphaned file itself; journal-less
        deployments ship the releasing owner's object snapshot. A
        REMOTE adopter derives its own local path (the coordinator
        cannot name a file on another host's disk)."""
        path = (None if self.workers[to_wid].remote
                else self._journal_path(gid, wid=to_wid))
        if self.replicator is not None:
            if released is not None:
                # The owner's final unshipped segments land first.
                self.replicator.submit(gid, released.get("ops") or [])
            if not knobs.flag("KUEUE_TPU_NO_SNAPSHOT_BOOT"):
                # Snapshot shipping: compact the replicated history to
                # live state so the adopter replays O(live-state), not
                # O(history). The kill switch (and any build failure
                # inside bootstrap_lines) falls back to raw lines.
                floor = int(
                    knobs.raw("KUEUE_TPU_SNAPSHOT_BOOT_FLOOR") or 256)
                lines, meta = self.replicator.bootstrap_lines(
                    gid, floor=floor)
                self.bootstrap_evidence = {**meta, "gid": gid}
                return path, {"lines": lines, "bootstrap": meta}
            return path, {"lines": self.replicator.read_lines(gid)}
        if path is None and released is not None:
            return None, {"entries": released.get("entries") or []}
        return path, None

    def _adopt_exchange(self, target, gid: int, path, seed):
        """One adopt round-trip with the torn-snapshot fallback: when a
        shipped SNAPSHOT seed fails the adopter's write verification
        (disk fault on the seed write), retry immediately with the raw
        replicated history — raw lines replay through the journal's
        torn/corrupt recovery, so the fallback is lossless. Raises
        WorkerDied like a bare recv would."""
        target.send(("adopt", gid, path, seed))
        msg = target.recv(timeout=self.round_timeout)
        if (msg[0] == "adopt_err" and self.replicator is not None
                and "snapshot-write-torn" in str(msg[2])):
            fallback = self.replicator.read_lines(gid)
            if self.bootstrap_evidence is not None \
                    and self.bootstrap_evidence.get("gid") == gid:
                self.bootstrap_evidence["torn_fallback"] = True
                self.bootstrap_evidence["snapshot"] = False
                self.bootstrap_evidence["lines"] = len(fallback)
            target.send(("adopt", gid, path, {"lines": fallback}))
            msg = target.recv(timeout=self.round_timeout)
        return msg

    def _reassign_dead(self) -> None:
        # Re-entrant: tick() already holds the lock; the RLock makes
        # this explicit for the ghost-marker writes below.
        with self._lock:
            self._reassign_dead_locked()

    def _reassign_dead_locked(self) -> None:
        for w in self.workers:
            if w.alive and not w.is_alive():
                w.alive = False
        survivors = [w for w in self.workers if w.alive]
        if not survivors:
            return
        for gid, wid in sorted(self.group_owner.items()):
            if self.workers[wid].alive:
                continue
            target = survivors[0]
            path, seed = self._adopt_seed(gid, target.wid)
            try:
                msg = self._adopt_exchange(target, gid, path, seed)
            except WorkerDied:
                target.alive = False
                return
            if msg[0] == "adopted":
                self.group_owner[gid] = target.wid
                # Re-announce the split set so the adopter defers the
                # roots it now co-owns (membership moved, groups didn't),
                # and re-route the ghosts it purged before the replay.
                target.send(("split", sorted(self._last_split)))
                self._ghost_sent = {
                    (wid, name) for wid, name in self._ghost_sent
                    if wid != target.wid}
                self._sync_ghosts()
            # adopt_err: the dead owner's flock lingers; retry next tick.

    def kill_replica(self, wid: int) -> None:
        """Kill one replica (SIGKILL in spawn mode; cooperative stop in
        loopback, which releases its journal flocks like process death
        would). The next tick reassigns its shard groups."""
        self.workers[wid].kill()

    # -- coordinator fail-over -----------------------------------------------

    def kill_coordinator(self) -> None:
        """Drill hook: the coordinator incarnation dies at the NEXT
        barrier round, at the worst moment — after arbitrating and
        journaling the round, before any replica hears its verdict. The
        runtime then elects a new incarnation that resumes the barrier
        from the journal (epoch bump + verdict replay) instead of
        stalling it."""
        with self._lock:
            self._coord_kill_pending = True

    def _coordinator_takeover(self, rounds, merged,
                              dead_verdicts) -> Dict[int, List[bool]]:
        """Replace the coordinator mid-round: release + retake the
        lease (the epoch source), rebuild a fresh incarnation from the
        retained admin specs, recover the in-flight round's journaled
        verdicts, and re-run the round. The takeover CONTRACT is that
        the resumed round answers exactly what the dead incarnation
        decided — violated means the journal and the arbitration logic
        disagree, which must surface, not ship."""
        old = self.coordinator
        old.close()
        self.elector.release()
        self.elector.step_now()
        coord = Coordinator(journal_path=old.journal_path,
                            epoch=self._lease_transitions())
        for rf in self._flavor_specs.values():
            coord.note_flavor(rf)
        for spec in self._cohort_spec_objs.values():
            coord.note_cohort(spec)
        for spec in self._cq_specs.values():
            coord.note_cluster_queue(spec)
        coord.set_split(self._last_split)
        replayed = coord.recover(in_flight=True)
        self.coordinator = coord
        verdicts = coord.run_round(rounds, usage=merged)
        if dead_verdicts is not None and verdicts != dead_verdicts:
            raise RuntimeError(
                "coordinator takeover diverged: the resumed round's "
                f"verdicts differ from the dead incarnation's (epoch "
                f"{old.epoch} -> {coord.epoch}, round {coord.rounds})")
        self.failover_evidence = {
            "epoch_before": old.epoch,
            "epoch_after": coord.epoch,
            "round": coord.rounds,
            "replayed_verdicts": replayed,
            "candidates": sum(len(r.get("candidates", ()))
                              for r in rounds),
        }
        return verdicts

    # -- elastic scaling (transport/elastic.py drives these) -----------------

    def add_worker(self) -> int:
        """Start one more replica (no shard groups yet — migrate some
        onto it). Scale-up half of the Aryl elastic loop."""
        with self._lock:
            wid = len(self.workers)
            self.workers.append(_WorkerHandle(
                wid, self.spawn,
                {**self._opts, "host_id": f"host-{wid}",
                 "state_dir": self._worker_state_dir(f"host-{wid}")},
                groups=[], listener=self.listener))
            return wid

    def migrate_group(self, gid: int, to_wid: int) -> bool:
        """Move one shard group to another LIVE replica: the owner
        releases it (journal detached, objects dropped), the target
        adopts it (journal replay — replicated lines in per-host mode,
        the shared file otherwise, the owner's snapshot without
        journals). Runs between barriers, so decisions stay identical:
        the group's pending workloads simply resume on the adopter."""
        with self._lock:
            from_wid = self.group_owner.get(gid)
            if from_wid is None or to_wid >= len(self.workers) \
                    or to_wid < 0:
                return False
            if from_wid == to_wid:
                return True
            target = self.workers[to_wid]
            if not target.alive:
                return False
            released = None
            owner = self.workers[from_wid]
            if owner.alive:
                # The object snapshot is only consumed by journal-less
                # adoption; with journals it is dead weight (megabytes
                # at bench scale) — tell the owner whether to build it.
                want_entries = (self.replicator is None
                                and self._journal_path(
                                    gid, wid=to_wid) is None)
                owner.send(("release", gid, want_entries))
                try:
                    msg = owner.recv(timeout=self.round_timeout)
                    if msg[0] != "released":
                        raise WorkerDied(
                            f"protocol violation from replica "
                            f"{owner.wid}: {msg[0]!r}")
                    released = msg[2]
                except WorkerDied:
                    owner.alive = False
            path, seed = self._adopt_seed(gid, to_wid, released=released)
            try:
                msg = self._adopt_exchange(target, gid, path, seed)
            except WorkerDied:
                target.alive = False
                msg = ("adopt_err", gid, "target died")
            if msg[0] != "adopted":
                # The owner already RELEASED: without a rollback the
                # group is orphaned (owner no longer holds it, and
                # _reassign_dead never fires for a live owner). Re-adopt
                # on the original owner from the same seed.
                if owner.alive:
                    # released=None: the first _adopt_seed already
                    # submitted the owner's final segment ops — a second
                    # submit would duplicate replica-journal lines.
                    rb_released = (released
                                   if self.replicator is None else None)
                    rb_path, rb_seed = self._adopt_seed(
                        gid, from_wid, released=rb_released)
                    try:
                        rb = self._adopt_exchange(
                            owner, gid, rb_path, rb_seed)
                        if rb[0] != "adopted":
                            raise WorkerDied(f"rollback failed: {rb!r}")
                    except WorkerDied as exc:
                        owner.alive = False
                        print(f"kueue-tpu: group {gid} migration AND "
                              f"rollback failed ({exc}); groups "
                              "reassign at the next barrier",
                              file=__import__("sys").stderr, flush=True)
                return False
            self.group_owner[gid] = to_wid
            for w in (owner, target):
                if w.alive:
                    w.send(("split", sorted(self._last_split)))
            self._ghost_sent = {
                (wid, name) for wid, name in self._ghost_sent
                if wid != to_wid}
            self._sync_ghosts()
            return True

    def remove_worker(self, wid: int) -> bool:
        """Drain one replica (migrate every group it owns to the least-
        loaded survivor) and stop it. Scale-down half of the elastic
        loop."""
        with self._lock:
            w = self.workers[wid]
            survivors = [x for x in self.workers
                         if x.alive and x.wid != wid]
            if not w.alive or not survivors:
                return False
            for gid in [g for g, ow in sorted(self.group_owner.items())
                        if ow == wid]:
                target = min(
                    survivors,
                    key=lambda x: (sum(1 for ow in self.group_owner.values()
                                       if ow == x.wid), x.wid))
                if not self.migrate_group(gid, target.wid):
                    return False
            w.kill()
            return True

    def reconcile_info(self) -> dict:
        """The SIGUSR2 Dumper's reconcile view: barrier round + epoch,
        per-shard-group backlog depth (the elastic signal), group
        ownership, stall evidence, the fleet topology (remote joins),
        and the last degraded window's catch-up evidence."""
        from kueue_tpu.metrics import REGISTRY

        out = {
            "tick": self.tick_no,
            "round": self.coordinator.rounds,
            "epoch": self.coordinator.epoch,
            "transport": self.transport,
            "remoteWorkers": self.remote,
            "backlogDepth": {str(g): n for g, n
                             in sorted(self.backlog_last.items())},
            "groupOwner": {str(g): w for g, w
                           in sorted(self.group_owner.items())},
            "stalls": self.stall_count,
            "hosts": {str(w.wid): {"host": w.host_id, "pid": w.pid,
                                   "alive": w.alive,
                                   "remote": w.remote}
                      for w in self.workers},
            "degradedHosts": {
                host: gauge for (host,), gauge in sorted(
                    REGISTRY.coordinator_degraded.values.items())
                if gauge},
            "leaseTransitions": {
                lease: int(count) for (lease,), count in sorted(
                    REGISTRY.lease_transitions_total.values.items())},
            "journalWriteErrors": {
                reason: int(count) for (reason,), count in sorted(
                    REGISTRY.journal_write_errors_total.values.items())},
        }
        if self.listener is not None:
            out["rejectedHellos"] = self.listener.rejected_hellos
        if self.degraded_evidence is not None:
            out["degradedWindow"] = {
                k: v for k, v in self.degraded_evidence.items()
                if k != "reports"}
        if self.bootstrap_evidence is not None:
            out["snapshotBootstrap"] = dict(self.bootstrap_evidence)
        return out

    # -- introspection -------------------------------------------------------

    def dump(self) -> dict:
        with self._lock:
            out = {"admitted": {}, "pending": {}, "usage": {},
                   "workloads": 0}
            for w in self.workers:
                if not w.alive:
                    continue
                w.send(("dump",))
                msg = w.recv(timeout=self.round_timeout)
                assert msg[0] == "dump", msg
                for k in ("admitted", "pending", "usage"):
                    out[k].update(msg[1][k])
                out["workloads"] += msg[1]["workloads"]
            return out

    def admitted_workloads(self, cq_name: str) -> List[str]:
        return self.dump()["admitted"].get(cq_name, [])

    def export_chrome(self, slowest_only: bool = False) -> dict:
        """ONE Perfetto-loadable Chrome trace for the whole deployment:
        every replica's ring dump rebased onto the parent's wall-clock
        epoch, pid lanes per process, and the coordinator's reconcile
        rounds bound to the replicas' in-cycle RTT spans as flow
        events. `slowest_only` narrows every process's dump to its
        slowest retained tick (the `?slowest=true` small-payload pull)."""
        from kueue_tpu.tracing import TRACER, merge_chrome_traces

        with self._lock:
            docs = [(os.getpid(), "coordinator",
                     TRACER.export_chrome(slowest_only=slowest_only),
                     "host-coordinator")]
            if not self.spawn:
                # Loopback replicas share this process's tracer ring —
                # the parent export above already holds every span.
                return merge_chrome_traces(docs)
            for w in self.workers:
                if not w.alive:
                    continue
                w.send(("trace", slowest_only))
                msg = w.recv(timeout=self.round_timeout)
                assert msg[0] == "trace", msg
                docs.append((msg[1], f"replica-{w.wid}", msg[2],
                             msg[3] if len(msg) > 3 else w.host_id))
        return merge_chrome_traces(docs)

    def close(self) -> None:
        with self._lock:
            # Drain + retire the fan-out writers first: their sockets
            # are about to be told to stop.
            for q in self._fanout_queues.values():
                q.put(None)
            for t in self._fanout_threads.values():
                t.join(timeout=5)
            self._fanout_queues.clear()
            self._fanout_threads.clear()
            for w in self.workers:
                if not w.alive:
                    continue
                try:
                    w.send(("stop",))
                    while True:
                        msg = w.recv(timeout=10)
                        if msg[0] == "stopped":
                            break
                except WorkerDied:
                    pass
                w.alive = False
                if w.proc is not None:
                    w.proc.join(timeout=10)
            self.coordinator.close()
            self.elector.release()
            if self.replicator is not None:
                self.replicator.close()
            if self.listener is not None:
                self.listener.close()


class ReplicaStoreBridge:
    """The partitioned watch stream: the StoreAdapter of the replica
    deployment. Subscribes every kind on the parent's apiserver-analog
    `Store` and routes each event through `ReplicaRuntime.apply_event`
    — admin kinds broadcast to every shard group, ClusterQueues /
    LocalQueues / Workloads to their cohort-hash group — so a CLI or
    HTTP-API driven deployment is fed exactly like a directly-driven
    one, and the parent Store stays the single read surface (GET /
    watch) for the whole multi-process deployment."""

    KINDS = (
        KIND_RESOURCE_FLAVOR,
        KIND_WORKLOAD_PRIORITY_CLASS,
        KIND_ADMISSION_CHECK,
        KIND_COHORT,
        KIND_CLUSTER_QUEUE,
        KIND_LOCAL_QUEUE,
        KIND_WORKLOAD,
    )

    def __init__(self, store: Store, runtime: ReplicaRuntime):
        self.store = store
        self.runtime = runtime
        runtime.status_store = store
        for kind in self.KINDS:
            if kind == KIND_WORKLOAD:
                # Bulk creates deliver one batched callback: ADDED runs
                # take the sharded fan-out (one routing pass, per-worker
                # writer threads) instead of N synchronous sends.
                store.watch(kind, self._on_event,
                            batch=self._on_workload_batch)
            else:
                store.watch(kind, self._on_event)

    def _on_event(self, ev) -> None:
        if self.runtime._applying_status == threading.get_ident():
            # Our own status mirror round-tripping on THIS thread (the
            # workers already hold the authoritative state); routing it
            # back would replay it as a takeover rebuild on the owner.
            # Other threads' writes (an HTTP create landing mid-mirror)
            # route normally.
            return
        # A synchronous route must observe every fan-out burst already
        # on the wire (cheap no-op when the writer queues are idle).
        self.runtime.flush_fanout()
        self.runtime.apply_event(ev.kind, ev.type, ev.obj, key=ev.key)

    def _on_workload_batch(self, events) -> None:
        if self.runtime._applying_status == threading.get_ident():
            return
        run: List[object] = []

        def flush():
            if run:
                self.runtime.submit_fanout(run)
                run.clear()

        for ev in events:
            if ev.type == ADDED:
                run.append(ev.obj)
            else:
                # MODIFIED/DELETED must observe every prior ADDED on the
                # worker before they route: drain the fan-out, then go
                # synchronous.
                flush()
                self.runtime.flush_fanout()
                self.runtime.apply_event(ev.kind, ev.type, ev.obj,
                                         key=ev.key)
        flush()
