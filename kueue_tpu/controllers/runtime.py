"""The in-process runtime: wires queue manager, cache and scheduler together
and applies workload lifecycle transitions.

This is the counterpart of the reference's controller wiring
(cmd/kueue/main.go + pkg/controller/core/): object writes feed the pending
queues and the admitted cache, the scheduler tick admits/preempts, and the
reconciler pass (`reconcile()`) applies the follow-on transitions that the
reference performs asynchronously through watch events
(core/workload_controller.go): evicted workloads release quota and requeue,
finished workloads release quota, admission-check state flips workloads from
QuotaReserved to Admitted.

Being an in-memory, synchronous analog of envtest, it is also the test
fixture for integration-style tests.
"""

from __future__ import annotations

import time as _time
from typing import Callable, Dict, List, Optional

from kueue_tpu import features
from kueue_tpu.api.types import (
    CONDITION_ADMITTED,
    CONDITION_EVICTED,
    CONDITION_FINISHED,
    CONDITION_PODS_READY,
    CONDITION_QUOTA_RESERVED,
    EVICTED_BY_DEACTIVATION,
    EVICTED_BY_PODS_READY_TIMEOUT,
    AdmissionCheck,
    ClusterQueue,
    LocalQueue,
    RequeueState,
    ResourceFlavor,
    Workload,
    WorkloadPriorityClass,
)
from kueue_tpu.config import Configuration, requeue_backoff_seconds
from kueue_tpu.metrics import REGISTRY
from kueue_tpu.core.cache import Cache
from kueue_tpu.core.workload import WorkloadInfo, WorkloadOrdering
from kueue_tpu.queue.manager import Manager, RequeueReason
from kueue_tpu.scheduler.preemption import DEFAULT_FAIR_STRATEGIES
from kueue_tpu.scheduler.scheduler import Scheduler
from kueue_tpu.tracing import TRACER
from kueue_tpu.utils import limitrange as limitrange_mod
from kueue_tpu.utils.limitrange import LimitRange
from kueue_tpu import events as events_mod
from kueue_tpu import webhooks


_ACCEL_PROBE: List = []


def _accelerator_present() -> bool:
    """True when jax's default backend is an accelerator (TPU/GPU).

    The probe must never hang the control plane: initializing an
    accelerator backend can block indefinitely when the device link is
    down, so detection runs in a SUBPROCESS with a timeout (an
    unreachable accelerator degrades to the host referee instead of
    wedging startup). A JAX_PLATFORMS=cpu environment short-circuits.
    The verdict is cached for the process lifetime."""
    if _ACCEL_PROBE:
        return _ACCEL_PROBE[0]
    import os
    import subprocess
    import sys

    result = False
    if os.environ.get("JAX_PLATFORMS", "").split(",")[0] == "cpu":
        result = False
    else:
        try:
            out = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print('backend=' + jax.default_backend())"],
                capture_output=True, timeout=45, text=True)
            # Parse the sentinel line only: site hooks may print banners.
            backends = [line[len("backend="):]
                        for line in out.stdout.splitlines()
                        if line.startswith("backend=")]
            result = (out.returncode == 0 and bool(backends)
                      and backends[-1] != "cpu")
        except Exception:
            result = False
    _ACCEL_PROBE.append(result)
    return result


class Framework:
    def __init__(self, batch_solver=None,
                 config: Optional[Configuration] = None,
                 ordering: Optional[WorkloadOrdering] = None,
                 pipeline_depth: Optional[int] = None,
                 clock: Callable[[], float] = _time.time):
        self.clock = clock
        self.config = config or Configuration()
        # Pipelined scheduling (depth > 1): keep up to depth-1 ticks'
        # device solves in flight while completing older ticks host-side.
        # Decisions stay admission-safe via the scheduler's staleness
        # re-validation; depth 1 is the reference-equivalent synchronous
        # mode. Defaults from the Configuration's tpuSolver section.
        if pipeline_depth is None:
            pipeline_depth = self.config.tpu_solver.pipeline_depth
        self.pipeline_depth = max(1, pipeline_depth)
        self._inflight_ticks: List = []
        # Whether the last tick_prepared call actually consumed its
        # predispatched tick (False = a backoff expiry abandoned it and
        # the lazy path ran) — the eager-encode accounting's source.
        self.predispatch_consumed = False
        if batch_solver is None:
            solver_enable = self.config.tpu_solver.enable
            if solver_enable is None:
                # Auto: the device solve path is the default whenever an
                # accelerator backend is present (a TPU-native framework
                # defaults to its TPU path); CPU-only hosts (CI) keep the
                # reference-equivalent host referee. Only probed when no
                # solver was handed in — the probe initializes the jax
                # backend, which callers that bring their own solver may
                # not want (or be able) to touch yet.
                solver_enable = _accelerator_present()
            if solver_enable:
                from kueue_tpu.models.flavor_fit import BatchSolver
                shard = self.config.tpu_solver.shard_devices
                mesh = None
                if shard == -1 or shard > 1:
                    # Multi-chip: shard the solve over the device mesh
                    # (parallel/mesh.py — CQ axis partitioned, cohort
                    # aggregation via ICI collectives).
                    from kueue_tpu.parallel.mesh import make_mesh
                    mesh = make_mesh(None if shard == -1 else shard)
                batch_solver = BatchSolver(
                    mesh=mesh,
                    shards=self.config.tpu_solver.cohort_shards,
                    # None (not False) when the config doesn't select
                    # the mode, so the KUEUE_TPU_HETERO=1 env default
                    # still works on a default-config deployment.
                    hetero=(True if self.config.tpu_solver.mode == "hetero"
                            else None))
        if getattr(batch_solver, "_mesh", None) is not None:
            # The sharded program runs to completion at dispatch (its
            # collectives ride ICI; there is no host-link round trip to
            # overlap), so depth > 1 would add pipelining's staleness
            # costs while hiding zero latency.
            if self.pipeline_depth > 1:
                import logging

                logging.getLogger("kueue_tpu").warning(
                    "tpuSolver: pipelineDepth=%d is ignored with a sharded "
                    "solver (shardDevices>1) — the sharded program has no "
                    "host-link latency to pipeline; forcing depth 1",
                    self.pipeline_depth)
            self.pipeline_depth = 1
        wfpr = self.config.wait_for_pods_ready
        if ordering is None:
            ordering = WorkloadOrdering(
                pods_ready_requeuing_timestamp=(
                    wfpr.requeuing_strategy.timestamp if wfpr else "Eviction"))
        self.ordering = ordering
        if self.config.fair_sharing is not None:
            # NOTE: fair sharing is a process-global switch (KEP-1714 scopes
            # it cluster-wide); an explicit config sets the gate either way.
            features.set_enabled(features.FAIR_SHARING,
                                 self.config.fair_sharing.enable)
        fair_strategies = (
            self.config.fair_sharing.preemption_strategies
            if self.config.fair_sharing is not None else DEFAULT_FAIR_STRATEGIES)
        self.namespaces: Dict[str, Dict[str, str]] = {"default": {}}
        self.workloads: Dict[str, Workload] = {}
        self.priority_classes: Dict[str, WorkloadPriorityClass] = {}
        # namespace -> LimitRanges; runtime-class name -> pod overhead
        # (the string-world inputs to workload.AdjustResources).
        self.limit_ranges: Dict[str, List[LimitRange]] = {}
        self.runtime_classes: Dict[str, Dict[str, int]] = {}
        self.cluster_queue_specs: Dict[str, ClusterQueue] = {}
        self.admission_checks: Dict[str, AdmissionCheck] = {}
        self._ns_summaries: Dict[str, limitrange_mod.Summary] = {}
        self.events = events_mod.EventRecorder()
        self.cache = Cache()
        self.queues = Manager(ordering=self.ordering,
                              namespace_lister=self.namespaces.get,
                              clock=clock)
        gate = None
        if wfpr is not None and wfpr.enable and wfpr.block_admission:
            gate = self._all_admitted_pods_ready
        # preemptionEngine auto-resolution: the batched engine is the
        # default whenever the batch solver runs. "native" is the C++
        # scan over the same packed batch tensors — the victim search is
        # sequential small-integer runtime work where a remote-attached
        # accelerator loses on link round trips; "jax"/"pallas" force one
        # packed XLA dispatch per round instead. "host" forces the
        # reference-equivalent per-entry host referee.
        engine_cfg = self.config.tpu_solver.preemption_engine
        if engine_cfg in (None, "auto"):
            engine = "native" if batch_solver is not None else None
        elif engine_cfg == "host":
            engine = None
        else:
            engine = engine_cfg
        self.scheduler = Scheduler(
            queues=self.queues, cache=self.cache,
            apply_admission=self._apply_admission,
            apply_preemption=self._apply_preemption,
            namespace_lister=self.namespaces.get,
            batch_solver=batch_solver,
            ordering=self.ordering,
            pods_ready_gate=gate,
            fair_strategies=fair_strategies,
            workload_validator=self._validate_workload_resources,
            preemption_engine=engine,
            clock=clock)
        self._evicted_dirty: List[Workload] = []
        # Workloads whose admission-check state machine needs attention
        # (QuotaReserved set, a check state written, eviction handling).
        # The reference's workload reconciler is event-driven; a full scan
        # over 50k workloads per tick is the scaling hazard this avoids.
        self._check_sync_pending: Dict[str, Workload] = {}
        self._quota_reserved_msgs: Dict[str, str] = {}
        from kueue_tpu.controllers.jobframework import JobReconciler
        self.job_reconciler = JobReconciler(self)
        # QueueVisibility snapshot workers (clusterqueue_controller.go:685):
        # top-N pending per CQ on the configured cadence, feature-gated.
        from kueue_tpu.controllers.visibility import QueueVisibilitySnapshotter
        qv = self.config.queue_visibility
        self.queue_visibility = QueueVisibilitySnapshotter(
            self.queues, max_count=qv.max_count,
            update_interval_seconds=qv.update_interval_seconds)

    # -- admin objects -------------------------------------------------------

    def create_namespace(self, name: str, labels: Optional[Dict[str, str]] = None) -> None:
        self.namespaces[name] = labels or {}

    def create_limit_range(self, lr: LimitRange) -> None:
        """Register a namespace LimitRange and re-adjust + requeue pending
        workloads in that namespace — the reference's Workload reconciler
        watches LimitRanges for exactly this (workload_controller.go
        LimitRange watch handler)."""
        self.limit_ranges.setdefault(lr.namespace, []).append(lr)
        self._ns_summaries.pop(lr.namespace, None)
        self._readjust_pending(namespace=lr.namespace)

    def create_runtime_class(self, name: str,
                             overhead: Dict[str, int]) -> None:
        self.runtime_classes[name] = dict(overhead)
        self._readjust_pending()

    def _readjust_pending(self, namespace: Optional[str] = None) -> None:
        """Re-run AdjustResources on not-yet-reserved workloads after a
        LimitRange/RuntimeClass change, and re-open parked queues so a
        previously-inadmissible workload gets another nomination."""
        for wl in self.workloads.values():
            if wl.has_quota_reservation or wl.is_finished:
                continue
            if namespace is not None and wl.namespace != namespace:
                continue
            limitrange_mod.adjust_resources(
                wl, self.limit_ranges.get(wl.namespace, []),
                self.runtime_classes)
            # adjust_resources mutates pod templates in place (overhead,
            # folded defaults) without replacing wl.pod_sets — drop the
            # validation memo so the next nomination re-validates.
            wl._resval_memo = None
            self.queues.add_or_update_workload(wl)
        self.queues.queue_inadmissible_workloads(
            list(self.queues.cluster_queues))

    def _ns_summary(self, namespace: str) -> limitrange_mod.Summary:
        """Summaries fold only on LimitRange writes, not per nomination."""
        s = self._ns_summaries.get(namespace)
        if s is None:
            s = limitrange_mod.summarize(self.limit_ranges.get(namespace, []))
            self._ns_summaries[namespace] = s
        return s

    def _validate_workload_resources(self, wl: Workload) -> List[str]:
        """Nomination-time gate (scheduler.go validateResources +
        validateLimitRange).

        Memoized per workload: a parked head re-validates every tick at
        north-star scale, but the outcome only depends on the pod-set
        specs (replaced wholesale on API updates — the memo keys on list
        identity) and the namespace's folded LimitRange summary (replaced
        on LimitRange writes — identity again)."""
        summary = self._ns_summary(wl.namespace)
        memo = getattr(wl, "_resval_memo", None)
        if memo is not None and memo[0] is wl.pod_sets and memo[1] is summary:
            return memo[2]
        reasons = limitrange_mod.validate_limits_fit_requests(wl)
        if summary:
            for i, ps in enumerate(wl.pod_sets):
                if ps.template is None:
                    continue
                reasons += summary.validate_pod_template(
                    ps.template, path=f"podSets[{i}].template")
        wl._resval_memo = (wl.pod_sets, summary, reasons)
        return reasons

    def create_admission_check(self, ac: "AdmissionCheck") -> None:
        errs = webhooks.validate_admission_check(ac)
        if errs:
            raise webhooks.ValidationError(errs)
        self.admission_checks[ac.name] = ac

    def update_admission_check(self, ac: "AdmissionCheck") -> None:
        old = self.admission_checks.get(ac.name)
        errs = (webhooks.validate_admission_check_update(ac, old)
                if old is not None else webhooks.validate_admission_check(ac))
        if errs:
            raise webhooks.ValidationError(errs)
        self.admission_checks[ac.name] = ac

    def update_local_queue(self, lq: LocalQueue) -> None:
        old = self.cache.local_queues.get(lq.key)
        errs = (webhooks.validate_local_queue_update(lq, old)
                if old is not None else webhooks.validate_local_queue(lq))
        if errs:
            raise webhooks.ValidationError(errs)
        self.cache.add_local_queue(lq)

    def create_resource_flavor(self, flavor: ResourceFlavor) -> None:
        errs = webhooks.validate_resource_flavor(flavor)
        if errs:
            raise webhooks.ValidationError(errs)
        self.cache.add_or_update_resource_flavor(flavor)
        # Requeue CQs that reference this flavor (the ResourceFlavor
        # reconciler's job in the reference, cache.go:712-723).
        using = [
            cq.name for cq in self.cache.cluster_queues.values()
            if any(fq.name == flavor.name
                   for rg in cq.resource_groups for fq in rg.flavors)
        ]
        if using:
            self.queues.queue_inadmissible_workloads(using)

    def create_cluster_queue(self, spec: ClusterQueue) -> None:
        webhooks.default_cluster_queue(spec)
        errs = webhooks.validate_cluster_queue(spec)
        if errs:
            raise webhooks.ValidationError(errs)
        self.cluster_queue_specs[spec.name] = spec
        self.cache.add_cluster_queue(spec)
        self.queues.add_cluster_queue(spec, pending=list(self.workloads.values()))

    def update_cluster_queue(self, spec: ClusterQueue) -> None:
        old = self.cluster_queue_specs.get(spec.name)
        errs = (webhooks.validate_cluster_queue_update(spec, old)
                if old is not None else webhooks.validate_cluster_queue(spec))
        if errs:
            raise webhooks.ValidationError(errs)
        self.cluster_queue_specs[spec.name] = spec
        self.cache.update_cluster_queue(spec)
        self.queues.update_cluster_queue(spec)

    def create_cohort(self, spec) -> None:
        """Hierarchical-cohort node (KEP-79): shared quota, limits, parent.

        Structure changes can make parked workloads admissible anywhere in
        the tree, so all inadmissible workloads are requeued."""
        errs = webhooks.validate_cohort(spec)
        if errs:
            raise webhooks.ValidationError(errs)
        self.cache.add_or_update_cohort_spec(spec)
        self.queues.queue_inadmissible_workloads(
            list(self.queues.cluster_queues))

    update_cohort = create_cohort

    def delete_cohort(self, name: str) -> None:
        self.cache.delete_cohort_spec(name)
        self.queues.queue_inadmissible_workloads(
            list(self.queues.cluster_queues))

    def delete_resource_flavor(self, name: str) -> None:
        """Delete a ResourceFlavor: drop it from the cache (topology
        ledger included) and prune every metric series labeled with it —
        a deleted flavor must stop exporting, exactly like a deleted CQ
        (metrics.ClearClusterQueueMetrics discipline). Without this the
        `topology_fragmentation` and per-(cq,flavor) series of a retired
        flavor lived until process exit."""
        self.cache.delete_resource_flavor(name)
        REGISTRY.topology_fragmentation.prune(
            lambda key: not key or key[0] != name)
        REGISTRY.cluster_queue_resource_usage.prune(
            lambda key: len(key) < 2 or key[1] != name)
        # Cohort-labeled quota gauges carry the flavor at index 2.
        for gauge in (REGISTRY.cluster_queue_resource_reservation,
                      REGISTRY.cluster_queue_borrowing_limit,
                      REGISTRY.cluster_queue_lending_limit):
            gauge.prune(lambda key: len(key) < 3 or key[2] != name)

    def delete_cluster_queue(self, name: str) -> None:
        self.cluster_queue_specs.pop(name, None)
        self.cache.delete_cluster_queue(name)
        self.queues.delete_cluster_queue(name)
        self._quota_reserved_msgs.pop(name, None)
        # Stale-series prune for every per-CQ gauge, including the
        # cohort-labeled quota trio that update_metrics_gauges only
        # touches when metrics.enableClusterQueueResources is on (a
        # series set while the knob was on must still die with its CQ).
        for gauge in (REGISTRY.cluster_queue_resource_reservation,
                      REGISTRY.cluster_queue_borrowing_limit,
                      REGISTRY.cluster_queue_lending_limit):
            gauge.prune(lambda key: len(key) < 2 or key[1] != name)
        self.update_metrics_gauges()

    def create_local_queue(self, lq: LocalQueue) -> None:
        errs = webhooks.validate_local_queue(lq)
        if errs:
            raise webhooks.ValidationError(errs)
        self.cache.add_local_queue(lq)
        self.queues.add_local_queue(lq, pending=list(self.workloads.values()))

    def delete_local_queue(self, lq: LocalQueue) -> None:
        self.cache.delete_local_queue(lq)
        self.queues.delete_local_queue(lq)

    def create_workload_priority_class(self, pc: WorkloadPriorityClass) -> None:
        self.priority_classes[pc.name] = pc

    # -- workload lifecycle --------------------------------------------------

    def submit(self, wl: Workload, *, validate: bool = True) -> None:
        """A new pending workload enters the system.

        `validate=False` skips the webhook validation pass only — a
        pure check that cannot mutate the object, so the admitted
        state is identical either way. Bulk trusted ingest (the twin's
        10^6-arrival replays) uses it; everything defaulting or
        resource-adjusting still runs."""
        webhooks.default_workload(wl)
        if validate:
            errs = webhooks.validate_workload(wl)
            if errs:
                raise webhooks.ValidationError(errs)
        # Fold RuntimeClass overhead, LimitRange defaults and limits->
        # requests into podset requests (workload.AdjustResources; done by
        # the Workload reconciler on create in the reference,
        # core/workload_controller.go:408-438).
        limitrange_mod.adjust_resources(
            wl, self.limit_ranges.get(wl.namespace, []), self.runtime_classes)
        if wl.priority_class and wl.priority_class in self.priority_classes:
            # Priority resolution from WorkloadPriorityClass
            # (reference: pkg/util/priority).
            wl.priority = self.priority_classes[wl.priority_class].value
        self.workloads[wl.key] = wl
        self.queues.add_or_update_workload(wl)

    def submit_batch(self, wls, *, validate: bool = True) -> int:
        """Bulk arrival of new pending workloads (the vectorized ingest
        lane): per-workload defaulting/validation/resource-adjustment in
        one sweep, then ONE queue-manager pass — one lock acquisition,
        one dirty mark per cohort, one wakeup — instead of N
        add_or_update_workload round trips. Decision state lands exactly
        as N submit() calls would (the per-workload steps run in order;
        only the lock/mark granularity changes). Validation failures
        raise before any workload is registered — the batch is all-or-
        nothing, unlike a per-object loop that registers the prefix."""
        wls = list(wls)
        all_errs = []
        for wl in wls:
            webhooks.default_workload(wl)
            if validate:
                all_errs.extend(webhooks.validate_workload(wl))
        if all_errs:
            raise webhooks.ValidationError(all_errs)
        for wl in wls:
            limitrange_mod.adjust_resources(
                wl, self.limit_ranges.get(wl.namespace, []),
                self.runtime_classes)
            if wl.priority_class and wl.priority_class in self.priority_classes:
                wl.priority = self.priority_classes[wl.priority_class].value
            self.workloads[wl.key] = wl
        return self.queues.add_or_update_workloads(wls)

    def restore_workload(self, wl: Workload) -> None:
        """Rebuild runtime state for a workload recovered from durable
        storage: admitted/reserved workloads re-account their quota into
        the cache (the reference's cache rebuild from the apiserver List,
        cache.go:295-328); pending ones go back through submit
        (queue/manager.go:121-134 re-adoption); finished ones are only
        recorded."""
        if wl.is_finished:
            self.workloads[wl.key] = wl
            return
        if wl.has_quota_reservation and wl.admission is not None:
            self.workloads[wl.key] = wl
            self.cache.add_or_update_workload(wl)
            # Two-phase admission state machines resume where they were.
            self._check_sync_pending[wl.key] = wl
            return
        self.submit(wl)

    def submit_job(self, job) -> Optional[Workload]:
        """Run a GenericJob through the queueing system (jobframework).

        Returns None when the job is not managed: no queue name with
        manageJobsWithoutQueueName off (left alone), or held suspended
        awaiting a queue with it on."""
        return self.job_reconciler.submit(job)

    def update_reclaimable_pods(self, wl: Workload,
                                reclaimable: Dict[str, int]) -> None:
        """Shrink a workload's held quota as pods complete (KEP-78;
        core/workload_controller.go reclaimable handling)."""
        # Webhook gate: counts within [0, podset count], non-decreasing while
        # quota is reserved (workload_webhook.go:375-390).
        proposed = Workload(
            name=wl.name, namespace=wl.namespace, queue_name=wl.queue_name,
            pod_sets=wl.pod_sets, conditions=wl.conditions,
            admission=wl.admission, reclaimable_pods=dict(reclaimable))
        errs = webhooks.validate_workload_update(proposed, wl)
        if errs:
            raise webhooks.ValidationError(errs)
        was_admitted = self.cache.is_assumed_or_admitted(wl)
        if was_admitted:
            self.cache.delete_workload(wl)
        wl.reclaimable_pods = dict(reclaimable)
        if wl.admission is not None and was_admitted:
            self.cache.add_or_update_workload(wl)
            # Freed quota may unblock cohort members.
            self.queues.queue_associated_inadmissible_workloads(wl)
        else:
            self.queues.add_or_update_workload(wl)

    def mark_pods_ready(self, wl: Workload, ready: bool = True) -> None:
        """The job integration reports pod readiness (KEP-349)."""
        wl.set_condition(CONDITION_PODS_READY, ready, reason="PodsReady",
                         now=self.clock())
        if ready:
            # Readiness may unblock gated admissions; re-open parked queues.
            self.queues.queue_inadmissible_workloads(
                list(self.queues.cluster_queues))

    def _all_admitted_pods_ready(self) -> bool:
        """cache.PodsReadyForAllAdmittedWorkloads (cache.go:118-143)."""
        for cq in self.cache.cluster_queues.values():
            for wi in cq.workloads.values():
                wl = self.workloads.get(wi.key)
                if wl is None:
                    wl = wi.obj
                if wl.is_admitted and not wl.condition_true(CONDITION_PODS_READY):
                    return False
        return True

    def finish(self, wl: Workload, success: bool = True,
               reason: str = "") -> None:
        """Mark a workload Finished and release its quota
        (core/workload_controller.go finished handling)."""
        if not reason:
            reason = "JobFinished" if success else "JobFailed"
        wl.set_condition(CONDITION_FINISHED, True, reason=reason,
                         now=self.clock())
        self.events.event(wl.key, events_mod.NORMAL,
                          events_mod.REASON_FINISHED, "Workload finished",
                          now=self.clock())
        released = self.cache.delete_workload(wl)
        if released is not None:
            self._note_quota_released(wl, released)
        self.queues.delete_workload(wl)
        self.queues.queue_associated_inadmissible_workloads(wl)

    def delete_workload(self, wl: Workload) -> None:
        self.workloads.pop(wl.key, None)
        released = self.cache.delete_workload(wl)
        if released is not None:
            self._note_quota_released(wl, released)
        self.queues.delete_workload(wl)
        self.queues.queue_associated_inadmissible_workloads(wl)
        # A deleted object's admission story dies with it (the LRU would
        # reap it eventually; doing it here keeps churn from crowding out
        # live workloads' records).
        self.scheduler.explain.forget(wl.key)

    def requeue_updated_workload(self, wl: Workload) -> None:
        """Re-enqueue a pending workload whose spec changed in place (the
        jobframework's updateWorkloadToMatchJob, reconciler.go:649-668),
        re-applying the creation path's resource adjustment and
        priority-class resolution so the refreshed workload matches a
        freshly-submitted identical one."""
        limitrange_mod.adjust_resources(
            wl, self.limit_ranges.get(wl.namespace, []), self.runtime_classes)
        if wl.priority_class and wl.priority_class in self.priority_classes:
            wl.priority = self.priority_classes[wl.priority_class].value
        self.queues.add_or_update_workload(wl)

    def move_workload_queue(self, wl: Workload, new_queue: str) -> None:
        """Move a pending workload to another LocalQueue (jobframework
        step 7.1, reconciler.go:406-416): remove it from the old queue's
        heap BEFORE renaming — queue resolution follows wl.queue_name."""
        self.queues.delete_workload(wl)
        wl.queue_name = new_queue
        self.queues.add_or_update_workload(wl)

    def evict_workload(self, wl: Workload, reason: str, message: str) -> None:
        """Set the Evicted condition and queue the quota release for the
        next reconcile pass (workload_controller.go eviction handling —
        deactivation, stop policies, check-based evictions)."""
        wl.set_condition(CONDITION_EVICTED, True, reason=reason,
                         message=message, now=self.clock())
        self._count_eviction(wl, reason)
        self._evicted_dirty.append(wl)

    def _note_quota_released(self, wl: Workload, wi: WorkloadInfo) -> None:
        """Lockstep-mirror a quota release (finish / delete / eviction)
        into the scheduler's incremental snapshot and the solver's usage
        tensor, so completion flux doesn't force per-CQ re-clones and
        tensor row re-reads every tick (the same discipline _admit applies
        on the admission side). `wi` is the info cache.delete_workload
        released — its totals are exactly what the cache subtracted."""
        self.scheduler._mirror.note_removal(wl, wi)
        bs = self.scheduler.batch_solver
        note = getattr(bs, "note_removal", None)
        if note is not None and wl.admission is not None:
            note(wl.admission.cluster_queue, wi.usage())

    def set_admission_check_state(self, wl: Workload, check: str, state: str,
                                  message: str = "") -> None:
        from kueue_tpu.api.types import AdmissionCheckState
        wl.admission_check_states[check] = AdmissionCheckState(
            name=check, state=state, message=message)
        self.note_check_state_changed(wl)

    def note_check_state_changed(self, wl: Workload) -> None:
        """Queue the workload for the next reconcile's check-state sync
        (the event that would wake the reference's workload reconciler).
        Admission-check controllers writing states directly call this."""
        self._check_sync_pending[wl.key] = wl

    # -- scheduler callbacks -------------------------------------------------

    def _apply_admission(self, wl: Workload) -> bool:
        # The API write is in-memory: nothing can fail here.
        if not wl.is_admitted:
            # Two-phase admission: queue for the reconcile pass's
            # check-state sync. A workload already Admitted at apply time
            # (checkless ClusterQueue — the admit path set the condition)
            # has nothing to sync; reconcile would visit and immediately
            # drop it.
            self._check_sync_pending[wl.key] = wl
        cq = wl.admission.cluster_queue if wl.admission else ""
        # One message string per ClusterQueue (this runs per admission).
        msg = self._quota_reserved_msgs.get(cq)
        if msg is None:
            msg = self._quota_reserved_msgs[cq] = \
                f"Quota reserved in ClusterQueue {cq}"
        self.events.event(
            wl.key, events_mod.NORMAL, events_mod.REASON_QUOTA_RESERVED,
            msg, now=self.clock())
        return True

    def _apply_preemption(self, wl: Workload, message: str) -> None:
        wl.set_condition(CONDITION_EVICTED, True, reason="Preempted",
                         message=message, now=self.clock())
        self.events.event(wl.key, events_mod.NORMAL,
                          events_mod.REASON_PREEMPTED, message,
                          now=self.clock())
        if wl.admission is not None:
            REGISTRY.preempted_workloads_total.inc(wl.admission.cluster_queue)
        self._count_eviction(wl, "Preempted")
        self._evicted_dirty.append(wl)

    def _count_eviction(self, wl: Workload, reason: str) -> None:
        cq = wl.admission.cluster_queue if wl.admission is not None else ""
        REGISTRY.evicted_workloads_total.inc(cq, reason)

    def update_metrics_gauges(self) -> None:
        """Refresh per-CQ gauges (reported by the CQ reconciler in the
        reference, clusterqueue_controller.go); stale series for deleted
        objects are pruned (metrics.ClearClusterQueueMetrics analog)."""
        live = set(self.queues.cluster_queues) | set(self.cache.cluster_queues)
        for gauge in (REGISTRY.pending_workloads,
                      REGISTRY.reserving_active_workloads,
                      REGISTRY.admitted_active_workloads,
                      REGISTRY.cluster_queue_status,
                      REGISTRY.cluster_queue_resource_usage,
                      REGISTRY.cluster_queue_fair_share):
            gauge.prune(lambda key: key and key[0] in live)
        for name, cq in self.cache.cluster_queues.items():
            live_fr = {(name, f, r) for f, res in cq.usage.items() for r in res}
            REGISTRY.cluster_queue_resource_usage.prune(
                lambda key: key[0] != name or key in live_fr)
        for name, pending_cq in self.queues.cluster_queues.items():
            REGISTRY.pending_workloads.set(
                name, "active", value=pending_cq.pending_active)
            REGISTRY.pending_workloads.set(
                name, "inadmissible", value=pending_cq.pending_inadmissible)
        for name, cq in self.cache.cluster_queues.items():
            reserving = len(cq.workloads)
            admitted = sum(
                1 for wi in cq.workloads.values()
                if (self.workloads.get(wi.key) or wi.obj).is_admitted)
            REGISTRY.reserving_active_workloads.set(name, value=reserving)
            REGISTRY.admitted_active_workloads.set(name, value=admitted)
            REGISTRY.cluster_queue_status.set(
                name, "active", value=1.0 if cq.active() else 0.0)
            for fname, resources in cq.usage.items():
                for rname, used in resources.items():
                    REGISTRY.cluster_queue_resource_usage.set(
                        name, fname, rname, value=used)
        if features.enabled(features.FAIR_SHARING):
            from kueue_tpu.solver.fair_share import dominant_resource_share
            # Serve the gauge from the share kernel's last-tick bulk
            # output instead of building a snapshot and running a per-CQ
            # dict DRF walk on every scrape; deleted ClusterQueues
            # cannot leak stale series — the bulk dict is refused the
            # moment the cache structure rotates (fair_shares_last) and
            # the prune above drops dead names either way. The referee
            # walk remains the fallback (no solver / no tick yet /
            # KUEUE_TPU_NO_DEVICE_FAIR=1).
            shares = None
            solver = getattr(self.scheduler, "batch_solver", None)
            if solver is not None:
                last = getattr(solver, "fair_shares_last", None)
                shares = last() if last is not None else None
            if shares is not None:
                live_cqs = self.cache.cluster_queues
                for name, value in shares.items():
                    if name in live_cqs:
                        REGISTRY.cluster_queue_fair_share.set(
                            name, value=value)
            else:
                snap = self.cache.snapshot()
                for name, cq in snap.cluster_queues.items():
                    REGISTRY.cluster_queue_fair_share.set(
                        name, value=dominant_resource_share(cq)[0])
        self._record_topology_metrics()
        if self.config.metrics.enable_cluster_queue_resources:
            self._record_resource_metrics()

    def _record_topology_metrics(self) -> None:
        """topology_fragmentation per (flavor, level): how shredded the
        free pod-slot capacity is across that level's domains. Stale
        series (flavor deleted / topology dropped) prune away."""
        ledger = self.cache.topology
        live = set()
        for fname, used in ledger.flavors.items():
            rf = self.cache.resource_flavors.get(fname)
            spec = rf.topology if rf is not None else None
            if spec is None:
                continue
            for li, level in enumerate(spec.levels):
                dom_free = spec.domain_free(used, li)
                total = sum(dom_free.values())
                frag = 0.0 if total <= 0 \
                    else 1.0 - max(dom_free.values()) / total
                REGISTRY.topology_fragmentation.set(fname, level, value=frag)
                live.add((fname, level))
        REGISTRY.topology_fragmentation.prune(lambda key: key in live)

    def _record_resource_metrics(self) -> None:
        """Optional per-CQ quota gauges (metrics.enableClusterQueueResources;
        clusterqueue_controller.go recordResourceMetrics): borrowing/lending
        limits from the spec quotas (lending only under the LendingLimit
        gate, metrics.go:219-225) and the reservation totals from the
        cache's reserved usage. Stale series prune like the reference's
        ClearClusterQueueResourceMetrics."""
        lending = features.enabled(features.LENDING_LIMIT)
        quota_keys = set()
        usage_keys = set()
        for name, cq in self.cache.cluster_queues.items():
            cohort = cq.cohort_name or ""
            for rg in cq.resource_groups:
                for fq in rg.flavors:
                    for rname, quota in fq.resources:
                        key = (cohort, name, fq.name, rname)
                        quota_keys.add(key)
                        REGISTRY.cluster_queue_borrowing_limit.set(
                            *key, value=float(quota.borrowing_limit or 0))
                        if lending:
                            REGISTRY.cluster_queue_lending_limit.set(
                                *key, value=float(quota.lending_limit or 0))
            for fname, resources in cq.usage.items():
                for rname, used in resources.items():
                    key = (cohort, name, fname, rname)
                    usage_keys.add(key)
                    REGISTRY.cluster_queue_resource_reservation.set(
                        *key, value=float(used))
        # Exact-set prune: a live CQ that moved cohorts or dropped a
        # flavor must not keep exporting the old series
        # (ClearClusterQueueResourceMetrics semantics).
        REGISTRY.cluster_queue_borrowing_limit.prune(
            lambda key: key in quota_keys)
        REGISTRY.cluster_queue_lending_limit.prune(
            lambda key: key in quota_keys)
        REGISTRY.cluster_queue_resource_reservation.prune(
            lambda key: key in usage_keys)

    # -- reconcile pass ------------------------------------------------------

    def reconcile(self) -> None:
        """Apply async lifecycle transitions (workload_controller.go analog)."""
        self._reconcile_not_ready_timeouts()
        evicted, self._evicted_dirty = self._evicted_dirty, []
        for wl in evicted:
            if wl.has_quota_reservation:
                released = self.cache.delete_workload(wl)
                if released is not None:
                    self._note_quota_released(wl, released)
                wl.admission = None
                wl.set_condition(CONDITION_QUOTA_RESERVED, False,
                                 reason="Evicted", now=self.clock())
                wl.set_condition(CONDITION_ADMITTED, False, reason="Evicted",
                                 now=self.clock())
                self.queues.queue_associated_inadmissible_workloads(wl)
            # Retry checks reset to Pending for the next attempt
            # (workload.SyncAdmissionChecks).
            for s in wl.admission_check_states.values():
                if s.state == "Retry":
                    s.state = "Pending"
            if wl.active:
                self.queues.add_or_update_workload(wl)
        # Two-phase admission: flip Admitted once every check is Ready;
        # Retry/Rejected checks evict (workload_controller.go:175-184,
        # :244-253). Event-driven: only workloads queued by an admission,
        # a check-state write, or an eviction are visited — the reference's
        # watch-triggered reconciles, not a full scan.
        for key, wl in list(self._check_sync_pending.items()):
            if self.workloads.get(key) is not wl \
                    or not wl.has_quota_reservation or wl.admission is None:
                del self._check_sync_pending[key]
                continue
            cq = self.cache.cluster_queues.get(wl.admission.cluster_queue)
            if cq is None:
                del self._check_sync_pending[key]
                continue
            checks = cq.admission_checks
            states = [wl.admission_check_states.get(c) for c in checks]
            if any(s is not None and s.state in ("Retry", "Rejected")
                   for s in states):
                rejected = any(s is not None and s.state == "Rejected"
                               for s in states)
                if rejected:
                    wl.active = False
                if not wl.is_evicted:
                    wl.set_condition(
                        CONDITION_EVICTED, True,
                        reason="AdmissionCheck",
                        message="At least one admission check is false",
                        now=self.clock())
                    self._count_eviction(wl, "AdmissionCheck")
                    self._evicted_dirty.append(wl)
                del self._check_sync_pending[key]
                continue
            if not wl.is_admitted and checks and all(
                    s is not None and s.state == "Ready" for s in states):
                wl.set_condition(CONDITION_ADMITTED, True, reason="Admitted",
                                 now=self.clock())
                self.cache.add_or_update_workload(wl)
            if wl.is_admitted:
                # Settled; a later check-state write re-queues it.
                del self._check_sync_pending[key]

    def _reconcile_not_ready_timeouts(self) -> None:
        """Evict admitted workloads that exceeded the PodsReady timeout, with
        exponential requeue backoff and deactivation after the backoff limit
        (workload_controller.go:342-406)."""
        wfpr = self.config.wait_for_pods_ready
        if wfpr is None or not wfpr.enable:
            return
        now = self.clock()
        limit = wfpr.requeuing_strategy.backoff_limit_count
        for wl in list(self.workloads.values()):
            if not wl.active or wl.is_evicted or not wl.is_admitted:
                continue
            if wl.condition_true(CONDITION_PODS_READY):
                continue
            admitted_at = wl.find_condition(CONDITION_ADMITTED).last_transition_time
            if now - admitted_at < wfpr.timeout_seconds:
                continue
            count = (wl.requeue_state.count if wl.requeue_state else 0) + 1
            if limit is not None and count > limit:
                wl.active = False
                wl.set_condition(CONDITION_EVICTED, True,
                                 reason=EVICTED_BY_DEACTIVATION,
                                 message="Deactivated by reaching the requeue "
                                         "backoffLimitCount", now=now)
                self._count_eviction(wl, EVICTED_BY_DEACTIVATION)
            else:
                wl.requeue_state = RequeueState(
                    count=count,
                    requeue_at=now + requeue_backoff_seconds(count))
                wl.set_condition(CONDITION_EVICTED, True,
                                 reason=EVICTED_BY_PODS_READY_TIMEOUT,
                                 message=f"Exceeded the PodsReady timeout "
                                         f"{wfpr.timeout_seconds}s", now=now)
                self._count_eviction(wl, EVICTED_BY_PODS_READY_TIMEOUT)
            self._evicted_dirty.append(wl)

    # -- driving -------------------------------------------------------------

    def tick(self) -> int:
        """One scheduling cycle plus the reconcile pass; returns admissions.

        The whole call is one tracer tick: every phase span recorded
        below (snapshot/tensorize/device_solve/nominate/admit/requeue/
        reconcile, the solver's dispatch attributes, lock waits, journal
        fsyncs) groups under it in the exported trace, and the finished
        tick enters the ring buffer — head+tail sampled so the slowest
        ticks survive for `GET /debug/traces`."""
        with TRACER.tick() as tick_span:
            self.queues.flush_expired_backoffs()
            if self.pipeline_depth <= 1:
                admitted = self.scheduler.schedule(timeout=0.0)
            else:
                tick = self.scheduler.schedule_async(timeout=0.0)
                if tick is not None:
                    self._inflight_ticks.append(tick)
                admitted = 0
                # Complete the oldest tick; when the queue ran dry, drain
                # one in-flight tick per call instead of all of them — a
                # burst drain would multiply a single tick's latency by
                # the pipeline depth (p99 spike), and progressive drain
                # preserves the same eventual state across
                # run_until_settled.
                keep = self.pipeline_depth - 1 if tick is not None \
                    else len(self._inflight_ticks) - 1
                while len(self._inflight_ticks) > max(keep, 0):
                    admitted += self.scheduler.schedule_finish(
                        self._inflight_ticks.pop(0))
            with TRACER.phase("reconcile"):
                self.reconcile()
                self.job_reconciler.reconcile()
                if features.enabled(features.QUEUE_VISIBILITY):
                    self.queue_visibility.maybe_update(self.clock())
            tick_span.set("admitted", admitted)
        return admitted

    def prewarm_idle(self) -> int:
        """Compile any imminent head-count-bucket rotations NOW — call in
        the idle gap between ticks (the serve loop does; so does the
        bench's completion-flux slot). Keeps XLA compiles out of ticks."""
        return self.scheduler.prewarm_idle()

    def microtick(self) -> int:
        """Event-driven admission between full ticks: solve only the
        cohorts dirtied since the last tick (Scheduler.microtick) and
        run the reconcile pass for whatever admitted, so two-phase
        admission checks and job objects advance without waiting for
        the next tick. No-op when nothing is dirty or the
        KUEUE_TPU_NO_MICROTICK=1 kill switch is set; returns
        admissions."""
        admitted = self.scheduler.microtick()
        if admitted:
            with TRACER.phase("reconcile"):
                self.reconcile()
                self.job_reconciler.reconcile()
        return admitted

    # -- eager encode (the barrier-stall fix for replica workers) ------------

    def predispatch(self) -> Optional["object"]:
        """Start the NEXT tick's ingest+encode+solve now, instead of
        idling until the next tick is driven — a replica worker calls
        this right after its barrier reply, so a laggard sibling's stall
        window does this worker's dispatch work. Only valid at depth 1
        (deeper pipelines already overlap). The returned in-flight tick
        MUST be either finished by `tick_prepared` or returned through
        `abandon_predispatch` — and the caller must abandon it if ANY
        state-changing input arrives before the tick is driven, which
        makes the eager path decision-identical to the lazy one."""
        if self.pipeline_depth > 1 or self._inflight_ticks:
            return None
        self.queues.flush_expired_backoffs()
        return self.scheduler.schedule_async(timeout=0.0)

    def abandon_predispatch(self, tick) -> None:
        """Invalidate a predispatched tick: push its popped heads back
        (unchanged — nothing was decided) and drop the in-flight solve.
        The un-fetched device work is the only waste."""
        if tick is not None:
            self.queues.restore_heads([e.info for e in tick.entries])

    def tick_prepared(self, tick) -> int:
        """Drive one tick whose dispatch half already ran (predispatch).
        A clock-gated backoff expiring between the predispatch and now
        means the lazy tick would have popped a different head set: the
        predispatched tick is abandoned and re-run fresh.
        `predispatch_consumed` reports which path actually ran — the
        caller's eager-encode accounting must not count an abandoned
        predispatch as a hit."""
        self.predispatch_consumed = False
        if tick is not None and self.queues.flush_expired_backoffs():
            self.abandon_predispatch(tick)
            tick = None
        if tick is None:
            return self.tick()
        self.predispatch_consumed = True
        with TRACER.tick() as tick_span:
            admitted = self.scheduler.schedule_finish(tick)
            with TRACER.phase("reconcile"):
                self.reconcile()
                self.job_reconciler.reconcile()
                if features.enabled(features.QUEUE_VISIBILITY):
                    self.queue_visibility.maybe_update(self.clock())
            tick_span.set("admitted", admitted)
            tick_span.set("predispatched", True)
        return admitted

    def run_until_settled(self, max_ticks: int = 100) -> int:
        """Tick until no progress is made; returns total admissions."""
        total = 0
        idle = 0
        for _ in range(max_ticks):
            n = self.tick()
            total += n
            # A dispatch-only tick (solves still in flight) is progress,
            # not idleness — the pipeline needs draining before settling.
            if n == 0 and not self._inflight_ticks:
                idle += 1
                if idle >= 2:
                    break
            else:
                idle = 0
        return total

    # -- introspection -------------------------------------------------------

    def admitted_workloads(self, cq_name: str) -> List[str]:
        cq = self.cache.cluster_queues[cq_name]
        return sorted(cq.workloads)

    def pending_workloads(self, cq_name: str) -> int:
        return self.queues.pending(cq_name)
