"""Watchable in-memory object store: the API front end.

The reference externalizes all state to the Kubernetes apiserver and wires
controllers through informer watch caches (SURVEY §2.5 "distributed
communication backend"). This module is that boundary for the embedded
runtime: a versioned, thread-safe object store with watch fan-out
(apiserver + client-go analog, usable like envtest in tests), plus
`StoreAdapter`, the controller that mirrors store writes into a running
`Framework` — the counterpart of pkg/controller/core's reconcilers feeding
queue.Manager and cache.Cache from watch events.

Webhooks run at the store boundary exactly as in the reference: defaulting
then validation on create, update validation (immutability rules) on
update (pkg/webhooks/).
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from kueue_tpu import webhooks
from kueue_tpu.api.types import (
    AdmissionCheck,
    ClusterQueue,
    LocalQueue,
    ResourceFlavor,
    Workload,
    WorkloadPriorityClass,
)

# Kind names (the CRD vocabulary).
KIND_CLUSTER_QUEUE = "ClusterQueue"
KIND_LOCAL_QUEUE = "LocalQueue"
KIND_RESOURCE_FLAVOR = "ResourceFlavor"
KIND_WORKLOAD = "Workload"
KIND_WORKLOAD_PRIORITY_CLASS = "WorkloadPriorityClass"
KIND_ADMISSION_CHECK = "AdmissionCheck"
KIND_COHORT = "Cohort"

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"

_CLUSTER_SCOPED = {
    KIND_CLUSTER_QUEUE, KIND_RESOURCE_FLAVOR,
    KIND_WORKLOAD_PRIORITY_CLASS, KIND_ADMISSION_CHECK, KIND_COHORT,
}

_VALIDATORS: Dict[str, Tuple[Optional[Callable], Optional[Callable]]] = {
    # kind -> (validate_create, validate_update)
    KIND_CLUSTER_QUEUE: (webhooks.validate_cluster_queue,
                         webhooks.validate_cluster_queue_update),
    KIND_LOCAL_QUEUE: (webhooks.validate_local_queue,
                       webhooks.validate_local_queue_update),
    KIND_RESOURCE_FLAVOR: (webhooks.validate_resource_flavor, None),
    KIND_WORKLOAD: (webhooks.validate_workload,
                    webhooks.validate_workload_update),
    KIND_ADMISSION_CHECK: (webhooks.validate_admission_check,
                           webhooks.validate_admission_check_update),
    KIND_WORKLOAD_PRIORITY_CLASS: (None, None),
    KIND_COHORT: (webhooks.validate_cohort,
                  lambda new, old: webhooks.validate_cohort(new)),
}

_DEFAULTERS: Dict[str, Callable] = {
    KIND_CLUSTER_QUEUE: webhooks.default_cluster_queue,
    KIND_WORKLOAD: webhooks.default_workload,
}


@dataclass
class Event:
    type: str          # ADDED | MODIFIED | DELETED
    kind: str
    key: str           # "namespace/name" or "name" for cluster-scoped
    obj: object
    resource_version: int


def _obj_key(kind: str, obj) -> str:
    if kind in _CLUSTER_SCOPED:
        return obj.name
    return f"{getattr(obj, 'namespace', 'default')}/{obj.name}"


def _workload_validation_equal(a: Workload, b: Workload) -> bool:
    """True when validate_workload(a) provably returns validate_workload(b)'s
    verdict: equal on every field the validator reads (pod_sets, queue_name,
    priority_class) and free of status state — status-bearing workloads
    (admission internals, reclaimable counts) always take the full check."""
    for wl in (a, b):
        if wl.conditions or wl.admission is not None or wl.reclaimable_pods \
                or wl.admission_check_states or wl.requeue_state is not None:
            return False
    return (a.queue_name == b.queue_name
            and a.priority_class == b.priority_class
            and a.pod_sets == b.pod_sets)


class Store:
    """Versioned object store with watch fan-out (apiserver analog).

    Writers publish an ENCODED copy-on-write view at write time: the
    scheduler mutates live objects in place under the runtime lock, so a
    reader encoding a live object mid-tick would race (or have to take the
    runtime lock and stall behind a whole tick — VERDICT r3 Weak #6).
    `encoded_get`/`encoded_list` serve the immutable docs under only the
    store's own lock; status becomes visible when the status sync
    publishes it, exactly like an apiserver read seeing the last write."""

    def __init__(self):
        self._lock = threading.RLock()
        self._objects: Dict[str, Dict[str, object]] = {}
        self._versions: Dict[Tuple[str, str], int] = {}
        # The published docs get their OWN lock: watchers (journal append,
        # watch fan-out) run under self._lock, and readers of the encoded
        # view must not wait on their I/O.
        self._docs_lock = threading.Lock()
        self._docs: Dict[Tuple[str, str], dict] = {}
        self._rv = itertools.count(1)
        self._watchers: Dict[str, List[Callable[[Event], None]]] = {}
        # Optional batch entry points, keyed by the per-event callback
        # they accompany: create_batch hands such a watcher the whole
        # event list in ONE call (one journal lock, one submit sweep)
        # instead of N per-event calls.
        self._batch_watchers: Dict[str, Dict[Callable, Callable]] = {}

    def _publish(self, kind: str, key: str, obj) -> Optional[dict]:
        from kueue_tpu.api import serialization
        try:
            doc = serialization.encode(kind, obj)
        except Exception:
            # Kinds without an encoder stay readable via get()/list().
            with self._docs_lock:
                self._docs.pop((kind, key), None)
            return None
        with self._docs_lock:
            self._docs[(kind, key)] = doc
        return doc

    def _unpublish(self, kind: str, key: str) -> None:
        with self._docs_lock:
            self._docs.pop((kind, key), None)

    def encoded_get(self, kind: str, key: str) -> Optional[dict]:
        """The immutable published doc for an object (None if absent)."""
        with self._docs_lock:
            return self._docs.get((kind, key))

    def encoded_list(self, kind: str,
                     namespace: Optional[str] = None) -> List[dict]:
        with self._docs_lock:
            docs = [self._docs[(k, key)]
                    for (k, key) in self._docs if k == kind]
        if namespace is not None:
            docs = [d for d in docs
                    if (d.get("metadata") or {}).get("namespace") == namespace]
        return docs

    # -- watch (informer analog) -------------------------------------------

    def watch(self, kind: str, callback: Callable[[Event], None],
              send_initial: bool = True,
              batch: Optional[Callable[[List[Event]], None]] = None) -> None:
        """Register a watcher; existing objects replay as ADDED first
        (informer initial list-then-watch semantics). `batch`, when
        given, receives a whole create_batch event list in one call
        instead of `callback` per event — same events, same order."""
        with self._lock:
            self._watchers.setdefault(kind, []).append(callback)
            if batch is not None:
                self._batch_watchers.setdefault(kind, {})[callback] = batch
            if send_initial:
                for key, obj in self._objects.get(kind, {}).items():
                    callback(Event(ADDED, kind, key, obj,
                                   self._versions[(kind, key)]))

    def unwatch(self, kind: str, callback: Callable[[Event], None]) -> None:
        """Deregister a watcher (watch-connection teardown)."""
        with self._lock:
            try:
                self._watchers.get(kind, []).remove(callback)
            except ValueError:
                pass
            self._batch_watchers.get(kind, {}).pop(callback, None)

    def _notify(self, event: Event) -> None:
        for cb in list(self._watchers.get(event.kind, [])):
            cb(event)

    def _notify_batch(self, kind: str, events: List[Event]) -> None:
        batch_fns = self._batch_watchers.get(kind, {})
        for cb in list(self._watchers.get(kind, [])):
            batch_fn = batch_fns.get(cb)
            if batch_fn is not None:
                batch_fn(events)
            else:
                for ev in events:
                    cb(ev)

    # -- CRUD (webhooked, like apiserver admission) ------------------------

    def create(self, kind: str, obj) -> object:
        with self._lock:
            defaulter = _DEFAULTERS.get(kind)
            if defaulter is not None:
                defaulter(obj)
            validate, _ = _VALIDATORS.get(kind, (None, None))
            if validate is not None:
                errs = validate(obj)
                if errs:
                    raise webhooks.ValidationError(errs)
            key = _obj_key(kind, obj)
            if key in self._objects.get(kind, {}):
                raise ValueError(f"{kind} {key} already exists")
            rv = next(self._rv)
            self._objects.setdefault(kind, {})[key] = obj
            self._versions[(kind, key)] = rv
            self._publish(kind, key, obj)
            self._notify(Event(ADDED, kind, key, obj, rv))
            return obj

    def create_batch(self, kind: str, objs) -> List[object]:
        """Create a burst of objects of one kind in one pass: one lock
        acquisition, one validation sweep (structurally identical
        workloads validate once — validation is a pure function of the
        fields it reads), and one batched watch flush, instead of N
        decode→webhook→fan-out round trips.

        Error semantics match the per-object loop: on a validation or
        key-collision failure the already-created prefix stays created
        (its events still flush) and the error propagates.

        KUEUE_TPU_NO_BATCH_INGEST=1 reverts to N create() calls."""
        from kueue_tpu import knobs
        if knobs.flag("KUEUE_TPU_NO_BATCH_INGEST"):
            out = []
            for obj in objs:  # the per-object twin, on purpose
                one = self.create(kind, obj)  # kueuelint: disable=PERF01
                out.append(one)
            return out
        from kueue_tpu.api import serialization
        out: List[object] = []
        events: List[Event] = []
        defaulter = _DEFAULTERS.get(kind)
        validate, _ = _VALIDATORS.get(kind, (None, None))
        exemplar = None  # last fully-validated workload (dedup anchor)
        exemplar_doc = None  # its published doc (encode-clone anchor)
        with self._lock:
            try:
                for obj in objs:
                    if defaulter is not None:
                        defaulter(obj)
                    cloned = False
                    if validate is not None:
                        if kind == KIND_WORKLOAD and exemplar is not None \
                                and _workload_validation_equal(obj, exemplar):
                            # Equal on every field validate_workload
                            # reads — the exemplar's (empty) verdict
                            # stands for this object too.
                            cloned = True
                        else:
                            errs = validate(obj)
                            if errs:
                                raise webhooks.ValidationError(errs)
                            if kind == KIND_WORKLOAD:
                                exemplar = obj
                    key = _obj_key(kind, obj)
                    if key in self._objects.get(kind, {}):
                        raise ValueError(f"{kind} {key} already exists")
                    rv = next(self._rv)
                    self._objects.setdefault(kind, {})[key] = obj
                    self._versions[(kind, key)] = rv
                    if cloned and exemplar_doc is not None:
                        # Validation-equal ⇒ encode-equal on podSets:
                        # publish a structural clone of the exemplar's
                        # doc instead of re-encoding the pod sets.
                        doc = serialization.encode_workload_cloned(
                            obj, exemplar_doc)
                        with self._docs_lock:
                            self._docs[(kind, key)] = doc
                    else:
                        doc = self._publish(kind, key, obj)
                        if kind == KIND_WORKLOAD and obj is exemplar:
                            exemplar_doc = doc
                    events.append(Event(ADDED, kind, key, obj, rv))
                    out.append(obj)
            finally:
                if events:
                    self._notify_batch(kind, events)
        return out

    def update(self, kind: str, obj) -> object:
        with self._lock:
            key = _obj_key(kind, obj)
            old = self._objects.get(kind, {}).get(key)
            if old is None:
                raise KeyError(f"{kind} {key} not found")
            _, validate_update = _VALIDATORS.get(kind, (None, None))
            if validate_update is not None and old is not obj:
                errs = validate_update(obj, old)
                if errs:
                    raise webhooks.ValidationError(errs)
            rv = next(self._rv)
            self._objects[kind][key] = obj
            self._versions[(kind, key)] = rv
            self._publish(kind, key, obj)
            self._notify(Event(MODIFIED, kind, key, obj, rv))
            return obj

    def update_status(self, kind: str, obj) -> object:
        """Status writes bypass spec validation (the /status subresource)."""
        with self._lock:
            key = _obj_key(kind, obj)
            if key not in self._objects.get(kind, {}):
                raise KeyError(f"{kind} {key} not found")
            rv = next(self._rv)
            self._objects[kind][key] = obj
            self._versions[(kind, key)] = rv
            self._publish(kind, key, obj)
            self._notify(Event(MODIFIED, kind, key, obj, rv))
            return obj

    def delete(self, kind: str, key: str) -> Optional[object]:
        with self._lock:
            obj = self._objects.get(kind, {}).pop(key, None)
            if obj is None:
                return None
            rv = next(self._rv)
            self._versions.pop((kind, key), None)
            self._unpublish(kind, key)
            self._notify(Event(DELETED, kind, key, obj, rv))
            return obj

    def get(self, kind: str, key: str) -> Optional[object]:
        with self._lock:
            return self._objects.get(kind, {}).get(key)

    def list(self, kind: str, namespace: Optional[str] = None) -> List[object]:
        with self._lock:
            objs = list(self._objects.get(kind, {}).values())
        if namespace is not None:
            objs = [o for o in objs
                    if getattr(o, "namespace", None) == namespace]
        return objs

    def resource_version(self, kind: str, key: str) -> Optional[int]:
        with self._lock:
            return self._versions.get((kind, key))


class StoreAdapter:
    """Mirrors store events into a Framework (core controllers analog).

    Watches every kueue kind and applies creates/updates/deletes to the
    runtime's queues and cache, like pkg/controller/core's reconcilers;
    after each scheduling pass, `sync_status` writes workload status back
    to the store (the SSA admission-status patch analog,
    workload.go:416-422).
    """

    def __init__(self, store: Store, framework):
        self.store = store
        self.fw = framework
        # Last-published status fingerprint per workload: unchanged status
        # is not re-published (the reference's SSA patch is a no-op server
        # side; here a no-op write would still fan out watch events and
        # append journal lines every tick).
        self._published: Dict[str, tuple] = {}
        store.watch(KIND_RESOURCE_FLAVOR, self._on_flavor)
        store.watch(KIND_CLUSTER_QUEUE, self._on_cluster_queue)
        store.watch(KIND_LOCAL_QUEUE, self._on_local_queue)
        store.watch(KIND_WORKLOAD_PRIORITY_CLASS, self._on_priority_class)
        store.watch(KIND_ADMISSION_CHECK, self._on_admission_check)
        store.watch(KIND_COHORT, self._on_cohort)
        store.watch(KIND_WORKLOAD, self._on_workload,
                    batch=self._on_workload_batch)

    def _on_flavor(self, ev: Event) -> None:
        if ev.type in (ADDED, MODIFIED):
            self.fw.create_resource_flavor(ev.obj)
        else:
            self.fw.delete_resource_flavor(ev.obj.name)

    def _on_cluster_queue(self, ev: Event) -> None:
        if ev.type == ADDED:
            self.fw.create_cluster_queue(ev.obj)
        elif ev.type == MODIFIED:
            self.fw.update_cluster_queue(ev.obj)
        else:
            self.fw.delete_cluster_queue(ev.obj.name)

    def _on_local_queue(self, ev: Event) -> None:
        if ev.type == ADDED:
            self.fw.create_local_queue(ev.obj)
        elif ev.type == MODIFIED:
            self.fw.update_local_queue(ev.obj)
        else:
            self.fw.delete_local_queue(ev.obj)

    def _on_cohort(self, ev: Event) -> None:
        if ev.type in (ADDED, MODIFIED):
            self.fw.create_cohort(ev.obj)
        else:
            self.fw.delete_cohort(ev.obj.name)

    def _on_priority_class(self, ev: Event) -> None:
        if ev.type in (ADDED, MODIFIED):
            self.fw.create_workload_priority_class(ev.obj)

    def _on_admission_check(self, ev: Event) -> None:
        if ev.type == ADDED:
            self.fw.create_admission_check(ev.obj)
        elif ev.type == MODIFIED:
            self.fw.update_admission_check(ev.obj)

    def _on_workload(self, ev: Event) -> None:
        if ev.type == ADDED:
            if ev.obj.has_quota_reservation or ev.obj.is_finished:
                # Only a durable-journal replay surfaces an ADDED workload
                # that already holds a reservation (live creates gain
                # status later, via update_status): rebuild instead of
                # re-queueing (cache.go:295-328).
                self.fw.restore_workload(ev.obj)
            else:
                self.fw.submit(ev.obj)
        elif ev.type == MODIFIED:
            cur = self.fw.workloads.get(ev.key)
            if cur is ev.obj:
                return  # our own status publish round-tripping
            # Shared-journal takeover replay (the standby attaching the
            # dead leader's journal) — the only source of MODIFIED events
            # carrying a DIFFERENT object (live status syncs publish the
            # framework's own instance, caught above). The recorded state
            # supersedes whatever this replica holds: REBUILD from it
            # (cache.go:295-328 semantics), never re-admit through the
            # scheduler. This must also process finish/evict transitions —
            # a replayed admitted-then-finished history would otherwise
            # leave the finished workload charging quota (and topology
            # slots) forever on the standby.
            if cur is not None:
                self.fw.delete_workload(cur)
            if ev.obj.is_finished or ev.obj.has_quota_reservation:
                self.fw.restore_workload(ev.obj)
            elif ev.obj.active:
                self.fw.submit(ev.obj)
            else:
                self.fw.workloads[ev.key] = ev.obj  # deactivated: record
        elif ev.type == DELETED:
            self.fw.delete_workload(ev.obj)

    def _on_workload_batch(self, events: List[Event]) -> None:
        """Batched workload fan-out (Store.create_batch): consecutive
        fresh pending ADDED events funnel through ONE Framework.submit_batch
        — one queue-manager lock, one dirty mark per cohort — instead of N
        submit() calls. The store already ran defaulting+validation at
        create, and validation is a pure check (submit's docstring), so
        validate=False here is decision-identical to the per-event path.
        Anything else (restores, MODIFIED, DELETED) flushes the run first
        and takes the per-event handler, preserving event order."""
        run: List[Workload] = []

        def flush():
            if run:
                self.fw.submit_batch(run, validate=False)
                run.clear()

        for ev in events:
            if ev.type == ADDED and not (
                    ev.obj.has_quota_reservation or ev.obj.is_finished):
                run.append(ev.obj)
            else:
                flush()
                self._on_workload(ev)
        flush()

    @staticmethod
    def _status_fingerprint(wl: Workload) -> tuple:
        rs = wl.requeue_state
        return (
            # Admission identity: a re-admission to another CQ (same
            # conditions shape) must republish.
            wl.admission.cluster_queue if wl.admission is not None else None,
            wl.admission is not None and tuple(
                (psa.name, psa.count) + tuple(sorted(psa.flavors.items()))
                for psa in wl.admission.pod_set_assignments),
            wl.active,
            tuple((c.type, c.status, c.reason, c.message,
                   c.last_transition_time) for c in wl.conditions),
            tuple(sorted(wl.reclaimable_pods.items())),
            tuple(sorted((k, s.state, s.message)
                         for k, s in wl.admission_check_states.items())),
            (rs.count, rs.requeue_at) if rs is not None else None,
        )

    def sync_status(self, collect: Optional[list] = None) -> None:
        """Write workload status back (SSA apply analog). The runtime owns
        the status fields; the store version is the published view.
        `collect` (when given) receives each workload published THIS call
        — the replica runtime ships exactly those statuses back to the
        parent deployment's read-surface Store."""
        published = self._published
        for wl in list(self.fw.workloads.values()):
            key = _obj_key(KIND_WORKLOAD, wl)
            fp = self._status_fingerprint(wl)
            if published.get(key) == fp:
                continue
            if self.store.get(KIND_WORKLOAD, key) is not None:
                self.store.update_status(KIND_WORKLOAD, wl)
                published[key] = fp
                if collect is not None:
                    collect.append(wl)
        if len(published) > 2 * len(self.fw.workloads) + 64:
            live = {_obj_key(KIND_WORKLOAD, wl)
                    for wl in self.fw.workloads.values()}
            for key in [k for k in published if k not in live]:
                del published[key]

    def tick(self) -> int:
        """One scheduling cycle + status publication."""
        admitted = self.fw.tick()
        self.sync_status()
        return admitted
