"""Visibility API: on-demand pending-workload listings.

Counterpart of reference pkg/visibility/ (the embedded
visibility.kueue.x-k8s.io apiserver, api/rest/pending_workloads_cq.go:60-91)
and the QueueVisibility snapshot workers
(clusterqueue_controller.go:685-720): ordered pending-workload views per
ClusterQueue or LocalQueue with positions and priorities, straight from the
queue manager's heaps.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from kueue_tpu.queue.manager import Manager
from kueue_tpu.tracing import ExplainStore


@dataclass
class PendingWorkloadInfo:
    name: str
    namespace: str
    local_queue: str
    priority: int
    position_in_cluster_queue: int
    position_in_local_queue: int
    # Admission explainability (?explain=true): the workload's recorded
    # scheduling attempts — every flavor tried with its verdict, topology
    # placement, final reason. None unless explain was requested.
    decisions: Optional[List[dict]] = field(default=None)


class VisibilityServer:
    def __init__(self, queues: Manager, max_count: int = 4000,
                 explain: Optional[ExplainStore] = None):
        self.queues = queues
        self.max_count = max_count
        # The scheduler's decision-record store (scheduler.explain);
        # None = the explainability surface reports no history.
        self.explain = explain

    def pending_workloads_in_cq(self, cq_name: str, offset: int = 0,
                                limit: Optional[int] = None,
                                explain: bool = False,
                                ) -> List[PendingWorkloadInfo]:
        """Pending workloads of a ClusterQueue in admission order."""
        cq = self.queues.cluster_queues.get(cq_name)
        if cq is None:
            return []
        limit = self.max_count if limit is None else limit
        # Heap order first (admission order), then the parking lot.
        items = sorted(cq.heap.items(),
                       key=lambda wi: (-wi.obj.priority,
                                       self.queues.ordering.queue_order_time(wi.obj)))
        items += sorted(cq.inadmissible.values(),
                        key=lambda wi: (-wi.obj.priority,
                                        self.queues.ordering.queue_order_time(wi.obj)))
        out: List[PendingWorkloadInfo] = []
        lq_positions = {}
        for pos, wi in enumerate(items):
            lq_key = f"{wi.obj.namespace}/{wi.obj.queue_name}"
            lq_pos = lq_positions.get(lq_key, 0)
            lq_positions[lq_key] = lq_pos + 1
            if pos < offset or len(out) >= limit:
                continue
            decisions = None
            if explain and self.explain is not None:
                decisions = self.explain.for_workload(wi.key)
            out.append(PendingWorkloadInfo(
                name=wi.obj.name, namespace=wi.obj.namespace,
                local_queue=wi.obj.queue_name, priority=wi.obj.priority,
                position_in_cluster_queue=pos,
                position_in_local_queue=lq_pos,
                decisions=decisions))
        return out

    def pending_workloads_in_lq(self, namespace: str, lq_name: str,
                                offset: int = 0,
                                limit: Optional[int] = None,
                                explain: bool = False,
                                ) -> List[PendingWorkloadInfo]:
        lq = self.queues.local_queues.get(f"{namespace}/{lq_name}")
        if lq is None:
            return []
        all_cq = self.pending_workloads_in_cq(lq.cluster_queue)
        mine = [p for p in all_cq
                if p.namespace == namespace and p.local_queue == lq_name]
        limit = self.max_count if limit is None else limit
        page = mine[offset:offset + limit]
        if explain and self.explain is not None:
            # Materialize decision records AFTER the LQ filter + paging:
            # the owning CQ may hold thousands of rows this listing
            # discards, and this runs under the API server's runtime
            # lock (a scheduler tick waits on it).
            for p in page:
                p.decisions = self.explain.for_workload(
                    f"{p.namespace}/{p.name}")
        return page


class QueueVisibilitySnapshotter:
    """Periodic top-N pending-workload snapshots into ClusterQueue status
    (reference: clusterqueue_controller.go:685-720 — the QueueVisibility
    snapshot workers — gated by the QueueVisibility feature and configured
    by queueVisibility.clusterQueues.maxCount / updateIntervalSeconds).

    Drive `maybe_update(now)` from the runtime loop; `snapshot(cq)` reads
    the last published view (the CQ .status.pendingWorkloadsStatus analog).
    """

    def __init__(self, queues: Manager, max_count: int = 10,
                 update_interval_seconds: float = 5.0):
        self.queues = queues
        self.max_count = max_count
        self.update_interval = update_interval_seconds
        self._server = VisibilityServer(queues, max_count=max_count)
        self._snapshots: dict = {}
        self._last_update: Optional[float] = None

    def maybe_update(self, now: float) -> bool:
        if (self._last_update is not None
                and now - self._last_update < self.update_interval):
            return False
        self._last_update = now
        self._snapshots = {
            name: self._server.pending_workloads_in_cq(
                name, limit=self.max_count)
            for name in self.queues.cluster_queues
        }
        return True

    def snapshot(self, cq_name: str) -> List[PendingWorkloadInfo]:
        return self._snapshots.get(cq_name, [])
