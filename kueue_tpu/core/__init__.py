"""Core state layer: workload resource model, admitted-state cache, snapshots."""

from kueue_tpu.core.workload import (
    WorkloadInfo,
    PodSetResources,
    AssignmentClusterQueueState,
    WorkloadOrdering,
)
from kueue_tpu.core.cache import Cache, CachedClusterQueue, Cohort
from kueue_tpu.core.snapshot import Snapshot
