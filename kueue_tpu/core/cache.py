"""Admitted-workload cache: quota state per ClusterQueue and cohort.

Counterpart of reference pkg/cache/: mirrors workloads holding quota into
per-ClusterQueue usage maps, supports optimistic assume/forget during
admission (cache.go:498-546), and produces per-tick snapshots that the
solver consumes (snapshot.go:95-201). LendingLimit guaranteed-quota math
follows clusterqueue.go:211-229,583-629.

FlavorResourceQuantities is `{flavor: {resource: int}}` throughout.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Optional, Set

from kueue_tpu import features
from kueue_tpu.api.types import (
    ClusterQueue,
    ClusterQueuePreemption,
    FlavorFungibility,
    LocalQueue,
    ResourceFlavor,
    ResourceGroup,
    StopPolicy,
    Workload,
)
from kueue_tpu.core.workload import WorkloadInfo
from kueue_tpu.utils import native_ledger

# Native fused-walk twin of _apply_usage/_lq_apply (kueue_tpu/native/
# ledger.cpp); None falls back to the pure-Python walks below.
_ledger = native_ledger.load()


def native_assume_available() -> bool:
    """True when the C++ bulk-assume walk is built. The scheduler's CSR
    commit defaults to ON exactly when this is False (measured on the
    northstar shape: the C++ per-triple walk beats Python-orchestrated
    numpy aggregation at ~1k admissions/tick, while the aggregation
    beats the pure-Python fallback); KUEUE_TPU_CSR_ASSUME=1/0 forces."""
    return _ledger is not None \
        and getattr(_ledger, "assume_batch", None) is not None

FlavorResourceQuantities = Dict[str, Dict[str, int]]


def frq_clone(q: FlavorResourceQuantities) -> FlavorResourceQuantities:
    return {f: dict(r) for f, r in q.items()}


def frq_add(dst: FlavorResourceQuantities, src: FlavorResourceQuantities) -> None:
    for f, res in src.items():
        d = dst.setdefault(f, {})
        for r, v in res.items():
            d[r] = d.get(r, 0) + v


class Cohort:
    """A set of ClusterQueues that can borrow from each other.

    `requestable_resources` / `usage` are populated only on snapshots
    (reference: pkg/cache/clusterqueue.go:78-90).

    With hierarchical cohorts (KEP-79) a cohort may carry a spec: its own
    shareable quota, per-(flavor,resource) borrowing/lending limits, and a
    parent link forming a tree; `parent`/`children` are populated on
    snapshots. A spec-less cohort is a flat 2-level cohort, byte-identical
    to the reference's semantics.
    """

    __slots__ = ("name", "members", "requestable_resources", "usage",
                 "allocatable_generation", "spec", "parent", "children",
                 "_root_name", "_is_hier", "_tree_cap", "_sorted_members")

    def __init__(self, name: str, spec=None):
        self.name = name
        self.members: Set["CachedClusterQueue"] = set()
        self.requestable_resources: FlavorResourceQuantities = {}
        self.usage: FlavorResourceQuantities = {}
        self.allocatable_generation = 0
        self.spec = spec  # Optional[CohortSpec]
        self.parent: Optional["Cohort"] = None
        self.children: List["Cohort"] = []
        # Lazy memos for the admission cycle's per-entry walks. Parent
        # links are fixed once a snapshot's tree is built (hierarchy
        # changes rebuild the snapshot wholesale), so both are stable for
        # the object's lifetime.
        self._root_name: Optional[str] = None
        self._is_hier: Optional[bool] = None
        # Whole-structure lendable capacity (hierarchy.tree_capacity),
        # memoized on roots: it depends only on specs and member quotas,
        # both structural (changes rebuild the snapshot's cohorts).
        self._tree_cap: Optional[dict] = None
        # Name-sorted member list (the deterministic preemption walk),
        # memoized because tree_cluster_queues runs once per preempting
        # head per tick. Every `members` mutation must clear it
        # (invalidate_memos, or note_members_changed where the
        # structural memos deliberately survive).
        self._sorted_members: Optional[List["CachedClusterQueue"]] = None

    # -- hierarchy helpers (KEP-79) -----------------------------------------

    def root(self) -> "Cohort":
        node = self
        while node.parent is not None:
            node = node.parent
        return node

    def invalidate_memos(self) -> None:
        """Reset the lazy walk memos. Cache-side Cohort objects mutate in
        place on membership/spec updates (snapshot-side clones are
        rebuilt wholesale instead), so every cache-side mutation path
        must call this or later readers would see stale roots/caps."""
        self._root_name = None
        self._is_hier = None
        self._tree_cap = None
        self._sorted_members = None
        root = self.root()
        if root is not self:
            root._tree_cap = None

    def note_members_changed(self) -> None:
        """Invalidate only the membership memo: the snapshot mirror swaps
        re-cloned members in place every refresh, which moves no
        structural state (roots, tree capacity) — those memos survive."""
        self._sorted_members = None

    def sorted_members(self) -> List["CachedClusterQueue"]:
        """`members` in NAME order (see tree_cluster_queues for why the
        walk must be deterministic), memoized until membership changes.

        KUEUE_TPU_FUZZ_MUTATION=unsorted-members reverts to the raw
        identity-hashed set iteration (the PR 8 victim-flip bug shape) —
        an oracle-mutation drill for the fuzz harness: tests/test_fuzz
        proves the decision-identity fuzzer CATCHES this bug class
        within a bounded seed budget. Inert unless the env gate is set;
        never set it in production."""
        sm = self._sorted_members
        if sm is None:
            from kueue_tpu import knobs
            if knobs.raw("KUEUE_TPU_FUZZ_MUTATION") == \
                    "unsorted-members":
                # The armed oracle-mutation drill IS the PR 8 bug on
                # purpose; DET01 catching this exact line is asserted by
                # tests/test_det_taint.py (the static half of the drill).
                sm = self._sorted_members = list(self.members)  # kueuelint: disable=DET01
            else:
                sm = self._sorted_members = sorted(
                    self.members, key=lambda c: c.name)
        return sm

    @property
    def root_name(self) -> str:
        rn = self._root_name
        if rn is None:
            rn = self._root_name = self.root().name
        return rn

    def tree_cap(self) -> dict:
        """Whole-structure lendable capacity of this cohort's tree
        (hierarchy.tree_capacity), memoized on the root: it depends only
        on specs and member quotas, both structural — any change rebuilds
        the snapshot's cohorts, so the memo lives as long as it is
        valid. This is the single home of that invalidation contract
        (KEP-1714 share denominators read it from several places)."""
        root = self.root()
        cap = root._tree_cap
        if cap is None:
            from kueue_tpu.core.hierarchy import tree_capacity
            cap = root._tree_cap = tree_capacity(root)
        return cap

    def is_hierarchical(self) -> bool:
        """True when the tree extends beyond a flat 2-level cohort."""
        h = self._is_hier
        if h is None:
            node = self.root()
            h = self._is_hier = (
                node is not self or bool(self.children)
                or (self.spec is not None
                    and bool(self.spec.resource_groups)))
        return h

    def tree_cluster_queues(self) -> List["CachedClusterQueue"]:
        """All member CQs in the subtree rooted here (preemption and
        reclaim act across the whole structure).

        Members are yielded in NAME order: `members` is an identity-
        hashed set, and raw iteration order varies with memory layout —
        which leaks into preemption candidate-queue order and flips the
        victim choice between equal-share ClusterQueues from one run to
        the next (caught by the fair churn goldens). Every
        decision-identity contract (goldens, HA replay, the shards=N ==
        shards=1 gate) needs this walk deterministic."""
        out: List["CachedClusterQueue"] = []
        stack = [self]
        while stack:
            node = stack.pop()
            out.extend(node.sorted_members())
            stack.extend(node.children)
        return out

    def own_quota(self, flavor: str, resource: str):
        """The cohort-level ResourceQuota for (flavor, resource), or None."""
        if self.spec is None:
            return None
        for rg in self.spec.resource_groups:
            if resource not in rg.covered_resources:
                continue
            for fq in rg.flavors:
                if fq.name == flavor:
                    return fq.resources_dict.get(resource)
        return None


class CachedClusterQueue:
    """Internal ClusterQueue state (reference: pkg/cache/clusterqueue.go:44-75)."""

    def __init__(self, spec: ClusterQueue,
                 resource_flavors: Dict[str, ResourceFlavor]):
        self.name = spec.name
        self.cohort: Optional[Cohort] = None
        self.cohort_name = spec.cohort
        self.resource_groups: List[ResourceGroup] = []
        self.rg_by_resource: Dict[str, ResourceGroup] = {}
        self.usage: FlavorResourceQuantities = {}
        self.admitted_usage: FlavorResourceQuantities = {}
        self.workloads: Dict[str, WorkloadInfo] = {}
        self.namespace_selector = spec.namespace_selector
        self.preemption: ClusterQueuePreemption = ClusterQueuePreemption()
        self.flavor_fungibility: FlavorFungibility = FlavorFungibility()
        self.admission_checks: Set[str] = set()
        self.fair_weight: float = 1.0
        self.guaranteed_quota: FlavorResourceQuantities = {}
        # Bumped when admitted workloads are deleted or resource groups change,
        # invalidating flavor-search resume state (clusterqueue.go:62-63).
        self.allocatable_generation = 1
        # Bumped on every usage mutation; the incremental tensor encoder
        # (solver/schema.py UsageEncoder) re-reads only rows whose version
        # moved, replacing the reference's full per-tick snapshot copy cost
        # (snapshot.go:95-129).
        self.usage_version = 0
        # Mirror dirty sinks (set by the owning Cache; None on snapshot
        # clones): every usage_version bump records this CQ's name so
        # SnapshotMirror.refresh visits only moved CQs instead of
        # version-scanning all of them.
        self._dirty_sinks = None
        self.has_missing_flavors = False
        self.is_stopped = False
        self.update(spec, resource_flavors)

    # -- spec mirroring -----------------------------------------------------

    def update(self, spec: ClusterQueue,
               resource_flavors: Dict[str, ResourceFlavor]) -> None:
        if [rg for rg in self.resource_groups] != list(spec.resource_groups):
            self.allocatable_generation += 1
        self.cohort_name = spec.cohort
        self.resource_groups = list(spec.resource_groups)
        self.rg_by_resource = {}
        for rg in self.resource_groups:
            for r in rg.covered_resources:
                self.rg_by_resource[r] = rg
        self.namespace_selector = spec.namespace_selector
        self.is_stopped = spec.stop_policy != StopPolicy.NONE
        self.admission_checks = set(spec.admission_checks)
        self.preemption = spec.preemption
        self.flavor_fungibility = spec.flavor_fungibility
        self.fair_weight = (spec.fair_sharing.weight
                            if spec.fair_sharing is not None else 1.0)

        # Prune usage for removed flavors/resources; keep existing counts.
        new_usage: FlavorResourceQuantities = {}
        new_admitted: FlavorResourceQuantities = {}
        for rg in self.resource_groups:
            for fq in rg.flavors:
                new_usage[fq.name] = {
                    r: self.usage.get(fq.name, {}).get(r, 0)
                    for r, _ in fq.resources
                }
                new_admitted[fq.name] = {
                    r: self.admitted_usage.get(fq.name, {}).get(r, 0)
                    for r, _ in fq.resources
                }
        self.usage = new_usage
        self.admitted_usage = new_admitted
        self.usage_version += 1
        if self._dirty_sinks is not None:
            self._mark_dirty()

        self.update_with_flavors(resource_flavors)

        # Guaranteed quota = nominal - lendingLimit when lending enabled
        # (reference: clusterqueue.go:211-229).
        self.guaranteed_quota = {}
        if features.enabled(features.LENDING_LIMIT):
            for rg in self.resource_groups:
                for fq in rg.flavors:
                    for rname, quota in fq.resources:
                        if quota.lending_limit is not None:
                            self.guaranteed_quota.setdefault(fq.name, {})[rname] = \
                                quota.nominal - quota.lending_limit

    def update_with_flavors(self, resource_flavors: Dict[str, ResourceFlavor]) -> None:
        self.has_missing_flavors = any(
            fq.name not in resource_flavors
            for rg in self.resource_groups for fq in rg.flavors)

    def active(self) -> bool:
        return not self.has_missing_flavors and not self.is_stopped

    # -- label keys per resource group (affinity mask input) ---------------

    def label_keys(self, rg: ResourceGroup,
                   resource_flavors: Dict[str, ResourceFlavor]) -> Set[str]:
        keys: Set[str] = set()
        for fq in rg.flavors:
            flv = resource_flavors.get(fq.name)
            if flv is not None:
                keys.update(k for k, _ in flv.node_labels)
        return keys

    # -- quota math (reference: clusterqueue.go:583-629) --------------------

    def _guaranteed(self, flavor: str, resource: str) -> int:
        if not features.enabled(features.LENDING_LIMIT):
            return 0
        return self.guaranteed_quota.get(flavor, {}).get(resource, 0)

    def requestable_cohort_quota(self, flavor: str, resource: str) -> int:
        """Total quota requestable by this CQ in its cohort; includes own
        guaranteed (non-lendable) quota when LendingLimit is enabled."""
        assert self.cohort is not None
        avail = self.cohort.requestable_resources.get(flavor, {}).get(resource, 0)
        return avail + self._guaranteed(flavor, resource)

    def used_cohort_quota(self, flavor: str, resource: str) -> int:
        assert self.cohort is not None
        used = self.cohort.usage.get(flavor, {}).get(resource, 0)
        if features.enabled(features.LENDING_LIMIT):
            cq_used = self.usage.get(flavor, {}).get(resource, 0)
            used += min(cq_used, self._guaranteed(flavor, resource))
        return used

    def fit_in_cohort_fused(self, cycle_usage: FlavorResourceQuantities,
                            assignment_usage: FlavorResourceQuantities,
                            lending: bool):
        """Admission-cycle gate for flat cohorts, fused into one walk over
        the assignment's (flavor, resource) pairs. Returns (has_common,
        fits): `has_common` mirrors scheduler._has_common_flavor_resources
        (a pair is common when the cycle dict holds it, regardless of
        value), `fits` mirrors fit_in_cohort(_common_usage_sum(...)) —
        only common pairs are capacity-checked, against the same
        requestable/used cohort pools (clusterqueue.go:130-144,
        scheduler.go:213-233). `lending` is the caller-hoisted
        LendingLimit gate (one feature lookup per cycle, not per pair)."""
        has_common = False
        fits = True
        cohort = self.cohort
        creq = cohort.requestable_resources
        cuse = cohort.usage
        for flavor, resources in assignment_usage.items():
            cyc_f = cycle_usage.get(flavor)
            if cyc_f is None:
                continue
            creq_f = creq.get(flavor)
            cuse_f = cuse.get(flavor)
            for resource, value in resources.items():
                cv = cyc_f.get(resource)
                if cv is None:
                    continue
                has_common = True
                if not fits:
                    continue
                if creq_f is None:
                    # flavor not requestable in the cohort at all
                    # (fit_in_cohort's membership check).
                    fits = False
                    continue
                g = self.guaranteed_quota.get(flavor, {}).get(resource, 0) \
                    if lending else 0
                avail = creq_f.get(resource, 0) + g
                used = cuse_f.get(resource, 0) if cuse_f is not None else 0
                if lending:
                    used += min(
                        self.usage.get(flavor, {}).get(resource, 0), g)
                if avail - used < value + cv:
                    fits = False
        return has_common, fits

    def fit_in_cohort(self, q: FlavorResourceQuantities) -> bool:
        """reference: clusterqueue.go:130-144; hierarchical trees use the
        KEP-79 T-invariant walk instead of the flat capacity arithmetic."""
        if self.cohort is not None and self.cohort.is_hierarchical():
            from kueue_tpu.core.hierarchy import fits_in_hierarchy
            return fits_in_hierarchy(self, q)
        for flavor, resources in q.items():
            if self.cohort is None or flavor not in self.cohort.requestable_resources:
                return False
            for resource, value in resources.items():
                available = (self.requestable_cohort_quota(flavor, resource)
                             - self.used_cohort_quota(flavor, resource))
                if available < value:
                    return False
        return True

    def is_borrowing(self) -> bool:
        if self.cohort is None:
            return False
        for rg in self.resource_groups:
            for fq in rg.flavors:
                fusage = self.usage.get(fq.name)
                if not fusage:
                    continue
                for rname, quota in fq.resources:
                    if fusage.get(rname, 0) > quota.nominal:
                        return True
        return False

    # -- workload usage accounting -----------------------------------------

    def _update_usage(self, wi: WorkloadInfo, usage: FlavorResourceQuantities,
                      m: int) -> None:
        # Only (flavor, resource) pairs configured on this CQ are tracked
        # (reference: clusterqueue.go:473-485). The flat precomputed
        # triples replace the nested podset/dict walk on this hottest of
        # accounting paths.
        for flv, res, v in wi.usage_triples:
            fusage = usage.get(flv)
            if fusage is not None and res in fusage:
                fusage[res] += v * m

    def _update_cohort_usage(self, wi: WorkloadInfo, m: int) -> None:
        """Lending-aware cohort usage delta; must run after _update_usage
        (reference: clusterqueue.go:487-508)."""
        assert self.cohort is not None
        cohort_usage = self.cohort.usage
        own_usage = self.usage
        for flv, res, v in wi.usage_triples:
            fusage = cohort_usage.get(flv)
            if fusage is None or res not in fusage:
                continue
            after = own_usage.get(flv, {}).get(res, 0) - self._guaranteed(flv, res)
            before = after - v * m
            if before > 0:
                fusage[res] -= before
            if after > 0:
                fusage[res] += after

    def _apply_usage(self, wi: WorkloadInfo, m: int, cohort_too: bool,
                     admitted: bool) -> None:
        """One fused walk over the workload's usage triples updating the
        CQ usage, the admitted split, and (non-lending) the cohort usage
        together — this runs once per assume/forget/preemption-simulation
        step and the separate walks dominated the admit phase otherwise.
        The lending-limit cohort path stays a second walk because its
        before/after clamps must observe the fully-updated own usage
        (clusterqueue.go:487-508)."""
        triples = wi.usage_triples
        usage = self.usage
        adm = self.admitted_usage if admitted else None
        cohort = self.cohort if cohort_too else None
        if cohort is not None and features.enabled(features.LENDING_LIMIT):
            if _ledger is not None:
                _ledger.apply_triples(usage, adm, None, triples, m)
            else:
                for flv, res, v in triples:
                    fus = usage.get(flv)
                    if fus is not None and res in fus:
                        fus[res] += v * m
                    if adm is not None:
                        f2 = adm.get(flv)
                        if f2 is not None and res in f2:
                            f2[res] += v * m
            self._update_cohort_usage(wi, m)
            return
        cus = cohort.usage if cohort is not None else None
        if _ledger is not None:
            _ledger.apply_triples(usage, adm, cus, triples, m)
            return
        for flv, res, v in triples:
            d = v * m
            fus = usage.get(flv)
            if fus is not None and res in fus:
                fus[res] += d
            if adm is not None:
                f2 = adm.get(flv)
                if f2 is not None and res in f2:
                    f2[res] += d
            if cus is not None:
                f3 = cus.get(flv)
                if f3 is not None and res in f3:
                    f3[res] += d

    def _mark_dirty(self) -> None:
        sinks = self._dirty_sinks
        if sinks is not None:
            name = self.name
            for s in sinks:
                s.add(name)

    def add_workload_usage(self, wi: WorkloadInfo, *, cohort_too: bool = False,
                           admitted: bool = False) -> None:
        self.workloads[wi.key] = wi
        self.usage_version += 1
        self._mark_dirty()
        self._apply_usage(wi, 1, cohort_too and self.cohort is not None,
                          admitted)

    def remove_workload_usage(self, wi: WorkloadInfo, *, cohort_too: bool = False,
                              admitted: bool = False) -> None:
        self.workloads.pop(wi.key, None)
        self.usage_version += 1
        self._mark_dirty()
        self._apply_usage(wi, -1, cohort_too and self.cohort is not None,
                          admitted)


class Cache:
    """Thread-safe mirror of admitted workloads (reference: pkg/cache/cache.go)."""

    def __init__(self):
        self._lock = threading.RLock()
        self.cluster_queues: Dict[str, CachedClusterQueue] = {}
        # One dirty-name set per registered SnapshotMirror (see
        # CachedClusterQueue._mark_dirty).
        self._mirror_dirty_sinks: List[set] = []
        # Admitted-set event sinks (the solver's AdmittedArena): every
        # workload that starts/stops holding quota fires
        # note_admitted(info) / forget_admitted(key) under the cache
        # lock, so subscribers mirror exactly what the cache accounted.
        self._admitted_sinks: List = []
        self.cohorts: Dict[str, Cohort] = {}
        # Hierarchical-cohort specs (KEP-79); cohorts named only by
        # ClusterQueue.cohort need no spec and stay flat.
        self.cohort_specs: Dict[str, "CohortSpec"] = {}
        self.resource_flavors: Dict[str, ResourceFlavor] = {}
        self.local_queues: Dict[str, LocalQueue] = {}
        # Per-LocalQueue usage stats, maintained incrementally on every
        # workload add/delete (cache.go:607-658 keeps LocalQueueUsage the
        # same way) so LocalQueue status reads are O(1) instead of a
        # workload scan under the cache lock.
        self._lq_stats: Dict[str, dict] = {}
        self.assumed_workloads: Dict[str, str] = {}  # wl key -> cq name
        # Topology leaf occupancy (kueue_tpu/topology): empty (and
        # zero-overhead on every path below) until a ResourceFlavor
        # declares a TopologySpec.
        from kueue_tpu.topology.state import TopologyLedger
        self.topology = TopologyLedger()
        # Bumped on every *structural* change (ClusterQueue specs, cohort
        # specs, flavors) but NOT on workload churn. The batched solver's
        # ClusterQueue encoding and the incremental snapshot key on this
        # instead of recomputing a per-CQ generation tuple each tick.
        self.structure_version = 1

    # -- hierarchical cohorts (KEP-79) --------------------------------------

    def add_or_update_cohort_spec(self, spec) -> None:
        with self._lock:
            self.cohort_specs[spec.name] = spec
            self.structure_version += 1
            self._invalidate_allocatable()

    def delete_cohort_spec(self, name: str) -> None:
        with self._lock:
            if self.cohort_specs.pop(name, None) is not None:
                self.structure_version += 1
                self._invalidate_allocatable()

    def _invalidate_allocatable(self) -> None:
        # Tree structure changed: every flavor-search resume state and
        # every cached encoding keyed on allocatable generations is stale.
        for cq in self.cluster_queues.values():
            cq.allocatable_generation += 1

    # -- flavors ------------------------------------------------------------

    def add_or_update_resource_flavor(self, flavor: ResourceFlavor) -> None:
        with self._lock:
            self.structure_version += 1
            self.resource_flavors[flavor.name] = flavor
            self.topology.set_flavor(flavor)
            for cq in self.cluster_queues.values():
                cq.update_with_flavors(self.resource_flavors)

    def delete_resource_flavor(self, name: str) -> None:
        with self._lock:
            self.structure_version += 1
            self.resource_flavors.pop(name, None)
            self.topology.drop_flavor(name)
            for cq in self.cluster_queues.values():
                cq.update_with_flavors(self.resource_flavors)

    def register_dirty_sink(self, sink: set) -> None:
        """Subscribe a SnapshotMirror's dirty-name set: every CQ usage
        mutation adds the CQ's name, replacing the mirror's full version
        scan with a visit of just the moved CQs."""
        with self._lock:
            self._mirror_dirty_sinks.append(sink)
            for cq in self.cluster_queues.values():
                cq._dirty_sinks = self._mirror_dirty_sinks
                sink.add(cq.name)

    def unregister_dirty_sink(self, sink: set) -> None:
        """Detach a retired mirror's sink so abandoned mirrors neither
        pin their dirty sets nor add per-mutation overhead (a scheduler
        replacement over a long-lived cache re-registers its new one)."""
        with self._lock:
            try:
                self._mirror_dirty_sinks.remove(sink)
            except ValueError:
                pass

    def register_admitted_sink(self, sink) -> None:
        """Subscribe to admitted-set events. `sink` implements
        note_admitted(info) and forget_admitted(key); both run under the
        cache lock (keep them O(row))."""
        with self._lock:
            if sink not in self._admitted_sinks:
                self._admitted_sinks.append(sink)

    def unregister_admitted_sink(self, sink) -> None:
        with self._lock:
            try:
                self._admitted_sinks.remove(sink)
            except ValueError:
                pass

    def _note_admitted_sinks(self, wi: WorkloadInfo) -> None:
        for sink in self._admitted_sinks:
            sink.note_admitted(wi)

    def _forget_admitted_sinks(self, key: str) -> None:
        for sink in self._admitted_sinks:
            sink.forget_admitted(key)

    # -- cluster queues ------------------------------------------------------

    def add_cluster_queue(self, spec: ClusterQueue) -> CachedClusterQueue:
        with self._lock:
            if spec.name in self.cluster_queues:
                raise ValueError(f"ClusterQueue {spec.name} already exists")
            cq = CachedClusterQueue(spec, self.resource_flavors)
            cq._dirty_sinks = self._mirror_dirty_sinks
            self.cluster_queues[spec.name] = cq
            self.structure_version += 1
            self._update_cohort_membership(cq)
            return cq

    def update_cluster_queue(self, spec: ClusterQueue) -> None:
        with self._lock:
            cq = self.cluster_queues[spec.name]
            cq.update(spec, self.resource_flavors)
            self.structure_version += 1
            self._update_cohort_membership(cq)

    def delete_cluster_queue(self, name: str) -> None:
        with self._lock:
            cq = self.cluster_queues.pop(name, None)
            if cq is None:
                return
            self.structure_version += 1
            # Release the accounted workloads from their LocalQueue stats:
            # with the CQ gone, a later delete_workload can no longer find
            # them to subtract (the reference recomputes LQ usage from the
            # live cache, cache.go:607-658).
            for wi in cq.workloads.values():
                self._lq_note(wi, -1)
                if self._admitted_sinks:
                    self._forget_admitted_sinks(wi.key)
            if cq.cohort is not None:
                cq.cohort.members.discard(cq)
                cq.cohort.invalidate_memos()
                if not cq.cohort.members:
                    self.cohorts.pop(cq.cohort.name, None)

    def _update_cohort_membership(self, cq: CachedClusterQueue) -> None:
        if cq.cohort is not None and cq.cohort.name != cq.cohort_name:
            cq.cohort.members.discard(cq)
            cq.cohort.invalidate_memos()
            if not cq.cohort.members:
                self.cohorts.pop(cq.cohort.name, None)
            cq.cohort = None
        if cq.cohort_name:
            cohort = self.cohorts.get(cq.cohort_name)
            if cohort is None:
                cohort = Cohort(cq.cohort_name)
                self.cohorts[cq.cohort_name] = cohort
            cohort.members.add(cq)
            cohort.invalidate_memos()
            cq.cohort = cohort

    def set_external_usage(self, name: str, usage) -> None:
        """Overwrite a ClusterQueue's usage with an EXTERNALLY OWNED view
        (the multi-process replica runtime's ghost members: split-tree
        CQs scheduled by another replica, whose authoritative usage
        arrives through the pre-tick exchange). Rides the sanctioned
        mutation plumbing — usage_version bump + mirror dirty mark — so
        the snapshot mirror and the solver's usage tensors pick the new
        values up exactly like a local admission. No-ops when the view
        is unchanged (a quiescent remote tree must not dirty this
        replica's tick)."""
        with self._lock:
            cq = self.cluster_queues.get(name)
            if cq is None or cq.usage == usage:
                return
            cq.usage = {f: dict(res) for f, res in usage.items()}
            cq.usage_version += 1
            cq._mark_dirty()

    # -- local queues --------------------------------------------------------

    def add_local_queue(self, lq: LocalQueue) -> None:
        with self._lock:
            self.local_queues[lq.key] = lq
            # Adopt already-accounted workloads into the stats (one scan
            # at LQ creation; afterwards maintenance is incremental).
            stats = self._fresh_lq_stats()
            self._lq_stats[lq.key] = stats
            cq = self.cluster_queues.get(lq.cluster_queue)
            if cq is not None:
                for wi in cq.workloads.values():
                    if wi.obj.namespace == lq.namespace \
                            and wi.obj.queue_name == lq.name:
                        self._lq_apply(stats, wi, 1)

    def delete_local_queue(self, lq: LocalQueue) -> None:
        with self._lock:
            self.local_queues.pop(lq.key, None)
            self._lq_stats.pop(lq.key, None)

    @staticmethod
    def _fresh_lq_stats() -> dict:
        return {"reserving": 0, "admitted": 0,
                "reservation": {}, "admitted_usage": {},
                "admitted_keys": set()}

    @staticmethod
    def _lq_apply(stats: dict, wi: WorkloadInfo, sign: int,
                  admitted: Optional[bool] = None) -> None:
        stats["reserving"] += sign
        # The admitted split is keyed: a workload whose Admitted condition
        # flips between accounting and release must subtract exactly what
        # it added.
        key = wi.key
        if sign > 0:
            counted = wi.obj.is_admitted if admitted is None else admitted
            if counted:
                stats["admitted_keys"].add(key)
        else:
            counted = key in stats["admitted_keys"]
            if counted:
                stats["admitted_keys"].discard(key)
        if counted:
            stats["admitted"] += sign
        triples = wi.usage_triples
        if _ledger is not None:
            _ledger.lq_apply(stats["reservation"],
                             stats["admitted_usage"] if counted else None,
                             triples, sign)
            return
        for flv, res, v in triples:
            f = stats["reservation"].setdefault(flv, {})
            f[res] = f.get(res, 0) + sign * v
        if counted:
            for flv, res, v in triples:
                f = stats["admitted_usage"].setdefault(flv, {})
                f[res] = f.get(res, 0) + sign * v

    def _lq_note(self, wi: WorkloadInfo, sign: int,
                 admitted: Optional[bool] = None) -> None:
        key = f"{wi.obj.namespace}/{wi.obj.queue_name}"
        stats = self._lq_stats.get(key)
        if stats is None:
            return
        # Only workloads accounted in the LQ's own ClusterQueue count:
        # adoption (add_local_queue) scans that CQ alone, so adds and
        # subtracts must apply the same filter or a delete-and-recreate
        # pointing at a new CQ would go negative when an old-CQ workload
        # releases (cache.go:607-658 recomputes from the LQ's CQ).
        lq = self.local_queues.get(key)
        if lq is None or lq.cluster_queue != wi.cluster_queue:
            return
        self._lq_apply(stats, wi, sign, admitted)

    def cluster_queue_for(self, wl: Workload) -> Optional[str]:
        lq = self.local_queues.get(f"{wl.namespace}/{wl.queue_name}")
        return lq.cluster_queue if lq else None

    # -- workloads (reference: cache.go:330-546) ----------------------------

    def add_or_update_workload(self, wl: Workload) -> bool:
        with self._lock:
            if wl.admission is None:
                return False
            self._delete_workload_locked(wl)
            cq = self.cluster_queues.get(wl.admission.cluster_queue)
            if cq is None:
                return False
            wi = WorkloadInfo(wl, cluster_queue=cq.name)
            cq.add_workload_usage(wi, admitted=wl.is_admitted)
            self._lq_note(wi, 1)
            if self.topology.flavors:
                self.topology.charge(wl.admission, 1)
            if self._admitted_sinks:
                self._note_admitted_sinks(wi)
            return True

    def delete_workload(self, wl: Workload) -> Optional[WorkloadInfo]:
        """Returns the released WorkloadInfo when usage was actually
        accounted (None otherwise) — callers mirroring the release into
        incremental encoders must not subtract usage that was never added,
        and can reuse the info's precomputed totals for the mirroring."""
        with self._lock:
            return self._delete_workload_locked(wl)

    def _delete_workload_locked(self, wl: Workload) -> Optional[WorkloadInfo]:
        key = wl.key
        cq_name = self.assumed_workloads.get(key)
        if cq_name is None and wl.admission is not None:
            cq_name = wl.admission.cluster_queue
        if cq_name is None:
            return None
        released = None
        cq = self.cluster_queues.get(cq_name)
        if cq is not None and key in cq.workloads:
            wi = cq.workloads[key]
            cq.remove_workload_usage(wi, admitted=wl.is_admitted)
            self._lq_note(wi, -1)
            if self.topology.flavors:
                self.topology.charge(wl.admission, -1)
            # Quota was freed: resume states against this CQ are now stale.
            cq.allocatable_generation += 1
            if self._admitted_sinks:
                self._forget_admitted_sinks(key)
            released = wi
        self.assumed_workloads.pop(key, None)
        return released

    def assume_workload(self, wl: Workload) -> WorkloadInfo:
        """Optimistically account a just-admitted workload before the API
        write lands (reference: cache.go:498-524). Returns the accounted
        info so callers can mirror the same totals without re-deriving."""
        with self._lock:
            if wl.admission is None:
                raise ValueError("workload has no admission")
            key = wl.key
            if key in self.assumed_workloads:
                raise ValueError(f"workload {key} already assumed")
            cq = self.cluster_queues.get(wl.admission.cluster_queue)
            if cq is None:
                raise ValueError(f"ClusterQueue {wl.admission.cluster_queue} not found")
            wi = WorkloadInfo(wl, cluster_queue=cq.name)
            adm = wl.is_admitted
            cq.add_workload_usage(wi, admitted=adm)
            self._lq_note(wi, 1, adm)
            self.assumed_workloads[key] = cq.name
            if self.topology.flavors:
                self.topology.charge(wl.admission, 1)
            if self._admitted_sinks:
                self._note_admitted_sinks(wi)
            return wi

    def assume_workloads(self, items, fast: bool = False) -> list:
        """Bulk assume under ONE lock acquisition: the admission cycle
        commits all of a tick's admissions at cycle end (the cycle's fit
        math runs against the frozen snapshot plus its own side-tracked
        reservations, so nothing in-cycle reads the cache — see
        scheduler._flush_assumes). `items` is
        [(workload, triples, info, admitted)]:

        - `triples` — precomputed admission usage flattening, or None to
          derive lazily (reclaim/partial-admission cases);
        - `info` — an existing WorkloadInfo to account (the scheduler
          entry's own; only passed when `triples` is set, i.e. the
          admission usage equals the spec-based totals the info already
          memoized). None constructs a fresh info;
        - `admitted` — the Admitted-condition verdict the caller just
          computed, or None to read it off the workload.

        `fast=True` asserts every item carries non-None triples/info/
        admitted AND info.cluster_queue == workload.admission.cluster_queue
        (the scheduler's flush guarantees this by construction) — the
        commit loop then runs in ONE native call (ledger.cpp assume_batch).

        Returns one entry per workload: the accounted WorkloadInfo on
        success, an error string otherwise."""
        out = []
        with self._lock:
            if fast and _ledger is not None \
                    and getattr(_ledger, "assume_batch", None) is not None:
                items = items if isinstance(items, list) else list(items)
                _ledger.assume_batch(
                    self.cluster_queues, self.assumed_workloads,
                    self.local_queues, self._lq_stats, items, out)
                if self.topology.flavors:
                    for (wl, _, _, _), res in zip(items, out):
                        if not isinstance(res, str):
                            self.topology.charge(wl.admission, 1)
                if self._admitted_sinks:
                    for res in out:
                        if not isinstance(res, str):
                            self._note_admitted_sinks(res)
                return out
            charge_topo = bool(self.topology.flavors)
            for wl, triples, info, admitted in items:
                if wl.admission is None:
                    out.append("workload has no admission")
                    continue
                key = wl.key
                if key in self.assumed_workloads:
                    out.append(f"workload {key} already assumed")
                    continue
                cq = self.cluster_queues.get(wl.admission.cluster_queue)
                if cq is None:
                    out.append(
                        f"ClusterQueue {wl.admission.cluster_queue} not found")
                    continue
                if info is not None and info.cluster_queue == cq.name:
                    wi = info
                else:
                    wi = WorkloadInfo(wl, cluster_queue=cq.name)
                if triples is not None:
                    wi._usage_triples = triples
                adm = wl.is_admitted if admitted is None else admitted
                cq.add_workload_usage(wi, admitted=adm)
                self._lq_note(wi, 1, adm)
                self.assumed_workloads[key] = cq.name
                if charge_topo:
                    self.topology.charge(wl.admission, 1)
                if self._admitted_sinks:
                    self._note_admitted_sinks(wi)
                out.append(wi)
        return out

    def assume_workloads_csr(self, items, coords, cq_names,
                             flavor_names, resource_names,
                             arena=None) -> list:
        """Bulk assume with the admission usage in CSR COORDINATE form —
        the `batch_usage_csr` gather shape the admission cycle's
        re-validation already consumes (scheduler admit.reval).

        `items` is [(workload, triples, info, ci, admitted)] — every
        row satisfies
        the `fast=True` contract of assume_workloads (the precomputed
        triples exist, the info IS the scheduler entry's own, and
        info.cluster_queue matches the admission) — and `coords` is
        (ent, fi, ri, val): item j's deduped integer usage coordinates
        live at `ent == j`, valid in the caller's encoding whose
        `cq_names`/`flavor_names`/`resource_names` map indices back to
        this cache's dict keys (item j's CQ index is `ci` in its row).

        The per-item work collapses to O(1) bookkeeping per workload
        (membership, assumed set, LocalQueue counters) plus ONE
        vectorized aggregation over the coordinate arrays: the whole
        cycle's same-(cq, flavor, resource) contributions land in each
        usage dict entry once (np.unique + np.add.at), instead of one
        nested dict walk per workload — the interpreter-bound
        admit.flush.assume shape BENCH_r05 measured. `arena` (an
        AdmittedArena) ingests the same batch in one scatter-add.

        Callers gate on `not self.topology.flavors` (topology charging
        stays per-admission on the classic path). Returns the same
        per-item result list as assume_workloads."""
        import numpy as np

        ent, fi, ri, val = coords
        n = len(items)
        out = []
        keep = np.zeros(n, dtype=bool)
        item_ci = np.full(n, -1, dtype=np.int64)
        item_adm = np.zeros(n, dtype=bool)
        item_gid = np.full(n, -1, dtype=np.int64)
        lq_gid: Dict[str, int] = {}
        lq_stats_by_gid: list = []
        keys: List[str] = []
        kept_cis: List[int] = []
        F = len(flavor_names)
        R = len(resource_names)
        with self._lock:
            cqs = self.cluster_queues
            assumed = self.assumed_workloads
            local_queues = self.local_queues
            lq_stats = self._lq_stats
            for j, (wl, triples, wi, ci_j, adm) in enumerate(items):
                admission = wl.admission
                if admission is None:
                    out.append("workload has no admission")
                    continue
                key = wl.key
                if key in assumed:
                    out.append(f"workload {key} already assumed")
                    continue
                cq = cqs.get(admission.cluster_queue)
                if cq is None:
                    out.append(
                        f"ClusterQueue {admission.cluster_queue} not found")
                    continue
                keep[j] = True
                # The info was built from the pending spec (no flavor
                # assignments); the accounted triples must ride it so a
                # later delete/forget subtracts exactly what was added —
                # same contract as the classic fast path.
                wi._usage_triples = triples
                item_ci[j] = ci_j
                item_adm[j] = adm
                cq.workloads[key] = wi
                cq.usage_version += 1
                cq._mark_dirty()
                assumed[key] = cq.name
                keys.append(key)
                kept_cis.append(ci_j)
                out.append(wi)
                lq_key = f"{wl.namespace}/{wl.queue_name}"
                stats = lq_stats.get(lq_key)
                if stats is not None:
                    lq = local_queues.get(lq_key)
                    if lq is None or lq.cluster_queue != wi.cluster_queue:
                        stats = None
                if stats is not None:
                    gid = lq_gid.get(lq_key)
                    if gid is None:
                        gid = lq_gid[lq_key] = len(lq_stats_by_gid)
                        lq_stats_by_gid.append(stats)
                    item_gid[j] = gid
                    stats["reserving"] += 1
                    if adm:
                        stats["admitted"] += 1
                        stats["admitted_keys"].add(key)

            if len(ent):
                cmask = keep[ent]
                cent = ent[cmask]
                cfi = fi[cmask]
                cri = ri[cmask]
                cval = val[cmask]
                cci = item_ci[cent]
                adm_w = item_adm[cent].astype(np.int64)
                # ONE aggregation pass for the CQ-level dicts: unique
                # (cq, flavor, resource) triples with the total and the
                # admitted-split sums riding the same inverse index. The
                # unique keys sort by cq first, so the store loop
                # resolves each ClusterQueue once per run.
                ukey, inv = np.unique((cci * F + cfi) * R + cri,
                                      return_inverse=True)
                usum = np.zeros(len(ukey), dtype=np.int64)
                np.add.at(usum, inv, cval)
                asum = np.zeros(len(ukey), dtype=np.int64)
                np.add.at(asum, inv, cval * adm_w)
                uci = (ukey // (F * R)).tolist()
                ufi = ((ukey // R) % F).tolist()
                uri = (ukey % R).tolist()
                usum_l = usum.tolist()
                asum_l = asum.tolist()
                cur_ci = -1
                cq = usage = admitted_usage = None
                for t in range(len(ukey)):
                    ci_t = uci[t]
                    if ci_t != cur_ci:
                        cur_ci = ci_t
                        cq = cqs.get(cq_names[ci_t])
                        usage = cq.usage if cq is not None else None
                        admitted_usage = cq.admitted_usage \
                            if cq is not None else None
                    if usage is None:
                        continue
                    fname = flavor_names[ufi[t]]
                    rname = resource_names[uri[t]]
                    fus = usage.get(fname)
                    if fus is not None and rname in fus:
                        fus[rname] += usum_l[t]
                        a_t = asum_l[t]
                        if a_t:
                            admitted_usage[fname][rname] += a_t
                # Per-LQ reservation (and admitted) sums: same shape,
                # grouped by the LQ id assigned in the item loop.
                gids = item_gid[cent]
                lmask = gids >= 0
                if lmask.any():
                    lkey = (gids[lmask] * F + cfi[lmask]) * R + cri[lmask]
                    lukey, linv = np.unique(lkey, return_inverse=True)
                    lsum = np.zeros(len(lukey), dtype=np.int64)
                    np.add.at(lsum, linv, cval[lmask])
                    lasum = np.zeros(len(lukey), dtype=np.int64)
                    np.add.at(lasum, linv, (cval * adm_w)[lmask])
                    lg = (lukey // (F * R)).tolist()
                    lf = ((lukey // R) % F).tolist()
                    lr = (lukey % R).tolist()
                    lsum_l = lsum.tolist()
                    lasum_l = lasum.tolist()
                    cur_g = -1
                    reservation = adm_res = None
                    for t in range(len(lukey)):
                        g_t = lg[t]
                        if g_t != cur_g:
                            cur_g = g_t
                            stats = lq_stats_by_gid[g_t]
                            reservation = stats["reservation"]
                            adm_res = stats["admitted_usage"]
                        fname = flavor_names[lf[t]]
                        rname = resource_names[lr[t]]
                        f3 = reservation.setdefault(fname, {})
                        f3[rname] = f3.get(rname, 0) + lsum_l[t]
                        la = lasum_l[t]
                        if la:
                            f4 = adm_res.setdefault(fname, {})
                            f4[rname] = f4.get(rname, 0) + la
            else:
                cent = np.empty(0, dtype=np.int64)
                cfi = cri = cval = cent

            if arena is not None and keys:
                remap = np.full(n, -1, dtype=np.int64)
                remap[np.nonzero(keep)[0]] = np.arange(len(keys))
                arena.note_batch(keys, kept_cis, remap[cent], cfi, cri,
                                 cval)
            if self._admitted_sinks:
                for sink in self._admitted_sinks:
                    if sink is arena:
                        continue
                    for res in out:
                        if not isinstance(res, str):
                            sink.note_admitted(res)
        return out

    def forget_workload(self, wl: Workload) -> None:
        with self._lock:
            if wl.key not in self.assumed_workloads:
                raise ValueError(f"workload {wl.key} is not assumed")
            self._delete_workload_locked(wl)

    def is_assumed_or_admitted(self, wl: Workload) -> bool:
        with self._lock:
            if wl.key in self.assumed_workloads:
                return True
            if wl.admission is None:
                return False
            cq = self.cluster_queues.get(wl.admission.cluster_queue)
            return cq is not None and wl.key in cq.workloads

    def assumed_or_admitted_bulk(self, wls) -> List[bool]:
        """is_assumed_or_admitted over many workloads under ONE lock
        acquisition (the tick gates every popped head through this)."""
        out = []
        with self._lock:
            assumed = self.assumed_workloads
            cqs = self.cluster_queues
            for wl in wls:
                if wl.key in assumed:
                    out.append(True)
                    continue
                adm = wl.admission
                if adm is None:
                    out.append(False)
                    continue
                cq = cqs.get(adm.cluster_queue)
                out.append(cq is not None and wl.key in cq.workloads)
        return out

    def usage(self, cq_name: str) -> FlavorResourceQuantities:
        with self._lock:
            return frq_clone(self.cluster_queues[cq_name].usage)

    def local_queue_status(self, lq_key: str) -> Optional[dict]:
        """Per-LocalQueue usage stats for the LQ reconciler's status
        (reference: cache.go:607-658 LocalQueueUsage — reserving/admitted
        workload counts plus per-flavor reservation and admitted usage).
        O(flavors) — maintained incrementally on workload add/delete, so
        status reads never scan workloads under the cache lock."""
        with self._lock:
            if lq_key not in self.local_queues:
                return None
            stats = self._lq_stats.get(lq_key)
            if stats is None:
                stats = self._fresh_lq_stats()
            return {
                "reservingWorkloads": stats["reserving"],
                "admittedWorkloads": stats["admitted"],
                "flavorsReservation": frq_clone(stats["reservation"]),
                "flavorUsage": frq_clone(stats["admitted_usage"]),
            }

    # -- snapshot ------------------------------------------------------------

    def snapshot(self):
        from kueue_tpu.core.snapshot import Snapshot
        with self._lock:
            return Snapshot.build(self)
