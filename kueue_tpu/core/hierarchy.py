"""Hierarchical-cohort feasibility (KEP-79, implemented from the KEP —
the reference snapshot designs but does not implement it).

The cohort structure is a tree: ClusterQueues are leaves, Cohorts inner
nodes; a Cohort may carry its own shareable quota and per-(flavor,resource)
borrowing/lending limits. Admission keeps the balance function

    T(cq, r)     = quota(cq, r) - usage(cq, r)
    T(cohort, r) = quota(cohort, r)
                   + sum over children c of min(lendingLimit(c, r), T(c, r))

within bounds: a workload may be admitted iff, after adding its usage,
`T(x, r) >= -borrowingLimit(x, r)` holds at every node x of the hierarchy
(keps/79-hierarchical-cohorts/README.md "Design Details"). Only the
admitting ClusterQueue's ancestor path can change, so the check walks that
path, propagating the (lending-clamped) delta upward.

A cycle in the tree stops all admissions within the affected structure
(the snapshot marks its ClusterQueues inactive; see core/snapshot.py).

Lending/borrowing limits at the ClusterQueue level participate in the tree
math whenever the tree is hierarchical; the flat 2-level path keeps the
reference's LendingLimit feature-gate semantics untouched.
"""

from __future__ import annotations

from typing import Optional, Tuple

from kueue_tpu.core.cache import CachedClusterQueue, Cohort


def _cq_quota(cq: CachedClusterQueue, flavor: str, resource: str):
    rg = cq.rg_by_resource.get(resource)
    if rg is None:
        return None
    for fq in rg.flavors:
        if fq.name == flavor:
            return fq.resources_dict.get(resource)
    return None


def _clamp(limit: Optional[int], t: int) -> int:
    """min(lendingLimit, T); no limit lets the whole balance through."""
    return t if limit is None else min(limit, t)


def _cq_t(cq: CachedClusterQueue, flavor: str, resource: str,
          ignore_usage: bool) -> Tuple[int, Optional[int]]:
    """(T, lendingLimit) of a leaf ClusterQueue."""
    quota = _cq_quota(cq, flavor, resource)
    if quota is None:
        return 0, 0  # nothing of this (flavor, resource) to lend
    used = 0 if ignore_usage else cq.usage.get(flavor, {}).get(resource, 0)
    return quota.nominal - used, quota.lending_limit


def subtree_t(cohort: Cohort, flavor: str, resource: str,
              ignore_usage: bool = False,
              memo: Optional[dict] = None,
              extra: Optional[dict] = None) -> int:
    """T(cohort): the balance the subtree can deliver (negative = its
    debt to the rest of the hierarchy). With `memo`, each node is computed
    once — callers walking several ancestors share one full-tree pass.

    `extra` is {cohort name: {flavor: {resource: val}}} of usage reserved
    inside each node's subtree but not yet visible in the snapshot (the
    admission cycle's same-tick bookkeeping, scheduler.go:204-275):
    subtracted at the node where it was recorded, it propagates upward
    through the lending clamps like real usage would."""
    if memo is not None and id(cohort) in memo:
        return memo[id(cohort)]
    own = cohort.own_quota(flavor, resource)
    total = own.nominal if own is not None else 0
    if extra is not None:
        total -= extra.get(cohort.name, {}).get(flavor, {}).get(resource, 0)
    for member in cohort.members:
        t, lend = _cq_t(member, flavor, resource, ignore_usage)
        total += _clamp(lend, t)
    for child in cohort.children:
        t = subtree_t(child, flavor, resource, ignore_usage, memo, extra)
        child_own = child.own_quota(flavor, resource)
        lend = child_own.lending_limit if child_own is not None else None
        total += _clamp(lend, t)
    if memo is not None:
        memo[id(cohort)] = total
    return total


def _node_limits(node: Cohort, flavor: str,
                 resource: str) -> Tuple[Optional[int], Optional[int]]:
    """(borrowingLimit, lendingLimit) of a cohort node; None = unlimited.
    A root node's borrowingLimit is always 0 — there is nobody above to
    borrow from (KEP-79 API comments)."""
    own = node.own_quota(flavor, resource)
    blim = own.borrowing_limit if own is not None else None
    lend = own.lending_limit if own is not None else None
    if node.parent is None:
        blim = 0
    return blim, lend


def hierarchical_lack(cq: CachedClusterQueue, flavor: str, resource: str,
                      val: int, ignore_usage: bool = False,
                      extra: Optional[dict] = None) -> int:
    """Largest T-invariant shortfall along cq's ancestor path after adding
    `val` of (flavor, resource) to it; 0 means the admission keeps every
    balance. With ignore_usage the check runs against an empty tree — the
    ceiling preemptions could ever free (the borrowWithinCohort bound).
    `extra` is per-node same-cycle reserved usage (see subtree_t)."""
    quota = _cq_quota(cq, flavor, resource)
    nominal = quota.nominal if quota is not None else 0
    lend = quota.lending_limit if quota is not None else None
    used = 0 if ignore_usage else cq.usage.get(flavor, {}).get(resource, 0)
    t_old = nominal - used
    delta = _clamp(lend, t_old) - _clamp(lend, t_old - val)

    lack = 0
    node = cq.cohort
    # One shared memo: every subtree below the path is walked exactly once
    # for the whole ancestor loop (an ancestor's T reuses its children's).
    memo: dict = {}
    while node is not None:
        t = subtree_t(node, flavor, resource, ignore_usage, memo, extra)
        t_new = t - delta
        blim, node_lend = _node_limits(node, flavor, resource)
        if blim is not None and t_new < -blim:
            lack = max(lack, -blim - t_new)
        delta = _clamp(node_lend, t) - _clamp(node_lend, t_new)
        node = node.parent
    return lack


def tree_capacity(root: Cohort) -> dict:
    """{flavor: {resource: lendable}} of the whole structure — cohort-level
    nominal quota plus every member ClusterQueue's lendable quota. The
    fair-sharing denominator (KEP-1714 share value) for hierarchical trees."""
    out: dict = {}

    def add(flavor, resource, v):
        out.setdefault(flavor, {})
        out[flavor][resource] = out[flavor].get(resource, 0) + v

    stack = [root]
    while stack:
        node = stack.pop()
        if node.spec is not None:
            for rg in node.spec.resource_groups:
                for fq in rg.flavors:
                    for rname, quota in fq.resources:
                        add(fq.name, rname, quota.nominal)
        for member in node.members:
            for rg in member.resource_groups:
                for fq in rg.flavors:
                    for rname, quota in fq.resources:
                        add(fq.name, rname,
                            quota.lending_limit
                            if quota.lending_limit is not None
                            else quota.nominal)
        stack.extend(node.children)
    return out


def fits_in_hierarchy(cq: CachedClusterQueue, usage, *,
                      ignore_usage: bool = False,
                      extra: Optional[dict] = None) -> bool:
    """All balances hold after adding a {flavor: {resource: val}} map.
    `extra` charges per-node same-cycle reservations (see subtree_t)."""
    for flavor, resources in usage.items():
        for resource, val in resources.items():
            if hierarchical_lack(cq, flavor, resource, val,
                                 ignore_usage=ignore_usage,
                                 extra=extra) > 0:
                return False
    return True
